"""IMPALA / A3C — decoupled-actor semantics, reformulated for TPU.

Capability parity with the reference's fifth config: "A3C / IMPALA on
Atari Pong (CNN encoder, N parallel actors, V-trace)" (BASELINE.json:11;
reference mount empty at survey, SURVEY.md §0).

The reference's genre runs N async host workers feeding a learner over
IPC queues (SURVEY.md §3.3); the off-policyness that V-trace corrects is
an *accident* of that asynchrony.  The TPU-native reformulation
(SURVEY.md §2.3 "Async actor-learner") keeps the semantics and drops the
host machinery:

- the N parallel actors become a vmapped env axis inside one jitted
  program (the same fused rollout as A2C);
- the actor policy is a deliberately STALE copy of the learner params,
  refreshed every `actor_refresh_every` learner steps — reproducing
  IMPALA's k-step policy lag explicitly and deterministically;
- behaviour log-probs are recorded at rollout time and V-trace
  (ops/returns.py) corrects the lag at the learner, exactly as IMPALA's
  importance weights correct queue-induced lag.

`correction="vtrace"` is IMPALA; `correction="none"` computes plain
λ-return advantages under the learner's critic with no importance
weighting — the A3C update rule (which simply tolerates the small bias
that staleness introduces), so both reference algorithms are covered by
one trainer.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from actor_critic_tpu.algos.common import (
    RolloutState,
    corrected_advantages,
    init_rollout,
    rollout_scan,
    episode_metrics_update,
    truncation_bootstrap_rewards,
)
from actor_critic_tpu.algos.metrics import aggregate_metrics
from actor_critic_tpu.envs.jax_env import JaxEnv
from actor_critic_tpu.models.networks import ActorCriticDiscrete, ActorCriticGaussian
from actor_critic_tpu.parallel import mesh as pmesh


@dataclasses.dataclass(frozen=True)
class ImpalaConfig:
    num_envs: int = 32          # the reference's "N parallel actors"
    rollout_steps: int = 20     # IMPALA's unroll length
    gamma: float = 0.99
    lr: float = 6e-4
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    rho_bar: float = 1.0        # V-trace ρ̄ clip
    c_bar: float = 1.0          # V-trace c̄ clip
    lam: float = 1.0            # V-trace λ (1.0 = canonical IMPALA)
    actor_refresh_every: int = 1  # k-step policy lag (1 = on-policy)
    correction: str = "vtrace"  # "vtrace" (IMPALA) | "none" (A3C)
    max_grad_norm: float = 40.0
    hidden: tuple[int, ...] = (64, 64)
    # RMSProp epsilon/decay follow the IMPALA paper's published settings.
    rms_decay: float = 0.99
    rms_eps: float = 0.1
    bf16_compute: bool = False

    def __post_init__(self):
        if self.correction not in ("vtrace", "none"):
            raise ValueError(f"unknown correction: {self.correction!r}")
        if self.actor_refresh_every < 1:
            raise ValueError("actor_refresh_every must be >= 1")


class ImpalaTrainState(NamedTuple):
    params: Any           # learner params
    actor_params: Any     # stale behaviour-policy params
    opt_state: Any
    rollout: RolloutState
    key: jax.Array
    update_step: jax.Array
    ep_return: jax.Array
    ep_length: jax.Array
    avg_return: jax.Array


def make_network(env: JaxEnv, cfg: ImpalaConfig):
    dtype = jnp.bfloat16 if cfg.bf16_compute else jnp.float32
    if env.spec.discrete:
        return ActorCriticDiscrete(
            num_actions=env.spec.action_dim,
            hidden=cfg.hidden,
            pixel_obs=env.spec.pixel_obs,
            compute_dtype=dtype,
        )
    return ActorCriticGaussian(
        action_dim=env.spec.action_dim, hidden=cfg.hidden, compute_dtype=dtype
    )


def make_eval_fn(env: JaxEnv, cfg: "ImpalaConfig"):
    """Greedy (mode-action) eval program (SURVEY.md §3.4)."""
    from actor_critic_tpu.algos.common import make_mode_eval

    return make_mode_eval(env, make_network(env, cfg))


def make_optimizer(cfg: ImpalaConfig) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.rmsprop(cfg.lr, decay=cfg.rms_decay, eps=cfg.rms_eps),
    )


def init_state(env: JaxEnv, cfg: ImpalaConfig, key: jax.Array) -> ImpalaTrainState:
    net = make_network(env, cfg)
    opt = make_optimizer(cfg)
    key, pkey, rkey = jax.random.split(key, 3)
    dummy = jnp.zeros((1, *env.spec.obs_shape), env.spec.obs_dtype)
    params = net.init(pkey, dummy)
    E = cfg.num_envs
    return ImpalaTrainState(
        params=params,
        # In sync until the first refresh boundary; materialized as a
        # distinct buffer so donating the whole state never aliases the
        # same array twice (donation is how the fused loops avoid copies).
        actor_params=jax.tree.map(jnp.copy, params),
        opt_state=opt.init(params),
        rollout=init_rollout(env, rkey, E),
        key=key,
        update_step=jnp.zeros((), jnp.int32),
        ep_return=jnp.zeros((E,)),
        ep_length=jnp.zeros((E,)),
        avg_return=jnp.zeros(()),
    )


def impala_loss(
    params: Any,
    apply_fn: Callable,
    traj,
    bootstrap_obs: jax.Array,
    cfg: ImpalaConfig,
    can_truncate: bool = True,
    time_axis_name: Optional[str] = None,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """V-trace (or A3C λ-return) actor-critic loss on a [T, E] trajectory.

    The learner re-evaluates π/V at the stored observations; `traj.log_prob`
    holds the BEHAVIOUR policy's log-probs from rollout time, so the
    ρ = π/μ importance ratios are exact even under parameter staleness.

    With `time_axis_name` the function runs INSIDE shard_map with the
    trajectory's TIME axis sharded over that mesh axis (sequence
    parallelism, SURVEY.md §5.7): the V-trace/GAE recurrences go through
    `parallel.seqpar` (halo exchange + per-segment affine scan + boundary
    chain over ICI), and the returned loss/metrics are LOCAL means whose
    gradients the caller must pmean over the axis (equal time shards make
    the pmean of local-mean grads exactly the global-mean grad).
    """
    T, E = traj.reward.shape
    obs = traj.obs.reshape(T * E, *traj.obs.shape[2:])
    actions = traj.action.reshape(T * E, *traj.action.shape[2:])

    dist, values = apply_fn(params, obs)
    target_log_probs = dist.log_prob(actions).reshape(T, E)
    values = values.reshape(T, E)
    # Explicit fp32 accumulators on every reduction: bit-identical in
    # fp32 mode (the heads cast up), precision-discipline-required under
    # --update-dtype bf16 (bf16 compute, fp32 accumulation).
    entropy = jnp.mean(dist.entropy(), dtype=jnp.float32)
    _, bootstrap_value = apply_fn(params, bootstrap_obs)

    if can_truncate:
        # Truncation bootstrap under the LEARNER's critic.
        flat_final = traj.final_obs.reshape(T * E, *traj.final_obs.shape[2:])
        _, final_values = apply_fn(params, flat_final)
        rewards = truncation_bootstrap_rewards(
            traj, final_values.reshape(T, E), cfg.gamma
        )
    else:
        rewards = traj.reward

    # Correction machinery shared with the async actor–learner PPO
    # update (ISSUE 6): V-trace or plain λ-return, sequence-parallel
    # when a time axis name is given.
    pg_advantages, value_targets, mean_rho = corrected_advantages(
        jax.lax.stop_gradient(target_log_probs),
        traj.log_prob,
        rewards,
        jax.lax.stop_gradient(values),
        traj.done,
        jax.lax.stop_gradient(bootstrap_value),
        cfg.gamma,
        cfg.lam,
        rho_bar=cfg.rho_bar,
        c_bar=cfg.c_bar,
        correction=cfg.correction,
        time_axis_name=time_axis_name,
    )

    pg_loss = -jnp.mean(
        jax.lax.stop_gradient(pg_advantages) * target_log_probs,
        dtype=jnp.float32,
    )
    v_loss = 0.5 * jnp.mean(
        (values - jax.lax.stop_gradient(value_targets)) ** 2,
        dtype=jnp.float32,
    )
    loss = pg_loss + cfg.value_coef * v_loss - cfg.entropy_coef * entropy
    return loss, {
        "loss": loss,
        "pg_loss": pg_loss,
        "v_loss": v_loss,
        "entropy": entropy,
        "mean_rho": mean_rho,
    }


def make_train_step(
    env: JaxEnv,
    cfg: ImpalaConfig,
    axis_name: Optional[str] = None,
) -> Callable[[ImpalaTrainState], tuple[ImpalaTrainState, dict[str, jax.Array]]]:
    """Fused rollout(stale actor) → V-trace → update → k-step actor refresh."""
    net = make_network(env, cfg)
    opt = make_optimizer(cfg)
    apply_fn = net.apply

    def train_step(state: ImpalaTrainState):
        key, rkey = jax.random.split(state.key)

        # Actors run the STALE params; behaviour log-probs are recorded.
        new_rollout, traj = rollout_scan(
            env, apply_fn, state.actor_params, state.rollout, rkey,
            cfg.rollout_steps,
        )

        grad_fn = jax.value_and_grad(impala_loss, has_aux=True)
        (_, metrics), grads = grad_fn(
            state.params, apply_fn, traj, new_rollout.obs, cfg,
            env.spec.can_truncate,
        )
        grads = pmesh.pmean_tree(grads, axis_name)
        updates, new_opt_state = opt.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        # k-step policy lag: actors pick up the learner params only at
        # refresh boundaries (k=1 degrades gracefully to on-policy, where
        # every ρ is exactly 1 — tested in tests/test_impala.py).
        new_step = state.update_step + 1
        refresh = (new_step % cfg.actor_refresh_every) == 0
        new_actor_params = jax.tree.map(
            lambda n, o: jnp.where(refresh, n, o), new_params, state.actor_params
        )

        ep_ret, ep_len, avg_ret, ep_metrics = episode_metrics_update(
            state.ep_return, state.ep_length, state.avg_return, traj
        )
        avg_ret = pmesh.pmean(avg_ret, axis_name)
        ep_metrics["avg_return_ema"] = avg_ret
        metrics = aggregate_metrics(metrics, ep_metrics, axis_name)

        new_state = ImpalaTrainState(
            params=new_params,
            actor_params=new_actor_params,
            opt_state=new_opt_state,
            rollout=new_rollout,
            key=key,
            update_step=new_step,
            ep_return=ep_ret,
            ep_length=ep_len,
            avg_return=avg_ret,
        )
        return new_state, metrics

    return train_step


def make_sp_update(
    env: JaxEnv, cfg: ImpalaConfig, mesh, axis_name=None, dp_axis_name=None
):
    """Sequence-parallel learner update for LONG trajectories (SURVEY.md
    §5.7 made load-bearing): the [T, E] trajectory's TIME axis is sharded
    over the mesh's "sp" axis, so each device forwards π/V on its T/D
    slice, the V-trace (or λ-return) recurrence runs through
    `parallel.seqpar` (one ppermute halo + per-segment affine scan + a
    tiny all_gather boundary chain — collectives ride ICI), and gradients
    pmean over the axis. Per-device activation memory and scan length
    drop from O(T) to O(T/D): trajectories too long for one device's HBM
    (or one scan's latency budget) become trainable.

    With `dp_axis_name` the update runs over a 2-D sp×dp mesh: the env
    batch axis additionally shards over dp (the recurrence is
    independent per env, so dp needs no extra communication beyond the
    gradient/metric pmean, which then reduces over BOTH axes).

    Returns jitted `(params, opt_state, traj, bootstrap_obs) →
    (params, opt_state, metrics)` on GLOBAL [T, E] arrays; T must divide
    by the mesh's sp size (and E by its dp size). Metric-equivalence
    with the unsharded update is tested on the 8-device CPU mesh, in
    both 1-D sp and 2×4 sp×dp layouts (tests/test_seqpar.py).
    """
    fn, _, _ = _sp_update_shardmap(env, cfg, mesh, axis_name, dp_axis_name)
    return jax.jit(fn)


def _sp_update_shardmap(env, cfg, mesh, axis_name=None, dp_axis_name=None):
    """The shard_map'd sp learner update, un-jitted, plus the traj /
    bootstrap PartitionSpecs — shared by `make_sp_update` (standalone)
    and `make_sp_train_step` (fused rollout→update program)."""
    from jax.sharding import PartitionSpec as P

    from actor_critic_tpu.parallel.seqpar import SP_AXIS

    axis_name = axis_name or SP_AXIS
    # lax.pmean accepts an axis-name tuple: one reduction over both axes.
    reduce_axes = (
        axis_name if dp_axis_name is None else (axis_name, dp_axis_name)
    )
    traj_spec = (
        P(axis_name) if dp_axis_name is None else P(axis_name, dp_axis_name)
    )
    boot_spec = P() if dp_axis_name is None else P(dp_axis_name)
    net = make_network(env, cfg)
    opt = make_optimizer(cfg)

    def local_update(params, opt_state, traj, bootstrap_obs):
        grad_fn = jax.value_and_grad(impala_loss, has_aux=True)
        (_, metrics), grads = grad_fn(
            params, net.apply, traj, bootstrap_obs, cfg,
            env.spec.can_truncate, axis_name,
        )
        grads = pmesh.pmean_tree(grads, reduce_axes)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = {k: pmesh.pmean(v, reduce_axes) for k, v in metrics.items()}
        return params, opt_state, metrics

    from actor_critic_tpu.parallel.mesh import shard_map

    fn = shard_map(
        local_update,
        mesh=mesh,
        in_specs=(P(), P(), traj_spec, boot_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return fn, traj_spec, boot_spec


def make_sp_train_step(
    env: JaxEnv, cfg: ImpalaConfig, mesh, axis_name=None, dp_axis_name=None
):
    """ONE jitted program: rollout(stale actor) → resharding constraint →
    sequence-parallel V-trace update → k-step actor refresh.

    This is the end-to-end form of the claim sp exists for: a trainer
    PRODUCES the long [T, E] trajectory (rollout is time-sequential by
    nature, so it runs env-parallel — sharded over the mesh's dp axis
    when present) and the learner consumes it time-sharded over sp, with
    XLA inserting the redistribution between the two layouts inside the
    same program. Metric/param equivalence with `make_train_step` is
    tested on the 8-device CPU mesh (tests/test_seqpar.py).
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    upd, traj_spec, _ = _sp_update_shardmap(
        env, cfg, mesh, axis_name, dp_axis_name
    )
    net = make_network(env, cfg)
    apply_fn = net.apply

    def train_step(state: ImpalaTrainState):
        key, rkey = jax.random.split(state.key)
        # The rollout is time-SEQUENTIAL (a scan), so it cannot be sp-
        # sharded; pin its carry replicated so sharding propagation from
        # the sp-resharded consumer below can't leak a partitioned
        # layout back into the per-step vmap (explicit-mesh axes are
        # part of the value types).
        rollout_in = jax.tree.map(
            lambda x: pmesh.reshard(x, NamedSharding(mesh, P())),
            state.rollout,
        )
        new_rollout, traj = rollout_scan(
            env, apply_fn, state.actor_params, rollout_in, rkey,
            cfg.rollout_steps,
        )
        # Episode accounting folds a scan over TIME, so it reads the
        # rollout-layout trajectory (before the time axis is sharded).
        ep_ret, ep_len, avg_ret, ep_metrics = episode_metrics_update(
            state.ep_return, state.ep_length, state.avg_return, traj
        )

        # Rollout materializes [T, E] time-major on the dp layout; the
        # reshard makes XLA redistribute the TIME axis over sp for the
        # learner (an all-to-all over ICI) inside this program. (The
        # mesh axes are Explicit-typed, so `reshard` is the constraint
        # API — with_sharding_constraint only talks to Auto axes.)
        traj_sp = jax.tree.map(
            lambda x: pmesh.reshard(
                x,
                NamedSharding(
                    mesh,
                    P(*traj_spec, *((None,) * (x.ndim - len(traj_spec)))),
                ),
            ),
            traj,
        )
        new_params, new_opt_state, metrics = upd(
            state.params, state.opt_state, traj_sp, new_rollout.obs
        )

        new_step = state.update_step + 1
        refresh = (new_step % cfg.actor_refresh_every) == 0
        new_actor_params = jax.tree.map(
            lambda n, o: jnp.where(refresh, n, o), new_params,
            state.actor_params,
        )
        ep_metrics["avg_return_ema"] = avg_ret
        # Same derived metric keys as make_train_step (mean_finished_
        # return, mean_ep_length, ...): upd's metrics are already
        # mesh-reduced and ep_metrics are global-array sums, so no axis.
        metrics = aggregate_metrics(metrics, ep_metrics, None)
        new_state = ImpalaTrainState(
            params=new_params,
            actor_params=new_actor_params,
            opt_state=new_opt_state,
            rollout=new_rollout,
            key=key,
            update_step=new_step,
            ep_return=ep_ret,
            ep_length=ep_len,
            avg_return=avg_ret,
        )
        return new_state, metrics

    return jax.jit(train_step)


def train(
    env: JaxEnv,
    cfg: ImpalaConfig,
    num_iterations: int,
    seed: int = 0,
    state: Optional[ImpalaTrainState] = None,
    log_every: int = 0,
    log_fn: Optional[Callable[[int, dict], None]] = None,
) -> tuple[ImpalaTrainState, dict[str, jax.Array]]:
    """Host loop around the fused step; `log_every=0` scans all iterations
    on-device in a single dispatch (same pattern as a2c.train)."""
    from actor_critic_tpu.algos.host_loop import fused_train_loop

    return fused_train_loop(
        make_train_step, init_state, env, cfg, num_iterations,
        seed=seed, state=state, log_every=log_every, log_fn=log_fn,
        scan_when_silent=True,
    )


# -- AOT warmup registry (utils/compile_cache.py, ISSUE 4) ------------------
# The sp (mesh-sharded) programs are exempt from warmup: they are built
# only by the explicit parallel drivers (see compile_cache.EXEMPT).
from actor_critic_tpu.utils import compile_cache as _compile_cache  # noqa: E402

_compile_cache.register_fused_warmups(
    "impala", ("impala", "a3c"), init_state, make_train_step, make_eval_fn
)
