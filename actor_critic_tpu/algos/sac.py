"""SAC — soft actor-critic with twin-Q and automatic entropy temperature.

Capability parity with the reference's SAC Humanoid config
(BASELINE.json:10: "twin-Q, entropy-temperature auto-tune"; reference
mount empty at survey, SURVEY.md §0). Same TPU-first shape as
algos/ddpg.py: the replay ring lives in HBM, and the fused path runs
collect → insert → J soft-policy-iteration updates as one jitted,
donated program (SURVEY §3.2 boundary fix).

Per update (Haarnoja et al. 2018, soft policy iteration):
  critic:  y = r + γ(1−term)·[min(Q̄₁,Q̄₂)(s', a') − α·log π(a'|s')],
           a' ~ π(·|s')  (fresh sample, tanh-Gaussian)
  actor:   min E[α·log π(a|s) − min(Q₁,Q₂)(s, a)]  (reparameterized)
  alpha:   min_α E[−α·(log π(a|s) + H_target)],  H_target = −action_dim
           (optimized in log α; the update uses the analytic gradient
           d/d(log α) = −α·E[log π + H_target])
  targets: Polyak on the twin critic only (no target actor in SAC).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from actor_critic_tpu import replay
from actor_critic_tpu.algos.common import (
    OffPolicyTransition,
    RolloutState,
    episode_metrics_update,
    init_rollout,
    offpolicy_rollout,
)
from actor_critic_tpu.algos.metrics import aggregate_metrics
from actor_critic_tpu.envs.jax_env import JaxEnv
from actor_critic_tpu.models.networks import SquashedGaussianActor, TwinQ
from actor_critic_tpu.ops.polyak import polyak_update
from actor_critic_tpu.parallel import mesh as pmesh


@dataclasses.dataclass(frozen=True)
class SACConfig:
    num_envs: int = 8
    steps_per_iter: int = 8
    updates_per_iter: int = 8
    buffer_capacity: int = 1_000_000
    batch_size: int = 256
    gamma: float = 0.99
    tau: float = 0.005
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    hidden: tuple[int, ...] = (256, 256)
    warmup_steps: int = 1_000
    init_alpha: float = 1.0
    # None → auto-tune toward target_entropy (default −action_dim);
    # a float here freezes α at that value (no alpha optimizer step).
    fixed_alpha: Optional[float] = None
    target_entropy: Optional[float] = None
    bf16_compute: bool = False
    # Quantized replay storage (ISSUE 8, replay/quantize.py): "fp32" |
    # "mixed" (int8-standardized obs/rewards, fp32 actions — the tanh
    # actor's actions concentrate where int8 is coarsest) | "int8".
    replay_dtype: str = "fp32"

    def __post_init__(self):
        if self.init_alpha <= 0.0:
            raise ValueError("init_alpha must be > 0 (α is parameterized in log)")
        if self.fixed_alpha is not None and self.fixed_alpha <= 0.0:
            raise ValueError("fixed_alpha must be > 0 (α is parameterized in log)")


class SACLearnerState(NamedTuple):
    """Device-resident SAC learner (actor, twin critic, α, replay)."""

    actor_params: Any
    critic_params: Any
    target_critic: Any
    actor_opt: Any
    critic_opt: Any
    log_alpha: jax.Array
    alpha_opt: Any
    replay: replay.ReplayState
    key: jax.Array
    update_count: jax.Array


class SACState(NamedTuple):
    """Fused-trainer state: learner + env batch + accounting."""

    learner: SACLearnerState
    rollout: RolloutState
    env_steps: jax.Array
    update_step: jax.Array
    ep_return: jax.Array
    ep_length: jax.Array
    avg_return: jax.Array


def _modules(action_dim: int, cfg: SACConfig):
    dtype = jnp.bfloat16 if cfg.bf16_compute else jnp.float32
    actor = SquashedGaussianActor(action_dim, cfg.hidden, compute_dtype=dtype)
    critic = TwinQ(cfg.hidden, compute_dtype=dtype)
    return actor, critic


def _target_entropy(action_dim: int, cfg: SACConfig) -> float:
    return (
        cfg.target_entropy if cfg.target_entropy is not None else -float(action_dim)
    )


def init_learner(
    obs_shape: tuple[int, ...], action_dim: int, cfg: SACConfig, key: jax.Array
) -> SACLearnerState:
    actor, critic = _modules(action_dim, cfg)
    akey, ckey, lkey = jax.random.split(key, 3)
    dummy_obs = jnp.zeros((1, *obs_shape), jnp.float32)
    dummy_act = jnp.zeros((1, action_dim), jnp.float32)
    actor_params = actor.init(akey, dummy_obs)
    critic_params = critic.init(ckey, dummy_obs, dummy_act)
    log_alpha = jnp.log(
        jnp.asarray(
            cfg.init_alpha if cfg.fixed_alpha is None else cfg.fixed_alpha,
            jnp.float32,
        )
    )
    example = OffPolicyTransition(
        obs=jnp.zeros(obs_shape, jnp.float32),
        action=jnp.zeros((action_dim,), jnp.float32),
        reward=jnp.zeros((), jnp.float32),
        next_obs=jnp.zeros(obs_shape, jnp.float32),
        terminated=jnp.zeros((), jnp.float32),
        done=jnp.zeros((), jnp.float32),
    )
    return SACLearnerState(
        actor_params=actor_params,
        critic_params=critic_params,
        # Distinct buffer from the online critic: the fused trainer
        # donates its state and XLA rejects aliased donations.
        target_critic=jax.tree.map(jnp.copy, critic_params),
        actor_opt=optax.adam(cfg.actor_lr).init(actor_params),
        critic_opt=optax.adam(cfg.critic_lr).init(critic_params),
        log_alpha=log_alpha,
        alpha_opt=optax.adam(cfg.alpha_lr).init(log_alpha),
        replay=replay.init(
            example, cfg.buffer_capacity,
            replay.offpolicy_codecs(cfg.replay_dtype),
        ),
        key=lkey,
        update_count=jnp.zeros((), jnp.int32),
    )


def init_state(env: JaxEnv, cfg: SACConfig, key: jax.Array) -> SACState:
    key, lkey, rkey = jax.random.split(key, 3)
    learner = init_learner(env.spec.obs_shape, env.spec.action_dim, cfg, lkey)
    E = cfg.num_envs
    return SACState(
        learner=learner,
        rollout=init_rollout(env, rkey, E),
        env_steps=jnp.zeros((), jnp.int32),
        update_step=jnp.zeros((), jnp.int32),
        ep_return=jnp.zeros((E,)),
        ep_length=jnp.zeros((E,)),
        avg_return=jnp.zeros(()),
    )


def make_eval_fn(env: JaxEnv, cfg: "SACConfig"):
    """Greedy (tanh-mean) eval program (SURVEY.md §3.4); see
    common.make_greedy_eval for the shared contract."""
    from actor_critic_tpu.algos.common import make_greedy_eval

    actor, _ = _modules(env.spec.action_dim, cfg)
    return make_greedy_eval(
        env, lambda p, o: actor.apply(p, o).mode(),
        lambda s: s.learner.actor_params,
    )


def make_explore_fn(action_dim: int, cfg: SACConfig):
    """Behavior policy: sample the tanh-Gaussian; uniform during warmup."""
    actor, _ = _modules(action_dim, cfg)

    def act(params, obs, key, env_steps):
        skey, ukey = jax.random.split(key)
        dist = actor.apply(params, obs)
        a = dist.sample(skey)
        rand = jax.random.uniform(ukey, a.shape, minval=-1.0, maxval=1.0)
        return jnp.where(env_steps < cfg.warmup_steps, rand, a)

    return act


def make_update_loop(
    action_dim: int,
    cfg: SACConfig,
    axis_name: Optional[str] = None,
) -> Callable[[SACLearnerState, jax.Array], tuple[SACLearnerState, dict]]:
    """Build `(learner, do_update) → (learner, metrics)`: a scan of
    `cfg.updates_per_iter` soft-policy-iteration steps. Warmup gating is
    a branchless `where`-select, as in ddpg.make_update_loop."""
    actor, critic = _modules(action_dim, cfg)
    h_target = _target_entropy(action_dim, cfg)
    codecs = replay.offpolicy_codecs(cfg.replay_dtype)

    def critic_loss_fn(critic_params, target_q, batch: OffPolicyTransition):
        q1, q2 = critic.apply(critic_params, batch.obs, batch.action)
        return jnp.mean((q1 - target_q) ** 2) + jnp.mean((q2 - target_q) ** 2), (
            jnp.mean(q1)
        )

    def actor_loss_fn(actor_params, critic_params, alpha, obs, key):
        dist = actor.apply(actor_params, obs)
        a, logp = dist.sample_and_log_prob(key)
        q1, q2 = critic.apply(critic_params, obs, a)
        q = jnp.minimum(q1, q2)
        return jnp.mean(alpha * logp - q), logp

    def select(mask, new, old):
        return jax.tree.map(lambda n, o: jnp.where(mask, n, o), new, old)

    def one_update(ls: SACLearnerState, do_update: jax.Array):
        key, skey, tkey, akey = jax.random.split(ls.key, 4)
        batch: OffPolicyTransition = replay.sample(
            ls.replay, skey, cfg.batch_size, codecs
        )
        alpha = jnp.exp(ls.log_alpha)

        # --- soft TD target ---
        next_dist = actor.apply(ls.actor_params, batch.next_obs)
        next_a, next_logp = next_dist.sample_and_log_prob(tkey)
        tq1, tq2 = critic.apply(ls.target_critic, batch.next_obs, next_a)
        next_v = jnp.minimum(tq1, tq2) - alpha * next_logp
        target_q = jax.lax.stop_gradient(
            batch.reward + cfg.gamma * (1.0 - batch.terminated) * next_v
        )

        # --- critic step ---
        (closs, q_mean), cgrads = jax.value_and_grad(critic_loss_fn, has_aux=True)(
            ls.critic_params, target_q, batch
        )
        cgrads = pmesh.pmean_tree(cgrads, axis_name)
        cupd, critic_opt = optax.adam(cfg.critic_lr).update(cgrads, ls.critic_opt)
        critic_params = optax.apply_updates(ls.critic_params, cupd)
        critic_params = select(do_update, critic_params, ls.critic_params)
        critic_opt = select(do_update, critic_opt, ls.critic_opt)

        # --- actor step (fresh reparameterized sample, updated critic) ---
        (aloss, logp), agrads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            ls.actor_params, critic_params, alpha, batch.obs, akey
        )
        agrads = pmesh.pmean_tree(agrads, axis_name)
        aupd, actor_opt = optax.adam(cfg.actor_lr).update(agrads, ls.actor_opt)
        actor_params = optax.apply_updates(ls.actor_params, aupd)
        actor_params = select(do_update, actor_params, ls.actor_params)
        actor_opt = select(do_update, actor_opt, ls.actor_opt)

        # --- temperature step (skipped entirely with fixed_alpha) ---
        if cfg.fixed_alpha is None:
            entropy_gap = jax.lax.stop_gradient(logp + h_target)
            alpha_grad = jnp.mean(-entropy_gap) * jnp.exp(ls.log_alpha)
            # d/d(log α) of E[−exp(log α)·(log π + H_t)] — scalar, no AD
            # needed; pmean'd for identical α across the dp axis.
            alpha_grad = pmesh.pmean(alpha_grad, axis_name)
            alupd, alpha_opt = optax.adam(cfg.alpha_lr).update(
                alpha_grad, ls.alpha_opt
            )
            log_alpha = optax.apply_updates(ls.log_alpha, alupd)
            log_alpha = jnp.where(do_update, log_alpha, ls.log_alpha)
            alpha_opt = select(do_update, alpha_opt, ls.alpha_opt)
        else:
            log_alpha, alpha_opt = ls.log_alpha, ls.alpha_opt

        target_critic = select(
            do_update,
            polyak_update(critic_params, ls.target_critic, cfg.tau),
            ls.target_critic,
        )

        new_ls = SACLearnerState(
            actor_params=actor_params,
            critic_params=critic_params,
            target_critic=target_critic,
            actor_opt=actor_opt,
            critic_opt=critic_opt,
            log_alpha=log_alpha,
            alpha_opt=alpha_opt,
            replay=ls.replay,
            key=key,
            update_count=ls.update_count + do_update.astype(jnp.int32),
        )
        metrics = {
            "critic_loss": closs,
            "actor_loss": aloss,
            "q_mean": q_mean,
            "alpha": jnp.exp(log_alpha),
            "entropy_est": -jnp.mean(logp),
        }
        return new_ls, metrics

    def update_loop(ls: SACLearnerState, do_update: jax.Array):
        def body(carry, _):
            return one_update(carry, do_update)

        ls, metrics = jax.lax.scan(body, ls, None, length=cfg.updates_per_iter)
        return ls, jax.tree.map(lambda m: m[-1], metrics)

    return update_loop


def make_train_step(
    env: JaxEnv,
    cfg: SACConfig,
    axis_name: Optional[str] = None,
) -> Callable[[SACState], tuple[SACState, dict[str, jax.Array]]]:
    """The fused collect→insert→update program (one jit dispatch)."""
    explore = make_explore_fn(env.spec.action_dim, cfg)
    update_loop = make_update_loop(env.spec.action_dim, cfg, axis_name)
    codecs = replay.offpolicy_codecs(cfg.replay_dtype)

    def train_step(state: SACState):
        ls = state.learner
        key, rkey = jax.random.split(ls.key)

        rollout, env_steps, traj = offpolicy_rollout(
            env, explore, ls.actor_params, state.rollout, rkey,
            cfg.steps_per_iter, state.env_steps,
        )
        flat = jax.tree.map(lambda x: x.reshape(-1, *x.shape[2:]), traj)
        # axis_name keeps the quantizer stats identical across dp (they
        # are replicated in parallel.dp.replay_specs).
        rbuf = replay.add_batch(ls.replay, flat, codecs, axis_name=axis_name)

        do_update = jnp.logical_and(
            env_steps >= cfg.warmup_steps, rbuf.size >= cfg.batch_size
        )
        ls, metrics = update_loop(ls._replace(replay=rbuf, key=key), do_update)

        ep_ret, ep_len, avg_ret, ep_metrics = episode_metrics_update(
            state.ep_return, state.ep_length, state.avg_return, traj
        )
        avg_ret = pmesh.pmean(avg_ret, axis_name)
        ep_metrics["avg_return_ema"] = avg_ret
        metrics = aggregate_metrics(metrics, ep_metrics, axis_name)

        new_state = SACState(
            learner=ls,
            rollout=rollout,
            env_steps=env_steps,
            update_step=state.update_step + 1,
            ep_return=ep_ret,
            ep_length=ep_len,
            avg_return=avg_ret,
        )
        return new_state, metrics

    return train_step


def train(
    env: JaxEnv,
    cfg: SACConfig,
    num_iterations: int,
    seed: int = 0,
    state: Optional[SACState] = None,
    log_every: int = 0,
    log_fn: Optional[Callable[[int, dict], None]] = None,
) -> tuple[SACState, dict[str, jax.Array]]:
    """Host loop around the fused step (single device)."""
    from actor_critic_tpu.algos.host_loop import fused_train_loop

    return fused_train_loop(
        make_train_step, init_state, env, cfg, num_iterations,
        seed=seed, state=state, log_every=log_every, log_fn=log_fn,
    )


# --------------------------------------------------------------------------
# Host-env path (MuJoCo Humanoid etc. — BASELINE.json:10)
# --------------------------------------------------------------------------

def make_host_act_fn(action_dim: int, cfg: SACConfig):
    return jax.jit(make_explore_fn(action_dim, cfg))


def make_host_ingest_update(action_dim: int, cfg: SACConfig):
    """Jitted (learner, [K,E] block, env_steps) → (learner, metrics)."""
    update_loop = make_update_loop(action_dim, cfg)
    codecs = replay.offpolicy_codecs(cfg.replay_dtype)

    @partial(jax.jit, donate_argnums=0)
    def ingest_update(ls: SACLearnerState, traj: OffPolicyTransition, env_steps):
        flat = jax.tree.map(lambda x: x.reshape(-1, *x.shape[2:]), traj)
        rbuf = replay.add_batch(ls.replay, flat, codecs)
        do_update = jnp.logical_and(
            env_steps >= cfg.warmup_steps, rbuf.size >= cfg.batch_size
        )
        return update_loop(ls._replace(replay=rbuf), do_update)

    return ingest_update


def make_device_ingest_update(
    action_dim: int, cfg: SACConfig, ring_codecs: dict
):
    """Device-data-plane ingest (ISSUE 13): in-jit ring gather + decode
    ahead of the replay scatter and update loop — zero host→device
    transfers per consumed block (ddpg.make_device_ingest_update
    docstring; SAC's update gate is batch_size, it has no n-step
    window)."""
    from actor_critic_tpu.data_plane import device_replay

    return device_replay.make_device_ingest_update(
        make_update_loop, action_dim, cfg, ring_codecs,
        min_size=cfg.batch_size,
    )


def make_greedy_act(action_dim: int, cfg: SACConfig):
    """Tanh-mean actor for host eval (host_loop.host_evaluate)."""
    actor, _ = _modules(action_dim, cfg)
    return lambda params, obs: actor.apply(params, obs).mode()


def train_host(
    pool,
    cfg: SACConfig,
    num_iterations: int,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    eval_every: int = 0,
    eval_envs: int = 4,
    eval_steps: int = 1000,
    ckpt=None,
    save_every: int = 0,
    resume: bool = False,
    overlap: bool = True,
    save_replay: bool = True,
):
    """SAC on a HostEnvPool (host rollout, device learner). Use
    normalize_obs=False AND normalize_reward=False on the pool: running-
    stat obs normalization scales replayed transitions inconsistently as
    the stats drift, and the critic then bootstraps across mixed frames —
    observed in-session to send SAC Humanoid-v5 into a Q/alpha runaway
    (alpha 0.2 -> 18, Q ~17k) that raw observations eliminate; TD targets
    likewise want raw reward scale.
    `overlap` acts via the numpy host mirror with 1-update-stale params
    so device updates run during collection (host_loop docstring)."""
    from actor_critic_tpu.algos.host_loop import off_policy_train_host
    from actor_critic_tpu.models.host_actor import (
        make_sac_host_explore,
        make_sac_host_greedy,
    )

    return off_policy_train_host(
        pool, cfg, num_iterations,
        init_learner=init_learner,
        make_act_fn=make_host_act_fn,
        make_ingest_update=make_host_ingest_update,
        seed=seed, log_every=log_every, log_fn=log_fn,
        eval_every=eval_every, make_greedy_act=make_greedy_act,
        eval_envs=eval_envs, eval_steps=eval_steps,
        ckpt=ckpt, save_every=save_every, resume=resume,
        overlap=overlap, make_host_explore=make_sac_host_explore,
        make_host_greedy=make_sac_host_greedy,
        save_replay=save_replay,
    )


def train_host_async(
    pools,
    cfg: SACConfig,
    num_iterations: int,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    eval_every: int = 0,
    eval_envs: int = 4,
    eval_steps: int = 1000,
    queue_depth: int = 4,
    max_staleness: Optional[int] = None,
    data_plane: str = "host",
    plane_codec: str = "fp32",
    transfer_pad_s: float = 0.0,
    publish_hook: Optional[Callable[[int, object], None]] = None,
):
    """SAC with decoupled actor services (ISSUE 9 satellite; mirrors
    ddpg.train_host_async — replay absorbs behavior staleness, only the
    ingest hand-off is wired through the queue; `data_plane="device"`
    stages blocks encoded in HBM, ISSUE 13). Returns
    (learner, history)."""
    from actor_critic_tpu.algos.host_loop import off_policy_train_host_async
    from actor_critic_tpu.models.host_actor import (
        make_sac_host_explore,
        make_sac_host_greedy,
    )

    return off_policy_train_host_async(
        pools, cfg, num_iterations,
        init_learner=init_learner,
        make_ingest_update=make_host_ingest_update,
        make_host_explore=make_sac_host_explore,
        make_host_greedy=make_sac_host_greedy,
        seed=seed, log_every=log_every, log_fn=log_fn,
        eval_every=eval_every, eval_envs=eval_envs, eval_steps=eval_steps,
        queue_depth=queue_depth, max_staleness=max_staleness,
        data_plane=data_plane, plane_codec=plane_codec,
        transfer_pad_s=transfer_pad_s,
        make_device_ingest_update=make_device_ingest_update,
        publish_hook=publish_hook,
    )


# -- AOT warmup registry (utils/compile_cache.py, ISSUE 4) ------------------
from actor_critic_tpu.utils import compile_cache as _compile_cache  # noqa: E402

_compile_cache.register_offpolicy_warmups(
    "sac", ("sac",),
    init_learner=init_learner,
    make_host_act_fn=make_host_act_fn,
    make_host_ingest_update=make_host_ingest_update,
    make_greedy_act=make_greedy_act,
    init_state=init_state,
    make_train_step=make_train_step,
    make_eval_fn=make_eval_fn,
)
