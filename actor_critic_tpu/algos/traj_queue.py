"""Bounded, fixed-shape trajectory queue between actor and learner
services (ISSUE 6 tentpole).

`host_loop`'s lockstep drivers run collection and updates in one thread:
`BlockBuffers` overlaps block N's transfer/update with block N+1's
collection, but one slow collection block still stalls every SGD step.
This module is the decoupling layer (IMPACT, arxiv 1912.00167; GA3C,
arxiv 1611.06256):

- `ActorService` — one thread per actor: steps its own host env pool
  (whose gym backend may itself shard over `envs/shard_pool.py` worker
  processes), acts through the numpy mirror (`models/host_actor.py`)
  with behavior params refreshed from the `PolicyPublisher` once per
  block, and pushes fixed-shape `[K, E, ...]` numpy blocks tagged with
  the behavior-policy VERSION into the queue. A straggler actor slows
  only its own contribution.
- `TrajQueue` — bounded ring of preallocated block slots. `put` copies
  the actor's double-buffered arrays into a slot (the actor's buffers
  are immediately reusable; queued blocks have stable storage), and a
  full queue DROPS THE OLDEST block rather than blocking the producer
  (back-pressure never stalls actors; the drop is counted). `get`
  additionally drops blocks whose version lag exceeds `max_staleness`
  relative to the consumer's published version. `policy="block"` is the
  strict mode the lockstep-equivalence tests run under.
- `PolicyPublisher` — versioned numpy behavior-param store. The learner
  publishes each update's INPUT params (concrete before dispatch, so
  publishing never waits on the device) with version = blocks consumed;
  actors read the latest at each block boundary. Versions are plain
  monotonically increasing ints carried next to the block, so the same
  tagging scheme survives a future `jax.distributed` multi-host learner
  (per-host actor fleets need only a shared counter, not shared
  memory) — see ROADMAP "Multi-host / multi-chip learner scaling".

The learner side lives with its algorithm (e.g. `ppo.train_host_async`)
and drains continuously: it never idles on a slow collection block as
long as ANY actor is producing, and corrects the resulting staleness
with the V-trace machinery shared through `algos/common.py`
(`corrected_advantages`).

Blocks are the PR 4 shape-stabilized buckets — every actor pushes the
same `[K, E, ...]` shapes, so the async learner reuses one compiled
update program and steady state compiles nothing new
(tests/test_async_host.py).

Telemetry: every queue registers a gauge with the resource sampler
(`telemetry/sampler.py register_gauge`) so depth / observe-staleness /
drop counters / learner idle ride `resources.jsonl` and `/metrics`
(`actor_critic_traj_queue_*`); `scripts/run_report.py` renders the
queue row in its Resources section.
"""

# jaxlint: hot-module

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, NamedTuple, Optional

import numpy as np

from actor_critic_tpu.utils import numguard


class TrajBlock(NamedTuple):
    """One queued trajectory block: fixed-shape numpy arrays plus the
    behavior-policy version they were collected under."""

    arrays: dict[str, np.ndarray]
    version: int   # PolicyPublisher version the actor acted with
    actor_id: int
    seq: int       # global put order (monotonic; diagnostics)


class TrajQueue:
    """Bounded FIFO of fixed-shape trajectory blocks with drop-oldest
    back-pressure and staleness-bounded consumption.

    Storage is a recycled slot pool: `put` copies into a free (or
    reclaimed-oldest) slot dict, `get` leases the slot to the consumer,
    `release` returns it. After the first few blocks the queue
    allocates nothing.

    `policy="drop_oldest"` (default): a full queue reclaims its oldest
    block for the incoming one — actors never wait on the learner.
    `policy="block"`: `put` waits for a free slot (the strict mode the
    lockstep-equivalence tests use).

    `max_staleness`: blocks whose `consumer_version - version` exceeds
    the bound at `get` time are dropped (counted in `drops_stale`);
    None disables the bound.
    """

    def __init__(
        self,
        depth: int,
        max_staleness: Optional[int] = None,
        policy: str = "drop_oldest",
        gauge_name: str = "traj_queue",
        register_gauge: bool = True,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if policy not in ("drop_oldest", "block"):
            raise ValueError(f"unknown policy {policy!r}")
        if max_staleness is not None and max_staleness < 0:
            raise ValueError("max_staleness must be >= 0 or None")
        self.depth = int(depth)
        self.max_staleness = max_staleness
        self.policy = policy
        self._cv = threading.Condition()
        self._pending: deque[TrajBlock] = deque()
        self._free: list[dict[str, np.ndarray]] = []
        self._leased = 0
        self._seq = 0
        self._consumer_version = 0
        self._puts = 0
        self._gets = 0
        self._drops_full = 0
        self._drops_stale = 0
        self._last_staleness = 0
        self._max_staleness_seen = 0
        self._idle_s = 0.0
        self._closed = False
        self._gauge_key: Optional[str] = None
        if register_gauge:
            from actor_critic_tpu.telemetry import sampler as _sampler

            self._gauge_key = _sampler.register_gauge(gauge_name, self.stats)

    # -- producer ----------------------------------------------------------
    def put(
        self,
        arrays: dict[str, np.ndarray],
        version: int,
        actor_id: int = 0,
        timeout: Optional[float] = None,
    ) -> bool:
        """Copy `arrays` into a queue slot. Returns True once enqueued;
        False only under `policy="block"` when no slot freed within
        `timeout` (drop-oldest never waits)."""
        with self._cv:
            if self.policy == "block":
                deadline = (
                    None if timeout is None else time.monotonic() + timeout
                )
                while self._in_flight() >= self.depth:
                    remaining = (
                        None if deadline is None
                        else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        return False
                    self._cv.wait(
                        0.1 if remaining is None else min(0.1, remaining)
                    )
            elif len(self._pending) and self._in_flight() >= self.depth:
                old = self._pending.popleft()
                self._free.append(old.arrays)
                self._drops_full += 1
            slot = self._free.pop() if self._free else {}
            for name, value in arrays.items():
                dst = slot.get(name)
                if (
                    dst is None
                    or dst.shape != value.shape
                    or dst.dtype != value.dtype
                ):
                    slot[name] = value.copy()
                else:
                    np.copyto(dst, value)
            self._pending.append(
                TrajBlock(slot, int(version), int(actor_id), self._seq)
            )
            self._seq += 1
            self._puts += 1
            self._cv.notify_all()
            return True

    def _in_flight(self) -> int:
        return len(self._pending) + self._leased

    # -- consumer ----------------------------------------------------------
    def set_consumer_version(self, version: int) -> None:
        """Record the learner's current version — the reference point the
        staleness bound (and the observe-staleness gauge) measures lag
        against."""
        with self._cv:
            self._consumer_version = int(version)

    def get(self, timeout: Optional[float] = None) -> Optional[TrajBlock]:
        """Oldest fresh-enough block (leased until `release`), or None
        after `timeout` with nothing consumable. Time spent waiting
        accumulates in the learner-idle gauge."""
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = time.monotonic()
        with self._cv:
            # try INSIDE the with: the idle accumulation then runs with
            # the lock already held (stats() readers race an unlocked
            # +=), and the hot path pays one acquisition, not two.
            try:
                while True:
                    while self._pending:
                        block = self._pending.popleft()
                        lag = self._consumer_version - block.version
                        if (
                            self.max_staleness is not None
                            and lag > self.max_staleness
                        ):
                            self._free.append(block.arrays)
                            self._drops_stale += 1
                            self._cv.notify_all()
                            continue
                        self._leased += 1
                        self._gets += 1
                        self._last_staleness = max(lag, 0)
                        self._max_staleness_seen = max(
                            self._max_staleness_seen, self._last_staleness
                        )
                        return block
                    remaining = (
                        None if deadline is None
                        else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        return None
                    self._cv.wait(
                        0.1 if remaining is None else min(0.1, remaining)
                    )
            finally:
                self._idle_s += time.monotonic() - t0

    def release(self, block: TrajBlock) -> None:
        """Return a leased block's storage to the slot pool (call after
        the host→device transfer; the arrays are rewritten by later
        puts)."""
        with self._cv:
            self._free.append(block.arrays)
            self._leased -= 1
            self._cv.notify_all()

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        with self._cv:
            return len(self._pending)

    def stats(self) -> dict:
        """Gauge row (sampler registry / `/metrics` / run_report): depth,
        drop counters, behavior-version lag of the last consumed block
        (`observe_staleness`), and cumulative learner idle seconds."""
        with self._cv:
            return {
                "capacity": self.depth,
                "depth": len(self._pending),
                "leased": self._leased,
                "puts": self._puts,
                "gets": self._gets,
                "drops_full": self._drops_full,
                "drops_stale": self._drops_stale,
                "observe_staleness": self._last_staleness,
                "staleness_max": self._max_staleness_seen,
                "learner_idle_s": round(self._idle_s, 3),
            }

    def close(self) -> None:
        # Test-and-set under the lock: two threads racing into close()
        # (learner teardown vs. an exception path) could otherwise both
        # pass the flag check and double-unregister the gauge.
        with self._cv:
            if self._closed:
                return
            self._closed = True
            gauge_key, self._gauge_key = self._gauge_key, None
        if gauge_key is not None:
            from actor_critic_tpu.telemetry import sampler as _sampler

            _sampler.unregister_gauge(gauge_key)


def validate_pools(pools) -> tuple:
    """(shared spec, per-actor env count) of an async actor fleet; the
    shared precondition of every async learner driver — the learner
    compiles ONE [K, E_a] program, so every pool must present the same
    env spec and width. One copy (like `consume_block`), so a future
    tightening of the invariant lands once."""
    if not pools:
        raise ValueError("need at least one actor pool")
    spec = pools[0].spec
    E_a = pools[0].num_envs
    for p in pools[1:]:
        if p.spec != spec or p.num_envs != E_a:
            raise ValueError(
                "actor pools must share one env spec and num_envs (the "
                "learner compiles ONE [K, E_a] program)"
            )
    return spec, E_a


def consume_block(
    queue: "TrajQueue",
    actors: list,
    timeout: float = 0.5,
    context: str = "",
) -> "TrajBlock":
    """Drain ONE block for a learner loop, surfacing actor failures
    while waiting: re-raises a dead actor's exception (`context`
    prefixes the message, e.g. "host 2 "), and a fully-exited fleet
    with nothing pending raises instead of spinning forever. The
    shared consume protocol of every async learner driver
    (ppo.train_host_async, host_loop.off_policy_train_host_async,
    multihost.train_multihost) — one copy, so a fix to the dead-actor
    surfacing never has to land three times."""
    while True:
        block = queue.get(timeout=timeout)
        if block is not None:
            return block
        for a in actors:
            if a.error is not None:
                raise RuntimeError(
                    f"{context}actor {a.actor_id} died"
                ) from a.error
        if not any(a.alive for a in actors):
            raise RuntimeError(
                "every actor thread exited with no blocks pending"
            )


def _snapshot_frozen(tree: Any) -> Any:
    """Copy every numpy leaf of a (dict/list/tuple-structured) params
    tree and mark the copies read-only. The publisher stores THESE, so
    (a) the publisher's caller keeps no writable alias of what actors
    read — later in-place mutation of the producer's own arrays cannot
    tear params under an actor mid-block — and (b) an actor that tries
    to write into behavior params crashes at the write site instead of
    silently corrupting every pool sharing the tree (the racesan
    write-after-publish tripwire, always on here)."""
    if isinstance(tree, np.ndarray):
        out = tree.copy()
        out.flags.writeable = False
        return out
    if isinstance(tree, dict):
        return {k: _snapshot_frozen(v) for k, v in tree.items()}
    if isinstance(tree, tuple):
        vals = [_snapshot_frozen(v) for v in tree]
        if hasattr(type(tree), "_fields"):
            # NamedTuple subclasses (jax.device_get keeps them) take
            # positional fields; plain tuple(*vals) would TypeError.
            return type(tree)(*vals)
        return tuple(vals)
    if isinstance(tree, list):
        return [_snapshot_frozen(v) for v in tree]
    return tree


class PolicyPublisher:
    """Thread-safe versioned store of numpy behavior params.

    The learner `publish`es each update's INPUT params with version =
    blocks consumed so far; actors `get` the latest at block
    boundaries. `wait_for` is the strict-mode hook: the equivalence
    tests pin each block's behavior version to exactly the lockstep
    driver's one-update-stale schedule.

    Stored params are frozen snapshots (`_snapshot_frozen`): `publish`
    copies the numpy leaves and flips `writeable = False`, so stale
    actor-side views can never be mutated and no caller retains a
    writable alias of what actors act with (ISSUE 7; the
    publish-aliasing pass exists to catch the by-reference variant of
    this class reappearing elsewhere).
    """

    def __init__(self, params: Any, version: int = 0):
        self._cv = threading.Condition()
        self._params = _snapshot_frozen(params)
        self._version = int(version)

    def publish(self, params: Any, version: int) -> None:
        # Finiteness gate (ISSUE 14): published behavior params drive
        # EVERY actor's next blocks — a nan/inf publish poisons each
        # collected trajectory and, through the importance ratios, the
        # learner itself. The refusal raises OUT of the learner loop
        # (a diverged learner must halt loudly AT the publish boundary,
        # not train on); what the gate guarantees is containment — the
        # poisoned tree is never installed, so the snapshot actors and
        # any post-mortem reader see is the last good one.
        numguard.check_finite(params, "behavior-params publish",
                              name="params")
        snapshot = _snapshot_frozen(params)  # copy OUTSIDE the lock
        with self._cv:
            self._params = snapshot
            self._version = int(version)
            self._cv.notify_all()

    def get(self) -> tuple[int, Any]:
        with self._cv:
            return self._version, self._params

    def wait_for(
        self,
        version: int,
        stop: Optional[threading.Event] = None,
        timeout: Optional[float] = None,
    ) -> bool:
        """Block until the published version reaches `version` (True), or
        `stop` is set / `timeout` elapses (False)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._version < version:
                if stop is not None and stop.is_set():
                    return False
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(
                    0.1 if remaining is None else min(0.1, remaining)
                )
            return True


class ActorService:
    """One collection thread: refresh behavior params, collect a
    `[K, E, ...]` block through `host_loop.host_collect`, push it.

    `make_act_fn(np_params, rng) -> act_fn(obs) -> (action, extras)`
    builds the per-block acting closure (the PPO driver wires the numpy
    policy mirror here); `block_extras(np_params, last_obs, block) ->
    dict` optionally appends per-block arrays computed under the SAME
    behavior params (e.g. PPO's mirror-computed truncation/bootstrap
    values). The service also records `last_obs` (the observation after
    the block's final step) into every block.

    `strict=True` reproduces the lockstep drivers' one-update-stale
    behavior schedule exactly (block 0 and 1 act under the initial
    params, block i>=2 under version i-1) — the contract the
    lockstep-equivalence tests assert bit-for-bit.
    """

    def __init__(
        self,
        actor_id: int,
        pool,
        queue: TrajQueue,
        publisher: PolicyPublisher,
        num_steps: int,
        make_act_fn: Callable[[Any, np.random.Generator], Callable],
        rng: np.random.Generator,
        stop: threading.Event,
        block_extras: Optional[Callable[[Any, np.ndarray, dict], dict]] = None,
        strict: bool = False,
    ):
        from actor_critic_tpu.algos.host_loop import (
            BlockBuffers,
            EpisodeTracker,
        )

        self.actor_id = int(actor_id)
        self.pool = pool
        self.tracker = EpisodeTracker(pool.num_envs)
        # jaxlint: thread-owned=actor (single writer: this service's own
        # thread bumps the progress counters; the learner only reads
        # them for logging and tolerates a stale read by one block)
        self.steps_collected = 0
        # jaxlint: thread-owned=actor (same single-writer contract as
        # steps_collected)
        self.blocks_pushed = 0
        self.error: Optional[BaseException] = None
        self._queue = queue
        self._publisher = publisher
        self._num_steps = int(num_steps)
        self._make_act_fn = make_act_fn
        self._rng = rng
        self._stop = stop
        self._block_extras = block_extras
        self._strict = strict
        self._buffers = BlockBuffers(num_steps)
        self._thread = threading.Thread(
            target=self._run, name=f"actor-{actor_id}", daemon=True
        )

    def start(self) -> "ActorService":
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread.ident is None:
            return  # never started (e.g. a resume that found the run done)
        self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def _run(self) -> None:
        from actor_critic_tpu.algos.host_loop import host_collect

        try:
            obs = self.pool.reset()
            i = 0
            while not self._stop.is_set():
                if self._strict and i >= 2:
                    # Lockstep schedule: block i acts under version i-1.
                    if not self._publisher.wait_for(i - 1, stop=self._stop):
                        return
                version, params = self._publisher.get()
                act_fn = self._make_act_fn(params, self._rng)
                obs, block = host_collect(
                    self.pool, obs, self._num_steps, act_fn, self.tracker,
                    buffers=self._buffers,
                )
                arrays = dict(block)
                arrays["last_obs"] = obs
                if self._block_extras is not None:
                    arrays.update(self._block_extras(params, obs, block))
                while not self._stop.is_set():
                    if self._queue.put(
                        arrays, version=version, actor_id=self.actor_id,
                        timeout=0.25,
                    ):
                        self.blocks_pushed += 1
                        self.steps_collected += (
                            self._num_steps * self.pool.num_envs
                        )
                        break
                i += 1
        except BaseException as e:  # surfaced by the learner's get loop
            self.error = e
