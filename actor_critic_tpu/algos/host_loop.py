"""Shared host-env rollout plumbing for the `train_host` paths.

PPO (on-policy), DDPG/TD3 and SAC (off-policy) all step a `HostEnvPool`
from a host loop (SURVEY.md §3.1-3.2 host boundary; reference mount
empty, §0) and need the same bookkeeping: stack per-step arrays into a
time-major [K, E] block for the single host→device transfer, and track
raw episode returns for reporting. This module owns both so the trainers
don't each carry a diverging copy.
"""

from __future__ import annotations

import time
import warnings
from functools import partial
from typing import Callable, Optional

import numpy as np

from actor_critic_tpu import telemetry


class EpisodeTracker:
    """Raw-return episode accounting across host steps."""

    def __init__(self, num_envs: int):
        self._ep_ret = np.zeros(num_envs)
        self.finished: list[float] = []

    def update(self, raw_reward: np.ndarray, done: np.ndarray) -> None:
        self._ep_ret += raw_reward
        for i in np.nonzero(done)[0]:
            # jaxlint: disable=host-sync (numpy episode accounting — no
            # device value; the coercion below is host-only)
            self.finished.append(float(self._ep_ret[i]))
            self._ep_ret[i] = 0.0

    def report(self, window: int = 20) -> dict[str, float]:
        return {
            "recent_return": (
                float(np.mean(self.finished[-window:]))
                if self.finished
                else float("nan")
            ),
            "episodes": float(len(self.finished)),
        }


class MergedEpisodeTracker:
    """Read-only `report()` view over several actors' EpisodeTrackers.

    The async actor–learner driver (ppo.train_host_async / ISSUE 6)
    runs one EpisodeTracker per actor thread; the learner's log rows
    want ONE recent-return figure across the fleet. Reads the tail of
    each tracker's `finished` list (appends from actor threads are
    atomic; a row that lands mid-read shows up next log row).
    """

    def __init__(self, trackers: list[EpisodeTracker]):
        self._trackers = trackers

    def report(self, window: int = 20) -> dict[str, float]:
        # Mean over EACH actor's last `window` episodes (up to A·window
        # entries) — truncating the concatenation to one window would
        # silently drop every actor but the last-listed one as soon as
        # it alone fills the window (straggler layouts are exactly the
        # case where actors finish episodes at very different rates).
        recent: list[float] = []
        total = 0
        for t in self._trackers:
            finished = t.finished
            total += len(finished)
            recent.extend(finished[-window:])
        return {
            "recent_return": (
                float(np.mean(recent)) if recent else float("nan")
            ),
            "episodes": float(total),
        }


class BlockBuffers:
    """Preallocated, double-buffered time-major [K, E, ...] block storage.

    The old collect path appended per-step arrays to Python lists and
    `np.stack`ed them into a fresh block every iteration — one full-block
    allocation + copy per iteration, forever. BlockBuffers instead writes
    each step straight into preallocated [K, E, ...] arrays (allocated
    lazily from the first recorded value's shape/dtype, then reused).

    DOUBLE buffering is the correctness half: `begin_block()` flips
    between two buffer sets, so the arrays handed to the device transfer
    for block N stay untouched while block N+1 is collected into the
    other set. That lets the (async-dispatched) host→device transfer and
    jitted update of block N overlap collection of block N+1 — the
    transfer-stage extension of the `overlap=True` stale-params
    machinery; a block's buffers are only rewritten two `begin_block()`s
    later, after its update has long been consumed.
    """

    def __init__(self, num_steps: int):
        if num_steps < 1:
            raise ValueError(f"num_steps must be >= 1, got {num_steps}")
        self.num_steps = int(num_steps)
        self._bufs: tuple[dict, dict] = ({}, {})
        self._active = 0
        self._seen: set[str] = set()

    def begin_block(self) -> None:
        """Flip to the other buffer set; its previous contents (block
        N-2) are dead by contract."""
        self._active ^= 1
        self._seen = set()

    def record(self, t: int, name: str, value) -> None:
        value = np.asarray(value)
        buf = self._bufs[self._active]
        arr = buf.get(name)
        if (
            arr is None
            or arr.shape[1:] != value.shape
            or arr.dtype != value.dtype
        ):
            arr = np.empty((self.num_steps, *value.shape), value.dtype)
            buf[name] = arr
        arr[t] = value  # copies into the preallocated slot
        self._seen.add(name)

    def block(self) -> dict[str, np.ndarray]:
        """The CURRENT block's arrays: only keys recorded since
        `begin_block()` — a key an earlier block recorded but this one
        didn't must not leak two-block-stale data into the update."""
        buf = self._bufs[self._active]
        return {k: buf[k] for k in buf if k in self._seen}


def host_collect(
    pool,
    obs: np.ndarray,
    num_steps: int,
    act_fn: Callable[[np.ndarray], tuple[np.ndarray, dict[str, np.ndarray]]],
    tracker: EpisodeTracker,
    buffers: Optional[BlockBuffers] = None,
) -> tuple[np.ndarray, dict[str, np.ndarray]]:
    """Step the pool `num_steps` times; return (last obs, [K, E] block).

    `act_fn(obs) -> (action, extras)`; extras (e.g. log_prob/value for
    on-policy) are recorded alongside the standard fields. The block's
    arrays are time-major [K, E, ...] numpy — exactly one device
    transfer's worth, written into `buffers` (a loop-lived BlockBuffers;
    the trainers pass one so blocks reuse preallocated double-buffered
    storage). With `buffers=None` a private BlockBuffers is allocated
    per call — correct, just without the reuse.
    """
    if buffers is None:
        buffers = BlockBuffers(num_steps)
    elif buffers.num_steps != num_steps:
        raise ValueError(
            f"buffers hold {buffers.num_steps}-step blocks, collect asked "
            f"for {num_steps}"
        )
    buffers.begin_block()
    record = buffers.record

    from actor_critic_tpu.utils import watchdog

    # Per-worker spans, only while a telemetry session is installed: the
    # sharded pool relays the workers' OWN per-step records after the
    # block (drain_telemetry — real pid lanes in the trace; 0 records
    # for backends without worker processes).
    drain_fn = None
    if telemetry.current() is not None:
        drain_fn = getattr(pool, "drain_telemetry", None)

    # One span per collection block, not per pool step: a MuJoCo run
    # takes millions of env steps, and the per-phase breakdown needs the
    # block total, not 10^6 micro-events.
    with telemetry.span("env_step", steps=num_steps):
        for t in range(num_steps):
            watchdog.beat()  # progress heartbeat (utils/watchdog.py)
            action, extras = act_fn(obs)
            out = pool.step(action)
            record(t, "obs", obs)
            record(t, "action", action)
            for k, v in extras.items():
                record(t, k, v)
            record(t, "reward", out.reward)
            record(t, "done", out.done)
            record(t, "terminated", out.terminated)
            record(t, "final_obs", out.final_obs)
            tracker.update(out.raw_reward, out.done)
            obs = out.obs

    if drain_fn is not None:
        # Worker→parent relay: the workers buffered one span per batch
        # step during the block; one drain round-trip per worker ships
        # them into spans.jsonl under the workers' real pids.
        try:
            drain_fn()
        except RuntimeError:
            raise  # dead worker: same contract as a step failure
        except Exception:
            pass  # telemetry must never take the run down

    return obs, buffers.block()


def host_evaluate(
    pool,
    act_fn: Callable[[np.ndarray], np.ndarray],
    max_steps: int = 1000,
) -> float:
    """Greedy host eval: mean RAW return of each env's FIRST episode
    (host counterpart of common.evaluate; SURVEY.md §3.4). `act_fn(obs)
    -> action` is the deterministic policy. Stops early once every env
    has finished an episode."""
    from actor_critic_tpu.utils import watchdog

    obs = pool.reset()
    E = pool.num_envs
    returns = np.zeros(E)
    alive = np.ones(E)
    for _ in range(max_steps):
        watchdog.beat()  # an eval sweep is progress, not a stall
        out = pool.step(act_fn(obs))
        returns += out.raw_reward * alive
        alive *= 1.0 - out.done
        obs = out.obs
        if not alive.any():
            break
    return float(returns.mean())


def host_ckpt_state(pool, save_replay: bool = True, **device_state) -> dict:
    """Assemble the host-trainer checkpoint pytree: the device-side state
    (learner/params/opt/key/env_steps) plus the pool's normalizer stats,
    every leaf coerced to an array so orbax round-trips it.

    `save_replay=False` strips the learner's replay ring down to a
    one-slot stub (SURVEY §5.4 scopes buffer checkpointing as optional):
    a Humanoid-scale ring is ~3 GB per save, untenable at a real save
    cadence. Resuming such a checkpoint restarts with an EMPTY buffer —
    the warmup gate (`size >= batch_size`) pauses updates for the few
    iterations the fresh ring needs to refill, then training continues
    on fresh experience only.
    """
    if not save_replay and "learner" in device_state:
        device_state = dict(device_state)
        device_state["learner"] = strip_replay(device_state["learner"])
    return {
        **device_state,
        "pool": np_tree(pool.get_state()),
    }


def strip_replay(learner):
    """Learner with its replay storage truncated to one slot (shape and
    dtype preserved so save/restore templates stay structurally stable;
    cursors ride along but are discarded on reattach). The quantizer's
    running mean/scale stats (`ReplayState.quant`, replay/quantize.py)
    are deliberately NOT touched: they are item-shaped (no capacity
    axis), cost bytes, and must survive a replay-free checkpoint — a
    resumed run re-encodes fresh transitions against the SAME
    standardization the restored critic trained under, instead of
    re-learning stats that would decode early post-resume batches
    through a different affine map."""
    import jax

    rb = learner.replay
    return learner._replace(
        replay=rb._replace(
            storage=jax.tree.map(lambda x: x[:1], rb.storage)
        )
    )


def np_tree(d):
    """Recursively np.asarray every leaf (python floats → 0-d arrays)."""
    if isinstance(d, dict):
        return {k: np_tree(v) for k, v in d.items()}
    return np.asarray(d)


from actor_critic_tpu.utils.cadence import should_save  # noqa: E402, F401


def host_maybe_save(
    ckpt, it: int, save_every: int, num_iterations: int, pool, metrics: dict,
    save_replay: bool = True, **device_state,
) -> None:
    """Save the host-trainer state on the `should_save` cadence (`it` is
    1-based). Syncs the device state first; the orbax device→host fetch
    is synchronous within save(), so donation in the next iteration is
    safe, and the disk write completes asynchronously."""
    if ckpt is None or not should_save(it, save_every, num_iterations):
        return
    with telemetry.span("checkpoint", step=it):
        _host_save(ckpt, it, pool, metrics, save_replay, device_state)


def _host_save(ckpt, it, pool, metrics, save_replay, device_state):
    import jax

    jax.block_until_ready(device_state)
    # The pool's action convention and the replay-saved flag ride the
    # tolerant metrics JSON (NOT the state tree: adding a leaf there
    # would structurally invalidate every pre-existing checkpoint under
    # orbax's exact-template restore) so host_resume can warn on a
    # convention flip and resume can build the matching template.
    metrics = {
        **(metrics or {}),
        "_pool_scale_actions": float(getattr(pool, "scales_actions", False)),
        "_replay_saved": float(save_replay),
    }
    ckpt.save(
        it, host_ckpt_state(pool, save_replay=save_replay, **device_state),
        metrics=metrics, force=True,
    )


def _warn_restore_mismatch(restored_pool: dict, pool, saved_scale) -> None:
    """The resume-contract warnings shared by the single-pool and
    async multi-pool restore paths: action-convention flips and
    normalization-contract flips must never degrade in silence.

    Normalization check: a checkpoint whose obs-normalizer accumulated
    real statistics came from a run that FED NORMALIZED observations to
    the networks. Resuming it into a raw-obs pool (e.g. after the
    off-policy default flipped to normalize_obs=False) silently puts
    the restored policy/critic off-distribution. (The flags themselves
    are not checkpointed, so the stats are the only available signal.)
    """
    try:
        saved_count = float(np.asarray(restored_pool["obs_rms"]["count"]))
    except (KeyError, TypeError):
        saved_count = 0.0
    if saved_scale is not None and bool(saved_scale) != getattr(
        pool, "scales_actions", False
    ):
        warnings.warn(
            "resuming a checkpoint trained under the "
            f"{'scaled' if saved_scale else 'clipped'}-action convention "
            "into a pool with scale_actions="
            f"{getattr(pool, 'scales_actions', False)} — the restored "
            "policy's actions will execute differently than they trained. "
            "Relaunch with the run's original --scale-actions setting.",
            stacklevel=3,
        )
    trained_normalized = saved_count > 1.0
    if trained_normalized != pool.normalizes_obs:
        was, now = (
            ("with obs normalization", "normalize_obs=False")
            if trained_normalized
            else ("on RAW observations", "normalize_obs=True")
        )
        warnings.warn(
            f"resuming a checkpoint trained {was} into a pool with {now} "
            "— the restored networks will act off-distribution (their "
            "observation scaling no longer matches the pool's). Rebuild "
            f"the pool with normalize_obs={trained_normalized} (or "
            "restart the run from scratch).",
            stacklevel=3,
        )


def host_resume(ckpt, template: dict, pool) -> tuple[Optional[dict], int]:
    """Restore the latest host checkpoint into `template`'s structure and
    push the pool state back; (None, 0) when nothing is saved yet.

    Resume semantics on host envs: learner/params/optimizer/PRNG/
    normalizer stats restore EXACTLY; the env simulator state does not
    (gymnasium can't serialize it), so the pool restarts fresh episodes —
    same contract as the reference genre's tf.train.Saver restarts.
    """
    step = ckpt.latest_step()
    if step is None:
        return None, 0
    restored = ckpt.restore(template, step)
    pool.set_state(restored["pool"])
    _warn_restore_mismatch(
        restored["pool"], pool,
        ckpt.restore_metrics(step).get("_pool_scale_actions"),
    )
    return restored, step


def async_host_ckpt_state(pools, **device_state) -> dict:
    """Checkpoint pytree for the ASYNC actor–learner drivers: the
    device state plus ALL A per-actor pools' normalizer states (each
    actor pool runs independent running stats — saving only one would
    resume A-1 actors with wrong observation scaling; ISSUE 9
    satellite). The learner thread snapshots pool stats while actor
    threads may be mid-block: each leaf read is atomic (numpy arrays
    rebound per update), so a snapshot can at worst be one batch-update
    stale per leaf — tolerable drift for running statistics, the same
    tolerance `host_resume` already grants the +1 reset batch."""
    return {
        **device_state,
        "pools": [np_tree(p.get_state()) for p in pools],
    }


def async_host_maybe_save(
    ckpt, it: int, save_every: int, num_iterations: int, pools,
    metrics: dict, data_plane: str = "host", **device_state,
) -> None:
    """Async-driver twin of `host_maybe_save` over the whole actor
    fleet's pools (`it` is 1-based consumed-block count). Device-plane
    runs (ISSUE 13) additionally carry the trajectory ring's quantizer
    stats in `device_state["ring_quant"]` — the stripped-ring contract:
    storage is transient collection data and never saved."""
    if ckpt is None or not should_save(it, save_every, num_iterations):
        return
    import jax

    with telemetry.span("checkpoint", step=it):
        jax.block_until_ready(device_state)
        metrics = {
            **(metrics or {}),
            "_pool_scale_actions": float(
                getattr(pools[0], "scales_actions", False)
            ),
            # Resume guard: the tree carries one pool state per actor,
            # so the fleet size must match (async_host_resume checks
            # this BEFORE orbax's opaque structure-mismatch error).
            "_async_actors": float(len(pools)),
            # Same guard for the data plane: a device-plane checkpoint
            # carries a ring_quant leaf the host plane's template lacks
            # (and vice versa) — fail with advice, not an orbax
            # structure error. 1.0 = device.
            "_data_plane_device": float(data_plane == "device"),
        }
        ckpt.save(
            it, async_host_ckpt_state(pools, **device_state),
            metrics=metrics, force=True,
        )


def async_host_resume(
    ckpt, template: dict, pools, data_plane: str = "host",
) -> tuple[Optional[dict], int]:
    """Restore the latest async checkpoint and push every actor pool's
    normalizer state back; (None, 0) when nothing is saved yet. The
    saved tree must carry the same number of pool states as the resuming
    fleet (`--async-actors` must not change across a resume — each
    pool's stats belong to its own actor's env shard), and the data
    plane must match the checkpoint's (the save trees differ)."""
    step = ckpt.latest_step()
    if step is None:
        return None, 0
    saved_metrics = ckpt.restore_metrics(step)
    saved_actors = saved_metrics.get("_async_actors")
    if saved_actors is not None and int(saved_actors) != len(pools):
        raise ValueError(
            f"checkpoint carries {int(saved_actors)} actor-pool states "
            f"but this run has {len(pools)} actors — resume with the "
            "original --async-actors count"
        )
    # Missing key = a checkpoint that predates the flag, which can only
    # be host-plane (the device plane shipped with the flag) — default
    # to 0.0 so a --data-plane device resume of a legacy checkpoint
    # gets THIS advice, not orbax's opaque structure-mismatch error.
    saved_plane = saved_metrics.get("_data_plane_device", 0.0)
    if bool(saved_plane) != (data_plane == "device"):
        saved_name = "device" if saved_plane else "host"
        raise ValueError(
            f"checkpoint was written by a --data-plane {saved_name} run "
            f"but this run uses --data-plane {data_plane} — the save "
            "trees differ (the device plane checkpoints its ring's "
            "quantizer stats); resume with the original flag"
        )
    restored = ckpt.restore(template, step)
    saved_pools = restored["pools"]
    if len(saved_pools) != len(pools):
        # Fallback for checkpoints predating the _async_actors metric.
        raise ValueError(
            f"checkpoint carries {len(saved_pools)} actor-pool states "
            f"but this run has {len(pools)} actors — resume with the "
            "original --async-actors count"
        )
    saved_scale = saved_metrics.get("_pool_scale_actions")
    for pool, saved in zip(pools, saved_pools):
        pool.set_state(saved)
        _warn_restore_mismatch(saved, pool, saved_scale)
    return restored, step


def off_policy_train_host(
    pool,
    cfg,
    num_iterations: int,
    *,
    init_learner: Callable,
    make_act_fn: Callable,
    make_ingest_update: Callable,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    eval_every: int = 0,
    make_greedy_act: Optional[Callable] = None,
    eval_envs: int = 4,
    eval_steps: int = 1000,
    ckpt=None,
    save_every: int = 0,
    resume: bool = False,
    overlap: bool = True,
    make_host_explore: Optional[Callable] = None,
    make_host_greedy: Optional[Callable] = None,
    save_replay: bool = True,
):
    """Shared host-env loop for the off-policy trainers (DDPG/TD3, SAC).

    Both algorithms drive a `HostEnvPool` identically — explore-act,
    stack a [K, E] block host-side, one transfer into the jitted
    ingest+update — and differ only in the factory callables:
      init_learner(obs_shape, action_dim, cfg, key) -> learner
      make_act_fn(action_dim, cfg) -> jitted (actor_params, obs, key,
                                              env_steps) -> action
      make_ingest_update(action_dim, cfg) -> jitted (learner, block,
                                              env_steps) -> (learner, metrics)
    The learner state must expose `.actor_params`. With `eval_every > 0`
    and `make_greedy_act(action_dim, cfg) -> (params, obs) -> action`, a
    frozen-stats eval pool runs a greedy episode sweep on that cadence
    and an `eval_return` metric rides the next log row.

    With `overlap` (default) and a `make_host_explore(spec, cfg) ->
    (np_params, obs, rng, env_steps) -> action` numpy mirror
    (models/host_actor.py), collection acts entirely on the host with
    params one update stale, so the dispatched device update runs WHILE
    the next rollout is collected — the host/device overlap of SURVEY
    §7.2 item 2. Without a mirror (or overlap=False) acting round-trips
    the device each pool step and blocks on the update. Returns
    (learner, history).
    """
    import jax
    import jax.numpy as jnp

    from actor_critic_tpu.algos.common import OffPolicyTransition

    key = jax.random.key(seed)
    key, lkey = jax.random.split(key)
    learner = init_learner(pool.spec.obs_shape, pool.spec.action_dim, cfg, lkey)
    act = make_act_fn(pool.spec.action_dim, cfg)
    ingest_update = make_ingest_update(pool.spec.action_dim, cfg)

    eval_pool = greedy = host_greedy = None
    if eval_every > 0 and make_greedy_act is not None:
        eval_pool = pool.eval_pool(eval_envs)
        greedy = jax.jit(make_greedy_act(pool.spec.action_dim, cfg))
        if make_host_greedy is not None:
            from actor_critic_tpu.models import host_actor

            if host_actor.supports_mirror(jax.device_get(learner.actor_params)):
                # Evals otherwise pay a device round-trip per step
                # (~26 ms on the tunnel × up to eval_steps).
                host_greedy = make_host_greedy(pool.spec, cfg)

    env_steps = 0
    start_it = 0
    if ckpt is not None and resume:
        # The TEMPLATE must mirror what the checkpoint actually holds:
        # the saved `_replay_saved` metric (not this run's flag) decides
        # whether the learner tree carries the full ring or the one-slot
        # stub. Legacy checkpoints (no flag) saved the full ring.
        step = ckpt.latest_step()
        saved_replay = True
        if step is not None:
            saved_replay = bool(
                ckpt.restore_metrics(step).get("_replay_saved", 1.0)
            )
        template_learner = learner if saved_replay else strip_replay(learner)
        template = host_ckpt_state(
            pool, learner=template_learner, key=key,
            env_steps=np.asarray(0, np.int64),
        )
        restored, start_it = host_resume(ckpt, template, pool)
        if restored is not None:
            restored_learner = restored["learner"]
            if not saved_replay:
                warnings.warn(
                    "resuming a replay-free checkpoint (save_replay=False): "
                    "the buffer restarts EMPTY — updates pause until it "
                    "refills past one batch, then continue on fresh "
                    "experience only.",
                    stacklevel=2,
                )
                # Reattach this run's zeroed full-capacity ring; the
                # stub's cursors are stale by construction. The restored
                # QUANTIZER stats are kept — strip_replay saved them in
                # full, and fresh transitions must encode against the
                # standardization the restored critic trained under.
                restored_learner = restored_learner._replace(
                    replay=learner.replay._replace(
                        quant=restored_learner.replay.quant
                    )
                )
            learner = restored_learner
            key = restored["key"]
            env_steps = int(restored["env_steps"])

    # reset() AFTER set_state: it re-zeroes the reward-normalizer's running
    # returns (correct — episodes restart on resume) while the restored
    # obs-normalizer stats absorb the reset batch as one ordinary update.
    obs = pool.reset()
    E = pool.num_envs
    tracker = EpisodeTracker(E)
    history: list = []
    metrics: dict = {}
    # Loop-lived double-buffered block storage: the transfer/update of
    # block N reads buffers the collection of block N+1 cannot touch.
    buffers = BlockBuffers(cfg.steps_per_iter)

    host_act = host_params = None
    if overlap and make_host_explore is not None:
        from actor_critic_tpu.models import host_actor

        np_params = jax.device_get(learner.actor_params)
        if host_actor.supports_mirror(np_params):
            host_act = make_host_explore(pool.spec, cfg)
            host_params = np_params
            rng = np.random.default_rng(seed + 0x5EED)

    # run_report "Resources" replay row: static ring-capacity facts
    # (capacity, bytes/transition vs fp32, codec mix). Static on purpose
    # — a live `size` read from the sampler thread would sync the host
    # on a donated in-flight device scalar.
    from actor_critic_tpu.replay import quantize as _quantize
    from actor_critic_tpu.telemetry import sampler as _sampler

    _replay_info = dict(
        _quantize.capacity_report(
            learner.replay,
            _quantize.offpolicy_codecs(getattr(cfg, "replay_dtype", "fp32")),
        ),
        mode=getattr(cfg, "replay_dtype", "fp32"),
    )
    _replay_gauge = _sampler.register_gauge("replay", lambda: _replay_info)
    try:
        for it in range(start_it, num_iterations):
            # Iteration boundary for any armed on-demand profile window
            # (telemetry/profiler.py): a capture starts/ends here so it
            # covers whole iterations.
            telemetry.profiler_tick()
            # Per-iteration span: the phase spans inside (env_step /
            # host_to_device / update / eval / log / checkpoint) nest
            # under it in the trace, giving per-iteration attribution.
            with telemetry.span("iteration", it=it + 1):

                if host_act is not None:

                    def explore_act(o):
                        nonlocal env_steps
                        action = host_act(host_params, o, rng, env_steps)
                        env_steps += E
                        return action, {}

                else:

                    def explore_act(o):
                        nonlocal key, env_steps
                        key, akey = jax.random.split(key)
                        # jaxlint: disable=host-sync (deliberate: without a
                        # numpy mirror the pool needs concrete host actions
                        # every step — the documented non-overlap fallback)
                        action = np.asarray(
                            act(learner.actor_params, jnp.asarray(o), akey,
                                jnp.asarray(env_steps, jnp.int32))
                        )
                        env_steps += E
                        return action, {}

                obs, block = host_collect(
                    pool, obs, cfg.steps_per_iter, explore_act, tracker,
                    buffers=buffers,
                )
                with telemetry.span("host_to_device"):
                    # jaxlint: disable=transfer-discipline (deliberate:
                    # the host plane's per-block upload — the lockstep
                    # loop transfers each collected block once by
                    # design; --data-plane device removes it, and
                    # perfsan budgets the bytes)
                    traj = OffPolicyTransition(
                        obs=jnp.asarray(block["obs"]),
                        action=jnp.asarray(block["action"]),
                        reward=jnp.asarray(block["reward"]),
                        next_obs=jnp.asarray(block["final_obs"]),
                        terminated=jnp.asarray(block["terminated"]),
                        done=jnp.asarray(block["done"]),
                    )
                if host_act is not None:
                    # Acting params for the NEXT rollout: this update's INPUT
                    # params, fetched BEFORE the dispatch (ingest_update donates
                    # the learner) — concrete already (the previous update
                    # finished during this collection), so the fetch doesn't
                    # wait, and the update dispatched below computes on-device
                    # while the next rollout is collected.
                    # jaxlint: disable=transfer-discipline (deliberate:
                    # the mirror's acting-params refresh — concrete by
                    # the overlap argument above, so no wait)
                    host_params = jax.device_get(learner.actor_params)
                # The jitted call returns at ENQUEUE time (async dispatch);
                # the span measures host-side cost only — blocking here to
                # measure device wall would cost the host/device overlap.
                with telemetry.span("update", dispatch="async"):
                    # jaxlint: disable=transfer-discipline (scalar
                    # env_steps counter rides the dispatch — 4 bytes)
                    learner, metrics = ingest_update(
                        learner, traj, jnp.asarray(env_steps, jnp.int32)
                    )
                extra = {"env_steps": env_steps}
                if eval_pool is not None and (it + 1) % eval_every == 0:
                    # NB: a fresh name — `act` is the jitted explore fn that the
                    # non-mirror explore_act closure reads late-bound; rebinding
                    # it here would crash collection after the first eval.
                    if host_greedy is not None:
                        # Blocks on the in-flight update: eval sees CURRENT params.
                        # jaxlint: disable=transfer-discipline (eval
                        # cadence, not the hot collect loop)
                        ev_params = jax.device_get(learner.actor_params)
                        # jaxlint: disable=transfer-discipline (mirror
                        # eval — np.asarray touches no device value)
                        eval_act = lambda o: np.asarray(host_greedy(ev_params, o))  # noqa: E731
                    else:
                        # jaxlint: disable=transfer-discipline (eval
                        # cadence: greedy eval must hand gym concrete
                        # host actions, once per eval step)
                        eval_act = lambda o: np.asarray(  # noqa: E731
                            greedy(learner.actor_params, jnp.asarray(o))
                        )
                    with telemetry.span("eval"):
                        extra["eval_return"] = host_evaluate(
                            eval_pool, eval_act, max_steps=eval_steps
                        )
                maybe_log(
                    it, log_every, metrics, tracker, history, log_fn,
                    extra=extra,
                    num_iterations=num_iterations,
                    # Force-log eval rows AND the first post-resume iteration (a
                    # resumed long run must produce evidence immediately, same
                    # rationale as should_log's it==1 clause).
                    force="eval_return" in extra or it == start_it,
                )
                host_maybe_save(
                    ckpt, it + 1, save_every, num_iterations, pool, metrics,
                    save_replay=save_replay,
                    learner=learner, key=key,
                    # jaxlint: disable=host-sync (python int → np scalar for
                    # the checkpoint tree; no device value is touched)
                    env_steps=np.asarray(env_steps, np.int64),
                )
        if ckpt is not None:
            ckpt.wait()  # the final async save must be durable before return
    finally:
        _sampler.unregister_gauge(_replay_gauge)
    return learner, history


def off_policy_train_host_async(
    pools,
    cfg,
    num_iterations: int,
    *,
    init_learner: Callable,
    make_ingest_update: Callable,
    make_host_explore: Callable,
    make_host_greedy: Optional[Callable] = None,
    seed: int = 0,
    log_every: int = 10,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    eval_every: int = 0,
    eval_envs: int = 4,
    eval_steps: int = 1000,
    queue_depth: int = 4,
    max_staleness: Optional[int] = None,
    data_plane: str = "host",
    plane_codec: str = "fp32",
    transfer_pad_s: float = 0.0,
    make_device_ingest_update: Optional[Callable] = None,
    publish_hook: Optional[Callable[[int, object], None]] = None,
):
    """Async actor–learner loop for the off-policy trainers (DDPG/TD3,
    SAC) — the ROADMAP item PR 6 left open: replay absorbs behavior-
    policy staleness natively (every consumed block just lands in the
    ring; updates sample uniformly regardless of which params collected
    a transition), so only the ingest hand-off needed wiring through
    `traj_queue.ActorService`.

    One actor thread per pool explores through the numpy mirror
    (`make_host_explore(spec, cfg)`, behavior params refreshed from the
    `PolicyPublisher` once per block) and pushes `[K, E_a]` transition
    blocks; this (learner) thread drains the queue and feeds each block
    to the jitted ingest+update program. `max_staleness` defaults to
    None — dropping stale blocks would throw away valid off-policy
    experience; the queue's drop-oldest back-pressure still bounds
    memory. Each actor warms up on uniform-random actions for its share
    (`warmup_steps / A`) of the fleet warmup budget: the mirror's gate
    compares against `cfg.warmup_steps`, so the actor feeds it its own
    step count scaled by the fleet size. The update gate sees the
    FLEET's total collected steps. `num_iterations` counts blocks
    consumed. Checkpointing is not wired for this mode (per-actor pools
    carry independent normalizer state; the PPO async driver grew the
    multi-pool save tree first — see ppo.train_host_async).

    `data_plane="device"` (ISSUE 13): actors stage encoded blocks in
    the HBM `data_plane.DeviceTrajRing` (codec per `plane_codec`) and
    `make_device_ingest_update(action_dim, cfg, ring_codecs)` — the
    per-algo factory ddpg/sac pass — builds the jitted program that
    gathers + decodes the slot, scatters it into the replay ring, and
    updates, with zero host→device transfers per consumed block.
    `transfer_pad_s` is the tunnel-wall testbed pad (ppo.train_host_async
    docstring).

    Returns (learner, history).
    """
    import threading

    import jax
    import jax.numpy as jnp

    from actor_critic_tpu.algos.common import OffPolicyTransition
    from actor_critic_tpu.algos.traj_queue import (
        ActorService,
        PolicyPublisher,
        TrajQueue,
        consume_block,
        validate_pools,
    )
    from actor_critic_tpu.models import host_actor

    spec, E_a = validate_pools(pools)
    A = len(pools)

    key = jax.random.key(seed)
    key, lkey = jax.random.split(key)
    learner = init_learner(spec.obs_shape, spec.action_dim, cfg, lkey)
    np_params = jax.device_get(learner.actor_params)
    if not host_actor.supports_mirror(np_params):
        raise ValueError(
            "async actor–learner mode needs the numpy actor mirror "
            "(MLP torso; models/host_actor.py)"
        )
    if data_plane not in ("host", "device"):
        raise ValueError(
            f"data_plane must be 'host' or 'device', got {data_plane!r}"
        )
    use_device_plane = data_plane == "device"
    if use_device_plane and make_device_ingest_update is None:
        raise ValueError(
            "data_plane='device' needs the algo's make_device_ingest_update "
            "factory (ddpg/sac pass it through train_host_async)"
        )
    host_explore = make_host_explore(spec, cfg)

    def actor_act_factory(actor_id: int):
        # Per-actor step counter, read/written only on that actor's
        # thread; scaled by A it approximates the fleet total, so the
        # mirror's `env_steps < warmup_steps` gate hands each actor its
        # 1/A share of the uniform-random warmup budget.
        counter = {"steps": 0}

        def make_act_fn(actor_params, rng):
            def act(o):
                action = host_explore(
                    actor_params, o, rng, counter["steps"] * A
                )
                counter["steps"] += np.asarray(o).shape[0]
                return action, {}

            return act

        return make_act_fn

    if use_device_plane:
        from actor_critic_tpu.data_plane import device_replay
        from actor_critic_tpu.data_plane import ring as dp_ring

        queue = dp_ring.DeviceTrajRing(
            depth=queue_depth,
            block_spec=device_replay.offpolicy_block_spec(spec, cfg, A),
            codec=plane_codec,
            max_staleness=max_staleness,
            policy="drop_oldest",
            transfer_pad_s=transfer_pad_s,
        )
        ingest_update = make_device_ingest_update(
            spec.action_dim, cfg, queue.codecs
        )
    else:
        queue = TrajQueue(
            depth=queue_depth, max_staleness=max_staleness,
            policy="drop_oldest",
        )
        ingest_update = make_ingest_update(spec.action_dim, cfg)
    publisher = PolicyPublisher(np_params, version=0)
    stop = threading.Event()
    actors = [
        ActorService(
            i, pool, queue, publisher, cfg.steps_per_iter,
            actor_act_factory(i),
            rng=np.random.default_rng(seed + 0x5EED + i * 7919),
            stop=stop,
        )
        for i, pool in enumerate(pools)
    ]

    eval_pool = host_greedy = None
    if eval_every > 0 and make_host_greedy is not None:
        eval_pool = pools[-1].eval_pool(eval_envs)
        host_greedy = make_host_greedy(spec, cfg)

    history: list = []
    metrics: dict = {}
    trackers = MergedEpisodeTracker([a.tracker for a in actors])
    try:
        for a in actors:
            a.start()
        for it in range(num_iterations):
            telemetry.profiler_tick()
            for a in actors:
                if a.error is not None:
                    raise RuntimeError(
                        f"actor {a.actor_id} died"
                    ) from a.error
            with telemetry.span("iteration", it=it + 1):
                queue.set_consumer_version(it)
                with telemetry.span("queue_wait", it=it + 1):
                    block = consume_block(queue, actors)
                # Behavior params for the actors' NEXT blocks: this
                # update's INPUT params, fetched BEFORE the donating
                # dispatch below (concrete — the previous update
                # finished during collection).
                # jaxlint: disable=transfer-discipline (deliberate: the
                # per-block behavior-params publish IS the async
                # contract — concrete by the overlap argument above)
                np_behavior = jax.device_get(learner.actor_params)
                publisher.publish(np_behavior, version=it)
                if publish_hook is not None:
                    # Serve-while-training (ISSUE 17): same snapshot
                    # cadence feeds the resident serving policy; the
                    # publisher copies its own leaves, so the hook may
                    # hand this tree to PolicyStore.swap.
                    publish_hook(it, np_behavior)
                staleness = max(it - block.version, 0)
                env_steps = sum(a.steps_collected for a in actors)
                if use_device_plane:
                    # Zero-transfer consume (ISSUE 13): the staged block
                    # already lives in HBM; the jitted ingest gathers +
                    # decodes it and scatters into the replay ring in
                    # one program — only the slot index crosses.
                    telemetry.instant("host_to_device", device_plane=True)
                    slot = np.int32(block.slot)
                    # jaxlint: disable=transfer-discipline (scalar
                    # env_steps counter — 4 bytes ride the dispatch)
                    steps = jnp.asarray(env_steps, jnp.int32)
                    with telemetry.span("update", dispatch="async"):
                        learner, metrics = queue.run(
                            lambda state: ingest_update(
                                learner, state, slot, steps
                            )
                        )
                    # After the dispatch: device execution order now
                    # reads the slot before any later overwrite.
                    queue.release(block)
                else:
                    with telemetry.span("host_to_device"):
                        if transfer_pad_s > 0:
                            time.sleep(transfer_pad_s)  # tunnel testbed
                        # jnp.array, NOT asarray: the transfer must
                        # snapshot the slot before release (the PR 6
                        # contract).
                        # jaxlint: disable=transfer-discipline (the
                        # host plane's per-block upload by design; the
                        # device branch above removes it — perfsan
                        # budgets both planes)
                        traj = OffPolicyTransition(
                            obs=jnp.array(block.arrays["obs"]),
                            action=jnp.array(block.arrays["action"]),
                            reward=jnp.array(block.arrays["reward"]),
                            next_obs=jnp.array(block.arrays["final_obs"]),
                            terminated=jnp.array(block.arrays["terminated"]),
                            done=jnp.array(block.arrays["done"]),
                        )
                    queue.release(block)
                    with telemetry.span("update", dispatch="async"):
                        # jaxlint: disable=transfer-discipline (scalar
                        # env_steps counter — 4 bytes)
                        learner, metrics = ingest_update(
                            learner, traj, jnp.asarray(env_steps, jnp.int32)
                        )
                qs = queue.stats()
                extra = {
                    "env_steps": env_steps,
                    "consumed_env_steps": (it + 1) * cfg.steps_per_iter * E_a,
                    "block_actor": block.actor_id,
                    "block_staleness": staleness,
                    "queue_depth": qs["depth"],
                    "queue_drops_full": qs["drops_full"],
                    "queue_drops_stale": qs["drops_stale"],
                    "learner_idle_s": qs["learner_idle_s"],
                }
                if eval_pool is not None and (it + 1) % eval_every == 0:
                    # Blocks on the in-flight update: eval sees CURRENT
                    # params, like the lockstep drivers.
                    # jaxlint: disable=transfer-discipline (eval
                    # cadence, not the per-block consume path)
                    ev_params = jax.device_get(learner.actor_params)
                    with telemetry.span("eval"):
                        extra["eval_return"] = host_evaluate(
                            eval_pool,
                            # jaxlint: disable=host-sync (numpy mirror
                            # eval — no device value is touched)
                            lambda o: np.asarray(host_greedy(ev_params, o)),
                            max_steps=eval_steps,
                        )
                maybe_log(
                    it, log_every, metrics, trackers, history, log_fn,
                    extra=extra, num_iterations=num_iterations,
                    force="eval_return" in extra or it == 0,
                )
    finally:
        stop.set()
        for a in actors:
            a.join(timeout=30.0)
        queue.close()
        if eval_pool is not None:
            eval_pool.close()
    return learner, history


def fused_train_loop(
    make_train_step: Callable,
    init_state: Callable,
    env,
    cfg,
    num_iterations: int,
    seed: int = 0,
    state=None,
    log_every: int = 0,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    scan_when_silent: bool = False,
    state_hook: Optional[Callable[[int, object], object]] = None,
):
    """Shared host loop around a fused (single-device) train step — the
    single body behind a2c/impala/ddpg/sac `.train`.

    With `scan_when_silent` and `log_every<=0` the whole loop is itself
    scanned on-device so the host dispatches O(1) programs (the a2c/
    impala fast path); otherwise each iteration is one donated jit call
    with optional periodic logging.

    `state_hook(it, state) -> state` runs on the HOST before each
    dispatch (it = 0-based upcoming iteration) — the between-dispatch
    rewrite seam the scenario-mixture curriculum uses to install new
    type-draw weights into the fleet state (envs/mixture.py
    `set_fleet_weights`; train.py's checkpointed path has the same seam
    in run_fused). Hooks must preserve every leaf's shape/dtype so the
    jitted step never retraces; setting one disables the scanned fast
    path (a host callback cannot run inside `lax.scan`).
    """
    import jax

    if state is None:
        state = init_state(env, cfg, jax.random.key(seed))
    step = make_train_step(env, cfg)

    if scan_when_silent and log_every <= 0 and state_hook is None:
        if num_iterations < 1:
            raise ValueError("num_iterations must be >= 1")

        # should_log policy: the FIRST and final iterations always log, so
        # the first update runs as its own dispatch (early evidence), then
        # the remaining n-1 are one scanned program — still O(1) dispatches.
        jit_step = jax.jit(step, donate_argnums=0)
        state, metrics = jit_step(state)
        if log_fn is not None:
            log_fn(1, {k: float(v) for k, v in metrics.items()})
        if num_iterations > 1:

            # donate_argnums matches jit_step above: `state` here is
            # jit_step's freshly produced output (rebound at its call),
            # so the scanned tail can reuse the buffers in place instead
            # of copy-preserving the full train state for one call
            # (found by donation-discipline, ISSUE 15).
            @partial(jax.jit, donate_argnums=0)
            def run(state):
                def body(s, _):
                    s, _m = step(s)
                    return s, None

                s, _ = jax.lax.scan(body, state, None, length=num_iterations - 2)
                # last of the remaining n-1 updates returns the metrics
                return step(s)

            state, metrics = run(state)
            if log_fn is not None:
                log_fn(num_iterations, {k: float(v) for k, v in metrics.items()})
        return state, metrics

    jit_step = jax.jit(step, donate_argnums=0)
    metrics: dict = {}
    for it in range(num_iterations):
        if state_hook is not None:
            state = state_hook(it, state)
        state, metrics = jit_step(state)
        if log_fn is not None and should_log(it + 1, log_every, num_iterations):
            # jaxlint: disable=host-sync (deliberate: the log-cadence
            # float() coercions are the loop's designed first sync point
            # — README "Observability")
            log_fn(it + 1, {k: float(v) for k, v in metrics.items()})
    return state, metrics


# Cadence policies live in utils/cadence.py (a leaf module, so
# utils/checkpoint.py can share them without importing algos); re-exported
# here because the loops and their tests address them via this module.
from actor_critic_tpu.utils.cadence import should_log  # noqa: E402, F401


def maybe_log(
    it: int,
    log_every: int,
    metrics: dict,
    tracker: EpisodeTracker,
    history: list,
    log_fn: Optional[Callable[[int, dict], None]],
    extra: Optional[dict] = None,
    num_iterations: int = 0,
    force: bool = False,
) -> None:
    """Append host-side metrics to `history` (and `log_fn`) on the shared
    `should_log` cadence (pass `num_iterations` so the final iteration is
    always logged; `force` for rows that must never drop, e.g. eval)."""
    if not (force or should_log(it + 1, log_every, num_iterations)):
        return
    # The float() coercions are the host loop's first sync point on the
    # dispatched update — the log span therefore absorbs any remaining
    # device wait (documented in README "Observability").
    with telemetry.span("log", it=it + 1):
        m = {k: float(v) for k, v in metrics.items()}
        m.update(tracker.report())
        if extra:
            m.update(extra)
        history.append((it + 1, m))
        if log_fn is not None:
            log_fn(it + 1, m)
