"""Shared host-env rollout plumbing for the `train_host` paths.

PPO (on-policy), DDPG/TD3 and SAC (off-policy) all step a `HostEnvPool`
from a host loop (SURVEY.md §3.1-3.2 host boundary; reference mount
empty, §0) and need the same bookkeeping: stack per-step arrays into a
time-major [K, E] block for the single host→device transfer, and track
raw episode returns for reporting. This module owns both so the trainers
don't each carry a diverging copy.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np


class EpisodeTracker:
    """Raw-return episode accounting across host steps."""

    def __init__(self, num_envs: int):
        self._ep_ret = np.zeros(num_envs)
        self.finished: list[float] = []

    def update(self, raw_reward: np.ndarray, done: np.ndarray) -> None:
        self._ep_ret += raw_reward
        for i in np.nonzero(done)[0]:
            self.finished.append(float(self._ep_ret[i]))
            self._ep_ret[i] = 0.0

    def report(self, window: int = 20) -> dict[str, float]:
        return {
            "recent_return": (
                float(np.mean(self.finished[-window:]))
                if self.finished
                else float("nan")
            ),
            "episodes": float(len(self.finished)),
        }


def host_collect(
    pool,
    obs: np.ndarray,
    num_steps: int,
    act_fn: Callable[[np.ndarray], tuple[np.ndarray, dict[str, np.ndarray]]],
    tracker: EpisodeTracker,
) -> tuple[np.ndarray, dict[str, np.ndarray]]:
    """Step the pool `num_steps` times; return (last obs, stacked block).

    `act_fn(obs) -> (action, extras)`; extras (e.g. log_prob/value for
    on-policy) are recorded alongside the standard fields. The block's
    arrays are time-major [K, E, ...] float/int numpy — exactly one
    device transfer's worth.
    """
    block: dict[str, list[np.ndarray]] = {}

    def record(name: str, value: np.ndarray) -> None:
        block.setdefault(name, []).append(value)

    for _ in range(num_steps):
        action, extras = act_fn(obs)
        out = pool.step(action)
        record("obs", obs)
        record("action", action)
        for k, v in extras.items():
            record(k, v)
        record("reward", out.reward)
        record("done", out.done)
        record("terminated", out.terminated)
        record("final_obs", out.final_obs)
        tracker.update(out.raw_reward, out.done)
        obs = out.obs

    return obs, {k: np.stack(v) for k, v in block.items()}


def maybe_log(
    it: int,
    log_every: int,
    metrics: dict,
    tracker: EpisodeTracker,
    history: list,
    log_fn: Optional[Callable[[int, dict], None]],
    extra: Optional[dict] = None,
) -> None:
    """Append host-side metrics to `history` (and `log_fn`) every
    `log_every` iterations."""
    if (it + 1) % max(log_every, 1) != 0:
        return
    m = {k: float(v) for k, v in metrics.items()}
    m.update(tracker.report())
    if extra:
        m.update(extra)
    history.append((it + 1, m))
    if log_fn is not None:
        log_fn(it + 1, m)
