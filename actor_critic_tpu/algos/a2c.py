"""A2C — synchronous advantage actor-critic, fully fused on-device.

Capability parity with the reference's A2C CartPole config
(BASELINE.json:7; reference mount empty at survey, SURVEY.md §0), built
the TPU way: one jitted program per train step containing

    lax.scan over T: [policy fwd → vmapped env.step]   (rollout)
    → GAE reverse scan                                  (targets)
    → policy-gradient + value-MSE + entropy loss        (update)
    → optax update (grads pmean-ed over the dp mesh axis)

so the host is touched once per iteration, not once per env step — the
design that makes the ≥1M env-steps/sec north star (BASELINE.json:5)
reachable where the reference's host-stepped loop cannot.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from actor_critic_tpu.algos.common import (
    TrainState,
    Transition,
    anneal_fraction,
    episode_metrics_update,
    gae_targets as gae,
    init_rollout,
    linear_anneal,
    rollout_scan,
    truncation_bootstrap_rewards,
)
from actor_critic_tpu.algos.metrics import aggregate_metrics
from actor_critic_tpu.envs.jax_env import JaxEnv
from actor_critic_tpu.models.networks import ActorCriticDiscrete, ActorCriticGaussian
from actor_critic_tpu.ops.returns import normalize_advantages
from actor_critic_tpu.parallel import mesh as pmesh


@dataclasses.dataclass(frozen=True)
class A2CConfig:
    num_envs: int = 64
    rollout_steps: int = 16  # T
    gamma: float = 0.99
    gae_lambda: float = 0.95
    lr: float = 3e-4
    value_coef: float = 0.5
    entropy_coef: float = 0.01
    max_grad_norm: float = 0.5
    hidden: tuple[int, ...] = (64, 64)
    normalize_adv: bool = False
    # Huber value loss with this delta (<=0 keeps plain MSE). A2C takes
    # ONE gradient step per rollout, so PPO's value-clip-vs-old would be
    # a mathematical no-op here (value ≡ value_old at the differentiation
    # point); Huber is the stabilizer that DOES engage — it clips each
    # sample's value-step gradient to ±delta without touching the
    # policy-gradient estimator. Round-5 measurement on the flagship
    # preset (results/a2c_s{0,2}_huber{5,10}.json): delta=5 certifies
    # seed 2 but BREAKS seed 0; delta=10 certifies seed 0 and lifts
    # seed 2's oscillation band to 299–499 without certifying it — the
    # knob relocates A2C's seed sensitivity, it does not remove it
    # (consistent with the round-4 sweep rejecting normalize_adv /
    # lower lr / tighter grad clip). Left off in the preset; available
    # per-run via --set value_huber_delta=N.
    value_huber_delta: float = 0.0
    # bfloat16 activations for MXU throughput; params/optimizer stay fp32.
    bf16_compute: bool = False
    # Linear annealing over the first `anneal_iters` train steps (0 = off):
    # lr → lr_final and entropy_coef → entropy_coef_final, both optional.
    # The flat-coefficient flagship preset never converged to a solve
    # (round-2 verdict); annealing is the standard fix.
    anneal_iters: int = 0
    lr_final: Optional[float] = None
    entropy_coef_final: Optional[float] = None


def make_network(env: JaxEnv, cfg: A2CConfig):
    dtype = jnp.bfloat16 if cfg.bf16_compute else jnp.float32
    if env.spec.discrete:
        return ActorCriticDiscrete(
            num_actions=env.spec.action_dim, hidden=cfg.hidden,
            pixel_obs=env.spec.pixel_obs, compute_dtype=dtype,
        )
    return ActorCriticGaussian(
        action_dim=env.spec.action_dim, hidden=cfg.hidden, compute_dtype=dtype
    )


def make_eval_fn(env: JaxEnv, cfg: "A2CConfig"):
    """Greedy (mode-action) eval program (SURVEY.md §3.4)."""
    from actor_critic_tpu.algos.common import make_mode_eval

    return make_mode_eval(env, make_network(env, cfg))


def make_optimizer(cfg: A2CConfig) -> optax.GradientTransformation:
    lr = cfg.lr
    if cfg.anneal_iters > 0 and cfg.lr_final is not None:
        # One optimizer step per train iteration, so the schedule's step
        # count IS the iteration count.
        lr = optax.linear_schedule(cfg.lr, cfg.lr_final, cfg.anneal_iters)
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adam(lr),
    )


def entropy_coef_at(cfg: A2CConfig, update_step: jax.Array) -> jax.Array:
    """Current entropy coefficient under the linear anneal (constant when
    annealing is off)."""
    return linear_anneal(
        cfg.entropy_coef,
        cfg.entropy_coef_final,
        anneal_fraction(update_step, cfg.anneal_iters),
    )


def init_state(env: JaxEnv, cfg: A2CConfig, key: jax.Array) -> TrainState:
    net = make_network(env, cfg)
    opt = make_optimizer(cfg)
    key, pkey, rkey = jax.random.split(key, 3)
    dummy = jnp.zeros((1, *env.spec.obs_shape), env.spec.obs_dtype)
    params = net.init(pkey, dummy)
    rstate = init_rollout(env, rkey, cfg.num_envs)
    E = cfg.num_envs
    return TrainState(
        params=params,
        opt_state=opt.init(params),
        rollout=rstate,
        key=key,
        update_step=jnp.zeros((), jnp.int32),
        ep_return=jnp.zeros((E,)),
        ep_length=jnp.zeros((E,)),
        avg_return=jnp.zeros(()),
    )


def a2c_loss(
    params: Any,
    apply_fn: Callable,
    traj: Transition,
    advantages: jax.Array,
    returns: jax.Array,
    cfg: A2CConfig,
    axis_name: Optional[str] = None,
    entropy_coef: Optional[jax.Array] = None,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Policy-gradient + value-MSE + entropy-bonus loss on a [T, E] batch.

    Re-evaluates the policy at the stored obs (same params as rollout, so
    ratio==1; the re-evaluation is what makes the loss differentiable).
    `axis_name` keeps advantage-normalization statistics global under dp.
    `entropy_coef` overrides cfg.entropy_coef (annealing threads the
    current value through here).
    """
    if entropy_coef is None:
        entropy_coef = jnp.asarray(cfg.entropy_coef)
    obs = traj.obs.reshape(-1, *traj.obs.shape[2:])
    actions = traj.action.reshape(-1, *traj.action.shape[2:])
    adv = advantages.reshape(-1)
    ret = returns.reshape(-1)
    if cfg.normalize_adv:
        adv = normalize_advantages(adv, axis_name)

    dist, value = apply_fn(params, obs)
    log_prob = dist.log_prob(actions)
    # Explicit fp32 accumulators on every reduction: bit-identical in
    # fp32 mode (the heads cast up), precision-discipline-required under
    # --update-dtype bf16 (bf16 compute, fp32 accumulation).
    entropy = jnp.mean(dist.entropy(), dtype=jnp.float32)

    pg_loss = -jnp.mean(
        jax.lax.stop_gradient(adv) * log_prob, dtype=jnp.float32
    )
    ret = jax.lax.stop_gradient(ret)
    if cfg.value_huber_delta > 0:
        # d/dv huber(v - ret) = clip(v - ret, ±delta): a per-sample bound
        # on the value step (see the config-field comment for why PPO's
        # clip-vs-old cannot work in A2C's single-step regime).
        v_loss = jnp.mean(
            optax.losses.huber_loss(value, ret, delta=cfg.value_huber_delta),
            dtype=jnp.float32,
        )
    else:
        v_loss = 0.5 * jnp.mean((value - ret) ** 2, dtype=jnp.float32)
    loss = pg_loss + cfg.value_coef * v_loss - entropy_coef * entropy
    return loss, {
        "loss": loss,
        "pg_loss": pg_loss,
        "v_loss": v_loss,
        "entropy": entropy,
    }


def make_train_step(
    env: JaxEnv,
    cfg: A2CConfig,
    axis_name: Optional[str] = None,
) -> Callable[[TrainState], tuple[TrainState, dict[str, jax.Array]]]:
    """Build the fused train step. `axis_name` names the dp mesh axis when
    running under shard_map (grads/metrics pmean-ed over it); None for
    single-device."""
    net = make_network(env, cfg)
    opt = make_optimizer(cfg)
    apply_fn = net.apply

    def train_step(state: TrainState) -> tuple[TrainState, dict[str, jax.Array]]:
        key, rkey = jax.random.split(state.key)

        # --- rollout (T steps, E envs, on-device) ---
        new_rollout, traj = rollout_scan(
            env, apply_fn, state.params, state.rollout, rkey, cfg.rollout_steps
        )

        # --- targets ---
        _, bootstrap_value = apply_fn(state.params, new_rollout.obs)
        if env.spec.can_truncate:
            # Value of pre-reset final obs for truncation bootstrap.
            T, E = traj.reward.shape
            _, final_values = apply_fn(
                state.params,
                traj.final_obs.reshape(T * E, *traj.final_obs.shape[2:]),
            )
            rewards = truncation_bootstrap_rewards(
                traj, final_values.reshape(T, E), cfg.gamma
            )
        else:
            rewards = traj.reward
        advantages, returns = gae(
            rewards, traj.value, traj.done, bootstrap_value, cfg.gamma, cfg.gae_lambda
        )

        # --- update ---
        grad_fn = jax.value_and_grad(a2c_loss, has_aux=True)
        (_, metrics), grads = grad_fn(
            state.params, apply_fn, traj, advantages, returns, cfg, axis_name,
            entropy_coef_at(cfg, state.update_step),
        )
        grads = pmesh.pmean_tree(grads, axis_name)
        updates, new_opt_state = opt.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        # --- metrics / accounting ---
        ep_ret, ep_len, avg_ret, ep_metrics = episode_metrics_update(
            state.ep_return, state.ep_length, state.avg_return, traj
        )
        # Keep the EMA replicated across the dp axis (it is part of the
        # replicated state; per-device episode streams would diverge).
        avg_ret = pmesh.pmean(avg_ret, axis_name)
        ep_metrics["avg_return_ema"] = avg_ret
        metrics = aggregate_metrics(metrics, ep_metrics, axis_name)

        new_state = TrainState(
            params=new_params,
            opt_state=new_opt_state,
            rollout=new_rollout,
            key=key,
            update_step=state.update_step + 1,
            ep_return=ep_ret,
            ep_length=ep_len,
            avg_return=avg_ret,
        )
        return new_state, metrics

    return train_step


def train(
    env: JaxEnv,
    cfg: A2CConfig,
    num_iterations: int,
    seed: int = 0,
    state: Optional[TrainState] = None,
    log_every: int = 0,
    log_fn: Optional[Callable[[int, dict], None]] = None,
    state_hook: Optional[Callable] = None,
) -> tuple[TrainState, dict[str, jax.Array]]:
    """Simple host loop around the fused step (single device).

    For N iterations without host logging, the loop body is itself scanned
    on-device (`log_every=0`) so the host dispatches O(1) programs.
    `state_hook` is the between-dispatch state rewrite seam (curriculum
    weight installs on mixture fleets — host_loop.fused_train_loop).
    """
    from actor_critic_tpu.algos.host_loop import fused_train_loop

    return fused_train_loop(
        make_train_step, init_state, env, cfg, num_iterations,
        seed=seed, state=state, log_every=log_every, log_fn=log_fn,
        scan_when_silent=True, state_hook=state_hook,
    )


# -- AOT warmup registry (utils/compile_cache.py, ISSUE 4) ------------------
from actor_critic_tpu.utils import compile_cache as _compile_cache  # noqa: E402

_compile_cache.register_fused_warmups(
    "a2c", ("a2c",), init_state, make_train_step, make_eval_fn
)
