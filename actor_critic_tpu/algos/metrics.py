"""Cross-device metric aggregation shared by the trainers."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from actor_critic_tpu.parallel import mesh as pmesh


def aggregate_metrics(
    metrics: dict, ep_metrics: dict, axis_name: Optional[str]
) -> dict:
    """Combine loss metrics (pmean) with episode accounting (psum-then-
    divide, so devices with zero finished episodes don't bias the mean)."""
    n = pmesh.psum(ep_metrics["episodes_finished"], axis_name)
    s = pmesh.psum(ep_metrics["finished_return_sum"], axis_name)
    out = {k: pmesh.pmean(v, axis_name) for k, v in metrics.items()}
    out["episodes_finished"] = n
    out["mean_finished_return"] = s / jnp.maximum(n, 1.0)
    if "finished_length_sum" in ep_metrics:
        ln = pmesh.psum(ep_metrics["finished_length_sum"], axis_name)
        out["mean_ep_length"] = ln / jnp.maximum(n, 1.0)
    # avg_return_ema is pmean'd by the caller before state update.
    out["avg_return_ema"] = ep_metrics["avg_return_ema"]
    return out
