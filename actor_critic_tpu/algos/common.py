"""Shared trainer plumbing: transition pytrees, train state, rollout scan.

The fused on-device rollout is the framework's answer to the reference's
per-step host↔device ping-pong (SURVEY.md §3.1 boundary analysis;
reference mount empty, §0): `lax.scan` over T timesteps of
(policy forward → vmapped env step), with the whole thing living inside
one jitted train step (BASELINE.json:5 north star).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from actor_critic_tpu.envs.jax_env import JaxEnv


class Transition(NamedTuple):
    """One time-slice of a vmapped rollout; arrays are [T, E, ...] after scan."""

    obs: jax.Array
    action: jax.Array
    log_prob: jax.Array
    value: jax.Array
    reward: jax.Array
    done: jax.Array        # episode ended this step (term or trunc)
    terminated: jax.Array  # true termination (cuts bootstrap)
    final_obs: jax.Array   # pre-reset obs of the step (== next obs if not done)


class RolloutState(NamedTuple):
    """Carry of the rollout scan (per-env state + current obs)."""

    env_state: Any
    obs: jax.Array


class TrainState(NamedTuple):
    """On-policy trainer state. Total env steps = update_step · T · E,
    computed on the host (int32-on-device would wrap within ~36 min at the
    1M steps/s target)."""

    params: Any
    opt_state: Any
    rollout: RolloutState
    key: jax.Array
    update_step: jax.Array  # number of train_step calls
    # Running episode-return accounting (per env).
    ep_return: jax.Array
    ep_length: jax.Array
    # Exponential-moving stats of completed-episode returns, for metrics.
    avg_return: jax.Array


def init_rollout(env: JaxEnv, key: jax.Array, num_envs: int) -> RolloutState:
    keys = jax.random.split(key, num_envs)
    env_state, obs = jax.vmap(env.reset)(keys)
    return RolloutState(env_state=env_state, obs=obs)


def rollout_scan(
    env: JaxEnv,
    apply_fn: Callable[[Any, jax.Array], tuple[Any, jax.Array]],
    params: Any,
    rstate: RolloutState,
    key: jax.Array,
    num_steps: int,
) -> tuple[RolloutState, Transition]:
    """Collect `num_steps` of experience from the vmapped env batch.

    `apply_fn(params, obs) -> (dist, value)`; actions are sampled per env
    with per-step keys. Returns time-major Transition with arrays
    [T, E, ...].
    """

    def step_fn(carry: RolloutState, step_key: jax.Array):
        dist, value = apply_fn(params, carry.obs)
        n_envs = carry.obs.shape[0]
        akeys = jax.random.split(step_key, n_envs)
        action = jax.vmap(lambda d, k: d.sample(k), in_axes=(0, 0))(dist, akeys)
        log_prob = jax.vmap(lambda d, a: d.log_prob(a))(dist, action)
        out = jax.vmap(env.step)(carry.env_state, action)
        trans = Transition(
            obs=carry.obs,
            action=action,
            log_prob=log_prob,
            value=value,
            reward=out.reward,
            done=out.done,
            terminated=out.info["terminated"],
            final_obs=out.info["final_obs"],
        )
        return RolloutState(env_state=out.state, obs=out.obs), trans

    step_keys = jax.random.split(key, num_steps)
    return jax.lax.scan(step_fn, rstate, step_keys)


class OffPolicyTransition(NamedTuple):
    """One replay-ready transition (DDPG/TD3/SAC; BASELINE.json:9-10).

    `next_obs` is the pre-reset successor observation (the env protocol's
    `final_obs`), so the TD bootstrap r + γ·(1−terminated)·Q(next_obs, ·)
    is correct across both terminations (masked) and time-limit
    truncations (bootstrapped through). `done` is kept for episode
    accounting, not for the bootstrap.
    """

    obs: jax.Array
    action: jax.Array
    reward: jax.Array
    next_obs: jax.Array
    terminated: jax.Array
    done: jax.Array


def offpolicy_rollout(
    env: JaxEnv,
    act_fn: Callable[[Any, jax.Array, jax.Array, jax.Array], jax.Array],
    params: Any,
    rstate: RolloutState,
    key: jax.Array,
    num_steps: int,
    env_steps: jax.Array,
) -> tuple[RolloutState, jax.Array, OffPolicyTransition]:
    """Collect `num_steps` exploration steps from the vmapped env batch.

    `act_fn(params, obs, key, env_steps) -> action` owns the exploration
    policy (noise, warmup-uniform gating). `env_steps` is this device's
    running env-step count, threaded through so warmup gating stays
    correct inside the scan; it SATURATES at 2^30 so an int32 wrap can
    never flip the warmup gate back on in a long run (total step counts
    belong on the host — see TrainState's docstring). Returns time-major
    [T, E, ...] transitions.
    """

    def step_fn(carry, step_key: jax.Array):
        rs, steps = carry
        action = act_fn(params, rs.obs, step_key, steps)
        out = jax.vmap(env.step)(rs.env_state, action)
        trans = OffPolicyTransition(
            obs=rs.obs,
            action=action,
            reward=out.reward,
            next_obs=out.info["final_obs"],
            terminated=out.info["terminated"],
            done=out.done,
        )
        steps = jnp.minimum(steps + rs.obs.shape[0], jnp.int32(1 << 30))
        return (RolloutState(env_state=out.state, obs=out.obs), steps), trans

    step_keys = jax.random.split(key, num_steps)
    (rstate, env_steps), traj = jax.lax.scan(step_fn, (rstate, env_steps), step_keys)
    return rstate, env_steps, traj


def gae_targets(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    lam: float,
    time_axis_name: Optional[str] = None,
) -> tuple[jax.Array, jax.Array]:
    """THE on-policy advantage seam (ISSUE 19): every trainer's GAE /
    λ-return target computation routes through here, so the estimator
    lowers through the Pallas kernel layer — `ops.pallas_scan.gae_auto`
    picks the fused in-VMEM reverse scan on TPU backends and the lax.scan
    reference everywhere else, keeping the whole update ONE program under
    jit on both planes. `time_axis_name` selects the sequence-parallel
    variant inside shard_map. Returns (advantages, returns)."""
    if time_axis_name is not None:
        from actor_critic_tpu.parallel.seqpar import seqpar_gae

        return seqpar_gae(
            rewards, values, dones, bootstrap_value, gamma, lam,
            axis_name=time_axis_name,
        )
    from actor_critic_tpu.ops.pallas_scan import gae_auto as _gae

    return _gae(rewards, values, dones, bootstrap_value, gamma, lam)


def corrected_advantages(
    target_log_probs: jax.Array,
    behavior_log_probs: jax.Array,
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    bootstrap_value: jax.Array,
    gamma: float,
    lam: float,
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    correction: str = "vtrace",
    time_axis_name: Optional[str] = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """THE staleness-correction machinery the off-policy-tolerant
    trainers share (IMPALA's fused learner in `algos/impala.py` and the
    async actor–learner PPO update in `algos/ppo.py` — ISSUE 6).

    `correction="vtrace"`: clipped-importance-weighted value targets and
    policy-gradient advantages (ops vtrace; ρ̄/c̄ clips, λ damping) — the
    behavior policy's log-probs were recorded at rollout time, so the
    ρ = π/μ ratios correct any parameter lag between collection and
    consumption. `correction="none"`: plain λ-return GAE under the
    learner's critic with no importance weighting (the A3C rule, which
    simply tolerates small staleness bias).

    All probability/value inputs must already be stop-gradiented by the
    caller (targets are targets). With π == μ the V-trace value targets
    equal the GAE returns exactly for any λ, and the pg advantages
    coincide at λ=1 (canonical IMPALA) — tested in
    tests/test_async_host.py. Returns (pg_advantages, value_targets,
    mean_clipped_rho).

    `time_axis_name` runs the recurrences sequence-parallel inside
    shard_map via `parallel.seqpar` (the impala sp learner's path).
    """
    from actor_critic_tpu.ops.pallas_scan import vtrace_auto as _vtrace

    if correction == "vtrace":
        if time_axis_name is not None:
            from actor_critic_tpu.parallel.seqpar import seqpar_vtrace

            vt = seqpar_vtrace(
                target_log_probs, behavior_log_probs, rewards, values,
                dones, bootstrap_value, gamma, rho_bar=rho_bar, c_bar=c_bar,
                lam=lam, axis_name=time_axis_name,
            )
        else:
            vt = _vtrace(
                target_log_probs, behavior_log_probs, rewards, values,
                dones, bootstrap_value, gamma, rho_bar=rho_bar, c_bar=c_bar,
                lam=lam,
            )
        return vt.pg_advantages, vt.vs, jnp.mean(vt.clipped_rhos)
    if correction == "none":
        pg_advantages, value_targets = gae_targets(
            rewards, values, dones, bootstrap_value, gamma, lam,
            time_axis_name=time_axis_name,
        )
        return pg_advantages, value_targets, jnp.ones(())
    raise ValueError(f"unknown correction: {correction!r}")


def anneal_fraction(
    update_step: jax.Array, anneal_iters: int
) -> Optional[jax.Array]:
    """update_step → clipped [0, 1] anneal fraction; None when annealing
    is off (anneal_iters <= 0). THE progress contract every coefficient
    schedule shares — compute it once per train step and thread it."""
    if anneal_iters <= 0:
        return None
    return jnp.clip(update_step.astype(jnp.float32) / anneal_iters, 0.0, 1.0)


def linear_anneal(
    initial: float, final, progress: Optional[jax.Array]
) -> jax.Array:
    """initial + (final − initial)·progress; the constant `initial` when
    the schedule is disabled (final is None) or progress is None."""
    if final is None or progress is None:
        return jnp.asarray(initial)
    return initial + (final - initial) * progress


def truncation_bootstrap_rewards(
    traj: Transition,
    final_values: jax.Array,
    gamma: float,
) -> jax.Array:
    """Patch rewards so truncated (not terminated) episode ends bootstrap.

    r_t ← r_t + γ·V(final_obs_t) where the episode was truncated at t.
    With this patch, `gae` can treat `done` as a hard cut (SURVEY §7.2.5:
    correct time-limit handling without branching inside the scan).
    """
    truncated = traj.done * (1.0 - traj.terminated)
    return traj.reward + gamma * final_values * truncated


def evaluate(
    env: JaxEnv,
    act_fn: Callable[[Any, jax.Array], jax.Array],
    params: Any,
    key: jax.Array,
    num_envs: int = 32,
    num_steps: int = 256,
    reset_fn: Optional[Callable] = None,
) -> jax.Array:
    """Greedy eval: mean return of each env's FIRST episode (SURVEY §3.4).

    `act_fn(params, obs) -> action` is the deterministic policy (mode /
    mean action). Rewards stop accumulating at the first `done`. Envs
    whose episode outlives `num_steps` are EXCLUDED from the mean (a
    partial return would understate exactly when the policy is good);
    if no env finishes within the horizon, the mean of the partial
    returns is reported instead — a lower bound, and the only number
    available. One jittable program; used by trainers' periodic eval
    and the learning tests. `reset_fn` overrides `env.reset` for
    partitioned eval fleets (the mixture's type-pinned per-type eval
    matrix, envs/mixture.py) — the episode loop itself is shared.
    """
    keys = jax.random.split(key, num_envs)
    env_state, obs = jax.vmap(reset_fn or env.reset)(keys)
    init = (env_state, obs, jnp.zeros(num_envs), jnp.ones(num_envs))

    def step(carry, _):
        env_state, obs, ret, alive = carry
        action = act_fn(params, obs)
        out = jax.vmap(env.step)(env_state, action)
        ret = ret + out.reward * alive
        alive = alive * (1.0 - out.done)
        return (out.state, out.obs, ret, alive), None

    (_, _, returns, alive), _ = jax.lax.scan(step, init, None, length=num_steps)
    finished = 1.0 - alive
    n_finished = jnp.sum(finished)
    finished_mean = jnp.sum(returns * finished) / jnp.maximum(n_finished, 1.0)
    return jnp.where(n_finished > 0, finished_mean, jnp.mean(returns))


def default_eval_steps(env: JaxEnv) -> int:
    """Eval horizon: the env's episode time-limit plus slack (so a good
    policy's episodes always FINISH within the eval and are counted), or
    512 when the env doesn't declare one."""
    h = env.spec.episode_horizon
    return h + 8 if h > 0 else 512


def make_greedy_eval(
    env: JaxEnv,
    act: Callable[[Any, jax.Array], jax.Array],
    params_of: Callable[[Any], Any],
):
    """THE eval-program factory shared by every algo's `make_eval_fn`:
    `act(params, obs) → action` is the algo's greedy policy, `params_of`
    extracts the acting params from its train state. Returns
    `eval_fn(state, key, num_envs=32, num_steps=default_eval_steps(env))`
    (jit with static_argnums=(2, 3))."""
    default_steps = default_eval_steps(env)

    def eval_fn(state, key, num_envs: int = 32, num_steps: int = default_steps):
        return evaluate(env, act, params_of(state), key, num_envs, num_steps)

    return eval_fn


def make_mode_eval(env: JaxEnv, net):
    """`make_greedy_eval` specialization for actor-critic nets whose
    `apply(params, obs) → (dist, value)`: greedy action = dist.mode(),
    params live at `state.params` (a2c/ppo/impala)."""

    def act(params, obs):
        dist, _ = net.apply(params, obs)
        return dist.mode()

    return make_greedy_eval(env, act, lambda s: s.params)


def episode_metrics_update(
    ep_return: jax.Array,
    ep_length: jax.Array,
    avg_return: jax.Array,
    traj: Transition,
    decay: float = 0.99,
) -> tuple[jax.Array, jax.Array, jax.Array, dict[str, jax.Array]]:
    """Fold a [T, E] trajectory into running per-env episode accounting.

    Returns updated (ep_return, ep_length, avg_return EMA, metrics).
    Runs inside jit; O(T·E) elementwise.
    """

    def fold(carry, x):
        ep_ret, ep_len, avg, n_done, sum_done, len_done = carry
        reward, done = x
        ep_ret = ep_ret + reward
        ep_len = ep_len + 1.0
        n_done = n_done + jnp.sum(done)
        sum_done = sum_done + jnp.sum(ep_ret * done)
        len_done = len_done + jnp.sum(ep_len * done)
        # EMA over completed episodes (batch-mean of finished returns).
        batch_done = jnp.sum(done)
        batch_mean = jnp.where(
            batch_done > 0, jnp.sum(ep_ret * done) / jnp.maximum(batch_done, 1.0), avg
        )
        avg = jnp.where(batch_done > 0, decay * avg + (1 - decay) * batch_mean, avg)
        ep_ret = ep_ret * (1.0 - done)
        ep_len = ep_len * (1.0 - done)
        return (ep_ret, ep_len, avg, n_done, sum_done, len_done), None

    (ep_return, ep_length, avg_return, n_done, sum_done, len_done), _ = jax.lax.scan(
        fold,
        (ep_return, ep_length, avg_return,
         jnp.zeros(()), jnp.zeros(()), jnp.zeros(())),
        (traj.reward, traj.done),
    )
    # Raw count and sums so dp callers can psum them and divide AFTER the
    # reduction (an unweighted pmean of per-device means would bias toward
    # devices with zero finished episodes).
    metrics = {
        "episodes_finished": n_done,
        "finished_return_sum": sum_done,
        "finished_length_sum": len_done,
        "avg_return_ema": avg_return,
    }
    return ep_return, ep_length, avg_return, metrics
