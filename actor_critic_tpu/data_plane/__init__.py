"""Device-resident data plane (ISSUE 13): trajectory and replay data
live in HBM end to end, so steady-state learner consumption performs
zero host→device transfers.

- `data_plane.ring` — the donated device trajectory ring
  (`DeviceTrajRing`): actors enqueue host-encoded int8/f16 blocks, the
  learner gathers + decodes inside its jitted update program.
- `data_plane.device_replay` — the off-policy twin: staged blocks feed
  the donated replay ring inside one jitted ingest+update program, plus
  the R2D2-style burn-in/train sequence consumer over
  `replay.sample_sequences`.
- `data_plane.codecs` — the host-side numpy mirror of the
  `replay/quantize.py` calibrate-then-freeze codecs (actors encode
  without touching the device) and the per-key trajectory codec specs.

Wiring: `train.py --data-plane {host,device}` on the async drivers
(`--async-actors`); README "Device data plane" covers when device beats
host and the codec trade-offs.
"""

from actor_critic_tpu.data_plane.codecs import (  # noqa: F401
    TRAJ_MODES,
    traj_codecs,
)
from actor_critic_tpu.data_plane.ring import (  # noqa: F401
    DeviceTrajRing,
    RingLease,
    RingState,
    gather_block,
    init_ring,
    make_enqueue,
)
from actor_critic_tpu.data_plane import device_replay  # noqa: F401
