"""Device-resident trajectory ring (ISSUE 13 tentpole).

The PR 6 `TrajQueue` is host numpy by design: every consumed block costs
one host→device transfer on the LEARNER's critical path — exactly like
lockstep, just off-thread for collection. This module keeps the
trajectory data in HBM end to end instead (Accelerated Methods, arxiv
1803.02811: large-batch device-side processing is where the parallelism
lives; IMPACT's per-block surrogate reuse, arxiv 1912.00167, only pays
when the block is already resident):

- **Storage** is a donated ring of fixed-shape encoded blocks living on
  the device: a pytree of `[depth, K, E, ...]` arrays at the codec
  storage dtype (`replay/quantize.py` kinds — raw / f16 / calibrated
  i8 / bool8, selected per block key by `codecs.traj_codecs`).
- **Actors enqueue encoded blocks**: the producer thread quantizes its
  numpy block on the host (`data_plane/codecs.py`, the numpy mirror of
  the quantize codecs — calibrate-then-freeze stats included), puts the
  encoded bytes to the device (int8 obs cross at 1/4 of the fp32
  bytes), and dispatches one donated `enqueue` program that scatters
  the block into its slot. The device-side cursor/version tree
  (`versions`/`seqs`/`count` riding `RingState`) tracks occupancy and
  the behavior-params version each slot was collected under; the host
  keeps a bit-equal mirror (the pending/free bookkeeping below) for
  scheduling decisions, so no device read-back is ever needed to pick
  a slot.
- **The learner gathers + decodes INSIDE its jitted update program**
  (`gather_block`, inlined by `ppo.make_device_update_step` and
  `device_replay.make_device_ingest_update`): steady-state consumption
  performs ZERO host→device transfers — the only traffic is the slot
  index scalar riding the dispatch.

Semantics carry over from `TrajQueue` unchanged: `policy="drop_oldest"`
reclaims the oldest pending slot when the ring is full (actors never
wait on the learner; the drop is counted), `policy="block"` is the
strict mode the lockstep-equivalence tests run under, and
`max_staleness` drops blocks whose behavior version aged past the bound
at `get` time. With the all-`raw` `fp32` codec the decoded block is
bit-identical to the host path's arrays, so `correction="none"` at
depth 1 is bitwise-equal to `train_host` (tests/test_async_host.py).

Donation discipline: `put` dispatches the donating `enqueue` and the
learner dispatches its (non-donating) gather+update under ONE lock, so
dispatch order — which is device execution order — always reads a slot
before the enqueue that overwrites it, and no thread can donate a state
handle another thread is about to dispatch with (the `run()` seam).
jaxlint's donation-aliasing pass covers the enqueue/gather call shapes
(tests/jaxlint_fixtures/donation_aliasing_*.py) and
`analysis/racesan.exercise_device_ring` drives the enqueue-vs-gather
interleavings with a leased-slot poisoner.

Calibration note: while the `i8` stats are still calibrating (first
`quantize.CALIBRATION_TRANSITIONS` transitions), a queued block may
decode under slightly newer stats than it was encoded with — the same
monotone-widening drift window the replay ring accepts, bounded by the
shallow ring depth; after the freeze, decode is exact-per-encode.

Telemetry: the ring registers a `device_ring` gauge (slots ×
bytes/block × codec mix, enqueue-transfer byte counters, and the
TrajQueue-compatible depth/staleness/drop row) with the resource
sampler; `scripts/run_report.py` renders it in Resources.
"""

# jaxlint: hot-module

from __future__ import annotations

import threading
import time
from collections import deque
from functools import partial
from typing import Any, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from actor_critic_tpu.data_plane import codecs as np_codecs
from actor_critic_tpu.replay import quantize
from actor_critic_tpu.utils import compile_cache as _compile_cache


class RingState(NamedTuple):
    """The device half of the ring: encoded block storage plus the
    cursor/version tree.

    `storage` holds one `[depth, ...block shape]` array per block key at
    the codec storage dtype; `quant` mirrors it with one
    `quantize.QuantStats` per key (live stats for `i8` keys, zero
    placeholders elsewhere — structure is codec-independent, so
    checkpoint templates and warmup eval_shapes never fork on the codec
    mode). `versions[slot]` is the behavior-params version the slot's
    block was collected under, `seqs[slot]` its global put sequence
    (occupancy: a slot is live iff its seq is among the newest), and
    `count` the total puts (saturating) — together the device-side
    source of truth the host bookkeeping mirrors."""

    storage: Any
    quant: Any
    versions: jax.Array  # int32 [depth]
    seqs: jax.Array      # int32 [depth]
    count: jax.Array     # int32 scalar


class RingLease(NamedTuple):
    """One consumed block's handle: the slot index to gather (leased
    until `release`) plus the version/actor bookkeeping the learner's
    log rows use — the `TrajBlock` of the device plane, minus the host
    arrays (the data never leaves HBM)."""

    slot: int
    version: int
    actor_id: int
    seq: int


def canonical_dtype(dtype) -> np.dtype:
    """The dtype a leaf actually stores at on this backend: x64-disabled
    jax truncates int64/float64, and the ring's byte accounting + host
    encode must agree with the device storage (the numpy mirror's
    argmax actions arrive int64 and store int32)."""
    return np.dtype(jax.dtypes.canonicalize_dtype(np.dtype(dtype)))


def init_ring(block_spec: dict, depth: int, codec_kinds: dict) -> RingState:
    """Zeroed ring for `depth` blocks shaped like `block_spec` (a dict
    of name → shape/dtype carriers, e.g. jax.ShapeDtypeStruct)."""
    storage = {
        name: jnp.zeros(
            (depth, *block_spec[name].shape),
            quantize.storage_dtype(
                codec_kinds[name],
                canonical_dtype(block_spec[name].dtype),
            ),
        )
        for name in block_spec
    }
    quant = {
        name: quantize.init_stats(
            codec_kinds[name], _item_struct(block_spec[name])
        )
        for name in block_spec
    }
    return RingState(
        storage=storage,
        quant=quant,
        versions=jnp.full((depth,), -1, jnp.int32),
        seqs=jnp.full((depth,), -1, jnp.int32),
        count=jnp.zeros((), jnp.int32),
    )


def _item_struct(leaf):
    """Stats-shape carrier: ring stats are SCALAR per block key (the
    host mirror folds the whole [K, E, ...] block as one batch —
    `np_init_stats(..., ())` — so the device placeholders must match;
    per-feature stats would demand the host re-derive the replay ring's
    item-axis convention for every block layout for no measured win)."""
    return jax.ShapeDtypeStruct((), jnp.dtype(leaf.dtype))


# One process-wide jit object (populated by the first make_enqueue
# call): the program closes over nothing, so every ring shares the
# dispatch cache — N rings with the same block spec compile ONCE, and
# the warmup planner's AOT lower targets the same object the live
# dispatch traces.
# jaxlint: thread-owned=main (first make_enqueue call happens on the
# constructing thread before any actor exists; later calls only read)
_ENQUEUE_JIT: list = []


def make_enqueue():
    """The donated scatter program: writes one encoded block into its
    slot and advances the cursor/version tree in place. One compiled
    program per (block spec × codec) — every actor of a run shares it.
    `quant` is the host's current stats tree, re-uploaded while
    calibrating and constant after the freeze, so the learner's in-jit
    decode always reads the stats the block was encoded against."""
    if _ENQUEUE_JIT:
        return _ENQUEUE_JIT[0]

    @partial(jax.jit, donate_argnums=0)
    def enqueue(state: RingState, encoded: dict, quant: Any,
                slot, version, seq) -> RingState:
        storage = jax.tree.map(
            lambda s, x: s.at[slot].set(x), state.storage, encoded
        )
        return RingState(
            storage=storage,
            quant=quant,
            versions=state.versions.at[slot].set(version),
            seqs=state.seqs.at[slot].set(seq),
            count=state.count + 1,
        )

    _ENQUEUE_JIT.append(enqueue)
    return enqueue


def gather_block(state: RingState, slot, codec_kinds: dict) -> dict:
    """Slot → decoded float block, INSIDE the caller's jitted program
    (dynamic-slice gather + codec decode; `slot` is a traced scalar).
    This is the zero-transfer consume: the learner's update closes over
    this call and the block never exists on the host."""
    return {
        name: quantize.decode(
            codec_kinds[name], state.quant[name], state.storage[name][slot]
        )
        for name in state.storage
    }


class DeviceTrajRing:
    """Host-side coordinator of the device ring: TrajQueue-compatible
    producer/consumer protocol (`put`/`get`/`release`/
    `set_consumer_version`/`stats`/`close`) over device-resident
    storage. `traj_queue.ActorService` pushes into it unchanged; the
    learner drives its jitted gather+update through `run()`.

    `codec` is a `codecs.traj_codecs` mode string ("fp32"/"f16"/"int8")
    or an explicit per-key kind dict. `transfer_pad_s` is a testbed
    knob (the `serving.PolicyEngine(dispatch_pad_s=...)` discipline):
    pads every host→device block transfer with a wall sleep modeling
    the ~26 ms axon tunnel, so the data-plane A/B bench can expose on
    CPU the transfer wall a real accelerator pays — in the device plane
    that wall lands on ACTOR threads at collection time, never on the
    learner.
    """

    def __init__(
        self,
        depth: int,
        block_spec: dict,
        codec: Any = "fp32",
        max_staleness: Optional[int] = None,
        policy: str = "drop_oldest",
        gauge_name: str = "device_ring",
        register_gauge: bool = True,
        transfer_pad_s: float = 0.0,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if policy not in ("drop_oldest", "block"):
            raise ValueError(f"unknown policy {policy!r}")
        if max_staleness is not None and max_staleness < 0:
            raise ValueError("max_staleness must be >= 0 or None")
        self.depth = int(depth)
        self.max_staleness = max_staleness
        self.policy = policy
        self.transfer_pad_s = float(transfer_pad_s)
        self._spec = dict(block_spec)
        self.codecs = (
            np_codecs.traj_codecs(codec, block_spec)
            if isinstance(codec, str) else dict(codec)
        )
        self._np_stats = {
            name: np_codecs.np_init_stats(self.codecs[name], ())
            for name in self._spec
        }
        self._stat_keys = [
            n for n, k in self.codecs.items() if k in quantize.STAT_KINDS
        ]
        # Per-key transitions-per-put for the calibration clock (the
        # freeze threshold is defined in TRANSITIONS, not elements):
        # ring blocks are time-major — every [K, E, ...] key carries
        # K·E transitions per put, and the [E, ...] keys (last_obs,
        # bootstrap_value) carry E. The modal leading pair across the
        # spec IS (K, E); keys not sharing it are the [E, ...] family.
        pairs = [
            tuple(leaf.shape[:2]) for leaf in self._spec.values()
            if len(leaf.shape) >= 2
        ]
        modal = max(set(pairs), key=pairs.count) if pairs else None
        self._transitions_per_put = {
            name: int(
                modal[0] * modal[1]
                if modal is not None and tuple(leaf.shape[:2]) == modal
                else (leaf.shape[0] if leaf.shape else 1)
            )
            for name, leaf in self._spec.items()
        }
        self._cv = threading.Condition()
        self._enqueue = make_enqueue()
        self._state = init_ring(block_spec, depth, self.codecs)
        self._quant_dev = self._state.quant
        self._free: list[int] = list(range(depth))
        self._pending: deque[RingLease] = deque()
        self._leased: set[int] = set()
        self._seq = 0
        self._consumer_version = 0
        self._puts = 0
        self._gets = 0
        self._drops_full = 0
        self._drops_stale = 0
        self._last_staleness = 0
        self._max_staleness_seen = 0
        self._idle_s = 0.0
        self._enqueue_bytes = 0
        self._closed = False
        self._gauge_key: Optional[str] = None
        if register_gauge:
            from actor_critic_tpu.telemetry import sampler as _sampler

            self._gauge_key = _sampler.register_gauge(gauge_name, self.stats)

    # -- byte accounting ---------------------------------------------------

    def bytes_per_block(self) -> int:
        """Encoded bytes one enqueue transfers (the codec-compressed
        figure the gauge row and bench records report)."""
        total = 0
        for name, leaf in self._spec.items():
            n = 1
            for d in leaf.shape:
                n *= d
            total += n * np_codecs.storage_np_dtype(
                self.codecs[name], canonical_dtype(leaf.dtype)
            ).itemsize
        return total

    def raw_bytes_per_block(self) -> int:
        """The same block's bytes at its device-canonical dtypes — what
        the host TrajQueue path transfers per consumed block."""
        total = 0
        for leaf in self._spec.values():
            n = 1
            for d in leaf.shape:
                n *= d
            total += n * canonical_dtype(leaf.dtype).itemsize
        return total

    # -- producer ----------------------------------------------------------

    def put(
        self,
        arrays: dict[str, np.ndarray],
        version: int,
        actor_id: int = 0,
        timeout: Optional[float] = None,
    ) -> bool:
        """Encode `arrays` on the host and scatter them into a ring
        slot on device. True once enqueued; False when no slot freed
        within `timeout` (under `policy="block"`, or drop-oldest with
        every slot leased). The caller's arrays are free to reuse
        immediately (encode copies)."""
        with self._cv:
            if self._closed:
                return False
            stats_changed = False
            for name in self._stat_keys:
                if name in arrays:
                    new = np_codecs.np_update_stats(
                        self.codecs[name], self._np_stats[name],
                        arrays[name],
                        num_transitions=self._transitions_per_put[name],
                    )
                    stats_changed |= new is not self._np_stats[name]
                    self._np_stats[name] = new
            stats = dict(self._np_stats)
            if stats_changed:
                # Small item-shaped tree; re-uploaded only while the
                # calibration window is open, constant after the freeze.
                self._quant_dev = {
                    name: quantize.QuantStats(
                        mean=jnp.asarray(st["mean"]),
                        scale=jnp.asarray(st["scale"]),
                        count=jnp.asarray(st["count"]),
                    )
                    for name, st in stats.items()
                }
        # Encode + transfer OUTSIDE the lock: numpy quantization and the
        # device put are the slow half and must not stall the learner's
        # dispatch seam. The stats snapshot above is immutable
        # (np_update_stats returns fresh arrays), so encoding against it
        # is race-free even while another actor keeps calibrating.
        encoded = {
            # astype to the device-canonical storage dtype BEFORE the
            # put: an int64 mirror action would otherwise ship 8 bytes
            # per element for jax to truncate to 4 on arrival.
            name: np_codecs.np_encode(
                self.codecs[name], stats[name], arrays[name]
            ).astype(
                np_codecs.storage_np_dtype(
                    self.codecs[name], canonical_dtype(self._spec[name].dtype)
                ),
                copy=False,
            )
            for name in self._spec
        }
        if self.transfer_pad_s > 0:
            time.sleep(self.transfer_pad_s)  # tunnel-wall testbed pad
        encoded_dev = jax.device_put(encoded)
        nbytes = sum(v.nbytes for v in encoded.values())
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._closed:
                    return False
                slot = self._claim_slot_locked()
                if slot is not None:
                    break
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(0.1 if remaining is None else min(0.1, remaining))
            seq = self._seq
            self._seq += 1
            # Donating dispatch under the lock: the learner's gather for
            # any other slot is either already dispatched (device order
            # reads it first) or will dispatch against the NEW state.
            # quant is read HERE, not from a pre-encode snapshot: two
            # actors racing through the unlocked encode window could
            # otherwise upload an OLDER stats tree after a newer one,
            # regressing state.quant below what a pending block was
            # encoded with — the current _quant_dev is always the
            # newest (monotone by construction), so any pending block
            # decodes under equal-or-wider stats, the documented drift
            # bound.
            self._state = self._enqueue(
                self._state, encoded_dev, self._quant_dev,
                np.int32(slot), np.int32(version), np.int32(seq),
            )
            self._pending.append(
                RingLease(int(slot), int(version), int(actor_id), seq)
            )
            self._puts += 1
            self._enqueue_bytes += nbytes
            self._cv.notify_all()
            return True

    def _claim_slot_locked(self) -> Optional[int]:
        """A writable slot, or None when the caller must wait: free
        slots first; under drop-oldest a full ring reclaims its oldest
        PENDING block (leased slots are never overwritten — the learner
        may still be reading them); under `policy="block"` a full ring
        always waits."""
        if self.policy == "block":
            if self._in_flight() < self.depth and self._free:
                return self._free.pop()
            return None
        if self._free:
            return self._free.pop()
        if self._pending:
            old = self._pending.popleft()
            self._drops_full += 1
            return old.slot
        return None  # every slot leased: wait for a release

    def _in_flight(self) -> int:
        return len(self._pending) + len(self._leased)

    # -- consumer ----------------------------------------------------------

    def set_consumer_version(self, version: int) -> None:
        with self._cv:
            self._consumer_version = int(version)

    def get(self, timeout: Optional[float] = None) -> Optional[RingLease]:
        """Oldest fresh-enough block's lease (slot stays unwritable
        until `release`), or None after `timeout`. Same staleness-drop
        semantics as TrajQueue.get."""
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = time.monotonic()
        with self._cv:
            try:
                while True:
                    while self._pending:
                        lease = self._pending.popleft()
                        lag = self._consumer_version - lease.version
                        if (
                            self.max_staleness is not None
                            and lag > self.max_staleness
                        ):
                            self._free.append(lease.slot)
                            self._drops_stale += 1
                            self._cv.notify_all()
                            continue
                        self._leased.add(lease.slot)
                        self._gets += 1
                        self._last_staleness = max(lag, 0)
                        self._max_staleness_seen = max(
                            self._max_staleness_seen, self._last_staleness
                        )
                        return lease
                    remaining = (
                        None if deadline is None
                        else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        return None
                    self._cv.wait(
                        0.1 if remaining is None else min(0.1, remaining)
                    )
            finally:
                self._idle_s += time.monotonic() - t0

    def release(self, lease: RingLease) -> None:
        """Return a leased slot to the writable pool (call after the
        LAST update dispatch against it — dispatch order then guarantees
        any later overwrite executes after the reads)."""
        with self._cv:
            self._leased.discard(lease.slot)
            self._free.append(lease.slot)
            self._cv.notify_all()

    def run(self, fn, *args, **kwargs):
        """Dispatch a learner program against the CURRENT ring state:
        `fn(state, *args, **kwargs)` under the ring lock, so no enqueue
        can donate the state handle between fetch and dispatch. The jit
        call inside `fn` returns at enqueue time (async dispatch), so
        the lock is held for dispatch only, never device execution."""
        with self._cv:
            return fn(self._state, *args, **kwargs)

    # -- checkpoint (strip/resume: stats survive, storage never saved) -----

    def quant_host(self) -> dict:
        """The host-side quantizer stats as a plain numpy tree — the
        ONLY part of the ring a checkpoint carries (the PR 8
        `strip_replay` contract, taken to its limit: trajectory blocks
        are transient collection data, so the 'stub' is no storage at
        all, just the calibrate-then-freeze stats a resumed run must
        re-encode against)."""
        with self._cv:
            return {
                name: {k: np.asarray(v) for k, v in st.items()}
                for name, st in self._np_stats.items()
            }

    def install_quant(self, tree: dict) -> None:
        """Adopt restored stats (resume-reattach: fresh storage, the
        run's original standardization)."""
        with self._cv:
            self._np_stats = {
                name: {
                    "mean": np.asarray(st["mean"], np.float32),
                    "scale": np.asarray(st["scale"], np.float32),
                    "count": np.asarray(st["count"], np.int32),
                }
                for name, st in tree.items()
            }
            self._quant_dev = {
                name: quantize.QuantStats(
                    mean=jnp.asarray(st["mean"]),
                    scale=jnp.asarray(st["scale"]),
                    count=jnp.asarray(st["count"]),
                )
                for name, st in self._np_stats.items()
            }
            self._state = self._state._replace(quant=self._quant_dev)

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        with self._cv:
            return len(self._pending)

    def codec_mix(self) -> str:
        return ",".join(f"{n}:{self.codecs[n]}" for n in sorted(self.codecs))

    def stats(self) -> dict:
        """Gauge row: the TrajQueue-compatible depth/staleness/drop
        fields plus the device-ring byte accounting (slots ×
        bytes/block × codec mix; enqueue transfer total; the learner's
        per-consume transfer is structurally zero — only the slot index
        rides the dispatch)."""
        with self._cv:
            return {
                "capacity": self.depth,
                "depth": len(self._pending),
                "leased": len(self._leased),
                "puts": self._puts,
                "gets": self._gets,
                "drops_full": self._drops_full,
                "drops_stale": self._drops_stale,
                "observe_staleness": self._last_staleness,
                "staleness_max": self._max_staleness_seen,
                "learner_idle_s": round(self._idle_s, 3),
                "slots": self.depth,
                "bytes_per_block": self.bytes_per_block(),
                "raw_bytes_per_block": self.raw_bytes_per_block(),
                "enqueue_bytes": self._enqueue_bytes,
                "consume_transfer_bytes": 0,
                "codec_mix": self.codec_mix(),
            }

    def close(self) -> None:
        with self._cv:
            if self._closed:
                return
            self._closed = True
            gauge_key, self._gauge_key = self._gauge_key, None
            self._cv.notify_all()
        if gauge_key is not None:
            from actor_critic_tpu.telemetry import sampler as _sampler

            _sampler.unregister_gauge(gauge_key)


# -- AOT warmup (utils/compile_cache.py registry; ISSUE 13) -----------------

def ctx_block_spec(ctx) -> dict:
    """The block spec a WarmupContext's run will push through the ring
    (shared by this module's enqueue planner and the per-algo update
    planners, so their signatures can never drift apart)."""
    if ctx.algo == "ppo":
        from actor_critic_tpu.algos import ppo

        return ppo.async_block_spec(
            ctx.spec, ctx.cfg, ctx.async_actors, ctx.async_correction
        )
    from actor_critic_tpu.data_plane import device_replay

    return device_replay.offpolicy_block_spec(
        ctx.spec, ctx.cfg, ctx.async_actors
    )


def abstract_ring_state(block_spec: dict, depth: int, kinds: dict):
    """Shape/dtype tree of the ring state via eval_shape (no device
    allocation — a deep pixel ring would otherwise materialize)."""
    return jax.eval_shape(partial(init_ring, block_spec, depth, kinds))


@_compile_cache.register_warmup("ring.make_enqueue")
def _warmup_enqueue(ctx):
    if (
        ctx.data_plane != "device"
        or not ctx.async_actors
        or ctx.fused
        or ctx.algo not in ("ppo", "ddpg", "td3", "sac")
    ):
        return None
    block_spec = ctx_block_spec(ctx)
    kinds = np_codecs.traj_codecs(ctx.plane_codec, block_spec)
    state_abs = abstract_ring_state(block_spec, ctx.queue_depth, kinds)
    encoded = {
        name: _compile_cache.array_struct(
            leaf.shape,
            np_codecs.storage_np_dtype(kinds[name], leaf.dtype),
        )
        for name, leaf in block_spec.items()
    }
    quant_abs = state_abs.quant
    s = _compile_cache.scalar_struct
    jitted = make_enqueue()
    return lambda: _compile_cache.aot_compile(
        jitted, state_abs, encoded, quant_abs,
        s(np.int32), s(np.int32), s(np.int32),
    )
