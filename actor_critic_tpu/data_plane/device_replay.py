"""Device replay plane for the off-policy trainers (ISSUE 13 tentpole,
part 2).

The quantized `ReplayState` ring already lives donated in HBM with
`add_batch`/`sample`/`sample_sequences` fused into the DDPG/TD3/SAC
update programs — but the ASYNC actor–learner drivers still hand each
consumed transition block to the learner as host numpy, paying one
host→device transfer per update cycle on the learner thread. This
module closes that gap: actors stage encoded blocks into a
`data_plane.ring.DeviceTrajRing`, and ONE jitted program per consumed
block gathers + decodes the staged slot, scatters it into the replay
ring, and runs the whole update loop — the learner performs zero
host→device transfers in steady state (only the slot index rides the
dispatch), and the replay ring itself never leaves the device.

Also here: the R2D2-style sequence consumer over
`replay.sample_sequences` (arxiv 1803.0933's burn-in/train window
split), buildable now that the 3.08× mixed-codec capacity supports
long windows — `sample_training_sequences` draws [B, burn_in + L]
windows of consecutive inserts, splits the burn-in prefix (recurrent
warmup; consumers stop gradients through it) from the train window,
and hands back the episode-validity mask consumers weight losses with
(`sequence_window_mask`; the same alive-before-done convention
`ddpg.nstep_batch` masks its n-step returns with, so the two consumers
can never disagree about where an episode ends inside a window). The
wrap/episode-boundary contract itself lives on
`replay.sample_sequences` (documented + tested in tests/test_replay.py
ahead of this consumer).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

from actor_critic_tpu import replay
from actor_critic_tpu.data_plane import ring as dp_ring
from actor_critic_tpu.utils import compile_cache as _compile_cache


def offpolicy_block_spec(spec, cfg, actors: int) -> dict:
    """The [K, E_a] transition-block spec an off-policy ActorService
    pushes (host_collect keys; E_a = num_envs // actors). `last_obs`
    rides along because ActorService records it into every block — the
    ingest ignores it, but the ring spec must match what `put` sees."""
    actors = max(int(actors), 1)
    K = cfg.steps_per_iter
    E = cfg.num_envs // actors
    s = _compile_cache.array_struct
    obs = lambda lead: s((*lead, *spec.obs_shape), spec.obs_dtype)  # noqa: E731
    return {
        "obs": obs((K, E)),
        "action": s((K, E, spec.action_dim), np.float32),
        "reward": s((K, E), np.float32),
        "done": s((K, E), np.float32),
        "terminated": s((K, E), np.float32),
        "final_obs": obs((K, E)),
        "last_obs": obs((E,)),
    }


def make_device_ingest_update(
    make_update_loop,
    action_dim: int,
    cfg,
    ring_codecs: dict,
    min_size: int,
):
    """Jitted `(learner, ring_state, slot, env_steps) → (learner,
    metrics)`: gather + decode the staged block INSIDE the program,
    scatter it into the (donated) replay ring, and run the algo's
    update loop — the device-plane twin of the per-algo
    `make_host_ingest_update`, shared by DDPG/TD3 and SAC through their
    `make_update_loop` factories. `min_size` is the algo's update-gate
    floor (DDPG: max(batch_size, nstep) — n-step windows must never
    clamp into zero-initialized ring slots; SAC: batch_size).

    The learner state is donated (argnum 0, the existing in-place
    replay discipline); the ring state is a READ-ONLY input — its
    donation belongs to the enqueue program, and dispatch ordering
    under the ring lock keeps the two from aliasing (ring.py docstring).
    """
    from actor_critic_tpu.algos.common import OffPolicyTransition

    update_loop = make_update_loop(action_dim, cfg)
    codecs = replay.offpolicy_codecs(cfg.replay_dtype)

    @partial(jax.jit, donate_argnums=0)
    def ingest_update(ls, ring_state: dp_ring.RingState, slot, env_steps):
        block = dp_ring.gather_block(ring_state, slot, ring_codecs)
        traj = OffPolicyTransition(
            obs=block["obs"],
            action=block["action"],
            reward=block["reward"],
            next_obs=block["final_obs"],
            terminated=block["terminated"],
            done=block["done"],
        )
        flat = jax.tree.map(lambda x: x.reshape(-1, *x.shape[2:]), traj)
        rbuf = replay.add_batch(ls.replay, flat, codecs)
        do_update = jnp.logical_and(
            env_steps >= cfg.warmup_steps, rbuf.size >= min_size
        )
        return update_loop(ls._replace(replay=rbuf), do_update)

    return ingest_update


# ---------------------------------------------------------------------------
# R2D2-style sequence consumer (replay.sample_sequences)
# ---------------------------------------------------------------------------

def sequence_window_mask(done: jax.Array) -> jax.Array:
    """[B, L] done flags → float32 validity mask: step t is valid iff
    no episode ended at a step STRICTLY BEFORE t inside the window —
    the step carrying the terminal reward is itself valid (it belongs
    to the episode), everything after it is a different episode and
    must not contribute (the `ddpg.nstep_batch` alive-before
    convention, factored out so every sequence consumer masks
    identically)."""
    d = done.astype(jnp.float32)
    return jnp.cumprod(
        jnp.concatenate([jnp.ones_like(d[:, :1]), 1.0 - d[:, :-1]], axis=1),
        axis=1,
    )


def split_burn_in(seq: Any, burn_in: int):
    """[B, burn_in + L] windows → (burn, train, train_mask): the R2D2
    split — `burn` (None when burn_in == 0) warms recurrent state with
    gradients stopped by the consumer; `train` carries the loss steps;
    `train_mask` is the episode-validity mask over the WHOLE window
    sliced to the train half, so a done inside the burn-in prefix
    correctly invalidates the train steps after it (they belong to the
    next episode — training on them against burn-in state from the
    previous one is the splice this mask exists to prevent)."""
    done = seq.done
    mask = sequence_window_mask(done)
    train = jax.tree.map(lambda x: x[:, burn_in:], seq)
    if burn_in == 0:
        return None, train, mask
    burn = jax.tree.map(lambda x: x[:, :burn_in], seq)
    return burn, train, mask[:, burn_in:]


def sample_training_sequences(
    state: replay.ReplayState,
    key: jax.Array,
    batch_size: int,
    seq_len: int,
    burn_in: int = 0,
    codecs: Optional[Any] = None,
):
    """Draw `batch_size` R2D2-style training windows from the replay
    ring: `burn_in + seq_len` CONSECUTIVE INSERTS per window
    (`replay.sample_sequences` — windows may wrap the physical ring but
    never cross the write-cursor seam; see its contract), split into
    (burn, train, train_mask). Callers ensure
    `size >= burn_in + seq_len` and, as with `DDPGConfig.nstep`, that
    consecutive inserts are one env's consecutive timesteps
    (num_envs == 1 for interleave-free windows)."""
    seq = replay.sample_sequences(
        state, key, batch_size, burn_in + seq_len, codecs
    )
    return split_burn_in(seq, burn_in)


# -- AOT warmup (ISSUE 13: every new jitted entry point has a planner) ------

@_compile_cache.register_warmup("device_replay.make_device_ingest_update")
def _warmup_device_ingest(ctx):
    if (
        ctx.data_plane != "device"
        or not ctx.async_actors
        or ctx.fused
        or ctx.algo not in ("ddpg", "td3", "sac")
    ):
        return None
    from actor_critic_tpu.algos import ddpg, sac
    from actor_critic_tpu.data_plane import codecs as np_codecs

    mod = ddpg if ctx.algo in ("ddpg", "td3") else sac
    cfg = ctx.cfg
    min_size = (
        max(cfg.batch_size, cfg.nstep)
        if hasattr(cfg, "nstep") else cfg.batch_size
    )
    block_spec = offpolicy_block_spec(ctx.spec, cfg, ctx.async_actors)
    kinds = np_codecs.traj_codecs(ctx.plane_codec, block_spec)
    learner_abs = jax.eval_shape(
        partial(
            mod.init_learner, tuple(ctx.spec.obs_shape),
            ctx.spec.action_dim, cfg,
        ),
        jax.random.key(0),
    )
    state_abs = dp_ring.abstract_ring_state(block_spec, ctx.queue_depth, kinds)
    jitted = make_device_ingest_update(
        mod.make_update_loop, ctx.spec.action_dim, cfg, kinds, min_size
    )
    s = _compile_cache.scalar_struct
    return lambda: _compile_cache.aot_compile(
        jitted, learner_abs, state_abs, s(np.int32), s(np.int32)
    )
