"""Host-side numpy mirror of the `replay/quantize.py` codecs, plus the
per-key codec specs for trajectory blocks (ISSUE 13).

The device trajectory ring (`data_plane/ring.py`) moves the encode to
the PRODUCER side: actor threads quantize each collected numpy block on
the host and put only the encoded bytes to the device — int8 obs cross
the tunnel at a quarter of the fp32 bytes, and the learner's in-jit
decode reads them back through the SAME stats the host encoded with
(they ride the ring state next to the storage). That demands a numpy
implementation of `quantize.encode`/`update_stats`: calling the jnp
versions from an actor thread would dispatch a device program per block
— the exact host↔device chatter the data plane exists to remove.

Consistency contract: encode (host numpy, these functions) and decode
(device, `quantize.decode`) always use ONE stats tree — the host
computes it, uploads it with every enqueue while calibrating, and
freezes it after `quantize.CALIBRATION_TRANSITIONS` transitions exactly
like the replay ring's device-side stats. Host/device float divergence
is therefore impossible by construction (nothing is computed twice);
tests/test_data_plane.py pins the round-trip error bounds to the
quantize table regardless.

Codec specs (`traj_codecs`) key on block-array NAMES, not tree
positions, because trajectory blocks are plain dicts whose key set
varies by algorithm and correction mode:

- observation-family keys (obs / final_obs / last_obs / next_obs) carry
  the bulk of every block's bytes and quantize well (f16, or calibrated
  i8);
- reward quantizes as calibrated i8 in the aggressive mode;
- done / terminated are exact {0,1} flags (bool8);
- action, log_prob, value, final_values, bootstrap_value stay raw:
  behavior log-probs feed the V-trace importance ratios and the
  recorded value is the clip anchor — quantizing either biases the
  correction itself, the one unsafe default (the `replay/quantize.py`
  action rationale, applied to the on-policy block).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from actor_critic_tpu.replay import quantize

# Block keys treated as observations by the trajectory-codec presets.
OBS_KEYS = ("obs", "final_obs", "last_obs", "next_obs")
# Keys that must never quantize (see module docstring).
RAW_KEYS = ("action", "log_prob", "value", "final_values", "bootstrap_value")
TRAJ_MODES = ("fp32", "f16", "int8")

_EPS = quantize._EPS
_MEAN_SATURATE = quantize._MEAN_SATURATE


def traj_codecs(mode: str, block_spec: dict[str, Any]) -> dict[str, str]:
    """Per-key codec-kind dict for a trajectory block shaped like
    `block_spec` (any mapping of name → array-like with a dtype).

    `fp32` is all-raw (the bitwise-equivalence mode); `f16` halves the
    observation bytes; `int8` additionally standardizes observations and
    rewards to calibrated int8 and packs the flags (the smallest
    enqueue, ~4x on the obs-dominated leaves).
    """
    if mode not in TRAJ_MODES:
        raise ValueError(
            f"data-plane codec must be one of {TRAJ_MODES}, got {mode!r}"
        )
    out: dict[str, str] = {}
    for name, leaf in block_spec.items():
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        if mode == "fp32" or name in RAW_KEYS or dtype != np.float32:
            # Non-float leaves (discrete int actions, uint8 pixel obs)
            # pass through: uint8 is already dense and int actions are
            # exact by requirement.
            out[name] = "raw"
        elif name in OBS_KEYS:
            out[name] = "f16" if mode == "f16" else "i8"
        elif name == "reward":
            out[name] = "i8" if mode == "int8" else "raw"
        elif name in ("done", "terminated"):
            out[name] = "bool8" if mode == "int8" else "raw"
        else:
            out[name] = "raw"
    return out


# ---------------------------------------------------------------------------
# numpy stats (calibrate-then-freeze, mirroring quantize.update_stats)
# ---------------------------------------------------------------------------

def np_init_stats(kind: str, item_shape: tuple[int, ...]) -> dict:
    """Zeroed numpy stats slot, same shape policy as quantize.init_stats
    (item-shaped mean/scale for `i8`, scalar placeholders otherwise,
    scale seeded at the _EPS floor)."""
    shape = tuple(item_shape) if kind in quantize.STAT_KINDS else ()
    return {
        "mean": np.zeros(shape, np.float32),
        "scale": np.full(shape, _EPS, np.float32),
        "count": np.zeros((), np.int32),
    }


def np_update_stats(
    kind: str, stats: dict, batch: np.ndarray,
    num_transitions: int | None = None,
) -> dict:
    """Fold one batch into the running stats (no-op for stat-free
    codecs): cumulative-average mean + monotone running-max scale, both
    FROZEN once `quantize.CALIBRATION_TRANSITIONS` transitions have been
    absorbed — the replay ring's calibrate-then-freeze contract, on the
    host.

    `num_transitions` is how many TRANSITIONS this batch represents —
    the unit the freeze threshold is defined in (`quantize.QuantStats`:
    "transitions absorbed"). The ring's stats are scalar-shaped, so the
    default element count would inflate a [K, E, obs_dim] block by the
    feature dim and freeze the calibration window obs_dim× too early
    (before the random warmup the freeze rationale depends on);
    `DeviceTrajRing` passes the per-key transition count derived from
    its block layout. With a constant feature size per key,
    transition-weighting and element-weighting produce the identical
    cumulative mean — only the freeze clock differs."""
    if kind not in quantize.STAT_KINDS:
        return stats
    count = int(stats["count"])
    if count >= quantize.CALIBRATION_TRANSITIONS:
        return stats  # frozen
    x = np.asarray(batch, np.float32)
    item_ndim = stats["mean"].ndim
    axes = tuple(range(x.ndim - item_ndim))
    b = 1
    for a in axes:
        b *= x.shape[a]
    n = b if num_transitions is None else int(num_transitions)
    w = np.float32(n) / np.float32(max(count + n, 1))
    mean = (stats["mean"] + (x.mean(axis=axes, dtype=np.float32)
                             - stats["mean"]) * w).astype(np.float32)
    absmax = np.abs(x - mean).max(axis=axes).astype(np.float32)
    scale = np.maximum(np.maximum(stats["scale"], absmax),
                       np.float32(_EPS))
    return {
        "mean": mean,
        "scale": scale,
        "count": np.asarray(min(count + n, _MEAN_SATURATE), np.int32),
    }


# jaxlint: disable=precision-discipline (audited fork: numpy twin of
# quantize.encode — same storage-dtype-forks-on-kind contract, same
# ring-allocated-with-the-same-kind consumer guarantee)
def np_encode(kind: str, stats: dict, x: np.ndarray) -> np.ndarray:
    """One host leaf → its stored representation (numpy twin of
    quantize.encode; the device decodes with the same stats).

    Saturates exactly like the device codec (see `quantize.encode`):
    out-of-range values clip to the representable range before the
    narrowing cast (an unclipped float→int8 cast WRAPS; float16
    overflows to inf); NaN narrows deterministically through nan_to_num
    on the int8 paths and propagates verbatim through f16 — identity
    for every finite in-range value, so the host-encode ==
    device-encode bit-exactness contract is unchanged."""
    if kind == "raw":
        return np.asarray(x)
    if kind == "f16":
        f16_max = float(np.finfo(np.float16).max)
        return np.clip(x, -f16_max, f16_max).astype(np.float16)
    if kind == "bool8":
        return np.round(
            np.clip(np.nan_to_num(x), 0.0, 1.0)
        ).astype(np.int8)
    if kind == "i8_unit":
        q = np.clip(
            np.nan_to_num(np.asarray(x, np.float32)), -1.0, 1.0
        ) * 127.0
        return np.round(q).astype(np.int8)
    if kind == "i8":
        z = (np.asarray(x, np.float32) - stats["mean"]) / stats["scale"]
        return np.round(
            np.clip(np.nan_to_num(z), -1.0, 1.0) * 127.0
        ).astype(np.int8)
    raise ValueError(f"unknown codec kind {kind!r}; valid: {quantize.KINDS}")


# jaxlint: disable=precision-discipline (audited fork: numpy twin of
# quantize.decode — raw passes the storage dtype through by design,
# uint8 pixel obs must reach the torso un-floated)
def np_decode(kind: str, stats: dict, q: np.ndarray) -> np.ndarray:
    """Numpy twin of quantize.decode (tests cross-check it against the
    device decode; the trainers only ever decode on device)."""
    if kind == "raw":
        return np.asarray(q)
    if kind == "f16":
        return np.asarray(q, np.float32)
    if kind == "bool8":
        return np.asarray(q, np.float32)
    if kind == "i8_unit":
        return np.asarray(q, np.float32) / 127.0
    if kind == "i8":
        return (np.asarray(q, np.float32) * (stats["scale"] / 127.0)
                + stats["mean"]).astype(np.float32)
    raise ValueError(f"unknown codec kind {kind!r}; valid: {quantize.KINDS}")


def storage_np_dtype(kind: str, dtype) -> np.dtype:
    """Numpy storage dtype for one leaf (mirrors quantize.storage_dtype)."""
    if kind == "raw":
        return np.dtype(dtype)
    if kind == "f16":
        return np.dtype(np.float16)
    if kind in ("i8", "i8_unit", "bool8"):
        return np.dtype(np.int8)
    raise ValueError(f"unknown codec kind {kind!r}; valid: {quantize.KINDS}")
