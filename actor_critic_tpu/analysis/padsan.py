"""padsan: deterministic padding-lane poison sanitizer (ISSUE 20
runtime half).

numsan proved the stack's response to poisoned VALUES; this module
proves the stack's *indifference* to poisoned PADDING. Every
shape-stabilization seam in the repo widens a ragged batch to a
compiled shape — bucket rows (`pad_to_bucket`), Mosaic lanes
(`pallas_scan._pad_lanes`), parked mixture members, fixed-shape
data-plane slots, masked chunk tails — and the mask discipline the
static passes lint (`pad-mask-discipline` / `mask-propagation` /
`slice-before-commit` in analysis/shapes.py) claims the junk lanes are
NEVER observable. padsan tests that claim the only way it can be
tested: run each REAL steady-state program TWICE per seeded schedule —
once with the pad lanes zeroed (the production fill) and once with
them poisoned from the menu

    nan      quiet NaN (the loudest possible junk: one leak NaN-ifies
             a reduction)
    big      +3e38 (near-f32-max: overflows any sum it touches)
    -big     -3e38
    int8sat  127.0, and the int-storage saturation point (±127/-128)
             for integer lanes the float menu cannot express

— and assert the valid-lane outputs are BITWISE identical. Zero vs
NaN vs 3e38 in a lane that is truly masked/sliced/unselected cannot
change a single output byte; any divergence is a junk-lane leak and
raises `PadSanError` naming the seed/scenario/poison for replay.

The five guarded programs (the steady-state paths, not toys):

- **chunked** — `make_chunked_step(...).masked`: the tail/realignment
  dispatch pads to the full stride and cuts with a traced `n_valid`;
  poisoned post-`n_valid` scan slots are computed-then-discarded by a
  select, which must be lane-exact even for NaN.
- **pallas** — the `ops.pallas_scan` GAE/λ/V-trace kernels at ragged
  E ∈ {7, 96, 200} (lane-padded to 128/128/256): poison is injected
  through the `_pad_lanes` seam and the sliced [:, :E] outputs must
  not move (per-env-column recurrences are independent by design).
- **mixture** — the heterogeneous fleet's `lax.switch` step: the
  3 parked member states are poison-filled and the live member's
  transition plus the mask-multiplied padded obs must be unchanged.
- **serving** — `PolicyEngine.act` across buckets with ragged n
  (standby backfill rows): poison rides the `pad_to_bucket` seam and
  the first-n actions must match the zero-fill dispatch bitwise.
- **device-plane** — `DeviceTrajRing` + in-jit `gather_block`: every
  slot EXCEPT the leased one is poison-filled and the gathered decode
  must be unchanged (the slot gather reads exactly one row).

Every schedule also routes a guard summary of the padded buffer
through the `masked_summary` seam (the sanctioned where-select masked
mean). **Reverted modes** prove the detectors work: `revert=
"unmasked-mean"` swaps the seam for a plain mean — the zero-fill and
poison-fill summaries then differ on every schedule and padsan must
CATCH it; `revert="no-slice"` (pallas, serving) compares the FULL
padded width instead of the valid slice — the junk lanes differ by
construction and must be caught. Both are regression-tested like
racesan/numsan/perfsan's reverted modes.

A clean schedule appends to `report["trace"]`, and `report["digest"]`
is a sha256 over the trace that is bit-identical per seed (same seed →
same poisons, same lanes, same observed bytes — replay a named seed to
reproduce). `quick_profile` is the fixed-seed sweep `scripts/tier1.sh`
runs between perfsan and the multihost smoke, under its own timeout.
"""

from __future__ import annotations

import hashlib
import random
from typing import Iterable, Optional

import numpy as np

POISONS = ("nan", "big", "-big", "int8sat")
_VALUES = {
    "nan": float("nan"),
    "big": 3.0e38,
    "-big": -3.0e38,
    "int8sat": 127.0,
}

# Which reverted-guard modes each scenario supports: every scenario
# carries a masked summary (so unmasked-mean is universal); only the
# two slice-back seams have a full-width output to "forget" to slice.
SCENARIO_REVERTS = {
    "chunked": ("unmasked-mean",),
    "pallas": ("unmasked-mean", "no-slice"),
    "mixture": ("unmasked-mean",),
    "serving": ("unmasked-mean", "no-slice"),
    "device-plane": ("unmasked-mean",),
}


class PadSanError(RuntimeError):
    """A junk lane leaked into a valid-lane output — or a reverted
    mask/slice guard's leak was detected (the sanitizer working)."""


def _check_revert(scenario: str, revert: Optional[str]) -> None:
    if revert is not None and revert not in SCENARIO_REVERTS[scenario]:
        raise ValueError(
            f"scenario {scenario!r} supports revert modes "
            f"{SCENARIO_REVERTS[scenario]}, got {revert!r}"
        )


def _fill(poison: str, dtype) -> float:
    """The poison fill for one storage dtype. Float lanes take the menu
    value; integer lanes (int8 ring storage, int action planes) take
    the dtype's saturation point — NaN/3e38 are not representable and a
    silent numpy wrap would make the poison seed-dependent garbage."""
    dt = np.dtype(dtype)
    if np.issubdtype(dt, np.floating):
        return _VALUES[poison]
    info = np.iinfo(dt)
    return float(info.min if poison == "-big" else info.max)


def masked_summary(x, mask, revert: Optional[str] = None) -> bytes:
    """The guard summary every schedule routes its padded buffer
    through: a where-select masked mean (the idiom
    `pad-mask-discipline` sanctions — NaN-safe, a multiply-mask would
    propagate 0*NaN). Returns the f64 BYTES so the A/B comparison is
    bitwise, NaN included. `revert="unmasked-mean"` is the reverted
    guard: a plain mean that reads the junk lanes."""
    x = np.asarray(x, np.float64)
    mask = np.broadcast_to(np.asarray(mask, np.float64), x.shape)
    if revert == "unmasked-mean":
        out = np.float64(np.mean(x))
    else:
        kept = np.where(mask > 0.0, x, 0.0)
        out = np.float64(np.sum(kept) / max(float(np.sum(mask)), 1.0))
    return out.tobytes()


def _assert_bitwise(a, b, what: str, seed: int, scenario: str,
                    poison: str, report: dict) -> None:
    a, b = np.asarray(a), np.asarray(b)
    same = (
        a.dtype == b.dtype and a.shape == b.shape
        and a.tobytes() == b.tobytes()
    )
    if not same:
        report["violations"] += 1
        raise PadSanError(
            f"seed {seed}: {scenario}/{poison} poison LEAKED into "
            f"{what} — zero-fill and poison-fill runs differ "
            "(a junk lane is observable; the mask/slice/select "
            "discipline is broken at this seam)"
        )


def _assert_summary(sa: bytes, sb: bytes, seed: int, scenario: str,
                    poison: str, revert: Optional[str],
                    report: dict) -> None:
    """The masked-summary detector: under the real seam A == B; under
    the reverted unmasked mean the poison is visible and MUST differ."""
    if revert == "unmasked-mean":
        if sa != sb:
            report["violations"] += 1
            raise PadSanError(
                f"seed {seed}: REVERTED GUARD DETECTED — the unmasked "
                f"mean read the {poison} junk lanes of the {scenario} "
                "pad buffer (zero-fill and poison-fill summaries "
                "differ); the masked where-select summary is the only "
                "thing keeping pad lanes unobservable"
            )
        raise PadSanError(  # pragma: no cover - poison fills are nonzero
            f"seed {seed}: {scenario} unmasked-mean revert NOT caught"
        )
    if sa != sb:
        report["violations"] += 1
        raise PadSanError(
            f"seed {seed}: {scenario}/{poison} poison moved the MASKED "
            "summary — the where-select mask is not covering the pad "
            "lanes"
        )


def _is_float_leaf(a) -> bool:
    """True for float-dtype array leaves; typed PRNG keys (whose
    extended dtype `np.dtype` rejects) and int/bool leaves are not
    poison targets."""
    try:
        return np.issubdtype(np.dtype(a.dtype), np.floating)
    except TypeError:
        return False


def _leaf_np(leaf):
    """Host bytes of one pytree leaf — typed PRNG keys go through
    `key_data` so they stay byte-comparable."""
    import jax

    try:
        np.dtype(leaf.dtype)
    except TypeError:
        leaf = jax.random.key_data(leaf)
    return np.asarray(jax.device_get(leaf))


def _digest(report: dict) -> str:
    return hashlib.sha256(
        repr((report["seed"], report["scenario"], report["trace"]))
        .encode()
    ).hexdigest()


def _sha(a) -> str:
    a = np.asarray(a)
    return hashlib.sha256(a.tobytes()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# chunked exerciser: make_chunked_step's masked tail program
# ---------------------------------------------------------------------------

_CHUNK_STRIDE, _CHUNK_D = 8, 6
_CHUNK_FIXTURE = None


def _chunk_fixture():
    """One REAL masked chunk program (compile_cache.make_chunked_step),
    compiled once per process: state carries the per-slot input plane
    `xs` so poisoned post-`n_valid` rows flow through the
    computed-then-discarded branch of the select."""
    global _CHUNK_FIXTURE
    if _CHUNK_FIXTURE is not None:
        return _CHUNK_FIXTURE
    import jax.numpy as jnp

    from actor_critic_tpu.utils import compile_cache

    def raw_step(s):
        x = s["xs"][s["i"]]
        acc = s["acc"] + jnp.tanh(x) * 0.5
        new = {"i": s["i"] + 1, "xs": s["xs"], "acc": acc}
        return new, {"acc_sum": jnp.sum(acc)}

    _CHUNK_FIXTURE = compile_cache.make_chunked_step(
        raw_step, _CHUNK_STRIDE
    )
    return _CHUNK_FIXTURE


def exercise_chunked(seed: int, revert: Optional[str] = None,
                     rounds: int = 2) -> dict:
    """Poisoned tail slots through the REAL masked chunk dispatch: the
    scan applies `raw_step` to every slot and discards the post-
    `n_valid` carries with a select, so a poisoned slot's NaN/3e38 is
    computed and thrown away — the final carry and the last-valid
    metrics slice must be bitwise those of the zero-padded run."""
    _check_revert("chunked", revert)
    import jax
    import jax.numpy as jnp

    step = _chunk_fixture()
    rng = random.Random(seed)
    report = {
        "seed": seed, "scenario": "chunked", "revert": revert,
        "programs": 0, "violations": 0, "trace": [],
    }
    for round_ in range(rounds):
        nprng = np.random.default_rng(seed * 61 + round_)
        k = rng.randrange(1, _CHUNK_STRIDE)  # always a partial chunk
        poison = POISONS[rng.randrange(len(POISONS))]
        xs = (nprng.normal(size=(_CHUNK_STRIDE, _CHUNK_D)) * 0.5).astype(
            np.float32
        )
        xs[k:] = 0.0
        xs_p = xs.copy()
        xs_p[k:] = _fill(poison, np.float32)
        outs = []
        for buf in (xs, xs_p):
            # fresh state per run: both programs donate their carry
            state = {
                "i": jnp.zeros((), jnp.int32),
                "xs": jnp.asarray(buf),
                "acc": jnp.zeros((_CHUNK_D,), jnp.float32),
            }
            state, metrics = step(state, k)
            outs.append((
                np.asarray(jax.device_get(state["acc"])),
                np.asarray(jax.device_get(metrics["acc_sum"])),
            ))
            report["programs"] += 1
        (acc_a, m_a), (acc_b, m_b) = outs
        _assert_bitwise(
            acc_a, acc_b, "the masked chunk carry", seed, "chunked",
            poison, report,
        )
        _assert_bitwise(
            m_a, m_b, "the last-valid metrics slice", seed, "chunked",
            poison, report,
        )
        row_mask = (np.arange(_CHUNK_STRIDE) < k).astype(np.float64)
        _assert_summary(
            masked_summary(xs, row_mask[:, None], revert),
            masked_summary(xs_p, row_mask[:, None], revert),
            seed, "chunked", poison, revert, report,
        )
        report["trace"].append((round_, k, poison, _sha(acc_a), _sha(m_a)))
    report["digest"] = _digest(report)
    return report


# ---------------------------------------------------------------------------
# pallas exerciser: the GAE/λ/V-trace kernels at ragged env batches
# ---------------------------------------------------------------------------

_PALLAS_ES = (7, 96, 200)  # lane-padded to 128 / 128 / 256
_PALLAS_T = 4
_PALLAS_OPS = ("gae", "lambda", "vtrace")


def _pallas_inputs(op: str, E: int, nprng) -> dict:
    T = _PALLAS_T
    f = lambda scale: (nprng.normal(size=(T, E)) * scale).astype(
        np.float32
    )
    ins = {
        "rewards": f(1.0),
        "values": f(0.5),
        "dones": (nprng.random((T, E)) < 0.15).astype(np.float32),
        "bootstrap_value": (nprng.normal(size=(E,)) * 0.5).astype(
            np.float32
        ),
    }
    if op == "vtrace":
        ins["target_log_probs"] = f(0.1) - 0.7
        ins["behaviour_log_probs"] = f(0.1) - 0.7
    return ins


def _pallas_call(op: str, ins: dict):
    from actor_critic_tpu.ops import pallas_scan

    if op == "gae":
        return pallas_scan.gae(
            ins["rewards"], ins["values"], ins["dones"],
            ins["bootstrap_value"], 0.99, 0.95,
        )
    if op == "lambda":
        return (pallas_scan.lambda_returns(
            ins["rewards"], ins["values"], ins["dones"],
            ins["bootstrap_value"], 0.99, 0.95,
        ),)
    return tuple(pallas_scan.vtrace(
        ins["target_log_probs"], ins["behaviour_log_probs"],
        ins["rewards"], ins["values"], ins["dones"],
        ins["bootstrap_value"], 0.99,
    ))


def exercise_pallas(seed: int, revert: Optional[str] = None,
                    rounds: int = 2) -> dict:
    """Poison through the `_pad_lanes` seam of the REAL Pallas scans at
    ragged E (the slice-back always engages): the B-run monkeypatches
    `pallas_scan._pad_lanes` to fill the added lanes with the poison
    instead of zeros, and the sliced [:, :E] outputs must not move —
    each env column is an independent recurrence, so a pad-lane value
    can only be observed if the slice-back or lane tiling is broken.
    `revert="no-slice"` replays the missing-slice bug explicitly: the
    kernel is launched at the already-padded width (no internal
    pad/slice) and the FULL-width outputs are compared — the junk lanes
    differ by construction and must be caught."""
    _check_revert("pallas", revert)
    import jax.numpy as jnp

    from actor_critic_tpu.ops import pallas_scan

    rng = random.Random(seed)
    report = {
        "seed": seed, "scenario": "pallas", "revert": revert,
        "programs": 0, "violations": 0, "trace": [],
    }
    for round_ in range(rounds):
        nprng = np.random.default_rng(seed * 67 + round_)
        op = _PALLAS_OPS[rng.randrange(len(_PALLAS_OPS))]
        E = _PALLAS_ES[rng.randrange(len(_PALLAS_ES))]
        poison = POISONS[rng.randrange(len(POISONS))]
        fill = _fill(poison, np.float32)
        ins = _pallas_inputs(op, E, nprng)
        Ep = pallas_scan._pad_env(E)
        assert pallas_scan.kernel_block(
            "lambda" if op == "lambda" else op, _PALLAS_T, E
        ) > 0, "kernel must engage for the schedule to test anything"

        if revert == "no-slice":
            # Explicit replica of the missing slice-back: launch at the
            # padded width (Ep is already a 128 multiple, so the kernel
            # neither pads nor slices) and compare EVERY lane.
            outs = []
            for pad_fill in (0.0, fill):
                wide = {
                    k: _np_pad_lanes(v, Ep, pad_fill)
                    for k, v in ins.items()
                }
                outs.append(_pallas_call(op, {
                    k: jnp.asarray(v) for k, v in wide.items()
                }))
                report["programs"] += 1
            for a, b in zip(*outs):
                a, b = np.asarray(a), np.asarray(b)
                if a.tobytes() != b.tobytes():
                    report["violations"] += 1
                    raise PadSanError(
                        f"seed {seed}: REVERTED GUARD DETECTED — "
                        f"committing the full Ep={Ep} width of the "
                        f"{op} kernel exposes the {poison} pad lanes "
                        "(zero-fill and poison-fill outputs differ); "
                        "the [:, :E] slice-back is the guard"
                    )
            raise PadSanError(  # pragma: no cover - lanes always differ
                f"seed {seed}: pallas no-slice revert NOT caught"
            )

        orig = pallas_scan._pad_lanes
        try:
            out_a = _pallas_call(
                op, {k: jnp.asarray(v) for k, v in ins.items()}
            )
            report["programs"] += 1

            def poisoned_pad_lanes(ep, *arrays):
                out = []
                for a in arrays:
                    pad = ep - a.shape[-1]
                    out.append(jnp.concatenate(
                        [a, jnp.full(
                            a.shape[:-1] + (pad,), fill, a.dtype
                        )],
                        axis=-1,
                    ) if pad else a)
                return out

            pallas_scan._pad_lanes = poisoned_pad_lanes
            out_b = _pallas_call(
                op, {k: jnp.asarray(v) for k, v in ins.items()}
            )
            report["programs"] += 1
        finally:
            pallas_scan._pad_lanes = orig
        for i, (a, b) in enumerate(zip(out_a, out_b)):
            _assert_bitwise(
                a, b, f"{op} output {i} (valid lanes)", seed, "pallas",
                poison, report,
            )
        lane_mask = (np.arange(Ep) < E).astype(np.float64)
        wide_a = _np_pad_lanes(ins["rewards"], Ep, 0.0)
        wide_b = _np_pad_lanes(ins["rewards"], Ep, fill)
        _assert_summary(
            masked_summary(wide_a, lane_mask[None, :], revert),
            masked_summary(wide_b, lane_mask[None, :], revert),
            seed, "pallas", poison, revert, report,
        )
        report["trace"].append(
            (round_, op, E, poison, [_sha(a) for a in out_a])
        )
    report["digest"] = _digest(report)
    return report


def _np_pad_lanes(a: np.ndarray, Ep: int, fill: float) -> np.ndarray:
    """Host-side twin of `pallas_scan._pad_lanes` with a chosen fill."""
    pad = Ep - a.shape[-1]
    if pad == 0:
        return a
    wide = np.full(a.shape[:-1] + (Ep,), fill, a.dtype)
    wide[..., : a.shape[-1]] = a
    return wide


# ---------------------------------------------------------------------------
# mixture exerciser: parked members of the lax.switch fleet step
# ---------------------------------------------------------------------------

_MIX_FIXTURE = None


def _mixture_fixture():
    """The REAL 4-type mixture env with jitted reset/step, built once
    per process (one switch program covers every type — the traced
    type_id compile-once contract)."""
    global _MIX_FIXTURE
    if _MIX_FIXTURE is not None:
        return _MIX_FIXTURE
    import jax

    from actor_critic_tpu.envs.mixture import make_mixture

    env = make_mixture("cartpole,pendulum,acrobot,maze")
    _MIX_FIXTURE = (
        env, jax.jit(env.reset_typed), jax.jit(env.step)
    )
    return _MIX_FIXTURE


def _fill_members(members, live: int, fill: float):
    """Every float leaf of every PARKED member state set to `fill`
    (non-float leaves — step counters, PRNG keys — pass through)."""
    import jax
    import jax.numpy as jnp

    def one(m):
        return jax.tree.map(
            lambda a: jnp.full_like(a, fill) if _is_float_leaf(a) else a,
            m,
        )

    return tuple(
        m if i == live else one(m) for i, m in enumerate(members)
    )


def _member_float_plane(members, live: int):
    """(flat f64 values, validity mask) over every float leaf of every
    member — the padded buffer the guard summary reads (live lanes
    valid, parked lanes junk)."""
    import jax

    vals, mask = [], []
    for i, m in enumerate(members):
        for leaf in jax.tree.leaves(m):
            if not _is_float_leaf(leaf):
                continue
            flat = np.asarray(jax.device_get(leaf), np.float64).ravel()
            vals.append(flat)
            mask.append(np.full(flat.shape, float(i == live)))
    return np.concatenate(vals), np.concatenate(mask)


def exercise_mixture(seed: int, revert: Optional[str] = None,
                     rounds: int = 2) -> dict:
    """Poisoned PARKED members through the REAL mixture step: the
    heterogeneous fleet keeps every member type's state resident and
    `lax.switch` steps only the live one, so a parked slot is the
    mixture's padding lane. Filling the 3 parked states with the poison
    must leave the live transition (obs/reward/done/info and the live
    member's next state) bitwise unchanged, and the mask-multiplied
    padded obs must keep its dead lanes at exactly 0.0."""
    _check_revert("mixture", revert)
    import jax
    import jax.numpy as jnp

    env, reset_t, step = _mixture_fixture()
    n_types = len(env.member_names)
    rng = random.Random(seed)
    report = {
        "seed": seed, "scenario": "mixture", "revert": revert,
        "programs": 0, "violations": 0, "trace": [],
    }
    for round_ in range(rounds):
        live = rng.randrange(n_types)
        poison = POISONS[rng.randrange(len(POISONS))]
        fill = _fill(poison, np.float32)
        key = jax.random.key(seed * 73 + round_)
        state, _obs0 = reset_t(key, jnp.asarray(live, jnp.int32))
        action = jnp.asarray(
            rng.randrange(env.spec.action_dim), jnp.int32
        )
        outs = []
        for pad_fill in (0.0, fill):
            s = state._replace(
                members=_fill_members(state.members, live, pad_fill)
            )
            out = step(s, action)
            report["programs"] += 1
            outs.append(out)
        out_a, out_b = outs
        for name, a, b in (
            ("obs", out_a.obs, out_b.obs),
            ("reward", out_a.reward, out_b.reward),
            ("done", out_a.done, out_b.done),
            ("terminated", out_a.info["terminated"],
             out_b.info["terminated"]),
            ("final_obs", out_a.info["final_obs"],
             out_b.info["final_obs"]),
        ):
            _assert_bitwise(
                jax.device_get(a), jax.device_get(b),
                f"the live transition's {name}", seed, "mixture",
                poison, report,
            )
        for la, lb in zip(
            jax.tree.leaves(out_a.state.members[live]),
            jax.tree.leaves(out_b.state.members[live]),
        ):
            _assert_bitwise(
                _leaf_np(la), _leaf_np(lb),
                "the live member's next state", seed, "mixture",
                poison, report,
            )
        # the obs mask contract: dead lanes exactly 0.0 even under
        # poison (the inline mask-multiply in mixture._pad)
        width = env.member_specs[live].obs_shape[0]
        dead = np.asarray(jax.device_get(out_b.obs))[width:]
        if dead.size and (dead != 0.0).any():
            report["violations"] += 1
            raise PadSanError(
                f"seed {seed}: mixture/{poison} poison reached the "
                f"padded obs lanes past width {width} — the mask "
                "multiply in mixture._pad is not holding them at 0.0"
            )
        va, ma = _member_float_plane(
            state._replace(
                members=_fill_members(state.members, live, 0.0)
            ).members, live,
        )
        vb, _ = _member_float_plane(
            state._replace(
                members=_fill_members(state.members, live, fill)
            ).members, live,
        )
        _assert_summary(
            masked_summary(va, ma, revert),
            masked_summary(vb, ma, revert),
            seed, "mixture", poison, revert, report,
        )
        report["trace"].append(
            (round_, env.member_names[live], poison,
             _sha(jax.device_get(out_a.obs)))
        )
    report["digest"] = _digest(report)
    return report


# ---------------------------------------------------------------------------
# serving exerciser: PolicyEngine.act across buckets with backfill rows
# ---------------------------------------------------------------------------

_SERVE_FIXTURE = None


def _serving_fixture():
    """One REAL warmed PolicyEngine, built once per process. The ddpg
    tanh actor (point-mass spec) is deliberate: its pad-row outputs
    under poison (tanh(±huge) = ±1.0, NaN stays NaN) always differ
    bitwise from the zero-fill rows (exactly 0.0 at init-scale
    params), so the no-slice revert is caught on EVERY schedule — a
    discrete argmax could coincide."""
    global _SERVE_FIXTURE
    if _SERVE_FIXTURE is not None:
        return _SERVE_FIXTURE
    from actor_critic_tpu.algos.ddpg import DDPGConfig
    from actor_critic_tpu.envs.testbeds import make_point_mass
    from actor_critic_tpu.serving import engine as serving

    spec = make_point_mass().spec
    cfg = DDPGConfig(hidden=(16, 16))
    eng = serving.PolicyEngine(
        spec, cfg, algo="ddpg", buckets=(1, 2, 4, 8)
    )
    params = serving.init_params(spec, cfg, "ddpg", seed=0)
    eng.warm(params)
    _SERVE_FIXTURE = (eng, params)
    return _SERVE_FIXTURE


def exercise_serving(seed: int, revert: Optional[str] = None,
                     rounds: int = 2) -> dict:
    """Poisoned bucket-backfill rows through the REAL `PolicyEngine.act`
    dispatch: ragged n pads to its bucket through `pad_to_bucket`, and
    the B-run's seam wrapper fills those standby rows with the poison —
    the n returned actions must be bitwise those of the zero-fill
    dispatch (the MLP is row-independent and act slices [:n]).
    `revert="no-slice"` dispatches the same padded batch directly and
    compares the FULL bucket width: the junk-row actions differ by
    construction and must be caught."""
    _check_revert("serving", revert)
    import jax

    from actor_critic_tpu.utils import compile_cache

    eng, params = _serving_fixture()
    rng = random.Random(seed)
    report = {
        "seed": seed, "scenario": "serving", "revert": revert,
        "programs": 0, "violations": 0, "trace": [],
    }
    for round_ in range(rounds):
        nprng = np.random.default_rng(seed * 79 + round_)
        n = (3, 5, 6, 7)[rng.randrange(4)]  # never a bucket size:
        poison = POISONS[rng.randrange(len(POISONS))]  # backfill engages
        fill = _fill(poison, np.float32)
        obs = (nprng.normal(size=(n, 1)) * 0.7).astype(np.float32)
        padded, mask = compile_cache.pad_to_bucket(obs, eng.buckets)
        padded_p = padded.copy()
        padded_p[n:] = fill

        if revert == "no-slice":
            outs = []
            for batch in (padded, padded_p):
                out = jax.device_get(
                    eng._program(params, jax.device_put(batch))
                )
                report["programs"] += 1
                outs.append(np.asarray(out))
            if outs[0].tobytes() != outs[1].tobytes():
                report["violations"] += 1
                raise PadSanError(
                    f"seed {seed}: REVERTED GUARD DETECTED — returning "
                    f"the full bucket width exposes the {poison} "
                    f"standby rows past n={n} (zero-fill and "
                    "poison-fill actions differ); act()'s [:n] slice "
                    "is the guard"
                )
            raise PadSanError(  # pragma: no cover - rows always differ
                f"seed {seed}: serving no-slice revert NOT caught"
            )

        acts_a = eng.act(params, obs)
        report["programs"] += 1
        orig = compile_cache.pad_to_bucket

        def poisoned_pad(x, buckets, axis=0):
            out, m = orig(x, buckets, axis)
            out = np.array(out)
            out[x.shape[0]:] = fill
            return out, m

        compile_cache.pad_to_bucket = poisoned_pad
        try:
            acts_b = eng.act(params, obs)
            report["programs"] += 1
        finally:
            compile_cache.pad_to_bucket = orig
        _assert_bitwise(
            acts_a, acts_b, f"the first-{n} actions", seed, "serving",
            poison, report,
        )
        _assert_summary(
            masked_summary(padded, mask[:, None], revert),
            masked_summary(padded_p, mask[:, None], revert),
            seed, "serving", poison, revert, report,
        )
        report["trace"].append((round_, n, poison, _sha(acts_a)))
    report["digest"] = _digest(report)
    return report


# ---------------------------------------------------------------------------
# device-plane exerciser: ring slots outside the leased gather
# ---------------------------------------------------------------------------

_DECODE_JITS: dict = {}


def _ring_decode(codecs_key: str, codecs: dict):
    """One jitted gather+decode program per codec layout, shared by
    every schedule's (fresh) ring — the learner's zero-transfer consume
    shape."""
    if codecs_key in _DECODE_JITS:
        return _DECODE_JITS[codecs_key]
    import jax

    from actor_critic_tpu.data_plane import ring as ring_mod

    fn = jax.jit(
        lambda state, slot: ring_mod.gather_block(state, slot, codecs)
    )
    _DECODE_JITS[codecs_key] = fn
    return fn


def exercise_device_plane(seed: int, revert: Optional[str] = None,
                          rounds: int = 2) -> dict:
    """Poisoned NON-leased slots through the REAL `DeviceTrajRing` +
    in-jit `gather_block`: a depth-3 ring holds one real block, every
    OTHER slot's storage is filled with the poison (int8 storage takes
    the saturating int fill), and the leased slot's decode must be
    bitwise unchanged — the slot gather dynamic-slices exactly one row,
    so a neighboring slot is a padding lane. A fresh ring per schedule
    keeps int8 calibration state seed-local (the decode jit and the
    shared enqueue program compile once)."""
    _check_revert("device-plane", revert)
    import jax
    import jax.numpy as jnp

    from actor_critic_tpu.data_plane import ring as ring_mod

    rng = random.Random(seed)
    report = {
        "seed": seed, "scenario": "device-plane", "revert": revert,
        "programs": 0, "violations": 0, "trace": [],
    }
    depth = 3
    spec = {
        "obs": jax.ShapeDtypeStruct((4, 6, 3), jnp.float32),
        "reward": jax.ShapeDtypeStruct((4, 6), jnp.float32),
        "action": jax.ShapeDtypeStruct((4, 6), jnp.int32),
    }
    for round_ in range(rounds):
        nprng = np.random.default_rng(seed * 83 + round_)
        kind = ("fp32", "int8")[rng.randrange(2)]
        poison = POISONS[rng.randrange(len(POISONS))]
        ring = ring_mod.DeviceTrajRing(
            depth, spec, codec=kind, register_gauge=False
        )
        decode = _ring_decode(
            repr(sorted(ring.codecs.items())), ring.codecs
        )
        block = {
            "obs": (nprng.normal(size=(4, 6, 3)) * 0.8).astype(
                np.float32
            ),
            "reward": (nprng.normal(size=(4, 6)) * 0.5).astype(
                np.float32
            ),
            "action": nprng.integers(0, 5, (4, 6)).astype(np.int32),
        }
        assert ring.put(block, version=round_)
        lease = ring.get()
        out_a = ring.run(
            lambda st: {
                k: np.asarray(jax.device_get(v))
                for k, v in decode(st, lease.slot).items()
            }
        )
        report["programs"] += 1
        # poison every slot EXCEPT the leased one, dtype-aware
        with ring._cv:
            st = ring._state
            storage = {}
            for name, arr in st.storage.items():
                host = np.array(jax.device_get(arr))
                f = _fill(poison, host.dtype)
                sel = np.arange(depth) != lease.slot
                host[sel] = f
                storage[name] = jax.device_put(host)
            ring._state = st._replace(storage=storage)
        out_b = ring.run(
            lambda st: {
                k: np.asarray(jax.device_get(v))
                for k, v in decode(st, lease.slot).items()
            }
        )
        report["programs"] += 1
        for name in sorted(out_a):
            _assert_bitwise(
                out_a[name], out_b[name],
                f"the leased slot's decoded {name!r}", seed,
                "device-plane", poison, report,
            )
        slot_mask = (np.arange(depth) == lease.slot).astype(np.float64)
        plane_a = np.zeros((depth, 4, 6), np.float64)
        plane_b = np.full(
            (depth, 4, 6), float(_fill(poison, np.float32)), np.float64
        )
        block_plane = np.asarray(block["reward"], np.float64)
        plane_a[lease.slot] = block_plane
        plane_b[lease.slot] = block_plane
        _assert_summary(
            masked_summary(plane_a, slot_mask[:, None, None], revert),
            masked_summary(plane_b, slot_mask[:, None, None], revert),
            seed, "device-plane", poison, revert, report,
        )
        ring.release(lease)
        report["trace"].append(
            (round_, kind, poison, int(lease.slot),
             {k: _sha(v) for k, v in sorted(out_a.items())})
        )
    report["digest"] = _digest(report)
    return report


# ---------------------------------------------------------------------------
# sweep + the tier-1 quick profile
# ---------------------------------------------------------------------------


def exercise_sweep(seeds: Iterable[int], scenario) -> dict:
    reports = [scenario(seed) for seed in seeds]
    return {
        "schedules": len(reports),
        "programs": sum(r.get("programs", 0) for r in reports),
        "violations": sum(r.get("violations", 0) for r in reports),
    }


def quick_profile(schedules: int = 16, seed0: int = 0) -> dict:
    """The tier-1 fast profile: `schedules` seeded poison schedules
    split across the five guarded programs — every pad seam must keep
    its junk lanes unobservable, bitwise. The compiled fixtures
    (masked chunk program, mixture switch, warmed engine buckets,
    enqueue/decode pair) build once per process; the Pallas kernels run
    interpret-mode on CPU."""
    n = max(schedules // 5, 1)
    chunked = exercise_sweep(
        range(seed0, seed0 + n), lambda s: exercise_chunked(s)
    )
    pallas = exercise_sweep(
        range(seed0, seed0 + n), lambda s: exercise_pallas(s)
    )
    mixture = exercise_sweep(
        range(seed0, seed0 + n), lambda s: exercise_mixture(s)
    )
    serving = exercise_sweep(
        range(seed0, seed0 + n), lambda s: exercise_serving(s)
    )
    device_plane = exercise_sweep(
        range(seed0, seed0 + (schedules - 4 * n)),
        lambda s: exercise_device_plane(s),
    )
    parts = (chunked, pallas, mixture, serving, device_plane)
    return {
        "schedules": sum(x["schedules"] for x in parts),
        "chunked": chunked,
        "pallas": pallas,
        "mixture": mixture,
        "serving": serving,
        "device_plane": device_plane,
        "programs": sum(x["programs"] for x in parts),
        "violations": sum(x["violations"] for x in parts),
    }
