"""jaxlint core: findings, check registry, suppressions, and the runner.

The analyzer is pure `ast` over source text — it NEVER imports the
modules it scans (the one registered exception, the `warmup-registry`
pass, imports the *registry* it validates against, not the scanned
files; see analysis/warmup.py). That keeps every check runnable in
tier-1 under `JAX_PLATFORMS=cpu` in milliseconds, with no device, no
env pools, and no import side effects.

Vocabulary:

- A **check** is a registered pass. Module-scope checks run once per
  scanned file and receive a `ModuleInfo`; repo-scope checks run once
  per analysis and receive the full `list[ModuleInfo]` (they correlate
  across files, e.g. the warmup registry against every jit site).
- A **Finding** names one defect at one source location. Its
  `fingerprint()` deliberately excludes the line NUMBER (check + path +
  enclosing function + stripped line text) so baselines survive
  unrelated edits above the finding.
- A `# jaxlint: disable=<check>[,<check>...]` comment on the flagged
  line suppresses those checks there (`disable=all` suppresses every
  check on the line). Suppressions are for findings that are correct
  about the pattern but wrong about the hazard — put the why in the
  same comment.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from typing import Callable, Iterable, Optional

# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect at one location. `context` is the enclosing top-level
    function ("<module>" at module scope); `line_text` is the stripped
    source line — together with check+path it forms the line-number-free
    baseline fingerprint."""

    check: str
    path: str  # repo-relative, POSIX separators
    line: int
    col: int
    message: str
    context: str = "<module>"
    line_text: str = ""

    def fingerprint(self) -> str:
        return f"{self.check}:{self.path}:{self.context}:{self.line_text}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: [{self.check}] "
            f"{self.message} (in {self.context})"
        )


class AnalysisError(Exception):
    """A scanned file could not be read/parsed — the CLI maps this to
    exit 2 (crash), distinct from exit 1 (findings)."""


# ---------------------------------------------------------------------------
# Parsed-module facts shared by every check
# ---------------------------------------------------------------------------

# Check names are comma-separated tokens; free-form reason text after
# them (e.g. "disable=host-sync (numpy scalar)") is not captured.
# Anchored to the comment start (like _HOT_RE below): a comment QUOTING
# a pragma ("# TODO: drop the `# jaxlint: disable=...` below") must not
# register a real suppression.
_DISABLE_RE = re.compile(
    r"^#\s*jaxlint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)"
)
# Anchored: the pragma must START the comment, so a comment QUOTING the
# pragma (docs, review notes — "the `# jaxlint: hot-module` pragma")
# cannot opt a file in.
_HOT_RE = re.compile(r"^#\s*jaxlint:\s*hot-module\b")
# Concurrency-audit annotation (analysis/thread_model.py): the attribute
# (or module global) assigned on the annotated line is owned by one
# thread role; the concurrency checks skip it. Anchored like the others
# so prose quoting the pragma cannot annotate anything.
_THREAD_OWNED_RE = re.compile(
    r"^#\s*jaxlint:\s*thread-owned=([A-Za-z0-9_\-]+)"
)


class ModuleInfo:
    """One parsed source file plus the derived facts checks keep
    re-needing: parent links, enclosing-function names, per-line
    suppressions, and import aliases."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        try:
            self.tree = ast.parse(source, filename=path)
        except (SyntaxError, ValueError) as e:
            raise AnalysisError(f"{relpath}: parse error: {e}") from e
        self._parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
        self.hot_module = False  # set by the comment scan below
        # lineno -> end of the SIMPLE statement starting there (so a
        # standalone pragma can cover a wrapped multiline expression).
        # Compound statements (if/for/while/def/...) are deliberately
        # absent: a pragma before a block header must cover the header
        # line only, never silently disable the whole block.
        _compound = (
            ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
            ast.AsyncWith, ast.Try, ast.FunctionDef,
            ast.AsyncFunctionDef, ast.ClassDef,
        )
        self._stmt_end: dict[int, int] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt) or isinstance(node, _compound):
                continue
            self._stmt_end[node.lineno] = max(
                self._stmt_end.get(node.lineno, node.lineno),
                node.end_lineno or node.lineno,
            )
        # lineno -> role from `# jaxlint: thread-owned=<role>` comments
        # (resolution to the annotated attribute/global lives in
        # analysis/thread_model.py).
        self.thread_owned: dict[int, str] = {}
        self._suppressions = self._scan_suppressions()
        self.aliases = self._scan_aliases()

    # -- structure ---------------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_function(self, node: ast.AST) -> str:
        """The TOP-LEVEL def the node sits in ("<module>" otherwise) —
        the same keying scripts/check_warmup_registry.py always used, so
        fingerprints and registry keys agree."""
        name = "<module>"
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(self._parents.get(anc), ast.Module):
                    name = anc.name
        return name

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # -- suppressions ------------------------------------------------------

    def _scan_suppressions(self) -> dict[int, set[str]]:
        """line -> set of disabled check names. A trailing comment
        suppresses its own line; a comment-ONLY line suppresses the next
        SIMPLE statement in full (every physical line of a wrapped
        call/assignment — findings anchor where the inner expression
        starts). Before a compound header (`if`/`for`/...) it covers the
        header line only, never the block. Read via tokenize so a
        `# jaxlint:` inside a string literal is not a pragma."""
        out: dict[int, set[str]] = {}

        def record(lineno: int, names: set[str]) -> None:
            names = {n for n in names if n}
            stripped = self.lines[lineno - 1].strip()
            if stripped.startswith("#"):
                # standalone pragma: cover the next code line AND, when
                # that line opens a multiline statement, every line of
                # it — findings anchor where the inner call starts,
                # which may be a continuation line.
                for j in range(lineno + 1, len(self.lines) + 1):
                    nxt = self.lines[j - 1].strip()
                    if nxt and not nxt.startswith("#"):
                        end = self._stmt_end.get(j, j)
                        for k in range(j, end + 1):
                            out.setdefault(k, set()).update(names)
                        return
                return
            out.setdefault(lineno, set()).update(names)

        try:
            tokens = tokenize.generate_tokens(iter(self.lines2()).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                if _HOT_RE.match(tok.string):
                    # hot-module pragma: COMMENT tokens only, so a
                    # docstring merely *mentioning* the pragma (this
                    # package's own docs do) cannot opt a file in.
                    self.hot_module = True
                mo = _THREAD_OWNED_RE.match(tok.string)
                if mo:
                    self.thread_owned[tok.start[0]] = mo.group(1)
                m = _DISABLE_RE.match(tok.string)
                if m:
                    record(
                        tok.start[0],
                        {n.strip() for n in m.group(1).split(",")},
                    )
        except (tokenize.TokenizeError, IndentationError, SyntaxError):
            # Fall back to comment-looking raw lines; string-literal
            # false positives only ever OVER-suppress one line.
            for i, ln in enumerate(self.lines, 1):
                if not ln.lstrip().startswith("#"):
                    continue
                if _HOT_RE.match(ln.lstrip()):
                    self.hot_module = True
                mo = _THREAD_OWNED_RE.match(ln.lstrip())
                if mo:
                    self.thread_owned[i] = mo.group(1)
                m = _DISABLE_RE.match(ln.lstrip())
                if m:
                    record(
                        i, {n.strip() for n in m.group(1).split(",")}
                    )
        return out

    def lines2(self):
        for ln in self.lines:
            yield ln + "\n"

    def suppressed(self, lineno: int, check: str) -> bool:
        names = self._suppressions.get(lineno, ())
        if check in names or "all" in names:
            return True
        # Deprecation aliases (ISSUE 15): a `disable=host-sync`
        # annotation written before the pass was absorbed into
        # transfer-discipline keeps suppressing at its site.
        return any(
            alias in names
            for alias, target in CHECK_ALIASES.items()
            if target == check
        )

    # -- imports -----------------------------------------------------------

    def _scan_aliases(self) -> dict[str, str]:
        """local name -> canonical dotted module ("np" -> "numpy",
        "jr" -> "jax.random", "random" -> "jax.random" for
        `from jax import random`)."""
        out: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    def scope_of(self, node: ast.AST) -> ast.AST:
        """The top-level def containing `node`, or the module — the
        statement-ordered analysis unit the dataflow passes share."""
        scope: ast.AST = self.tree
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if isinstance(self.parent(anc), ast.Module):
                    scope = anc
        return scope

    def exclusive_branches(self, a: ast.AST, b: ast.AST) -> bool:
        """Whether `a` and `b` sit in different arms of a common `if` —
        at most one of them executes, so path-sensitive checks (reuse,
        double consumption) must not pair them."""
        pa = self._branch_map(a)
        pb = self._branch_map(b)
        return any(
            pa[key] != pb[key] for key in pa.keys() & pb.keys()
        )

    def _branch_map(self, node: ast.AST) -> dict[int, str]:
        """id(if-node) -> arm ('body'/'orelse') for each `if` ancestor."""
        out: dict[int, str] = {}
        child = node
        for anc in self.ancestors(node):
            if isinstance(anc, ast.If):
                if any(child is n for n in anc.body):
                    out[id(anc)] = "body"
                elif any(child is n for n in anc.orelse):
                    out[id(anc)] = "orelse"
            child = anc
        return out

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an expression, alias-resolved at the
        root: `jr.split` -> "jax.random.split", `np.asarray` ->
        "numpy.asarray". None for non-name expressions."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.aliases.get(cur.id, cur.id)
        parts.append(root)
        return ".".join(reversed(parts))


def target_names(tgt: ast.AST, roots: bool = False) -> list[str]:
    """Bare names an assignment target binds (tuple/list unpacking
    included). With `roots`, subscript/attribute targets contribute
    their base name too (`state["k"] = ...` mutates `state` — the
    aliasing-sensitive passes want that; the binding-sensitive ones do
    not)."""
    if isinstance(tgt, ast.Name):
        return [tgt.id]
    if isinstance(tgt, (ast.Tuple, ast.List)):
        return [n for e in tgt.elts for n in target_names(e, roots)]
    if roots:
        while isinstance(tgt, (ast.Subscript, ast.Attribute)):
            tgt = tgt.value
        if isinstance(tgt, ast.Name):
            return [tgt.id]
    return []


# ---------------------------------------------------------------------------
# Check registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Check:
    name: str
    doc: str  # one line, printed by --list-checks
    fn: Callable
    scope: str = "module"  # "module" | "repo"


_CHECKS: dict[str, Check] = {}

# Renamed/absorbed checks stay resolvable (ISSUE 15): `--select
# host-sync` runs transfer-discipline, and a `disable=host-sync`
# annotation suppresses it — annotations and CI invocations written
# against the old name cannot silently stop working.
CHECK_ALIASES: dict[str, str] = {"host-sync": "transfer-discipline"}


def resolve_check_name(name: str) -> str:
    return CHECK_ALIASES.get(name, name)


def register_check(name: str, doc: str, scope: str = "module"):
    """Decorator registering `fn(module_info) -> list[Finding]` (module
    scope) or `fn(list[ModuleInfo]) -> list[Finding]` (repo scope)."""

    def deco(fn):
        _CHECKS[name] = Check(name=name, doc=doc, fn=fn, scope=scope)
        return fn

    return deco


def registered_checks() -> tuple[Check, ...]:
    _ensure_builtin_checks()
    return tuple(_CHECKS[k] for k in sorted(_CHECKS))


def _ensure_builtin_checks() -> None:
    # Import-for-side-effect: each pass module registers itself. Kept
    # lazy so `import actor_critic_tpu.analysis.core` alone stays cheap.
    from actor_critic_tpu.analysis import (  # noqa: F401
        concurrency,
        distributed,
        donation,
        numerics,
        perf,
        prng,
        recompile,
        shapes,
        tracer_leak,
        warmup,
    )


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def iter_python_files(paths: Iterable[str], repo_root: str) -> list[str]:
    """Expand files/dirs to sorted .py paths (skips __pycache__ and
    hidden directories). Missing paths raise AnalysisError (exit 2: a
    typo'd path must not read as a clean run)."""
    out: list[str] = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(repo_root, p)
        if os.path.isfile(ap):
            out.append(ap)
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [
                    d for d in sorted(dirnames)
                    if d != "__pycache__" and not d.startswith(".")
                ]
                out.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames)
                    if f.endswith(".py")
                )
        else:
            raise AnalysisError(f"no such file or directory: {p}")
    return out


def load_modules(paths: Iterable[str], repo_root: str) -> list[ModuleInfo]:
    modules: list[ModuleInfo] = []
    for path in iter_python_files(paths, repo_root):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            raise AnalysisError(f"{path}: {e}") from e
        modules.append(
            ModuleInfo(path, os.path.relpath(path, repo_root), source)
        )
    return modules


def run_checks(
    modules: list[ModuleInfo],
    checks: Optional[Iterable[str]] = None,
    skip: Iterable[str] = (),
) -> list[Finding]:
    """All findings over the parsed modules, suppression-filtered and
    sorted by location. `checks` selects a subset by name; `skip` drops
    names from whatever was selected. Unknown names raise (a typo'd
    check filter must not read as a clean run)."""
    _ensure_builtin_checks()
    # dict.fromkeys: alias resolution can map two requested names onto
    # one check (`--select host-sync,transfer-discipline`) — it must
    # run once, not twice.
    selected = (
        list(dict.fromkeys(resolve_check_name(c) for c in checks))
        if checks is not None
        else sorted(_CHECKS)
    )
    skip = [resolve_check_name(c) for c in skip]
    unknown = [c for c in [*selected, *skip] if c not in _CHECKS]
    if unknown:
        raise AnalysisError(
            f"unknown check(s): {', '.join(sorted(set(unknown)))} "
            f"(have: {', '.join(sorted(_CHECKS))})"
        )
    selected = [c for c in selected if c not in set(skip)]

    by_rel = {m.relpath: m for m in modules}
    findings: list[Finding] = []
    for name in selected:
        check = _CHECKS[name]
        if check.scope == "repo":
            raw = check.fn(modules)
        else:
            raw = [f for m in modules for f in check.fn(m)]
        for f in raw:
            mod = by_rel.get(f.path)
            if mod is not None:
                if not f.line_text:
                    f = dataclasses.replace(
                        f, line_text=mod.line_text(f.line)
                    )
                if mod.suppressed(f.line, f.check):
                    continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    return findings


def analyze_paths(
    paths: Iterable[str],
    repo_root: str,
    checks: Optional[Iterable[str]] = None,
    skip: Iterable[str] = (),
) -> list[Finding]:
    """Parse + run in one call — the API scripts/jaxlint.py and the
    tests drive."""
    return run_checks(load_modules(paths, repo_root), checks=checks, skip=skip)
