"""host-sync: ABSORBED into transfer-discipline (ISSUE 15).

ISSUE 5's host-sync pass flagged device syncs inside hot collection
loops; `analysis/perf.py`'s transfer-discipline pass now owns that
class — the same device→host sync taxonomy plus `jax.device_get` and
the host→device upload family, over hot modules AND detected step
loops repo-wide. This module is the deprecation shim that keeps the
old spellings working:

- `--select host-sync` resolves to transfer-discipline
  (`core.CHECK_ALIASES`), so CI invocations written against the old
  name keep running the successor pass;
- `# jaxlint: disable=host-sync` annotations keep suppressing
  transfer-discipline findings at their sites (`ModuleInfo.suppressed`
  consults the same alias table);
- `HOT_BASENAMES` (the step-loop owner set) now lives in
  `analysis/perf_model.py`; the re-export below exists only for
  out-of-tree consumers that imported it from this module — nothing
  in-repo does any more.

Baseline entries were migrated in place (`check` rewritten to
transfer-discipline; fingerprints re-anchor automatically because the
check name is part of them) — run `scripts/jaxlint.py --prune-stale`
after removing any remaining host-sync entries of your own.
"""

from __future__ import annotations

from actor_critic_tpu.analysis.perf_model import HOT_BASENAMES  # noqa: F401

CHECK = "host-sync"  # historical name; resolves via core.CHECK_ALIASES
