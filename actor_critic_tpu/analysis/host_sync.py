"""host-sync: device synchronization inside hot collection/step loops.

The host loops stay fast by keeping dispatch ASYNC: the jitted update
returns at enqueue time and the device computes while the host collects
the next block. Any `.item()`, `np.asarray(device_value)`,
`jax.block_until_ready(...)`, or `float()/int()` coercion inside the
loop body blocks the host on the device EVERY iteration and silently
serializes the pipeline — the regression is invisible until someone
profiles. Deliberate sync points (the log-cadence `float()` coercions,
the non-mirror acting path's action materialization) are real and
documented — suppress them in place with the reason, which is exactly
what a reviewer needs to see next to the call.

Scope: files whose basename is in `HOT_BASENAMES` (the step-loop owners
the ISSUE names) plus any file carrying a `# jaxlint: hot-module`
pragma line (how fixtures — and future hot modules — opt in). Only
calls with a `for`/`while`/comprehension ancestor flag; straight-line
setup code syncs once, not per step.
"""

from __future__ import annotations

import ast

from actor_critic_tpu.analysis.core import Finding, ModuleInfo, register_check

CHECK = "host-sync"

# The step-loop owners (ISSUE 5). Other modules opt in via the
# `# jaxlint: hot-module` pragma.
HOT_BASENAMES = {"host_loop.py", "ppo.py", "compile_cache.py"}

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_SYNC_FREE_CALLS = {"len", "round", "abs"}  # cheap host-side builtins


def _in_loop(mod: ModuleInfo, node: ast.AST) -> bool:
    # Real iteration only: a lone comprehension (e.g. the log-cadence
    # `{k: float(v) ...}` coercion) runs once per CALL, not per step —
    # it is hot only when the call site itself sits in a step loop.
    return any(isinstance(a, _LOOPS) for a in mod.ancestors(node))


def _sync_kind(mod: ModuleInfo, call: ast.Call) -> str | None:
    """A description of the blocking call, or None."""
    dotted = mod.dotted(call.func)
    if isinstance(call.func, ast.Attribute):
        if call.func.attr == "item" and not call.args:
            return "`.item()`"
        if call.func.attr == "block_until_ready":
            return "`block_until_ready`"
    if dotted == "jax.block_until_ready":
        return "`jax.block_until_ready`"
    if dotted in ("numpy.asarray", "numpy.array"):
        return f"`{dotted.replace('numpy', 'np')}`"
    if dotted in ("float", "int") and call.args:
        arg = call.args[0]
        if isinstance(arg, ast.Constant):
            return None
        if isinstance(arg, ast.Call):
            inner = mod.dotted(arg.func) or ""
            if (
                inner.startswith("numpy.")
                or inner.startswith("math.")
                or inner in _SYNC_FREE_CALLS
            ):
                return None  # numpy/host math — no device involved
        return f"`{dotted}()`"
    return None


@register_check(
    CHECK,
    "device sync (.item()/np.asarray/block_until_ready/float()) inside "
    "a hot collection/step loop",
)
def check_host_sync(mod: ModuleInfo) -> list[Finding]:
    basename = mod.relpath.rsplit("/", 1)[-1]
    if basename not in HOT_BASENAMES and not mod.hot_module:
        return []
    findings: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not _in_loop(mod, node):
            continue
        kind = _sync_kind(mod, node)
        if kind is None:
            continue
        findings.append(
            Finding(
                CHECK, mod.relpath, node.lineno, node.col_offset,
                f"{kind} inside a hot loop blocks the host on the device "
                "every iteration, serializing the async dispatch "
                "pipeline — hoist it to the log cadence, keep the value "
                "on device, or suppress with the reason if the sync is "
                "deliberate",
                mod.enclosing_function(node),
            )
        )
    return findings
