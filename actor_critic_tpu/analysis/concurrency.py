"""Concurrency passes: lock-discipline, publish-aliasing,
check-then-act (ISSUE 7 tentpole).

Each is grounded in a concurrency bug PR 6 actually hit:

- **lock-discipline** — the global open-span stack corrupted by
  interleaved actor threads: a COMPOUND write (aug-assign, container
  mutation, subscript store) to state shared across thread roles must
  happen under a held lock or carry a `# jaxlint: thread-owned=<role>`
  annotation with the audited reason. Plain reference stores and plain
  reads are GIL-atomic and stay out of scope (thread_model.py documents
  the model assumptions).
- **publish-aliasing** — the zero-copy queue-slot race: an ndarray
  handed to a cross-thread channel (`put`/`publish`/`send`) must be a
  snapshot, not a view of a preallocated/recycled slot; and on the
  consumer side, `np.asarray`/`jnp.asarray` (which may alias host
  memory zero-copy) over a block that is `release`d back to a slot pool
  in the same scope reads memory the next `put` rewrites.
- **check-then-act** — unlocked read-test-write windows on shared
  flags/counters (`if self._closed: return` ... `self._closed = True`):
  two threads pass the test before either writes. Double-checked
  locking (the WRITE under the lock) is recognized and stays clean.

lock-discipline and check-then-act are repo-scope: they consult the
whole-repo `ThreadModel` (thread entry points resolved across files).
publish-aliasing is per-module. A write that is part of a
check-then-act pair is reported by check-then-act only, so one defect
never double-flags.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
    target_names,
)
from actor_critic_tpu.analysis.thread_model import (
    CALLER_ROLE,
    MUTATING_METHODS,
    ClassModel,
    ThreadModel,
    self_attr,
)

LOCK_DISCIPLINE = "lock-discipline"
PUBLISH_ALIASING = "publish-aliasing"
CHECK_THEN_ACT = "check-then-act"

# Cross-thread channel method names (TrajQueue.put, PolicyPublisher
# .publish, multiprocessing pipe send).
CHANNEL_METHODS = {"put", "put_nowait", "publish", "send", "send_bytes"}

# numpy constructors that yield preallocated storage a producer refills.
_ALLOCATORS = {
    f"numpy.{n}"
    for n in (
        "zeros", "empty", "ones", "full", "zeros_like", "empty_like",
        "ones_like", "full_like", "frombuffer",
    )
}

# Wrapping any of these around a hazard source makes it a snapshot.
_SNAPSHOT_DOTTED = {
    "numpy.array", "jax.numpy.array", "numpy.copy", "copy.deepcopy",
}
_SNAPSHOT_METHODS = {"copy", "tobytes"}

# Possibly-zero-copy host-array coercions the consumer-side rule flags.
_ALIASING_DOTTED = {"numpy.asarray", "jax.numpy.asarray", "numpy.frombuffer"}


# ---------------------------------------------------------------------------
# shared machinery
# ---------------------------------------------------------------------------


class _Access:
    """One compound write to a `self.<attr>` or module-global container/
    counter: the interleaving-sensitive operation class."""

    __slots__ = ("node", "name", "method", "kind")

    def __init__(self, node: ast.AST, name: str, method: str, kind: str):
        self.node = node      # anchor for the finding
        self.name = name      # attribute or global name
        self.method = method  # enclosing method name ("" at module level)
        self.kind = kind      # human-readable operation description


def _under_lock(
    mod: ModuleInfo,
    node: ast.AST,
    lock_attrs: Iterable[str],
    module_locks: Iterable[str],
) -> bool:
    """Whether `node` sits inside a `with self.<lock>:` /
    `with <module_lock>:` context, or in a method whose name ends in
    `_locked` (the held-by-contract naming convention: such helpers are
    only called with the lock already taken)."""
    lock_attrs = set(lock_attrs)
    module_locks = set(module_locks)
    for anc in mod.ancestors(node):
        if isinstance(
            anc, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and anc.name.endswith("_locked"):
            return True
        if not isinstance(anc, (ast.With, ast.AsyncWith)):
            continue
        for item in anc.items:
            expr = item.context_expr
            attr = self_attr(expr)
            if attr is not None and attr in lock_attrs:
                return True
            if isinstance(expr, ast.Name) and expr.id in module_locks:
                return True
    return False


def _enclosing_function_node(
    mod: ModuleInfo, node: ast.AST
) -> Optional[ast.AST]:
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def _enclosing_method(cls: ClassModel, node: ast.AST, mod: ModuleInfo) -> str:
    for anc in mod.ancestors(node):
        if (
            isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef))
            and mod.parent(anc) is cls.node
        ):
            return anc.name
    return ""


def _compound_writes_in(
    mod: ModuleInfo, root: ast.AST, cls: Optional[ClassModel]
) -> list[_Access]:
    """Compound writes inside `root`. With `cls`, `self.<attr>` targets;
    without, bare-Name targets (module-global candidates — the caller
    filters by what the scope actually binds locally)."""
    out: list[_Access] = []

    def method_of(node: ast.AST) -> str:
        return _enclosing_method(cls, node, mod) if cls else ""

    for node in ast.walk(root):
        if isinstance(node, ast.AugAssign):
            tgt = node.target
            # `self.x += 1`, `GLOBAL += 1`, and the subscripted forms
            # (`STATS["hits"] += 1`) are all read-modify-write.
            if isinstance(tgt, ast.Subscript):
                tgt = tgt.value
            name = _target_name(tgt, cls)
            if name:
                out.append(
                    _Access(node, name, method_of(node), "augmented write")
                )
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for tgt in targets:
                if not isinstance(tgt, ast.Subscript):
                    continue
                name = _target_name(tgt.value, cls)
                if name:
                    out.append(
                        _Access(tgt, name, method_of(tgt), "subscript store")
                    )
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr not in MUTATING_METHODS:
                continue
            name = _target_name(node.func.value, cls)
            if name:
                out.append(
                    _Access(
                        node, name, method_of(node),
                        f"`.{node.func.attr}()` mutation",
                    )
                )
    return out


def _target_name(node: ast.AST, cls: Optional[ClassModel]) -> Optional[str]:
    """`self.<attr>` → attr (class mode); bare Name → id (module mode)."""
    if cls is not None:
        return self_attr(node)
    return node.id if isinstance(node, ast.Name) else None


def _attr_touches(cls: ClassModel, mod: ModuleInfo) -> dict[str, set[str]]:
    """attr -> methods that read or write it (any access counts toward
    role reach; only compound writes are flagged)."""
    out: dict[str, set[str]] = {}
    for node in ast.walk(cls.node):
        attr = self_attr(node)
        if attr is None:
            continue
        method = _enclosing_method(cls, node, mod)
        if method:
            out.setdefault(attr, set()).add(method)
    return out


def _module_global_names(mod: ModuleInfo) -> set[str]:
    names: set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                names.update(target_names(tgt))
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            names.update(target_names(stmt.target))
    return names


def _locally_bound(scope: ast.AST, name: str) -> bool:
    """Whether a function scope binds `name` locally (so a reference is
    NOT the module global), unless it declares it `global`."""
    if isinstance(scope, ast.Module):
        return False
    for node in ast.walk(scope):
        if isinstance(node, ast.Global) and name in node.names:
            return False
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            if any(name in target_names(t) for t in node.targets):
                return True
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if name in target_names(node.target):
                return True
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if name in target_names(node.target):
                return True
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        args = scope.args
        all_args = (
            args.posonlyargs + args.args + args.kwonlyargs
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        )
        if any(a.arg == name for a in all_args):
            return True
    return False


# ---------------------------------------------------------------------------
# check-then-act pair detection (shared with lock-discipline for dedup)
# ---------------------------------------------------------------------------


class _CtaPair:
    __slots__ = ("test_if", "writes", "name", "scope_desc")

    def __init__(self, test_if: ast.If, writes: list[ast.AST], name: str,
                 scope_desc: str):
        self.test_if = test_if
        self.writes = writes  # EVERY unlocked write in the window —
        #                       lock-discipline excludes them all, so
        #                       one defect never double-flags
        self.name = name
        self.scope_desc = scope_desc  # "self._closed" / "_REGISTRY"

    @property
    def write(self) -> ast.AST:
        return self.writes[0]  # anchor for the finding message


def _reads_in(node: ast.AST, cls: Optional[ClassModel]) -> set[str]:
    """Names/attrs the expression reads, in the requested mode."""
    out: set[str] = set()
    for sub in ast.walk(node):
        name = _target_name(sub, cls)
        if name:
            out.add(name)
    return out


def _writes_to(
    stmt: ast.AST, name: str, cls: Optional[ClassModel]
) -> list[ast.AST]:
    """Write sites (plain OR compound) to attr/global `name` in `stmt`."""
    out: list[ast.AST] = []
    for node in ast.walk(stmt):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if _target_name(tgt, cls) == name:
                    out.append(node)
                elif isinstance(tgt, ast.Subscript) and _target_name(
                    tgt.value, cls
                ) == name:
                    out.append(node)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is None and isinstance(node, ast.AnnAssign):
                continue
            tgt = node.target
            if _target_name(tgt, cls) == name:
                out.append(node)
            elif isinstance(tgt, ast.Subscript) and _target_name(
                tgt.value, cls
            ) == name:
                out.append(node)
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr in MUTATING_METHODS and _target_name(
                node.func.value, cls
            ) == name:
                out.append(node)
    return out


_EXITS = (ast.Return, ast.Raise, ast.Continue, ast.Break)


def _cta_pairs_in_scope(
    mod: ModuleInfo,
    scope: ast.AST,
    names: set[str],
    cls: Optional[ClassModel],
    lock_attrs: set[str],
    module_locks: set[str],
) -> list[_CtaPair]:
    """Unlocked test-then-write pairs on `names` within one function:
    the `if` reads the flag outside a lock, and an unlocked write to the
    same flag sits in the if body/orelse — or anywhere after an if whose
    body exits early (the `if done: return` guard shape)."""
    pairs: list[_CtaPair] = []
    for node in ast.walk(scope):
        if not isinstance(node, ast.If):
            continue
        if _under_lock(mod, node, lock_attrs, module_locks):
            continue
        tested = _reads_in(node.test, cls) & names
        for name in sorted(tested):
            candidates: list[ast.AST] = []
            for stmt in node.body + node.orelse:
                candidates.extend(_writes_to(stmt, name, cls))
            if node.body and isinstance(node.body[-1], _EXITS):
                end = node.end_lineno or node.lineno
                for stmt in ast.walk(scope):
                    if (
                        isinstance(stmt, ast.stmt)
                        and stmt.lineno > end
                    ):
                        candidates.extend(_writes_to(stmt, name, cls))
            unlocked = [
                w
                for w in candidates
                if not _under_lock(mod, w, lock_attrs, module_locks)
            ]
            if unlocked:
                desc = f"self.{name}" if cls else name
                pairs.append(_CtaPair(node, unlocked, name, desc))
    return pairs


def _class_cta_pairs(
    model: ThreadModel, mod: ModuleInfo, cls: ClassModel
) -> list[_CtaPair]:
    names = {
        a
        for a in _attr_touches(cls, mod)
        if a not in cls.lock_attrs and a not in cls.owned_attrs
    }
    module_locks = model.module_locks.get(mod.relpath, set())
    pairs: list[_CtaPair] = []
    for mname, fn in cls.methods().items():
        if mname == "__init__":
            continue
        pairs.extend(
            _cta_pairs_in_scope(
                mod, fn, names, cls, cls.lock_attrs, module_locks
            )
        )
    return pairs


def _module_cta_pairs(
    model: ThreadModel, mod: ModuleInfo
) -> list[_CtaPair]:
    """Check-then-act on module GLOBALS, from any function or method in
    a threaded module (the PR 6 span-stack bug mutated a module global
    from class methods — depth must not matter)."""
    if not model.is_threaded_module(mod):
        return []
    module_locks = model.module_locks.get(mod.relpath, set())
    names = {
        n
        for n in _module_global_names(mod)
        if n not in module_locks
        and (mod.relpath, n) not in model.owned_globals
    }
    pairs: list[_CtaPair] = []
    seen: set[tuple[int, str]] = set()
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scoped = {n for n in names if not _locally_bound(fn, n)}
        if not scoped:
            continue
        cls = model.class_model(mod, fn)
        lock_attrs = cls.lock_attrs if cls else set()
        for pair in _cta_pairs_in_scope(
            mod, fn, scoped, None, lock_attrs, module_locks
        ):
            # Nested defs are walked from every enclosing function;
            # report each (if, name) pair once.
            key = (id(pair.test_if), pair.name)
            if key not in seen:
                seen.add(key)
                pairs.append(pair)
    return pairs


def _all_cta_pairs(model: ThreadModel, mod: ModuleInfo) -> list[_CtaPair]:
    pairs = _module_cta_pairs(model, mod)
    for (relpath, _), cls in model.classes.items():
        if relpath != mod.relpath:
            continue
        if not (cls.threaded or cls.lock_attrs):
            continue
        pairs.extend(_class_cta_pairs(model, mod, cls))
    return pairs


# Single-entry cache: lock-discipline and check-then-act are separate
# registered checks but need the SAME thread model and CTA pairs (the
# latter for findings, the former only to de-duplicate) — without
# sharing, every lint run would derive the repo-wide facts twice. The
# cached modules list is held strongly, so the id()-keyed entry can
# never alias a garbage-collected ModuleInfo.
_SHARED: dict = {}


def _shared_analysis(
    modules: list[ModuleInfo],
) -> tuple[ThreadModel, dict[int, list[_CtaPair]]]:
    key = tuple(id(m) for m in modules)
    entry = _SHARED.get("entry")
    if entry is not None and entry[0] == key:
        return entry[1], entry[2]
    model = ThreadModel(modules)
    pairs = {id(m): _all_cta_pairs(model, m) for m in modules}
    _SHARED["entry"] = (key, model, pairs, list(modules))
    return model, pairs


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


@register_check(
    LOCK_DISCIPLINE,
    "compound write to cross-thread shared state outside its lock "
    "(PR 6 span-stack class); audited single-writer attrs carry "
    "`# jaxlint: thread-owned=<role>`",
    scope="repo",
)
def check_lock_discipline(modules: list[ModuleInfo]) -> list[Finding]:
    model, pairs = _shared_analysis(modules)
    findings: list[Finding] = []
    for mod in modules:
        cta_writes = {
            id(w) for p in pairs[id(mod)] for w in p.writes
        }
        findings.extend(_class_lock_findings(model, mod, cta_writes))
        findings.extend(_module_lock_findings(model, mod, cta_writes))
    return findings


def _class_lock_findings(
    model: ThreadModel, mod: ModuleInfo, cta_writes: set[int]
) -> list[Finding]:
    findings: list[Finding] = []
    module_locks = model.module_locks.get(mod.relpath, set())
    for (relpath, _), cls in model.classes.items():
        if relpath != mod.relpath or not (cls.threaded or cls.lock_attrs):
            continue
        touches = _attr_touches(cls, mod)
        for acc in _compound_writes_in(mod, cls.node, cls):
            if acc.method in ("", "__init__"):
                continue  # pre-publication (happens-before Thread.start)
            if acc.name in cls.lock_attrs or acc.name in cls.owned_attrs:
                continue
            if id(acc.node) in cta_writes:
                continue  # reported by check-then-act
            if _under_lock(mod, acc.node, cls.lock_attrs, module_locks):
                continue
            if cls.lock_attrs:
                shared = True  # a lock-owning class declares shared state
            else:
                roles: set[str] = set()
                for m in touches.get(acc.name, ()):
                    roles |= cls.roles_of(m)
                writer_roles = cls.roles_of(acc.method)
                shared = len(roles) > 1 or (
                    writer_roles != {CALLER_ROLE}
                    and not acc.name.startswith("_")
                )
            if not shared:
                continue
            lock_hint = (
                f"`with self.{sorted(cls.lock_attrs)[0]}:`"
                if cls.lock_attrs
                else "a lock"
            )
            findings.append(
                Finding(
                    LOCK_DISCIPLINE, mod.relpath,
                    acc.node.lineno, acc.node.col_offset,
                    f"{acc.kind} to `self.{acc.name}` in "
                    f"`{cls.name}.{acc.method}` outside {lock_hint} — the "
                    "attribute is reachable from more than one thread "
                    "role, and a compound write interleaves; hold the "
                    "lock, or annotate the attribute "
                    "`# jaxlint: thread-owned=<role>` with the audited "
                    "reason",
                    mod.enclosing_function(acc.node),
                )
            )
    return findings


def _module_lock_findings(
    model: ThreadModel, mod: ModuleInfo, cta_writes: set[int]
) -> list[Finding]:
    if not model.is_threaded_module(mod):
        return []
    findings: list[Finding] = []
    module_locks = model.module_locks.get(mod.relpath, set())
    globals_ = _module_global_names(mod) - module_locks
    for acc in _compound_writes_in(mod, mod.tree, None):
        fn = _enclosing_function_node(mod, acc.node)
        if fn is None:
            continue  # module-scope statements run at import, one thread
        if acc.name not in globals_:
            continue
        if _locally_bound(fn, acc.name):
            continue
        if (mod.relpath, acc.name) in model.owned_globals:
            continue
        if id(acc.node) in cta_writes:
            continue
        cls = model.class_model(mod, acc.node)
        lock_attrs = cls.lock_attrs if cls else set()
        if not _under_lock(mod, acc.node, lock_attrs, module_locks):
            findings.append(
                Finding(
                    LOCK_DISCIPLINE, mod.relpath,
                    acc.node.lineno, acc.node.col_offset,
                    f"{acc.kind} to module global `{acc.name}` outside a "
                    "module lock, in a module that runs threads — "
                    "interleaved compound writes corrupt shared state "
                    "(the PR 6 open-span-stack bug); guard it with a "
                    "module-level lock or annotate the global "
                    "`# jaxlint: thread-owned=<role>` with the audited "
                    "reason",
                    mod.enclosing_function(acc.node),
                )
            )
    return findings


# ---------------------------------------------------------------------------
# check-then-act
# ---------------------------------------------------------------------------


@register_check(
    CHECK_THEN_ACT,
    "unlocked read-test-write window on a shared flag/counter "
    "(two threads pass the test before either writes)",
    scope="repo",
)
def check_check_then_act(modules: list[ModuleInfo]) -> list[Finding]:
    _model, pairs = _shared_analysis(modules)
    findings: list[Finding] = []
    for mod in modules:
        for pair in pairs[id(mod)]:
            findings.append(
                Finding(
                    CHECK_THEN_ACT, mod.relpath,
                    pair.test_if.lineno, pair.test_if.col_offset,
                    f"`{pair.scope_desc}` is tested here and written at "
                    f"line {pair.write.lineno} with no lock held across "
                    "the window — two threads can both pass the test "
                    "before either writes; take the lock around "
                    "test-and-set (double-checked locking keeps the "
                    "fast path), or annotate the state "
                    "`# jaxlint: thread-owned=<role>` with the audited "
                    "reason",
                    mod.enclosing_function(pair.test_if),
                )
            )
    return findings


# ---------------------------------------------------------------------------
# publish-aliasing
# ---------------------------------------------------------------------------


def _alloc_attrs(mod: ModuleInfo, cls_node: ast.ClassDef) -> set[str]:
    """Attributes the class assigns from a numpy allocator — the
    preallocated slots a producer refills between publishes."""
    out: set[str] = set()
    for node in ast.walk(cls_node):
        if not isinstance(node, ast.Assign):
            continue
        if not (
            isinstance(node.value, ast.Call)
            and mod.dotted(node.value.func) in _ALLOCATORS
        ):
            continue
        for tgt in node.targets:
            attr = self_attr(tgt)
            if attr:
                out.add(attr)
    return out


def _is_snapshotted(mod: ModuleInfo, node: ast.AST, stop: ast.AST) -> bool:
    """Whether a copy-like call wraps `node` on the way up to `stop`."""
    for anc in mod.ancestors(node):
        if anc is stop:
            return False
        if isinstance(anc, ast.Call):
            if (
                isinstance(anc.func, ast.Attribute)
                and anc.func.attr in _SNAPSHOT_METHODS
            ):
                return True
            if mod.dotted(anc.func) in _SNAPSHOT_DOTTED:
                return True
    return False


def _innermost_loop(mod: ModuleInfo, node: ast.AST) -> Optional[ast.AST]:
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
            return anc
    return None


def _latest_assign(
    mod: ModuleInfo, scope: ast.AST, name: str, before: int
) -> Optional[tuple[int, ast.AST]]:
    best: Optional[tuple[int, ast.AST]] = None
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign) or node.lineno >= before:
            continue
        if any(name in target_names(t) for t in node.targets):
            if best is None or node.lineno > best[0]:
                best = (node.lineno, node.value)
    return best


def _producer_findings(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    alloc_cache: dict[ast.AST, set[str]] = {}
    for call in ast.walk(mod.tree):
        if not (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr in CHANNEL_METHODS
        ):
            continue
        payload = list(call.args) + [k.value for k in call.keywords]
        scope = mod.scope_of(call)
        loop = _innermost_loop(mod, call)
        cls_node = next(
            (
                a
                for a in mod.ancestors(call)
                if isinstance(a, ast.ClassDef)
            ),
            None,
        )
        if cls_node is not None and cls_node not in alloc_cache:
            alloc_cache[cls_node] = _alloc_attrs(mod, cls_node)
        slots = alloc_cache.get(cls_node, set())
        context = mod.enclosing_function(call)
        for arg in payload:
            for sub in ast.walk(arg):
                attr = self_attr(sub)
                if attr is not None and attr in slots:
                    if _is_snapshotted(mod, sub, call):
                        continue
                    findings.append(
                        Finding(
                            PUBLISH_ALIASING, mod.relpath,
                            sub.lineno, sub.col_offset,
                            f"`self.{attr}` is a preallocated slot the "
                            "producer refills, handed to cross-thread "
                            f"channel `.{call.func.attr}()` without a "
                            "snapshot — the consumer's view is "
                            "rewritten on the next fill; pass "
                            "`.copy()`/np.array, or suppress with the "
                            "reason if the channel itself copies",
                            context,
                        )
                    )
                    continue
                if (
                    loop is not None
                    and isinstance(sub, ast.Name)
                    and isinstance(sub.ctx, ast.Load)
                ):
                    latest = _latest_assign(
                        mod, scope, sub.id, call.lineno
                    )
                    if latest is None:
                        continue
                    lineno, value = latest
                    inside_loop = (
                        loop.lineno <= lineno <= (loop.end_lineno or lineno)
                    )
                    if inside_loop:
                        continue
                    if not (
                        isinstance(value, ast.Call)
                        and mod.dotted(value.func) in _ALLOCATORS
                    ):
                        continue
                    if _is_snapshotted(mod, sub, call):
                        continue
                    findings.append(
                        Finding(
                            PUBLISH_ALIASING, mod.relpath,
                            sub.lineno, sub.col_offset,
                            f"`{sub.id}` is allocated once outside this "
                            "loop (line "
                            f"{lineno}) and handed to cross-thread "
                            f"channel `.{call.func.attr}()` every "
                            "iteration — each publish aliases the same "
                            "storage the next iteration rewrites; "
                            "snapshot it (`.copy()`/np.array) or move "
                            "the allocation into the loop",
                            context,
                        )
                    )
    return findings


# Method calls that yield views/iterators over their receiver's storage
# (taint flows through them); every OTHER call returns a fresh value and
# is a taint barrier — the same rule donation.py uses for restore-taint.
_ALIAS_ATTR_CALLS = {
    "items", "values", "keys", "reshape", "view", "transpose", "ravel",
    "squeeze", "swapaxes",
}


def _tainted_reads(
    mod: ModuleInfo, expr: ast.AST, tainted: set[str]
) -> set[str]:
    """Tainted names `expr` can ALIAS: reached without crossing a
    fresh-value call boundary (snapshot constructors, jitted updates,
    arbitrary functions all return storage of their own)."""
    hits: set[str] = set()

    def visit(n: ast.AST, local: set[str]) -> None:
        if isinstance(n, ast.Name):
            if n.id in local:
                hits.add(n.id)
        elif isinstance(n, ast.Call):
            aliasing = mod.dotted(n.func) in _ALIASING_DOTTED or (
                isinstance(n.func, ast.Attribute)
                and n.func.attr in _ALIAS_ATTR_CALLS
            )
            if aliasing:
                if isinstance(n.func, ast.Attribute):
                    visit(n.func.value, local)
                for a in n.args:
                    visit(a, local)
        elif isinstance(n, (ast.Attribute, ast.Subscript, ast.Starred)):
            visit(n.value, local)
        elif isinstance(n, (ast.Tuple, ast.List, ast.Set)):
            for e in n.elts:
                visit(e, local)
        elif isinstance(n, ast.Dict):
            for v in n.values:
                if v is not None:
                    visit(v, local)
        elif isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                            ast.DictComp)):
            inner = set(local)
            for g in n.generators:
                if _tainted_reads(mod, g.iter, inner):
                    inner.update(target_names(g.target))
            exprs = (
                [n.key, n.value]
                if isinstance(n, ast.DictComp)
                else [n.elt]
            )
            for e in exprs:
                visit(e, inner)
        elif isinstance(n, ast.IfExp):
            visit(n.body, local)
            visit(n.orelse, local)
        # operators (BinOp etc.) materialize fresh arrays: barrier

    visit(expr, tainted)
    return hits


def _consumer_findings(mod: ModuleInfo) -> list[Finding]:
    """`asarray`-then-`release` in one scope: the zero-copy view reads a
    slot the pool recycles (the PR 6 copy-on-transfer bug)."""
    findings: list[Finding] = []
    scopes: dict[ast.AST, list[str]] = {}
    for call in ast.walk(mod.tree):
        if (
            isinstance(call, ast.Call)
            and isinstance(call.func, ast.Attribute)
            and call.func.attr == "release"
            and len(call.args) == 1
            and isinstance(call.args[0], ast.Name)
        ):
            scopes.setdefault(mod.scope_of(call), []).append(
                call.args[0].id
            )
    for scope, released in scopes.items():
        tainted = set(released)
        # Propagate through view-preserving assignments and tainted
        # comprehension targets until stable (two passes cover the
        # chains this flags; fresh-value calls are barriers).
        for _ in range(2):
            for node in ast.walk(scope):
                if isinstance(node, ast.Assign):
                    if _tainted_reads(mod, node.value, tainted):
                        for t in node.targets:
                            tainted.update(target_names(t))
                elif isinstance(node, ast.comprehension):
                    if _tainted_reads(mod, node.iter, tainted):
                        tainted.update(target_names(node.target))
        for call in ast.walk(scope):
            if not (
                isinstance(call, ast.Call)
                and mod.dotted(call.func) in _ALIASING_DOTTED
            ):
                continue
            hit = _tainted_reads(mod, call, tainted)
            if not hit:
                continue
            if _is_snapshotted(mod, call, scope):
                continue
            fn = mod.dotted(call.func)
            short = fn.replace("numpy", "np").replace("jax.np", "jnp")
            findings.append(
                Finding(
                    PUBLISH_ALIASING, mod.relpath,
                    call.lineno, call.col_offset,
                    f"`{short}` may alias host memory zero-copy, and "
                    f"`{sorted(hit)[0]}` comes from a block that is "
                    "`release`d back to its slot pool in this scope — "
                    "the next `put` rewrites the slot while the view "
                    "is still read (PR 6 copy-on-transfer bug); "
                    "snapshot with np.array/jnp.array before releasing",
                    mod.enclosing_function(call),
                )
            )
    return findings


@register_check(
    PUBLISH_ALIASING,
    "ndarray view of a recycled/preallocated slot crossing a thread "
    "channel (put/publish/send) or aliased past its release "
    "(PR 6 zero-copy queue race)",
)
def check_publish_aliasing(mod: ModuleInfo) -> list[Finding]:
    return _producer_findings(mod) + _consumer_findings(mod)
