"""jaxlint shape/padding passes (ISSUE 20 static half).

Three checks over analysis/shape_model.py's per-scope padding flow —
the SHAPES dimension's lint surface, alongside racesan/fleetsan/
numsan/perfsan's lint siblings:

- **pad-mask-discipline** — a reduction (mean/sum/max/logsumexp/
  argmax/...) over an axis a padding producer widened, with neither a
  mask multiply/`where` nor an inline valid-slice. The canonical miss:
  `padded, mask = pad_to_bucket(obs, buckets); jnp.mean(padded)` —
  the mean silently rescales by n/bucket and every gradient built on
  it is wrong by the same factor.
- **mask-propagation** — a padded array crossing a USER function
  boundary (a jit seam, a dispatch, a helper) without its mask riding
  along and without the result being sliced back afterwards. The
  callee has no way to know which lanes are real; the mixture obs
  contract (pad * mask) and the serving act contract (`out[:n]`) are
  the two sanctioned shapes.
- **slice-before-commit** — a padded buffer reaching a commit point
  (publish/save/swap/put/enqueue/send/... — durable or
  externally-visible state) without the slice-back. Junk lanes that
  cross a commit stop being "compute junk, slice it away" and become
  someone else's wrong answer.

The runtime companion is analysis/padsan.py: these passes prove the
discipline is WRITTEN; padsan poisons the pad lanes of the real
steady-state programs and proves it HOLDS bitwise.
"""

from __future__ import annotations

import ast
from typing import Iterable

from actor_critic_tpu.analysis import shape_model
from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
)

PAD_MASK = "pad-mask-discipline"
MASK_PROP = "mask-propagation"
SLICE_COMMIT = "slice-before-commit"


def _own_calls(stmt: ast.stmt) -> Iterable[ast.Call]:
    """Call nodes in `stmt`'s OWN expressions — nested statements are
    separate entries in the scope flow, so descending into them here
    would double-visit (an `if` header owns its test, not its body)."""
    stack: list[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                continue
            if isinstance(child, ast.Call):
                yield child
            stack.append(child)


def _finding(
    check: str, mod: ModuleInfo, node: ast.AST, message: str
) -> Finding:
    return Finding(
        check=check,
        path=mod.relpath,
        line=node.lineno,
        col=node.col_offset,
        message=message,
        context=mod.enclosing_function(node),
    )


def _arg_exprs(call: ast.Call) -> list[ast.AST]:
    return list(call.args) + [kw.value for kw in call.keywords]


def _padded_arg_names(mod: ModuleInfo, call: ast.Call, env: dict) -> list[str]:
    """Padded bindings passed (possibly behind a shape-preserving
    wrapper: `program(p, jax.device_put(padded))`) as call arguments."""
    out = []
    for a in _arg_exprs(call):
        inner = shape_model._unwrap_preserving(mod, a)
        if isinstance(inner, ast.Name) and inner.id in env:
            out.append(inner.id)
    return sorted(set(out))


def _mask_rides_along(call: ast.Call, flow) -> bool:
    for a in _arg_exprs(call):
        for n in shape_model.bare_names(a):
            if n in flow.masks or shape_model.is_maskish(n):
                return True
    return False


def _result_sliced(mod: ModuleInfo, stmt: ast.stmt, call: ast.Call, flow) -> bool:
    """Whether the call's RESULT is cut back to valid lanes: inline
    (`program(p, padded)[:n]`), or via the assignment target appearing
    under a slice later in the scope (`out = program(...)`, then
    `np.asarray(out)[:n]`)."""
    for anc in mod.ancestors(call):
        if isinstance(anc, ast.stmt):
            break
        if isinstance(anc, ast.Subscript) and shape_model._contains_slice(
            anc.slice
        ):
            return True
    targets, value = shape_model._assign_parts(stmt)
    if targets is None or value is None:
        return False
    if not any(n is call for n in ast.walk(value)):
        return False
    from actor_critic_tpu.analysis.core import target_names

    names = {n for t in targets for n in target_names(t)}
    return bool(names & flow.sliced)


@register_check(
    PAD_MASK,
    "reduction over a padding-widened axis without a mask or valid-slice",
)
def check_pad_mask_discipline(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for flow in shape_model.module_flows(mod):
        if shape_model.is_producer_scope(flow.scope):
            continue
        for stmt in flow.stmts:
            env = flow.env_before[id(stmt)]
            if not env:
                continue
            for call in _own_calls(stmt):
                operand = shape_model.reduction_operand(mod, call)
                if operand is None:
                    continue
                hit = sorted(shape_model.bare_names(operand) & set(env))
                if not hit:
                    continue
                if any(kw.arg == "where" for kw in call.keywords):
                    continue  # np-style masked reduction
                if shape_model.has_mask_guard(mod, operand, flow.masks):
                    continue
                if shape_model.has_valid_slice(operand, set(hit)):
                    continue
                b = env[hit[0]]
                mask_hint = (
                    f"its mask `{b.mask}` is in scope — multiply or "
                    f"`where` it in, or reduce over `{hit[0]}[:n]`"
                    if b.mask
                    else "no mask was kept — slice back to the valid "
                    "prefix before reducing, or keep the mask from "
                    "the producer"
                )
                findings.append(
                    _finding(
                        PAD_MASK, mod, call,
                        f"reduction over `{hit[0]}`, which `{b.producer}` "
                        f"(line {b.lineno}) widened with junk lanes: the "
                        f"result silently rescales by n_valid/n_padded "
                        f"(a mean over a 7-of-128-lane pad is off 18x); "
                        f"{mask_hint}",
                    )
                )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


@register_check(
    MASK_PROP,
    "padded array crosses a function/jit seam without its mask or a "
    "slice-back",
)
def check_mask_propagation(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for flow in shape_model.module_flows(mod):
        if shape_model.is_producer_scope(flow.scope):
            continue
        for stmt in flow.stmts:
            env = flow.env_before[id(stmt)]
            if not env:
                continue
            for call in _own_calls(stmt):
                if shape_model.reduction_operand(mod, call) is not None:
                    continue  # pad-mask-discipline's domain
                dotted = shape_model.call_name(mod, call)
                if shape_model._is_lib_root(mod, dotted):
                    continue  # library math preserves lanes
                if shape_model.producer_kind(mod, call):
                    continue
                last = (dotted or "").split(".")[-1]
                if last in shape_model.COMMIT_NAMES:
                    continue  # slice-before-commit's domain
                padded_args = _padded_arg_names(mod, call, env)
                if not padded_args:
                    continue
                if _mask_rides_along(call, flow):
                    continue
                if _result_sliced(mod, stmt, call, flow):
                    continue
                b = env[padded_args[0]]
                callee = dotted or "<callee>"
                findings.append(
                    _finding(
                        MASK_PROP, mod, call,
                        f"`{padded_args[0]}` (padded by `{b.producer}`, "
                        f"line {b.lineno}) crosses `{callee}` without its "
                        f"mask, and the result is never sliced back: the "
                        f"callee cannot tell junk lanes from real ones — "
                        f"pass the mask/n_valid along, or slice the "
                        f"result to the valid prefix",
                    )
                )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


@register_check(
    SLICE_COMMIT,
    "padded buffer reaches a commit point (publish/save/enqueue/...) "
    "without slice-back",
)
def check_slice_before_commit(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for flow in shape_model.module_flows(mod):
        if shape_model.is_producer_scope(flow.scope):
            continue
        for stmt in flow.stmts:
            env = flow.env_before[id(stmt)]
            if not env:
                continue
            for call in _own_calls(stmt):
                dotted = shape_model.call_name(mod, call)
                last = (dotted or "").split(".")[-1]
                if last not in shape_model.COMMIT_NAMES:
                    continue
                for name in _padded_arg_names(mod, call, env):
                    b = env[name]
                    findings.append(
                        _finding(
                            SLICE_COMMIT, mod, call,
                            f"`{name}` (padded by `{b.producer}`, line "
                            f"{b.lineno}) reaches commit point `{last}` "
                            f"with its junk lanes intact: once committed "
                            f"(published/checkpointed/enqueued/served) "
                            f"the pad rows become downstream wrong "
                            f"answers — commit `{name}[:n]` instead",
                        )
                    )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings
