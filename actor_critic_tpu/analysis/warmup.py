"""warmup-registry: every `jax.jit` entry point in
`algos/`/`models/`/`serving/` must have an AOT warmup planner
(compile_cache.register_warmup) or an exemption with a reason
(compile_cache.EXEMPT) — ISSUE 4's lint, folded into the jaxlint
framework as a registered pass (ISSUE 5); ISSUE 10 added the serving
scan dir (the gateway's bucketed act programs register serving-side
planners).
`scripts/check_warmup_registry.py` is now a thin shim over this module.

This is the ONE pass that imports project code: it validates the scan
against the live registry, which only exists after the algo modules'
import-time `register_warmup` calls run. The import is lazy (inside the
check), so every other pass — and any `--skip warmup-registry` run —
stays import-free. The AST side (`jit_sites`) keys each site by
"<module>.<enclosing top-level function>", exactly as the original
script did, so registry keys and EXEMPT entries carry over unchanged.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Iterable, Optional

from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
)

CHECK = "warmup-registry"

SCAN_DIRS = (
    "actor_critic_tpu/algos",
    "actor_critic_tpu/models",
    "actor_critic_tpu/serving",  # gateway act programs (ISSUE 10)
    "actor_critic_tpu/data_plane",  # device ring/replay programs (ISSUE 13)
)
_EXEMPT_HOME = "actor_critic_tpu/utils/compile_cache.py"


def _sites_in_tree(tree: ast.AST) -> list[tuple[str, int]]:
    """(enclosing top-level function name, lineno) for each `jax.jit`
    reference ("<module>" at module scope) — the original
    check_warmup_registry.py traversal, kept byte-compatible in
    semantics: direct calls, decorators, and partial(jax.jit, ...) all
    contain the same `jax.jit` Attribute node."""
    sites: list[tuple[str, int]] = []

    def is_jax_jit(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "jit"
            and isinstance(node.value, ast.Name)
            and node.value.id == "jax"
        )

    def scan(node: ast.AST, enclosing: str) -> None:
        for child in ast.iter_child_nodes(node):
            name = enclosing
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and enclosing == "<module>":
                name = child.name
            if is_jax_jit(child):
                sites.append((enclosing, child.lineno))
            scan(child, name)

    scan(tree, "<module>")
    return sites


def jit_sites(path: str) -> list[tuple[str, int]]:
    """(enclosing top-level function name, lineno) per `jax.jit`
    reference in the file — the API the shim re-exports and
    tests/test_warmup_registry.py exercises."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    return _sites_in_tree(tree)


def load_registry() -> tuple[set[str], dict[str, str]]:
    """(registered keys, EXEMPT) from the live package — importing
    actor_critic_tpu.config pulls in every algo module, whose
    register_warmup calls run as import side effects."""
    import actor_critic_tpu.config  # noqa: F401 — registration side effect
    import actor_critic_tpu.data_plane  # noqa: F401 — device-plane planners
    import actor_critic_tpu.serving  # noqa: F401 — serving-side planners
    from actor_critic_tpu.utils import compile_cache

    return set(compile_cache.registered_warmups()), dict(compile_cache.EXEMPT)


def site_findings(
    sites: dict[str, list[tuple[str, int]]],
    registered: Iterable[str],
    exempt: dict[str, str],
    check_stale: bool = True,
) -> list[Finding]:
    """Pure comparison: sites keyed "<module>.<function>" mapped to
    [(relpath, lineno), ...] against the registry. Testable without any
    project import (the fixture tests inject their own registry).
    `check_stale=False` skips the stale-exemption direction — only
    sound when `sites` covers the FULL scan dirs (a partial scan
    legitimately misses the sites its exemptions name)."""
    registered = set(registered)
    findings: list[Finding] = []
    for key, locations in sorted(sites.items()):
        if key in registered or key in exempt:
            continue
        relpath, lineno = locations[0]
        findings.append(
            Finding(
                CHECK, relpath, lineno, 0,
                f"unregistered jax.jit entry point {key!r} — register an "
                "AOT warmup planner in its module "
                "(compile_cache.register_warmup) or add it to "
                "compile_cache.EXEMPT with a reason",
                key.split(".", 1)[-1],
            )
        )
    if not check_stale:
        return findings
    # Stale exemptions rot fastest (a refactor renames the function and
    # the exemption silently stops covering anything).
    for key in sorted(exempt):
        if key not in sites:
            findings.append(
                Finding(
                    CHECK, _EXEMPT_HOME, 1, 0,
                    f"stale exemption {key!r} in compile_cache.EXEMPT — "
                    "no such jax.jit site exists anymore",
                    "<module>",
                    line_text=f"EXEMPT[{key!r}]",
                )
            )
    return findings


def sites_from_modules(
    modules: Iterable[ModuleInfo],
    scan_dirs: tuple[str, ...] = SCAN_DIRS,
) -> dict[str, list[tuple[str, int]]]:
    out: dict[str, list[tuple[str, int]]] = {}
    prefixes = tuple(d.rstrip("/") + "/" for d in scan_dirs)
    for mod in modules:
        if not mod.relpath.startswith(prefixes):
            continue
        base = mod.relpath.rsplit("/", 1)[-1]
        if base == "__init__.py":
            continue
        modname = base[:-3]
        for func, lineno in _sites_in_tree(mod.tree):
            out.setdefault(f"{modname}.{func}", []).append(
                (mod.relpath, lineno)
            )
    return out


@register_check(
    CHECK,
    "jax.jit entry points in algos//models//serving/ lacking an AOT "
    "warmup registration or EXEMPT reason (first-dispatch compile "
    "returns)",
    scope="repo",
)
def check_warmup_registry(modules: list[ModuleInfo]) -> list[Finding]:
    sites = sites_from_modules(modules)
    if not sites:
        # The scan didn't cover the SCAN_DIRS (fixture runs, partial
        # paths): nothing to validate, and importing the registry would
        # be pure overhead.
        return []
    registered, exempt = load_registry()
    # An unregistered site is unregistered regardless of scan scope;
    # stale-exemption validation is only sound when the scan covered
    # EVERY file of the scan dirs (a single-file scan would otherwise
    # report every other module's exemptions as stale).
    return site_findings(
        sites, registered, exempt, check_stale=_full_scan(modules)
    )


def _full_scan(modules: list[ModuleInfo]) -> bool:
    """Whether `modules` covers every .py file of SCAN_DIRS on disk."""
    scanned = {m.relpath for m in modules}
    root = None
    for m in modules:
        if m.path.replace(os.sep, "/").endswith(m.relpath):
            root = m.path[: len(m.path) - len(m.relpath)] or "."
            break
    if root is None:
        return False
    for rel in SCAN_DIRS:
        d = os.path.join(root, rel)
        if not os.path.isdir(d):
            continue
        for fname in os.listdir(d):
            if not fname.endswith(".py") or fname == "__init__.py":
                continue
            if f"{rel}/{fname}" not in scanned:
                return False
    return True


# ---------------------------------------------------------------------------
# Original-CLI behavior, re-exported by the scripts/ shim
# ---------------------------------------------------------------------------

def collect_sites(
    repo_root: Optional[str] = None,
) -> dict[str, list[str]]:
    """registry key -> ['path:line', ...] over the scanned packages
    (the original script's API, path-string locations included)."""
    root = repo_root or _repo_root()
    out: dict[str, list[str]] = {}
    for rel in SCAN_DIRS:
        d = os.path.join(root, rel)
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py") or fname == "__init__.py":
                continue
            path = os.path.join(d, fname)
            for func, lineno in jit_sites(path):
                out.setdefault(f"{fname[:-3]}.{func}", []).append(
                    f"{os.path.relpath(path, root)}:{lineno}"
                )
    return out


def _repo_root() -> str:
    # analysis/ -> actor_critic_tpu/ -> repo
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def main(argv=None) -> int:
    """The standalone lint: exit 0 when clean, 1 with a per-site report
    on stderr otherwise (scripts/check_warmup_registry.py's contract,
    unchanged — including the multi-location "at path:line, path:line"
    report lines, which is why this mirrors site_findings() rather than
    formatting its Findings; change the coverage rule in BOTH)."""
    registered, exempt = load_registry()
    sites = collect_sites()

    problems: list[str] = []
    for key, locations in sorted(sites.items()):
        if key in registered or key in exempt:
            continue
        problems.append(
            f"UNREGISTERED jax.jit entry point {key!r} at "
            f"{', '.join(locations)} — register an AOT warmup planner "
            "in its module (compile_cache.register_warmup) or add it to "
            "compile_cache.EXEMPT with a reason"
        )
    for key in sorted(exempt):
        if key not in sites:
            problems.append(
                f"STALE exemption {key!r} in compile_cache.EXEMPT — "
                "no such jax.jit site exists anymore"
            )

    if problems:
        print("\n".join(problems), file=sys.stderr)
        print(
            f"\ncheck_warmup_registry: {len(problems)} problem(s); "
            f"{len(sites)} jit site(s), {len(registered)} registered, "
            f"{len(exempt)} exempt.",
            file=sys.stderr,
        )
        return 1
    print(
        f"check_warmup_registry: OK — {len(sites)} jax.jit site(s) in "
        f"algos//models//serving/ all covered ({len(registered)} "
        f"registered warmups, {len(exempt)} exemptions)."
    )
    return 0
