"""racesan: deterministic race sanitizer for the async actor–learner
stack (ISSUE 7 runtime side).

The static concurrency passes reason about code; this module makes the
RUNTIME deterministic enough to reproduce and detect the races they
reason about. Two tools, composable:

1. **Cooperative scheduler** (`CoopScheduler`) — real threads, but at
   most ONE runs at a time: every thread parks at yield points and a
   seeded RNG picks who proceeds, so a given seed replays its
   interleaving bit-identically (`trace` records it). Yield points come
   from `instrument()` (method-boundary yields) and `trace_locks()`
   (yields around lock acquire/release — NEVER while holding, so a
   parked thread can never hold a lock the running thread needs).
   Sweeping seeds permutes interleavings; ~100 seeded schedules over
   the queue/publisher units run in well under tier-1 noise.

   The scheduler requires NON-BLOCKING participants: a thread that
   parks inside a real `Condition.wait` while scheduled deadlocks the
   permutation (nobody else may run), so exercisers use
   `policy="drop_oldest"` queues and `get(timeout=0)` retry loops; a
   hung schedule trips `run()`'s deadline with a `RacesanError` rather
   than eating the pytest budget.

2. **Write-after-publish poisoner** — flips `flags.writeable = False`
   on numpy blocks at the handoff boundary so the racing WRITE crashes
   at its own site instead of silently corrupting gradients:
   `freeze_on_publish(publisher)` freezes the producer's retained view
   of every published params tree (in-place mutation after publish →
   ValueError where the mutation happens); `attach_queue_poisoner(q)`
   freezes leased block slots (a producer recycling a slot the learner
   still holds → ValueError in `put`'s copy) and SCRIBBLES a sentinel
   over released slots before they re-enter the pool, so a consumer
   that kept a zero-copy alias past `release` (the PR 6
   copy-on-transfer bug) reads deterministic garbage the exerciser's
   checksum catches on the very first schedule, instead of a
   corruption that needs an unlucky preemption.

The built-in exercisers (`exercise_queue`, `exercise_publisher`) are
the units tier-1 runs (tests/test_racesan.py, scripts/racesan.py):
producers/consumers with per-block fill checksums, a `consumer="alias"`
mode that reproduces the reverted PR 6 consumer, and an
`exercise_sweep` driver that aggregates seeds.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Iterable, Optional

import numpy as np


class RacesanError(RuntimeError):
    """A detected race, or a schedule that stopped making progress."""


# ---------------------------------------------------------------------------
# cooperative scheduler
# ---------------------------------------------------------------------------


class CoopScheduler:
    """Seeded cooperative scheduler: spawned threads run one at a time,
    handing control over only at yield points, where the seeded RNG
    picks the next runnable thread. Candidate order is sorted by thread
    name before each pick, so OS arrival order cannot perturb replay."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._cv = threading.Condition()
        self._local = threading.local()
        # jaxlint: thread-owned=main (spawn() is setup-phase only —
        # guarded by _started — so registration happens on the driving
        # thread before any participant thread exists; run() only reads)
        self._threads: dict[str, threading.Thread] = {}
        self._runnable: set[str] = set()
        self._live: set[str] = set()
        self._current: Optional[str] = None
        self._aborted = False
        self._started = False
        # Start barrier: no picks until EVERY participant has parked at
        # its "start" yield — otherwise the first thread the OS happens
        # to run would schedule itself to completion before the others
        # even register, collapsing every seed onto one interleaving.
        self._open = False
        self.trace: list[tuple[str, str]] = []  # (thread, yield tag)
        self.errors: list[tuple[str, BaseException]] = []

    # -- registration ------------------------------------------------------

    def spawn(self, name: str, fn: Callable[[], None]) -> None:
        """Register a participant; threads start inside run()."""
        if self._started:
            raise RacesanError("spawn() after run() started")
        if name in self._threads:
            raise RacesanError(f"duplicate participant name {name!r}")

        def body() -> None:
            self._local.name = name
            try:
                self._park_until_scheduled("start")
                fn()
            except _Aborted:
                pass
            except BaseException as e:
                with self._cv:
                    self.errors.append((name, e))
                    # A dead participant ends the schedule: abort so
                    # the survivors unwind instead of yielding against
                    # a version/progress that will never arrive.
                    self._aborted = True
            finally:
                with self._cv:
                    self._live.discard(name)
                    self._runnable.discard(name)
                    if self._current == name:
                        self._pick_next_locked()
                    self._cv.notify_all()

        self._threads[name] = threading.Thread(
            target=body, name=f"racesan-{name}", daemon=True
        )

    # -- scheduling core ---------------------------------------------------

    def yield_point(self, tag: str = "") -> None:
        """Hand control back to the scheduler. No-op on threads the
        scheduler does not manage (the main thread driving setup)."""
        name = getattr(self._local, "name", None)
        if name is None:
            return
        self._park_until_scheduled(tag)

    def _park_until_scheduled(self, tag: str) -> None:
        name = self._local.name
        with self._cv:
            if self._aborted:
                # Checked at ENTRY too: a thread the scheduler picks
                # straight back (sole survivor ping-pong) never sits in
                # the wait loop below, and must still unwind.
                raise _Aborted()
            self._runnable.add(name)
            if self._open and (
                self._current == name or self._current is None
            ):
                self._pick_next_locked()
            self._cv.notify_all()
            while self._current != name:
                if self._aborted:
                    raise _Aborted()
                self._cv.wait(0.05)
            # Record on RESUMPTION, not on park: park order at the
            # start barrier is OS arrival order, but the sequence of
            # scheduling decisions is seed-deterministic — that is the
            # replayable trace.
            self.trace.append((name, tag))

    def _pick_next_locked(self) -> None:
        candidates = sorted(self._runnable)
        if not candidates:
            self._current = None
            return
        self._current = candidates[self._rng.randrange(len(candidates))]
        self._runnable.discard(self._current)

    # -- driving -----------------------------------------------------------

    def run(self, timeout_s: float = 10.0) -> list[tuple[str, str]]:
        """Start every participant, drive the schedule to completion,
        and return the trace. Raises the first participant error, or
        RacesanError if the schedule stops making progress before
        `timeout_s` (a real blocking wait inside a scheduled region)."""
        self._started = True
        with self._cv:
            self._live = set(self._threads)
        for t in self._threads.values():
            t.start()
        deadline = time.monotonic() + timeout_s
        with self._cv:
            # Start barrier: open the schedule only once every
            # participant is parked, then make the first (seeded) pick.
            while len(self._runnable) < len(self._live):
                if time.monotonic() > deadline:
                    break
                self._cv.wait(0.05)
            self._open = True
            if self._current is None:
                self._pick_next_locked()
            self._cv.notify_all()
            while self._live:
                if time.monotonic() > deadline:
                    self._aborted = True
                    self._cv.notify_all()
                    break
                self._cv.wait(0.05)
        for t in self._threads.values():
            t.join(timeout=1.0)
        if self.errors:
            name, err = self.errors[0]
            raise err
        with self._cv:
            if self._aborted:
                raise RacesanError(
                    f"schedule (seed={self.seed}) made no progress for "
                    f"{timeout_s:.0f}s — a participant blocked outside "
                    "the scheduler (real lock wait / full blocking "
                    "queue); racesan participants must stay non-blocking"
                )
            return list(self.trace)

    # -- instrumentation ---------------------------------------------------

    def instrument(self, obj: Any, *methods: str) -> Any:
        """Wrap bound methods with enter/exit yield points (in place)."""
        for m in methods:
            orig = getattr(obj, m)

            def wrapped(*a, __orig=orig, __m=m, **kw):
                self.yield_point(f"{__m}:enter")
                try:
                    return __orig(*a, **kw)
                finally:
                    self.yield_point(f"{__m}:exit")

            setattr(obj, m, wrapped)
        return obj

    def trace_locks(self, obj: Any, *attrs: str) -> Any:
        """Replace lock/condition attributes (default `_cv`) with traced
        proxies that yield BEFORE acquire and AFTER release — the
        boundaries where interleavings differ — never while holding."""
        for attr in attrs or ("_cv",):
            setattr(
                obj, attr, _TracedLock(getattr(obj, attr), self, attr)
            )
        return obj


class _Aborted(BaseException):
    """Internal: unwinds a parked thread when the schedule aborts."""


class _TracedLock:
    """Condition/Lock proxy adding scheduler yields around the `with`
    boundary. Everything else delegates, so `notify_all`/`wait` inside
    the wrapped object keep working."""

    def __init__(self, inner: Any, sched: CoopScheduler, tag: str):
        self._inner = inner
        self._sched = sched
        self._tag = tag

    def __enter__(self):
        self._sched.yield_point(f"{self._tag}:acquire")
        return self._inner.__enter__()

    def __exit__(self, *exc):
        out = self._inner.__exit__(*exc)
        self._sched.yield_point(f"{self._tag}:release")
        return out

    def __getattr__(self, name: str):
        return getattr(self._inner, name)


# ---------------------------------------------------------------------------
# write-after-publish poisoner
# ---------------------------------------------------------------------------


def iter_array_leaves(tree: Any):
    """Yield every ndarray in a dict/list/tuple-structured tree."""
    if isinstance(tree, np.ndarray):
        yield tree
    elif isinstance(tree, dict):
        for v in tree.values():
            yield from iter_array_leaves(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from iter_array_leaves(v)


def freeze_leaves(tree: Any) -> Any:
    """writeable=False on every leaf IN PLACE: the write-after-publish
    tripwire — a racing in-place write now raises ValueError at its own
    site. Returns the tree for chaining."""
    for a in iter_array_leaves(tree):
        a.flags.writeable = False
    return tree


def thaw_leaves(tree: Any) -> Any:
    for a in iter_array_leaves(tree):
        if a.base is None:  # views regain writability through their base
            a.flags.writeable = True
    return tree


def _scribble_value(dtype: np.dtype):
    if np.issubdtype(dtype, np.floating):
        return np.finfo(dtype).min
    if np.issubdtype(dtype, np.bool_):
        return True
    if np.issubdtype(dtype, np.integer):
        return np.iinfo(dtype).min
    return 0


def scribble_leaves(tree: Any) -> Any:
    """Overwrite every leaf with its dtype's sentinel — the quarantine
    fill that turns a stale zero-copy alias into deterministic garbage
    instead of a schedule-dependent corruption."""
    for a in iter_array_leaves(tree):
        a.fill(_scribble_value(a.dtype))
    return tree


def freeze_on_publish(publisher: Any) -> Any:
    """Wrap `publisher.publish` so the PRODUCER'S RETAINED view of every
    published params tree is frozen at the publish boundary: mutating it
    in place afterwards crashes at the write site. (The hardened
    `PolicyPublisher` additionally snapshots+freezes what it STORES; the
    poisoner covers the producer's own copy, and any publisher-shaped
    object that still stores by reference.)"""
    orig = publisher.publish

    def publish(params: Any, version: int) -> None:
        freeze_leaves(params)
        return orig(params, version)

    publisher.publish = publish
    return publisher


def freeze_on_deposit(mailbox: Any) -> Any:
    """Wrap `mailbox.deposit` so the DEPOSITOR'S retained view of every
    deposited params tree is frozen at the deposit boundary — the
    mailbox-writer mirror of `freeze_on_publish`: an in-place refresh
    of a tree the learner may still be consuming crashes at the write
    site. (The hardened `ParamMailbox` additionally snapshots+freezes
    what it STORES — same contract as `PolicyPublisher.publish`.)"""
    orig = mailbox.deposit

    def deposit(params: Any, version: int, peer: int) -> bool:
        freeze_leaves(params)
        return orig(params, version, peer)

    mailbox.deposit = deposit
    return mailbox


def attach_queue_poisoner(queue: Any, scribble: bool = True) -> Any:
    """Poison a TrajQueue-shaped object (get/release protocol):

    - `get` freezes the leased block's slot arrays — any producer-side
      write into a slot the consumer still holds (a recycle-under-the-
      learner race) raises at the write site;
    - `release` thaws, then (with `scribble`) sentinel-fills the slot
      BEFORE it re-enters the pool — a consumer alias held past release
      reads the sentinel deterministically."""
    orig_get = queue.get
    orig_release = queue.release

    def get(timeout: Optional[float] = None):
        block = orig_get(timeout)
        if block is not None:
            freeze_leaves(block.arrays)
        return block

    def release(block) -> None:
        thaw_leaves(block.arrays)
        if scribble:
            scribble_leaves(block.arrays)
        orig_release(block)

    queue.get = get
    queue.release = release
    return queue


# ---------------------------------------------------------------------------
# exercisers (the tier-1 units)
# ---------------------------------------------------------------------------


def _fill_value(producer: int, block: int) -> float:
    return float(producer * 1000 + block + 1)


def exercise_queue(
    seed: int,
    producers: int = 2,
    blocks_per_producer: int = 4,
    depth: int = 2,
    shape: tuple[int, ...] = (4, 3),
    poison: bool = True,
    consumer: str = "snapshot",
    timeout_s: float = 10.0,
) -> dict:
    """One seeded schedule over a TrajQueue: P producers refill a
    preallocated buffer and put(); one consumer drains with
    `get(timeout=0)` retries and verifies every consumed block is a
    uniform fill (torn or recycled storage shows mixed values).

    `consumer="snapshot"` is the correct PR 6 consumer (np.array before
    release); `consumer="alias"` reproduces the reverted copy-on-
    transfer bug (np.asarray view read after release) — under the
    poisoner's scribble it is detected on EVERY schedule. Returns a
    report dict; detection raises RacesanError via run()."""
    from actor_critic_tpu.algos.traj_queue import TrajQueue

    if consumer not in ("snapshot", "alias"):
        raise ValueError(f"unknown consumer mode {consumer!r}")
    queue = TrajQueue(
        depth=depth, policy="drop_oldest", register_gauge=False
    )
    sched = CoopScheduler(seed)
    sched.trace_locks(queue, "_cv")
    if poison:
        attach_queue_poisoner(queue)
    report = {
        "seed": seed, "consumed": 0, "produced": 0,
        "race_detected": False, "consumer": consumer,
    }
    done = {"producers": 0}

    def producer(p: int) -> None:
        buf = np.zeros(shape, np.float32)
        for b in range(blocks_per_producer):
            buf.fill(_fill_value(p, b))
            sched.yield_point("filled")
            # jaxlint: disable=publish-aliasing (deliberate slot reuse:
            # TrajQueue.put copies into its own pool — reusing the fill
            # buffer is exactly the producer contract under test)
            queue.put({"x": buf}, version=b, actor_id=p)
        # Participants are serialized by the scheduler (one runs at a
        # time), so the shared progress dict needs no lock here.
        done["producers"] += 1

    def consume() -> None:
        expect = producers * blocks_per_producer
        while True:
            all_done = done["producers"] >= producers
            block = queue.get(timeout=0)
            if block is None:
                if all_done and len(queue) == 0:
                    return
                sched.yield_point("idle")
                continue
            if consumer == "snapshot":
                view = {k: np.array(v) for k, v in block.arrays.items()}
                queue.release(block)
            else:
                # The reverted PR 6 consumer: zero-copy view, released
                # before the read completes.
                # jaxlint: disable=publish-aliasing (this IS the bug —
                # the alias-mode consumer exists to prove the poisoner
                # catches it)
                view = {k: np.asarray(v) for k, v in block.arrays.items()}
                queue.release(block)
                sched.yield_point("post-release")
            x = view["x"]
            uniform = bool(np.all(x == x.flat[0]))
            expected = {
                _fill_value(p, b)
                for p in range(producers)
                for b in range(blocks_per_producer)
            }
            if not uniform or float(x.flat[0]) not in expected:
                report["race_detected"] = True
                raise RacesanError(
                    f"consumed block corrupted under seed {seed}: "
                    f"uniform={uniform}, value={float(x.flat[0])!r} — "
                    "slot storage was recycled/scribbled while a view "
                    "was still live (PR 6 zero-copy class)"
                )
            report["consumed"] += 1
            if report["consumed"] >= expect:
                return

    for p in range(producers):
        sched.spawn(f"producer-{p}", lambda p=p: producer(p))
    sched.spawn("consumer", consume)
    try:
        sched.run(timeout_s=timeout_s)
    finally:
        report["produced"] = queue.stats()["puts"]
        report["trace_len"] = len(sched.trace)
        queue.close()
    return report


def exercise_publisher(
    seed: int,
    versions: int = 6,
    actors: int = 2,
    shape: tuple[int, ...] = (3, 2),
    poison: bool = True,
    buggy_producer: bool = False,
    timeout_s: float = 10.0,
) -> dict:
    """One seeded schedule over a PolicyPublisher: a learner publishes
    uniform-fill params trees, actor threads read and verify uniformity.
    `buggy_producer=True` mutates the producer's RETAINED tree in place
    after publishing — the write-after-publish poisoner turns that into
    a ValueError at the mutation site on every schedule."""
    from actor_critic_tpu.algos.traj_queue import PolicyPublisher

    sched = CoopScheduler(seed)
    params0 = {"w": np.full(shape, 0.5, np.float32)}
    publisher = PolicyPublisher(params0, version=0)
    if poison:
        freeze_on_publish(publisher)
    report = {
        "seed": seed, "published": 0, "reads": 0, "race_detected": False,
    }

    def learner() -> None:
        retained = {"w": np.full(shape, 0.5, np.float32)}
        for v in range(1, versions + 1):
            if buggy_producer:
                # In-place refresh of the SAME tree that was published
                # last round — the PR 6-class hazard the poisoner
                # freezes: crashes here, at the write.
                retained["w"][...] = float(v)
            else:
                retained = {"w": np.full(shape, float(v), np.float32)}
            sched.yield_point("pre-publish")
            publisher.publish(retained, version=v)
            report["published"] = v
            sched.yield_point("published")

    def actor(i: int) -> None:
        # Read (and verify) until the final version is observed — the
        # learner always publishes it, so every schedule terminates.
        while True:
            version, params = publisher.get()
            w = params["w"]
            if not bool(np.all(w == w.flat[0])):
                report["race_detected"] = True
                raise RacesanError(
                    f"actor {i} read torn params at version {version} "
                    f"under seed {seed}"
                )
            report["reads"] += 1
            if version >= versions:
                return
            sched.yield_point("read")

    sched.spawn("learner", learner)
    for i in range(actors):
        sched.spawn(f"actor-{i}", lambda i=i: actor(i))
    sched.run(timeout_s=timeout_s)
    return report


def exercise_mailbox(
    seed: int,
    versions: int = 6,
    consumers: int = 2,
    shape: tuple[int, ...] = (3, 2),
    poison: bool = True,
    buggy_depositor: bool = False,
    timeout_s: float = 10.0,
) -> dict:
    """One seeded schedule over the multihost `ParamMailbox` (ISSUE 9):
    a writer-role thread deposits uniform-fill peer-param trees with
    increasing versions; consumer threads `take`/`peek` and verify
    uniformity (torn storage shows mixed values) and strict version
    monotonicity across takes (latest-wins must never hand a consumer
    an older tree than one it already took). `buggy_depositor=True`
    refreshes the depositor's RETAINED tree in place after depositing —
    under the poisoner that crashes at the write site on every
    schedule, the same frozen-snapshot contract
    `PolicyPublisher.publish` carries. NB: imports the multihost module
    (which pulls jax transitively); the queue/publisher exercisers stay
    jax-free."""
    from actor_critic_tpu.parallel.multihost import ParamMailbox

    sched = CoopScheduler(seed)
    mailbox = ParamMailbox()
    sched.trace_locks(mailbox, "_lock")
    if poison:
        freeze_on_deposit(mailbox)
    report = {
        "seed": seed, "deposits": 0, "takes": 0, "reads": 0,
        "race_detected": False,
    }

    def writer() -> None:
        retained = {"w": np.full(shape, 0.0, np.float32)}
        for v in range(1, versions + 1):
            if buggy_depositor:
                # In-place refresh of the tree deposited last round —
                # the hazard the freeze turns into a write-site crash.
                retained["w"][...] = float(v)
            else:
                retained = {"w": np.full(shape, float(v), np.float32)}
            sched.yield_point("pre-deposit")
            mailbox.deposit(retained, version=v, peer=0)
            report["deposits"] = v
            sched.yield_point("deposited")

    def consumer(i: int) -> None:
        last_taken = -1
        while True:
            out = mailbox.take()
            if out is not None:
                version, _, params = out
                w = params["w"]
                if not bool(np.all(w == w.flat[0])):
                    report["race_detected"] = True
                    raise RacesanError(
                        f"consumer {i} took torn mailbox params at "
                        f"version {version} under seed {seed}"
                    )
                if version <= last_taken:
                    report["race_detected"] = True
                    raise RacesanError(
                        f"mailbox handed consumer {i} version {version} "
                        f"after {last_taken} under seed {seed} — "
                        "latest-wins violated"
                    )
                last_taken = version
                report["takes"] += 1
            peeked = mailbox.peek()
            report["reads"] += 1
            if peeked is not None and peeked[0] >= versions:
                return
            sched.yield_point("idle")

    sched.spawn("mailbox-writer", writer)
    for i in range(consumers):
        sched.spawn(f"consumer-{i}", lambda i=i: consumer(i))
    sched.run(timeout_s=timeout_s)
    return report


def attach_batcher_poisoner(batcher: Any) -> Any:
    """Freeze every enqueued payload at the submit boundary (the
    serving MicroBatcher's handoff, ISSUE 10): with the correct
    copy-on-submit the frozen array is the batcher's OWN copy — nobody
    may write an enqueued payload — while with `copy=False` (the
    aliasing submit `exercise_batcher(alias_submit=True)` drives) the
    frozen array IS the client's buffer, so the client's next in-place
    refill crashes at the write site on every schedule. One poisoner,
    both contracts — the queue-slot freeze logic pointed at the
    serving handoff."""
    orig = batcher.submit

    def submit(obs, policy_id=None, copy=True):
        req = orig(obs, policy_id=policy_id, copy=copy)
        freeze_leaves(req.obs)
        return req

    batcher.submit = submit
    return batcher


def freeze_on_swap(store: Any) -> Any:
    """Wrap `store.swap` so the SWAPPER'S retained view of every
    installed params tree is frozen at the swap boundary — the
    policy-store mirror of `freeze_on_publish`: an in-place refresh of
    a tree whose copy a flush may still be serving crashes at the write
    site. (The store's install path additionally snapshots what it
    STORES via the engine's prepare_params.)"""
    orig = store.swap

    def swap(policy_id, params, version=None, prepare=True):
        freeze_leaves(params)
        return orig(policy_id, params, version=version, prepare=prepare)

    store.swap = swap
    return store


class _StubServingEngine:
    """jax-free engine stand-in for the batcher exerciser: action =
    obs[:, 0] * params['scale'][0], so every response is checkable
    against the version it claims (scale == version + 1). Carries the
    frozen-snapshot install contract the real engine's prepare_params
    (checkpoint.uncommit) provides on device."""

    max_rows = 8

    def prepare_params(self, params: Any) -> Any:
        return freeze_leaves({k: np.array(v) for k, v in params.items()})

    def act(self, params: Any, obs: np.ndarray) -> np.ndarray:
        return np.asarray(obs)[:, 0] * params["scale"][0]


def exercise_batcher(
    seed: int,
    clients: int = 2,
    requests_per_client: int = 4,
    swaps: int = 3,
    poison: bool = True,
    alias_submit: bool = False,
    buggy_swapper: bool = False,
    timeout_s: float = 10.0,
) -> dict:
    """One seeded schedule over the serving MicroBatcher + PolicyStore
    (ISSUE 10): client threads submit uniform-fill obs batches of mixed
    row counts, a swapper thread hot-swaps the resident policy between
    flushes, and the dispatcher runs as an explicit participant
    (`start=False` + `_flush_once(block=False)`). Every response must
    equal fill * (version + 1) for the VERSION IT CLAIMS (a flush that
    mixes params across a swap, or tears a payload, breaks this), and
    per-client versions must be non-decreasing (FIFO flush order).

    `alias_submit=True` reproduces the payload-aliasing submit
    (`copy=False` + client buffer reuse) — under the poisoner the
    client's refill crashes at the write site on every schedule.
    `buggy_swapper=True` mutates the swapper's RETAINED params tree in
    place after installing it — `freeze_on_swap` turns that into a
    ValueError at the mutation site."""
    from actor_critic_tpu.serving.batcher import MicroBatcher
    from actor_critic_tpu.serving.policy_store import PolicyStore

    obs_dim = 2
    sched = CoopScheduler(seed)
    store = PolicyStore()
    engine = _StubServingEngine()
    store.register("default", engine, {"scale": np.ones(1, np.float32)})
    batcher = MicroBatcher(
        store, max_wait_us=0.0, queue_limit=64, start=False
    )
    sched.trace_locks(batcher, "_cv")
    sched.trace_locks(store, "_lock")
    if poison:
        attach_batcher_poisoner(batcher)
        freeze_on_swap(store)
    report = {
        "seed": seed, "responses": 0, "swaps": 0, "scrapes": 0,
        "race_detected": False, "alias_submit": alias_submit,
    }
    progress = {"clients_done": 0, "swapper_done": False}

    def _fill(c: int, i: int) -> float:
        return float(100 * c + i + 1)

    def client(c: int) -> None:
        rows = (c % 3) + 1
        buf = np.zeros((rows, obs_dim), np.float32)
        reqs = []
        for i in range(requests_per_client):
            if alias_submit:
                # Refill the SAME buffer the previous submit aliased —
                # under the poisoner's freeze this write (i > 0) is the
                # crash site; without it, value checks catch the tear
                # on schedules that flush after the refill.
                buf.fill(_fill(c, i))
                req = batcher.submit(buf, copy=False)
            else:
                buf = np.full((rows, obs_dim), _fill(c, i), np.float32)
                req = batcher.submit(buf, copy=True)
            reqs.append((i, req))
            sched.yield_point("submitted")
        last_version = -1
        for i, req in reqs:
            while not req.done.is_set():
                sched.yield_point("awaiting")
            if req.error is not None:
                raise req.error
            actions, version = req.result
            expect = _fill(c, i) * (version + 1.0)
            ok = actions.shape == (rows,) and bool(
                np.all(actions == expect)
            )
            if not ok or version < last_version:
                report["race_detected"] = True
                raise RacesanError(
                    f"client {c} request {i}: got {actions!r} under "
                    f"version {version} (after {last_version}), expected "
                    f"uniform {expect} under seed {seed} — torn payload "
                    "or cross-version flush"
                )
            last_version = version
            report["responses"] += 1
        # Serialized by the scheduler; no lock needed (exercise_queue's
        # progress-dict convention).
        progress["clients_done"] += 1

    def swapper() -> None:
        retained = {"scale": np.ones(1, np.float32)}
        for v in range(1, swaps + 1):
            if buggy_swapper:
                # In-place refresh of the tree installed last round —
                # the frozen-snapshot install crashes this write.
                retained["scale"][...] = float(v + 1)
            else:
                retained = {"scale": np.full(1, float(v + 1), np.float32)}
            sched.yield_point("pre-swap")
            store.swap("default", retained, version=v)
            report["swaps"] = v
            sched.yield_point("swapped")
        progress["swapper_done"] = True

    def dispatcher() -> None:
        while True:
            drained = (
                progress["clients_done"] >= clients
                and progress["swapper_done"]
                and batcher.queue_depth() == 0
            )
            if drained:
                return
            batcher._flush_once(block=False)
            sched.yield_point("flushed")

    def scraper() -> None:
        # /metrics scrape as a schedule participant (ISSUE 16): the
        # exporter's reads — gauge() + per-policy histogram snapshots —
        # interleave with hot-swaps and flushes on every seeded
        # schedule. A scrape must never see a torn histogram (cumulative
        # buckets non-monotone, or +Inf bucket != count) and its
        # counters must never run backwards between scrapes.
        from actor_critic_tpu.telemetry import histo

        last_count: dict = {}
        while not (
            progress["clients_done"] >= clients
            and progress["swapper_done"]
        ):
            row = batcher.gauge()
            report["scrapes"] += 1
            for k, v in row.items():
                if not histo.is_snapshot(v):
                    continue
                cum = v["buckets"]
                if any(b < a for b, a in zip(cum[1:], cum)) or (
                    cum[-1] != v["count"]
                ):
                    report["race_detected"] = True
                    raise RacesanError(
                        f"scrape saw torn histogram {k}: buckets {cum} "
                        f"count {v['count']} under seed {seed}"
                    )
                if v["count"] < last_count.get(k, 0):
                    report["race_detected"] = True
                    raise RacesanError(
                        f"scrape saw histogram {k} count run backwards "
                        f"({last_count[k]} -> {v['count']}) under "
                        f"seed {seed}"
                    )
                last_count[k] = v["count"]
            sched.yield_point("scraped")

    for c in range(clients):
        sched.spawn(f"client-{c}", lambda c=c: client(c))
    sched.spawn("swapper", swapper)
    sched.spawn("dispatcher", dispatcher)
    sched.spawn("scraper", scraper)
    try:
        sched.run(timeout_s=timeout_s)
    finally:
        report["queue_depth"] = batcher.queue_depth()
        batcher.close(timeout=0.1)
    return report


def attach_ring_poisoner(ring: Any) -> Any:
    """Leased-slot write tripwire for the DEVICE trajectory ring
    (ISSUE 13; `data_plane/ring.py`). The ring's blocks live in HBM, so
    the numpy `writeable=False` freeze cannot reach them — but every
    overwrite passes through exactly one choke point, the slot claim:
    wrap `_claim_slot_locked` so a put that claims a slot the learner
    still holds LEASED crashes at the claim site. The correct ring
    never trips it (leased slots are excluded from free/reclaim by
    construction); the `buggy_writer` revert in `exercise_device_ring`
    — drop-oldest reclaiming the lease like a pending block — trips it
    on every schedule where the writer meets a held lease."""
    orig = ring._claim_slot_locked

    def claim():
        slot = orig()
        if slot is not None and slot in ring._leased:
            raise RacesanError(
                f"device-ring enqueue claimed LEASED slot {slot} — the "
                "learner's in-flight gather would read the overwrite "
                "(write-after-publish, device-plane class)"
            )
        return slot

    ring._claim_slot_locked = claim
    return ring


def exercise_device_ring(
    seed: int,
    producers: int = 2,
    blocks_per_producer: int = 3,
    depth: int = 2,
    poison: bool = True,
    consumer: str = "leased",
    buggy_writer: bool = False,
    timeout_s: float = 30.0,
) -> dict:
    """One seeded schedule over the REAL `DeviceTrajRing`: producer
    threads enqueue uniform-fill blocks (encoded host-side, scattered
    into HBM by the donated enqueue program), a consumer leases slots,
    gathers them back off the device, and verifies each block is the
    uniform fill its lease's version promises — actor-enqueue vs
    learner-gather interleavings, scheduled one thread at a time.

    `consumer="released"` reproduces the alias-class bug: the consumer
    RELEASES the slot before reading it, so a drop-oldest overwrite of
    the freed slot lands under its read — caught by the value check on
    schedules where the writer runs inside the window.
    `buggy_writer=True` reverts the lease protection (drop-oldest may
    reclaim a LEASED slot, as if it were merely pending) — the
    poisoner's claim-site check catches it on every schedule where a
    full ring meets a held lease. NB: dispatches real jitted programs;
    first call per process pays one enqueue compile."""
    import jax

    from actor_critic_tpu.data_plane import ring as dp_ring

    if consumer not in ("leased", "released"):
        raise ValueError(f"unknown consumer mode {consumer!r}")
    if buggy_writer:
        # Depth 1 makes the hazard unconditional: while the consumer
        # holds the single slot's lease, EVERY producer put finds free
        # and pending empty, and the reverted claim reaches for the
        # leased slot — the poisoner then fires on every schedule
        # instead of only those where drop-oldest pressure lines up.
        depth = 1
    block_spec = {"x": jax.ShapeDtypeStruct((2, 2), np.float32)}
    ring = dp_ring.DeviceTrajRing(
        depth=depth, block_spec=block_spec, codec="fp32",
        policy="drop_oldest", register_gauge=False,
    )
    if buggy_writer:
        # Reverted lease protection: treat a leased slot like a pending
        # one — the pre-ISSUE 13 hazard the poisoner exists to catch.
        orig_claim = ring._claim_slot_locked

        def claim_ignoring_leases():
            slot = orig_claim()
            if slot is None and ring._leased:
                slot = next(iter(sorted(ring._leased)))
                ring._drops_full += 1
            return slot

        ring._claim_slot_locked = claim_ignoring_leases
    sched = CoopScheduler(seed)
    sched.trace_locks(ring, "_cv")
    if poison:
        attach_ring_poisoner(ring)
    report = {
        "seed": seed, "consumed": 0, "race_detected": False,
        "consumer": consumer,
    }
    done = {"producers": 0}
    expect = {
        float(_fill_value(p, b))
        for p in range(producers)
        for b in range(blocks_per_producer)
    }

    def producer(p: int) -> None:
        buf = np.zeros((2, 2), np.float32)
        payload = {"x": buf}
        for b in range(blocks_per_producer):
            fill = _fill_value(p, b)
            buf.fill(fill)
            sched.yield_point("filled")
            while True:
                # jaxlint: disable=publish-aliasing (deliberate buffer
                # reuse: DeviceTrajRing.put ENCODES — copies — the
                # arrays host-side before the device put, so reusing
                # the fill buffer is the producer contract under test)
                if ring.put(payload, int(fill), p, timeout=0):
                    break
                sched.yield_point("put-retry")
        done["producers"] += 1  # serialized by the scheduler

    def consume() -> None:
        total = producers * blocks_per_producer
        while True:
            all_done = done["producers"] >= producers
            lease = ring.get(timeout=0)
            if lease is None:
                if all_done and len(ring) == 0:
                    return
                sched.yield_point("idle")
                continue
            if consumer == "released":
                # The bug: the slot re-enters the writable pool while
                # this thread still intends to read it.
                ring.release(lease)
                sched.yield_point("post-release")
            x = np.asarray(
                ring.run(lambda state: state.storage["x"][lease.slot])
            )
            uniform = bool(np.all(x == x.flat[0]))
            value = float(x.flat[0])
            if not uniform or value != float(lease.version) or (
                value not in expect
            ):
                report["race_detected"] = True
                raise RacesanError(
                    f"device-ring block corrupted under seed {seed}: "
                    f"uniform={uniform}, value={value!r}, lease version "
                    f"{lease.version} — a slot was overwritten under a "
                    "live read (device-plane zero-copy class)"
                )
            if consumer == "leased":
                ring.release(lease)
            report["consumed"] += 1
            if report["consumed"] >= total:
                return

    for p in range(producers):
        sched.spawn(f"producer-{p}", lambda p=p: producer(p))
    sched.spawn("consumer", consume)
    try:
        sched.run(timeout_s=timeout_s)
    finally:
        report["produced"] = ring.stats()["puts"]
        report["trace_len"] = len(sched.trace)
        ring.close()
    return report


def exercise_sweep(
    seeds: Iterable[int],
    scenario: Callable[[int], dict],
) -> dict:
    """Run `scenario(seed)` across seeds; aggregate. Detection raises —
    a clean sweep returns counts tier-1 can assert on."""
    reports = []
    for seed in seeds:
        reports.append(scenario(seed))
    return {
        "schedules": len(reports),
        "consumed": sum(r.get("consumed", 0) for r in reports),
        "reads": sum(r.get("reads", 0) for r in reports),
        "published": sum(r.get("published", 0) for r in reports),
        "deposits": sum(r.get("deposits", 0) for r in reports),
        "takes": sum(r.get("takes", 0) for r in reports),
        "responses": sum(r.get("responses", 0) for r in reports),
        "swaps": sum(r.get("swaps", 0) for r in reports),
        "scrapes": sum(r.get("scrapes", 0) for r in reports),
        "races": sum(1 for r in reports if r.get("race_detected")),
    }


def quick_profile(schedules: int = 100, seed0: int = 0) -> dict:
    """The tier-1 fast profile: `schedules` seeded interleavings split
    across the queue (snapshot consumer, poisoned), publisher (correct
    producer, poisoned), multihost param-mailbox (correct depositor,
    poisoned), and serving micro-batcher (copy-on-submit, poisoned,
    request/flush/hot-swap interleavings — ISSUE 10) units — every
    schedule must sweep clean. ~100 schedules run in a few seconds on
    one CPU core."""
    quarter = max(schedules // 4, 1)
    q = exercise_sweep(
        range(seed0, seed0 + quarter),
        lambda s: exercise_queue(s, poison=True, consumer="snapshot"),
    )
    p = exercise_sweep(
        range(seed0, seed0 + quarter),
        lambda s: exercise_publisher(s, poison=True),
    )
    m = exercise_sweep(
        range(seed0, seed0 + quarter),
        lambda s: exercise_mailbox(s, poison=True),
    )
    b = exercise_sweep(
        range(seed0, seed0 + (schedules - 3 * quarter)),
        lambda s: exercise_batcher(s, poison=True),
    )
    return {
        "schedules": (
            q["schedules"] + p["schedules"] + m["schedules"]
            + b["schedules"]
        ),
        "queue": q,
        "publisher": p,
        "mailbox": m,
        "batcher": b,
        "races": q["races"] + p["races"] + m["races"] + b["races"],
    }
