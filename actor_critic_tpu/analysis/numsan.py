"""numsan: deterministic NaN/Inf/saturation fault sanitizer (ISSUE 14
runtime half).

racesan made THREAD interleavings seeded and replayable, fleetsan
lifted that to PROCESSES; this module applies the same contract to the
NUMERICS dimension. Each seeded schedule poisons EXACTLY ONE designated
leaf element — rewards, observations, params (the post-update tree a
divergence produces), quant stats, or a published snapshot — with one
fault from the poison menu:

    nan        quiet NaN
    inf/-inf   ±infinity
    denormal   an f32 subnormal (~1e-42): must be TOLERATED everywhere
               (proves the guards do not over-fire)
    saturate   an int8/f16-saturating magnitude (3.7e5): codecs must
               clip to the representable range, never wrap or overflow

inside the REAL objects — `ppo.make_host_update_step` (the actual
jitted update program), the `quantize`/`data_plane.codecs` codec pair,
`PolicyPublisher`/`write_params`/`read_params`/`PolicyStore.swap`, and
a real orbax `Checkpointer` — and asserts the stack's NAMED response:

- **divergence event** — a nonfinite reward/obs poison must surface as
  a non-finite loss that fires `DivergenceMonitor`'s `non_finite_loss`
  (the telemetry forensic record);
- **checkpoint refusal** — `Checkpointer.save` of a poisoned state
  raises `NonFiniteError` and the previous step stays latest/restorable;
- **publish/mailbox/swap rejection** — `PolicyPublisher.publish`,
  `multihost.write_params`, and `PolicyStore.swap` refuse the snapshot
  and the previous good one stays visible;
- **codec saturation** — int8 codecs emit exactly ±127 (bool8: {0,1},
  f16: ±65504) for saturating/infinite inputs, encode NaN to the
  deterministic midpoint, and the numpy mirror stays bit-identical to
  the device codec under poison.

A failed assertion raises `NumSanError` (the sanitizer detecting a
missing/reverted guard); a clean schedule appends to `report["trace"]`,
which is bit-identical per seed (same seed → same poisons, same leaf
positions, same observed values — replay a named seed to reproduce).
**Reverted-guard modes** prove the detectors work: `revert="publish"` /
`revert="checkpoint"` / `revert="bf16-update"` no-op
`numguard.check_finite` (the one seam every production gate routes
through) and numsan must then CATCH the poison on the far side of the
sink; `revert="codec-wrap"` runs the pre-fix encoder
(`round(x).astype(int8)` — wraps) against the saturation checker. All
are caught deterministically on every schedule and regression-tested.

ISSUE 19 adds the **bf16-update schedule**: the `--update-dtype bf16`
program (`bf16_compute=True` — bf16 matmuls, fp32 master params /
optimizer state / loss accumulators) must produce a FINITE loss on
clean data, and its poisoned post-update params must hit the same
publish/checkpoint/serve wall as the fp32 plane's.

`quick_profile` is the fixed-seed sweep `scripts/tier1.sh` runs between
fleetsan and pytest, under its own timeout.
"""

from __future__ import annotations

import math
import random
import tempfile
from typing import Iterable, Optional

import numpy as np

from actor_critic_tpu.utils import numguard

POISONS = ("nan", "inf", "-inf", "denormal", "saturate")
NONFINITE = ("nan", "inf", "-inf")
_VALUES = {
    "nan": float("nan"),
    "inf": float("inf"),
    "-inf": float("-inf"),
    "denormal": 1e-42,
    "saturate": 3.7e5,
}


class NumSanError(RuntimeError):
    """A guard failed to block (or tolerate) a poison — or a reverted
    guard's leak was detected (the sanitizer working)."""


def _flat_float_leaves(tree, path=""):
    """[(path, array)] of float leaves, sorted by path — the stable
    enumeration the seeded leaf choice indexes into."""
    out = []
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.extend(_flat_float_leaves(tree[k], f"{path}/{k}"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.extend(_flat_float_leaves(v, f"{path}[{i}]"))
    elif hasattr(tree, "dtype") and np.issubdtype(
        np.dtype(tree.dtype), np.floating
    ):
        out.append((path, tree))
    return out


def _poison_tree(tree, rng: random.Random, poison: str):
    """Poison ONE element of ONE float leaf in a (mutable-numpy) tree;
    returns (leaf_path, flat_index). The tree must hold writable numpy
    arrays."""
    leaves = _flat_float_leaves(tree)
    if not leaves:
        raise ValueError("no float leaves to poison")
    path, arr = leaves[rng.randrange(len(leaves))]
    idx = rng.randrange(max(arr.size, 1))
    arr.reshape(-1)[idx] = _VALUES[poison]
    return path, idx


class _guards_disabled:
    """Context manager that no-ops `numguard.check_finite` — the
    reverted-guard mode. Every production gate routes through this one
    module attribute, so one seam reverts them all."""

    def __enter__(self):
        self._orig = numguard.check_finite
        numguard.check_finite = lambda *a, **k: None
        return self

    def __exit__(self, *exc):
        numguard.check_finite = self._orig


# ---------------------------------------------------------------------------
# update exerciser: the real jitted PPO update + DivergenceMonitor
# ---------------------------------------------------------------------------

_UPDATE_FIXTURE = None


def _update_fixture():
    """One tiny REAL host-PPO update program, compiled once per process
    and shared by every schedule (the poison varies, the program does
    not — exactly the production shape)."""
    global _UPDATE_FIXTURE
    if _UPDATE_FIXTURE is not None:
        return _UPDATE_FIXTURE
    import jax

    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs.jax_env import EnvSpec

    spec = EnvSpec(
        obs_shape=(4,), action_dim=2, discrete=True,
        obs_dtype=np.float32, can_truncate=True,
    )
    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=4, epochs=1, num_minibatches=1,
        hidden=(8,),
    )
    key = jax.random.key(0)
    params, opt_state = ppo.init_host_params(spec, cfg, key)
    update = ppo.make_host_update_step(spec, cfg)
    _UPDATE_FIXTURE = (cfg, params, opt_state, update, key)
    return _UPDATE_FIXTURE


def _synth_block(cfg, nprng: np.random.Generator) -> dict:
    T, E = cfg.rollout_steps, cfg.num_envs
    return {
        "obs": nprng.normal(size=(T, E, 4)).astype(np.float32),
        "action": nprng.integers(0, 2, (T, E)),
        "log_prob": (nprng.normal(size=(T, E)) * 0.1 - 0.69).astype(
            np.float32
        ),
        "value": nprng.normal(size=(T, E)).astype(np.float32),
        "reward": np.ones((T, E), np.float32),
        "done": np.zeros((T, E), np.float32),
        "terminated": np.zeros((T, E), np.float32),
        "final_obs": nprng.normal(size=(T, E, 4)).astype(np.float32),
        "last_obs": nprng.normal(size=(E, 4)).astype(np.float32),
    }


def exercise_update(seed: int, rounds: int = 2) -> dict:
    """Seeded poisons (rewards/obs) through the REAL update program:
    nonfinite poisons must surface as a non-finite loss that fires the
    DivergenceMonitor's `non_finite_loss`; denormal/saturate poisons
    must leave the loss finite and the monitor quiet."""
    import jax

    from actor_critic_tpu.telemetry.health import DivergenceMonitor

    cfg, params, opt_state, update, key = _update_fixture()
    rng = random.Random(seed)
    report = {
        "seed": seed, "scenario": "update", "trace": [],
        "divergence_events": 0, "violations": 0,
    }
    for round_ in range(rounds):
        block = _synth_block(cfg, np.random.default_rng(seed * 31 + round_))
        target = ("reward", "obs")[rng.randrange(2)]
        # Per-target poison menus: an ±inf OBSERVATION is squashed
        # finite by the tanh torso (tanh(±inf) = ±1 — measured, and
        # worth knowing: the network itself is an inf-but-not-nan
        # guard), so only nan survives the forward pass from obs;
        # rewards flow linearly through GAE and carry all three.
        menu = POISONS if target == "reward" else (
            "nan", "denormal", "saturate"
        )
        poison = menu[rng.randrange(len(menu))]
        _, idx = _poison_tree({target: block[target]}, rng, poison)
        _p, _o, metrics = update(
            params, opt_state, block["obs"], block["action"],
            block["log_prob"], block["value"], block["reward"],
            block["done"], block["terminated"], block["final_obs"],
            block["last_obs"], key,
        )
        loss = float(jax.device_get(metrics["loss"]))
        events: list = []
        monitor = DivergenceMonitor(
            lambda kind, **f: events.append((kind, f))
        )
        monitor.observe(round_, {"loss": loss})
        fired = [
            f for kind, f in events
            if kind == "divergence" and f.get("reason") == "non_finite_loss"
        ]
        if poison in NONFINITE:
            if math.isfinite(loss):
                report["violations"] += 1
                raise NumSanError(
                    f"seed {seed}: {poison} poison of {target}[{idx}] "
                    f"vanished — the loss came out finite ({loss!r}); "
                    "the update program is masking non-finites instead "
                    "of surfacing them to the DivergenceMonitor"
                )
            if not fired:
                report["violations"] += 1
                raise NumSanError(
                    f"seed {seed}: non-finite loss {loss!r} did NOT "
                    "fire DivergenceMonitor non_finite_loss — the "
                    "divergence guard is reverted/blind"
                )
            report["divergence_events"] += 1
        else:
            if not math.isfinite(loss):
                report["violations"] += 1
                raise NumSanError(
                    f"seed {seed}: tolerated poison {poison} of "
                    f"{target}[{idx}] made the loss non-finite "
                    f"({loss!r}) — denormal/large-but-finite inputs "
                    "must train through"
                )
            if fired:
                report["violations"] += 1
                raise NumSanError(
                    f"seed {seed}: DivergenceMonitor fired on a finite "
                    f"loss {loss!r} — the guard over-fires"
                )
        report["trace"].append(
            (round_, target, poison, idx, repr(loss),
             "divergence" if fired else "clean")
        )
    return report


# ---------------------------------------------------------------------------
# publish exerciser: PolicyPublisher + file mailbox + PolicyStore.swap
# ---------------------------------------------------------------------------


class _StubEngine:
    max_rows = 8

    def prepare_params(self, params):
        out = {k: np.array(v) for k, v in params.items()}
        for v in out.values():
            v.flags.writeable = False
        return out

    def act(self, params, obs):
        return np.asarray(obs)[:, 0] * params["w"].flat[0]


def _params_tree(fill: float = 1.0) -> dict:
    return {
        "w": np.full((3, 2), fill, np.float32),
        "b": np.full((2,), fill, np.float32),
    }


def exercise_publish(seed: int, revert: bool = False) -> dict:
    """Seeded poisons against the three publish-shaped guards, driving
    the REAL objects: `PolicyPublisher.publish`, `write_params` (with a
    `read_params` read-back of the mailbox file), and
    `PolicyStore.swap`. Nonfinite → all three refuse and the previous
    snapshot stays visible; denormal → all three accept (no
    over-firing). With `revert=True` the gates are no-op'd and the
    checker must CATCH the poison on the far side of each sink."""
    from actor_critic_tpu.algos.traj_queue import PolicyPublisher
    from actor_critic_tpu.parallel.multihost import (
        read_params,
        write_params,
    )
    from actor_critic_tpu.serving.policy_store import PolicyStore

    rng = random.Random(seed)
    # Reverted-guard mode draws from the nonfinite menu only: every
    # schedule must detect the leak (a denormal leaks nothing).
    menu = NONFINITE if revert else (NONFINITE + ("denormal",))
    poison = menu[rng.randrange(len(menu))]
    report = {
        "seed": seed, "scenario": "publish", "poison": poison,
        "trace": [], "rejections": 0, "violations": 0,
    }
    good = _params_tree(0.5)
    poisoned = _params_tree(0.5)
    path, idx = _poison_tree(poisoned, rng, poison)

    publisher = PolicyPublisher(good, version=1)
    store = PolicyStore()
    store.register("default", _StubEngine(), good, version=1)
    with tempfile.TemporaryDirectory(prefix="numsan_") as mailbox:
        write_params(mailbox, 0, 1, good)

        def attempt(name, fn):
            """Run one poisoned commit; returns 'rejected'/'accepted'."""
            try:
                fn()
            except numguard.NonFiniteError:
                report["rejections"] += 1
                return "rejected"
            return "accepted"

        sinks = [
            ("publish", lambda: publisher.publish(poisoned, 2)),
            ("write_params", lambda: write_params(
                mailbox, 0, 2, poisoned
            )),
            ("swap", lambda: store.swap("default", poisoned, version=2)),
        ]
        if revert:
            with _guards_disabled():
                for name, fn in sinks:
                    outcome = attempt(name, fn)
                    report["trace"].append((name, poison, path, idx, outcome))
            # The detector: with the gates reverted, a nonfinite poison
            # must now be CAUGHT on the far side of each sink.
            if poison in NONFINITE:
                leaked = []
                if numguard.nonfinite_leaves(publisher.get()[1]):
                    leaked.append("publisher")
                out = read_params(mailbox, 0, good)
                if out is not None and numguard.nonfinite_leaves(out[1]):
                    leaked.append("mailbox")
                if numguard.nonfinite_leaves(
                    dict(store.get("default").params)
                ):
                    leaked.append("store")
                if leaked:
                    report["violations"] += 1
                    raise NumSanError(
                        f"seed {seed}: REVERTED GUARD DETECTED — "
                        f"{poison} poison at {path}[{idx}] reached "
                        f"{'/'.join(leaked)} with check_finite no-op'd "
                        "(the production gates are the only thing "
                        "standing between a diverged learner and the "
                        "fleet/clients)"
                    )
            return report
        for name, fn in sinks:
            outcome = attempt(name, fn)
            report["trace"].append((name, poison, path, idx, outcome))
            if poison in NONFINITE and outcome != "rejected":
                report["violations"] += 1
                raise NumSanError(
                    f"seed {seed}: {name} ACCEPTED a {poison}-poisoned "
                    f"tree ({path}[{idx}]) — the finiteness gate is "
                    "missing/reverted"
                )
            if poison == "denormal" and outcome != "accepted":
                report["violations"] += 1
                raise NumSanError(
                    f"seed {seed}: {name} rejected a denormal — the "
                    "gate over-fires (only nan/±inf may refuse)"
                )
        # After a refusal the previous good snapshots must still be
        # visible everywhere (denormal legitimately published v2 — the
        # invariant there is just that nothing non-finite is stored).
        version, params = publisher.get()
        if numguard.nonfinite_leaves(params) or (
            poison in NONFINITE and version != 1
        ):
            raise NumSanError(
                f"seed {seed}: publisher lost its good snapshot"
            )
        out = read_params(mailbox, 0, good)
        if poison in NONFINITE and (
            out is None or out[0] != 1
            or numguard.nonfinite_leaves(out[1])
        ):
            raise NumSanError(
                f"seed {seed}: mailbox lost its good snapshot"
            )
        handle = store.get("default")
        if poison in NONFINITE and handle.version != 1:
            raise NumSanError(
                f"seed {seed}: store swapped despite the refusal"
            )
    return report


# ---------------------------------------------------------------------------
# checkpoint exerciser: a real orbax Checkpointer (quant stats ride too)
# ---------------------------------------------------------------------------


def exercise_checkpoint(seed: int, revert: bool = False) -> dict:
    """Seeded poisons against the checkpoint commit gate: a REAL
    `Checkpointer` saves a finite state at step 0; the poisoned state
    (params OR the quant-stats leaves riding the same tree) must refuse
    at step 1 with step 0 still latest and restorable. `revert=True`
    no-ops the gate and the checker must detect the poisoned commit in
    the restored tree."""
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    rng = random.Random(seed)
    menu = NONFINITE if revert else (NONFINITE + ("denormal",))
    poison = menu[rng.randrange(len(menu))]
    report = {
        "seed": seed, "scenario": "checkpoint", "poison": poison,
        "trace": [], "refusals": 0, "violations": 0,
    }
    state = {
        "params": _params_tree(0.25),
        "quant_stats": {
            "mean": np.zeros((4,), np.float32),
            "scale": np.full((4,), 1e-6, np.float32),
        },
    }
    with tempfile.TemporaryDirectory(prefix="numsan_ckpt_") as root:
        with Checkpointer(root, max_to_keep=2) as ckpt:
            ckpt.save(0, state, force=True)
            ckpt.wait()
            path, idx = _poison_tree(state, rng, poison)
            outcome = "accepted"
            if revert:
                with _guards_disabled():
                    ckpt.save(1, state, force=True)
                    ckpt.wait()
            else:
                try:
                    ckpt.save(1, state, force=True)
                    ckpt.wait()
                except numguard.NonFiniteError:
                    outcome = "refused"
                    report["refusals"] += 1
            report["trace"].append((poison, path, idx, outcome))
            latest = ckpt.latest_step()
            template = {
                "params": _params_tree(0.0),
                "quant_stats": {
                    "mean": np.zeros((4,), np.float32),
                    "scale": np.zeros((4,), np.float32),
                },
            }
            restored = ckpt.restore(template, latest)
            bad = numguard.nonfinite_leaves(
                {k: np.asarray(v) for k, v in
                 {"p": restored["params"]["w"],
                  "s": restored["quant_stats"]["scale"],
                  "m": restored["quant_stats"]["mean"],
                  "b": restored["params"]["b"]}.items()}
            )
            if revert and poison in NONFINITE:
                if latest == 1 and bad:
                    report["violations"] += 1
                    raise NumSanError(
                        f"seed {seed}: REVERTED GUARD DETECTED — "
                        f"{poison} poison at {path}[{idx}] COMMITTED "
                        "at step 1 and restores poisoned (every "
                        "future resume now inherits it)"
                    )
                return report
            if poison in NONFINITE:
                if outcome != "refused":
                    report["violations"] += 1
                    raise NumSanError(
                        f"seed {seed}: checkpoint COMMITTED a {poison}-"
                        f"poisoned state ({path}[{idx}]) — the commit "
                        "gate is missing/reverted"
                    )
                if latest != 0 or bad:
                    report["violations"] += 1
                    raise NumSanError(
                        f"seed {seed}: refusal did not preserve the "
                        f"previous good checkpoint (latest={latest})"
                    )
            else:
                if outcome != "accepted" or latest != 1:
                    report["violations"] += 1
                    raise NumSanError(
                        f"seed {seed}: checkpoint refused a denormal — "
                        "the gate over-fires"
                    )
    return report


# ---------------------------------------------------------------------------
# bf16-update exerciser: the --update-dtype bf16 program feeds the gates
# ---------------------------------------------------------------------------

_BF16_UPDATE_FIXTURE = None


def _bf16_update_fixture():
    """The `--update-dtype bf16` twin of `_update_fixture`: the same
    tiny REAL program with `bf16_compute=True` (bf16 matmuls, fp32
    master params / optimizer state / loss accumulators), compiled once
    per process."""
    global _BF16_UPDATE_FIXTURE
    if _BF16_UPDATE_FIXTURE is not None:
        return _BF16_UPDATE_FIXTURE
    import jax

    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs.jax_env import EnvSpec

    spec = EnvSpec(
        obs_shape=(4,), action_dim=2, discrete=True,
        obs_dtype=np.float32, can_truncate=True,
    )
    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=4, epochs=1, num_minibatches=1,
        hidden=(8,), bf16_compute=True,
    )
    key = jax.random.key(0)
    params, opt_state = ppo.init_host_params(spec, cfg, key)
    update = ppo.make_host_update_step(spec, cfg)
    _BF16_UPDATE_FIXTURE = (cfg, params, opt_state, update, key)
    return _BF16_UPDATE_FIXTURE


def _numpy_tree(tree):
    """Writable-numpy deep copy of a params pytree (nested dicts of
    arrays) — the shape `_poison_tree` mutates."""
    if isinstance(tree, dict):
        return {k: _numpy_tree(v) for k, v in tree.items()}
    return np.array(tree)


class _TreeStubEngine:
    """`_StubEngine` for NESTED (real-network) param trees: prepare
    flattens to a path->array dict so the far side of
    `PolicyStore.swap` stays leaf-checkable under the reverted-guard
    mode."""

    max_rows = 8

    def prepare_params(self, params):
        out = {p: np.array(a) for p, a in _flat_float_leaves(params)}
        for v in out.values():
            v.flags.writeable = False
        return out

    def act(self, params, obs):
        first = sorted(params)[0]
        return np.asarray(obs)[:, 0] * float(params[first].flat[0])


def exercise_bf16_update(seed: int, revert: bool = False) -> dict:
    """ISSUE 19's bf16-update poison schedule. First the REAL
    `bf16_compute=True` update program runs on a CLEAN block and its
    loss must come out finite (the fp32-accumulator discipline: bf16
    matmuls may not manufacture non-finites at fixture scale). Then the
    POST-UPDATE fp32 master params — the tree a bf16 divergence would
    hand downstream — are poisoned, and the same commit gates the fp32
    plane relies on must refuse them at every sink: PUBLISHED
    (`PolicyPublisher.publish`, `write_params`), CHECKPOINTED (a real
    `Checkpointer`), and SERVED (`PolicyStore.swap`). Denormals pass
    everywhere (no over-firing). `revert=True` no-ops the gates and the
    checker must CATCH the poison on the far side of each sink."""
    import jax

    from actor_critic_tpu.algos.traj_queue import PolicyPublisher
    from actor_critic_tpu.parallel.multihost import (
        read_params,
        write_params,
    )
    from actor_critic_tpu.serving.policy_store import PolicyStore
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    rng = random.Random(seed)
    menu = NONFINITE if revert else (NONFINITE + ("denormal",))
    poison = menu[rng.randrange(len(menu))]
    report = {
        "seed": seed, "scenario": "bf16-update", "poison": poison,
        "trace": [], "rejections": 0, "refusals": 0, "violations": 0,
    }
    cfg, params, opt_state, update, key = _bf16_update_fixture()
    block = _synth_block(cfg, np.random.default_rng(seed * 47 + 1))
    new_params, _, metrics = update(
        params, opt_state, block["obs"], block["action"],
        block["log_prob"], block["value"], block["reward"],
        block["done"], block["terminated"], block["final_obs"],
        block["last_obs"], key,
    )
    loss = float(jax.device_get(metrics["loss"]))
    if not math.isfinite(loss):
        report["violations"] += 1
        raise NumSanError(
            f"seed {seed}: the bf16 update produced a non-finite loss "
            f"({loss!r}) on CLEAN data — the fp32-accumulator "
            "discipline is missing/reverted"
        )
    good = _numpy_tree(jax.device_get(new_params))
    poisoned = _numpy_tree(good)
    path, idx = _poison_tree(poisoned, rng, poison)

    publisher = PolicyPublisher(good, version=1)
    store = PolicyStore()
    store.register("default", _TreeStubEngine(), good, version=1)
    with tempfile.TemporaryDirectory(
        prefix="numsan_bf16_mbox_"
    ) as mailbox, tempfile.TemporaryDirectory(
        prefix="numsan_bf16_ckpt_"
    ) as ckroot:
        write_params(mailbox, 0, 1, good)
        with Checkpointer(ckroot, max_to_keep=2) as ckpt:
            ckpt.save(0, {"params": good}, force=True)
            ckpt.wait()

            def attempt(name, fn, counter):
                try:
                    fn()
                except numguard.NonFiniteError:
                    report[counter] += 1
                    return "rejected"
                return "accepted"

            def save_poisoned():
                ckpt.save(1, {"params": poisoned}, force=True)
                ckpt.wait()

            sinks = [
                ("publish",
                 lambda: publisher.publish(poisoned, 2), "rejections"),
                ("write_params",
                 lambda: write_params(mailbox, 0, 2, poisoned),
                 "rejections"),
                ("swap",
                 lambda: store.swap("default", poisoned, version=2),
                 "rejections"),
                ("checkpoint", save_poisoned, "refusals"),
            ]
            if revert:
                with _guards_disabled():
                    for name, fn, counter in sinks:
                        outcome = attempt(name, fn, counter)
                        report["trace"].append(
                            (name, poison, path, idx, outcome)
                        )
                # The detector: gates no-op'd, so the nonfinite poison
                # must now be CAUGHT past every sink.
                leaked = []
                if numguard.nonfinite_leaves(publisher.get()[1]):
                    leaked.append("publisher")
                out = read_params(mailbox, 0, good)
                if out is not None and numguard.nonfinite_leaves(out[1]):
                    leaked.append("mailbox")
                if numguard.nonfinite_leaves(
                    dict(store.get("default").params)
                ):
                    leaked.append("store")
                if ckpt.latest_step() == 1 and numguard.nonfinite_leaves(
                    ckpt.restore({"params": _numpy_tree(good)}, 1)[
                        "params"
                    ]
                ):
                    leaked.append("checkpoint")
                if leaked:
                    report["violations"] += 1
                    raise NumSanError(
                        f"seed {seed}: REVERTED GUARD DETECTED — "
                        f"{poison} poison at {path}[{idx}] of the bf16 "
                        f"update's params reached {'/'.join(leaked)} "
                        "with check_finite no-op'd (a diverged bf16 "
                        "learner must hit the same wall as the fp32 "
                        "plane)"
                    )
                return report
            for name, fn, counter in sinks:
                outcome = attempt(name, fn, counter)
                report["trace"].append((name, poison, path, idx, outcome))
                if poison in NONFINITE and outcome != "rejected":
                    report["violations"] += 1
                    raise NumSanError(
                        f"seed {seed}: {name} ACCEPTED the bf16 "
                        f"update's {poison}-poisoned params "
                        f"({path}[{idx}]) — the finiteness gate is "
                        "missing/reverted on the bf16 path"
                    )
                if poison == "denormal" and outcome != "accepted":
                    report["violations"] += 1
                    raise NumSanError(
                        f"seed {seed}: {name} rejected a denormal from "
                        "the bf16 update — the gate over-fires"
                    )
            if poison in NONFINITE:
                # every good snapshot must have survived the refusals
                version, pub = publisher.get()
                mbox = read_params(mailbox, 0, good)
                if (
                    version != 1 or numguard.nonfinite_leaves(pub)
                    or mbox is None or mbox[0] != 1
                    or numguard.nonfinite_leaves(mbox[1])
                    or store.get("default").version != 1
                    or ckpt.latest_step() != 0
                ):
                    raise NumSanError(
                        f"seed {seed}: a refusal did not preserve the "
                        "previous good bf16 snapshot"
                    )
    return report


# ---------------------------------------------------------------------------
# codec exerciser: saturation semantics, host mirror == device
# ---------------------------------------------------------------------------

_I8_KINDS = ("i8", "i8_unit", "bool8")


def exercise_codec(seed: int, revert: bool = False) -> dict:
    """Seeded poisons through the REAL codec pair: int8 codecs must
    saturate (±127; bool8 {0,1}) on inf/saturating magnitudes and
    encode NaN to the deterministic midpoint; f16 clips to ±65504
    instead of overflowing to inf; and the numpy mirror must stay
    BIT-IDENTICAL to the device codec under poison (the
    host-encode == device-decode contract must not fork on garbage).
    `revert=True` runs the pre-fix wrap encoder against the checker."""
    import jax.numpy as jnp

    from actor_critic_tpu.data_plane import codecs as np_codecs
    from actor_critic_tpu.replay import quantize

    rng = random.Random(seed)
    # Reverted-codec mode pins the saturating poison: the wrap is then
    # detected on every schedule (inf→int8 casts are platform-defined).
    poison = "saturate" if revert else POISONS[rng.randrange(len(POISONS))]
    report = {
        "seed": seed, "scenario": "codec", "poison": poison,
        "trace": [], "saturations": 0, "violations": 0,
    }
    nprng = np.random.default_rng(seed)
    batch = (nprng.normal(size=(8,)) * 0.3).astype(np.float32)
    idx = rng.randrange(batch.size)
    batch[idx] = _VALUES[poison]
    np_stats = {
        "mean": np.float32(0.1), "scale": np.float32(2.0),
        "count": np.int32(4096),
    }

    if revert:
        # The REVERTED (pre-fix) bool8 encoder: round-then-cast WRAPS
        # out-of-range magnitudes instead of saturating.
        q = np.round(batch).astype(np.int8)
        if poison in ("saturate", "inf") and not (
            0 <= int(q[idx]) <= 1
        ):
            report["violations"] += 1
            raise NumSanError(
                f"seed {seed}: REVERTED CODEC DETECTED — bool8 "
                f"round-then-cast wrapped a {poison} flag to "
                f"{int(q[idx])} (valid range {{0, 1}}); the narrowing "
                "cast must clip first"
            )
        return report

    for kind in _I8_KINDS + ("f16",):
        jstats = quantize.QuantStats(
            mean=jnp.asarray(np_stats["mean"]),
            scale=jnp.asarray(np_stats["scale"]),
            count=jnp.asarray(np_stats["count"]),
        )
        host = np_codecs.np_encode(kind, np_stats, batch)
        dev = np.asarray(quantize.encode(
            kind, jstats, jnp.asarray(batch),
            quantize.storage_dtype(kind, jnp.float32),
        ))
        same = host.dtype == dev.dtype and (
            np.array_equal(host, dev, equal_nan=True)
            if np.issubdtype(host.dtype, np.floating)
            else np.array_equal(host, dev)
        )
        if not same:
            report["violations"] += 1
            raise NumSanError(
                f"seed {seed}: host/device codec mismatch for {kind} "
                f"under {poison} poison — the mirror contract forked "
                "on garbage input"
            )
        v = host[idx]
        ok = True
        if kind in ("i8", "i8_unit"):
            bound = 127
            if poison == "nan":
                # nan_to_num → midpoint: 0 for i8_unit, the mean band
                # for i8 (z == 0 after the scrub)
                ok = int(v) == (
                    0 if kind == "i8_unit"
                    else int(np.round(0.0))
                )
            elif poison in ("inf", "saturate"):
                ok = int(v) == bound
                report["saturations"] += ok
            elif poison == "-inf":
                ok = int(v) == -bound
                report["saturations"] += ok
            else:
                ok = -bound <= int(v) <= bound
        elif kind == "bool8":
            if poison in ("inf", "saturate"):
                ok = int(v) == 1
                report["saturations"] += ok
            elif poison in ("nan", "-inf", "denormal"):
                ok = int(v) == 0
            if not (0 <= int(min(host)) and int(max(host)) <= 1):
                ok = False
        else:  # f16
            if poison == "nan":
                ok = bool(np.isnan(v))  # deterministic propagation
            else:
                f16_max = float(np.finfo(np.float16).max)
                ok = bool(np.isfinite(v)) and abs(float(v)) <= f16_max
                if poison in ("inf", "saturate"):
                    report["saturations"] += ok
        if not ok:
            report["violations"] += 1
            raise NumSanError(
                f"seed {seed}: codec {kind} mishandled {poison} at "
                f"[{idx}]: encoded {v!r} — saturation contract "
                "violated (wrap/overflow instead of clip)"
            )
        decoded = np_codecs.np_decode(kind, np_stats, host)
        dec_ok = (
            bool(np.isnan(decoded[idx]))
            if (kind == "f16" and poison == "nan")
            else bool(np.all(np.isfinite(decoded)))
        )
        if not dec_ok:
            report["violations"] += 1
            raise NumSanError(
                f"seed {seed}: codec {kind} decode re-introduced a "
                f"non-finite under {poison}"
            )
        report["trace"].append((kind, poison, idx, repr(v)))
    return report


# ---------------------------------------------------------------------------
# sweep + the tier-1 quick profile
# ---------------------------------------------------------------------------


def exercise_sweep(seeds: Iterable[int], scenario) -> dict:
    reports = [scenario(seed) for seed in seeds]
    return {
        "schedules": len(reports),
        "divergence_events": sum(
            r.get("divergence_events", 0) for r in reports
        ),
        "rejections": sum(r.get("rejections", 0) for r in reports),
        "refusals": sum(r.get("refusals", 0) for r in reports),
        "saturations": sum(r.get("saturations", 0) for r in reports),
        "violations": sum(r.get("violations", 0) for r in reports),
    }


def quick_profile(schedules: int = 16, seed0: int = 0) -> dict:
    """The tier-1 fast profile: `schedules` seeded fault schedules split
    across the five exercisers — every guard class must both FIRE on
    nonfinite poisons and stay QUIET on tolerated ones. The two update
    programs (fp32 and bf16) compile once per process; everything else
    is tmpfs/numpy-speed."""
    n = max(schedules // 5, 1)
    update = exercise_sweep(
        range(seed0, seed0 + n), lambda s: exercise_update(s)
    )
    bf16 = exercise_sweep(
        range(seed0, seed0 + n), lambda s: exercise_bf16_update(s)
    )
    publish = exercise_sweep(
        range(seed0, seed0 + n), lambda s: exercise_publish(s)
    )
    checkpoint = exercise_sweep(
        range(seed0, seed0 + n), lambda s: exercise_checkpoint(s)
    )
    codec = exercise_sweep(
        range(seed0, seed0 + (schedules - 4 * n)),
        lambda s: exercise_codec(s),
    )
    parts = (update, bf16, publish, checkpoint, codec)
    return {
        "schedules": sum(x["schedules"] for x in parts),
        "update": update,
        "bf16_update": bf16,
        "publish": publish,
        "checkpoint": checkpoint,
        "codec": codec,
        "violations": sum(x["violations"] for x in parts),
    }
