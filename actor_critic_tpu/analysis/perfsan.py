"""perfsan: dispatch/transfer budget sanitizer (ISSUE 15 runtime half).

racesan made thread interleavings seeded and replayable, fleetsan
lifted that to processes, numsan to numeric faults; this module applies
the same contract to the PERFORMANCE dimension. The repo's headline
perf claims are contracts — PR 13's device plane promises "steady-state
consumption transfers zero bytes", PR 10's gateway promises "a swap
never recompiles" — and until now they were pinned by hand-written
per-test assertions. perfsan runs the REAL steady-state programs and
meters four quantities per steady-state block:

- **dispatches** — every XLA execution, counted at the C++ jit
  fastpath's `post_hook` (the seam `jax_debug_nans` uses): steady-state
  jit calls AND warmed eager ops fire it, with the program name, at
  nanoseconds of overhead. A Python-level reduction or stray eager op
  inside a hot loop shows up as extra dispatches no static pass can
  miss-count.
- **transfers / transferred bytes** — explicit host↔device crossings,
  counted by patching the `jax.device_put` / `jax.device_get` /
  `jnp.array` / `jnp.asarray` seams for the measured block (numpy-input
  uploads and device-array downloads contribute their `nbytes`).
- **recompiles** — the compile-funnel listener's monotonic event count
  (`telemetry.profiler`, ISSUE 3), the same counter the 0-recompile
  tests index.

Measured scopes additionally run under `jax.transfer_guard`: the
device-plane learner and the fused mixture step run "disallow", so any
IMPLICIT crossing (a numpy argument riding a dispatch, host scalars
uploaded per step) raises instead of silently re-paying the tunnel —
which is why the exercisers stage the slot-index scalar with an
explicit `device_put`: the one sanctioned transfer becomes a metered
4-byte line item instead of an invisible implicit upload.

Each steady-state program is checked against the committed
`perf_budgets.json` manifest (max dispatches / transfers / transferred
bytes / recompiles per steady-state block). The four programs:

    ppo_update_host     the async V-trace learner consuming host-plane
                        blocks (jnp.array upload per block — budgeted,
                        not forbidden: that upload IS the host plane)
    ppo_update_device   the same learner on the HBM DeviceTrajRing —
                        gather+decode in-jit; budget pins 1 dispatch,
                        1 transfer (the slot scalar), 4 bytes, 0
                        recompiles per consumed block, and the actor's
                        int8 enqueue bytes ride a sibling budget
    offpolicy_ingest    DDPG's fused gather+scatter+update program
                        (device_replay.make_device_ingest_update)
    serving_dispatch    PolicyEngine.act on a warmed bucket, including
                        a mid-stream hot-swap (prepare_params →
                        checkpoint.uncommit) that must not recompile
    serving_overlap     the same act budget measured through a RUNNING
                        MicroBatcher with max_inflight=2 (ISSUE 17):
                        flight workers dispatch, so the overlapped
                        machinery must add NO device work per act
    serving_proxy_hop   one FleetProxy relay to a stub-engine replica
                        gateway: an ALL-ZERO budget — the proxy hop
                        carries no device state at all
    mixture_fleet_step  the heterogeneous mixture fleet's fused scan
                        block — zero transfers, one dispatch per call

**Reverted modes** prove the meter works, deterministically on every
run: `revert="host-gather"` re-introduces the pre-PR-13 per-block host
gather (device_get + re-upload inside the learner scope) and must blow
the device plane's transfer budget; `revert="unfused"` splits the
ISSUE-19 fused consume back into an advantage program plus an update
program per block and must blow the fused plane's dispatch budget;
`revert="uncommit"` installs an
orbax-restored (committed) tree into the gateway with `prepare=False`
— dropping `checkpoint.uncommit` from the swap — and the next dispatch
must blow the 0-recompile budget (committed arrays lower byte-different
HLO; the PR 4/PR 10 class).

`quick_profile` is the sweep `scripts/tier1.sh` runs between numsan and
pytest, under its own timeout.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
from typing import Iterable, Optional

import numpy as np

PROGRAMS = (
    "ppo_update_host",
    "ppo_update_device",
    "ppo_update_fused",
    "offpolicy_ingest",
    "serving_dispatch",
    "serving_overlap",
    "serving_proxy_hop",
    "mixture_fleet_step",
)

BUDGET_KEYS = (
    "max_dispatches_per_block",
    "max_transfers_per_block",
    "max_transferred_bytes_per_block",
    "max_recompiles",
)

DEFAULT_MANIFEST_BASENAME = "perf_budgets.json"


class PerfSanError(RuntimeError):
    """A steady-state program exceeded its committed budget — or a
    reverted mode's regression was detected (the sanitizer working)."""


class ManifestError(PerfSanError):
    """The budget manifest itself is missing/malformed — a crash
    (exit 2), never a detection: a lost manifest must not read as a
    caught regression."""


def default_manifest_path(repo_root: Optional[str] = None) -> str:
    if repo_root is None:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    return os.path.join(repo_root, DEFAULT_MANIFEST_BASENAME)


def load_manifest(path: str) -> dict:
    """The budget manifest; a missing/malformed file is a PerfSanError
    (the budgets are part of the contract — absence must not read as a
    clean run)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise ManifestError(f"budget manifest {path}: {e}") from e
    if not isinstance(data, dict) or not isinstance(
        data.get("programs"), dict
    ):
        raise ManifestError(
            f"budget manifest {path}: expected "
            "{'version': 1, 'programs': {...}}"
        )
    # Strict key validation: a typo'd or dropped max_* key would
    # silently UN-GATE that counter forever — refuse loudly instead.
    allowed = set(BUDGET_KEYS) | {"transfer_guard"}
    for name, entry in data["programs"].items():
        if not isinstance(entry, dict):
            raise ManifestError(
                f"budget manifest {path}: program {name!r} entry must "
                "be an object"
            )
        unknown = sorted(set(entry) - allowed)
        missing = sorted(set(BUDGET_KEYS) - set(entry))
        if unknown or missing:
            raise ManifestError(
                f"budget manifest {path}: program {name!r} has "
                + (f"unknown key(s) {unknown} " if unknown else "")
                + (f"missing budget key(s) {missing}" if missing else "")
            )
    return data["programs"]


# ---------------------------------------------------------------------------
# counters
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Counters:
    """What one measured scope observed."""

    dispatches: int = 0
    transfers: int = 0
    transferred_bytes: int = 0
    recompiles: int = 0
    dispatch_names: dict = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "dispatches": self.dispatches,
            "transfers": self.transfers,
            "transferred_bytes": self.transferred_bytes,
            "recompiles": self.recompiles,
            "dispatch_names": dict(
                sorted(self.dispatch_names.items())
            ),
        }


def worst_of(counters: Iterable[Counters]) -> Counters:
    """Component-wise max across measured blocks — the value a `max_*`
    budget gates (a block exceeding ONE counter must not hide behind a
    sibling block that maxed a different one)."""
    out = Counters()
    for c in counters:
        out.dispatches = max(out.dispatches, c.dispatches)
        out.transfers = max(out.transfers, c.transfers)
        out.transferred_bytes = max(
            out.transferred_bytes, c.transferred_bytes
        )
        out.recompiles = max(out.recompiles, c.recompiles)
        for name, n in c.dispatch_names.items():
            out.dispatch_names[name] = max(
                out.dispatch_names.get(name, 0), n
            )
    return out


def _tree_nbytes(tree) -> int:
    import jax

    return sum(
        int(getattr(leaf, "nbytes", 0))
        for leaf in jax.tree.leaves(tree)
    )


def _host_nbytes(tree) -> int:
    """Bytes of HOST-side leaves only — numpy arrays/scalars AND bare
    Python numbers (jax.tree.leaves flattens lists/tuples into them):
    an upload seam fed an already-device array moves nothing, but a
    per-block `jnp.asarray(env_steps)` built from a Python int crosses
    just the same and must not be invisible to the meter."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, (np.ndarray, np.generic)):
            total += int(leaf.nbytes)
        elif isinstance(leaf, (bool, int, float, complex)):
            total += int(np.asarray(leaf).nbytes)
    return total


@contextlib.contextmanager
def measure(guard: Optional[str] = None):
    """Count dispatches/transfers/bytes/recompiles for the enclosed
    block, optionally under a `jax.transfer_guard(guard)` scope.
    Yields a live `Counters` the caller reads after the block. Not
    reentrant (one funnel, one meter)."""
    import jax
    import jax.numpy as jnp
    from jaxlib import xla_extension as xe

    from actor_critic_tpu.telemetry import profiler

    profiler.ensure_compile_introspection()
    c = Counters()
    gs = xe.jax_jit.global_state()
    prev_hook = gs.post_hook

    def hook(fun, *args, **kwargs):
        c.dispatches += 1
        name = getattr(fun, "__name__", None) or "?"
        c.dispatch_names[name] = c.dispatch_names.get(name, 0) + 1
        if prev_hook is not None:
            prev_hook(fun, *args, **kwargs)

    orig_put, orig_get = jax.device_put, jax.device_get
    orig_array, orig_asarray = jnp.array, jnp.asarray

    def counting_put(x, *a, **k):
        # Only HOST-side input bytes cross; a defensive re-placement
        # of an already-device tree moves nothing and must not burn
        # the transfer budget.
        nbytes = _host_nbytes(x)
        if nbytes:
            c.transfers += 1
            c.transferred_bytes += nbytes
        return orig_put(x, *a, **k)

    def counting_get(x, *a, **k):
        # Only DEVICE-side leaves cross on a get; host numpy passed
        # through device_get is a no-op copy-out.
        nbytes = _tree_nbytes(x) - _host_nbytes(x)
        if nbytes:
            c.transfers += 1
            c.transferred_bytes += nbytes
        return orig_get(x, *a, **k)

    def counting_array(x, *a, **k):
        nbytes = _host_nbytes(x)
        if nbytes:
            c.transfers += 1
            c.transferred_bytes += nbytes
        return orig_array(x, *a, **k)

    def counting_asarray(x, *a, **k):
        nbytes = _host_nbytes(x)
        if nbytes:
            c.transfers += 1
            c.transferred_bytes += nbytes
        return orig_asarray(x, *a, **k)

    n0 = profiler.compile_event_count()
    gs.post_hook = hook
    jax.device_put, jax.device_get = counting_put, counting_get
    jnp.array, jnp.asarray = counting_array, counting_asarray
    try:
        ctx = (
            jax.transfer_guard(guard)
            if guard is not None
            else contextlib.nullcontext()
        )
        with ctx:
            yield c
    finally:
        gs.post_hook = prev_hook
        jax.device_put, jax.device_get = orig_put, orig_get
        jnp.array, jnp.asarray = orig_array, orig_asarray
        c.recompiles = profiler.compile_event_count() - n0


def check_budget(program: str, counters: Counters, budgets: dict) -> None:
    """Raise PerfSanError when any counter exceeds the program's
    committed budget (an absent program entry is itself a violation —
    a new steady-state program must commit a budget)."""
    budget = budgets.get(program)
    if budget is None:
        raise PerfSanError(
            f"{program}: no budget entry in the manifest — every "
            "steady-state program must commit max dispatches/"
            "transfers/bytes/recompiles per block"
        )
    actuals = {
        "max_dispatches_per_block": counters.dispatches,
        "max_transfers_per_block": counters.transfers,
        "max_transferred_bytes_per_block": counters.transferred_bytes,
        "max_recompiles": counters.recompiles,
    }
    over = [
        (key, actuals[key], budget[key])
        for key in BUDGET_KEYS
        if key in budget and actuals[key] > int(budget[key])
    ]
    if over:
        detail = "; ".join(
            f"{k}: measured {a} > budget {b}" for k, a, b in over
        )
        names = ", ".join(
            f"{n}x{c}" for n, c in sorted(counters.dispatch_names.items())
        )
        raise PerfSanError(
            f"BUDGET VIOLATION in {program}: {detail} "
            f"(dispatches by program: {names or 'none'}) — either a "
            "regression re-entered the steady-state path, or a "
            "deliberate change must recommit perf_budgets.json"
        )


# ---------------------------------------------------------------------------
# shared fixtures (tiny REAL programs, compiled once per process)
# ---------------------------------------------------------------------------

_PPO_FIXTURE = None


def _ppo_fixture():
    global _PPO_FIXTURE
    if _PPO_FIXTURE is not None:
        return _PPO_FIXTURE
    import jax

    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs.jax_env import EnvSpec

    spec = EnvSpec(
        obs_shape=(4,), action_dim=2, discrete=True,
        obs_dtype=np.float32, can_truncate=True,
    )
    cfg = ppo.PPOConfig(
        num_envs=4, rollout_steps=8, epochs=1, num_minibatches=1,
        hidden=(16,),
    )
    key = jax.random.key(0)
    params, opt_state = ppo.init_host_params(spec, cfg, key)
    _PPO_FIXTURE = (spec, cfg, params, opt_state, key)
    return _PPO_FIXTURE


def _ppo_block(cfg, seed: int) -> dict:
    rng = np.random.default_rng(seed)
    T, E = cfg.rollout_steps, cfg.num_envs
    obs = rng.normal(size=(T, E, 4)).astype(np.float32)
    return {
        "obs": obs,
        "action": rng.integers(0, 2, (T, E)),
        "log_prob": (rng.normal(size=(T, E)) * 0.1 - 0.69).astype(
            np.float32
        ),
        "value": rng.normal(size=(T, E)).astype(np.float32),
        "reward": np.ones((T, E), np.float32),
        "done": np.zeros((T, E), np.float32),
        "terminated": np.zeros((T, E), np.float32),
        "final_obs": obs.copy(),
        "last_obs": rng.normal(size=(E, 4)).astype(np.float32),
    }


_BLOCK_ORDER = (
    "obs", "action", "log_prob", "value", "reward", "done",
    "terminated", "final_obs", "last_obs",
)


# ---------------------------------------------------------------------------
# program exercisers
# ---------------------------------------------------------------------------


def exercise_ppo_update_host(blocks: int = 3, seed: int = 0) -> dict:
    """The async V-trace learner consuming HOST-plane blocks: the
    jnp.array per-block upload (the PR 6 copy-on-transfer contract) is
    the budgeted transfer — this program's budget PRICES the host
    plane, the device twin below removes it."""
    import jax
    import jax.numpy as jnp

    from actor_critic_tpu.algos import ppo

    spec, cfg, params, opt_state, key = _ppo_fixture()
    update = ppo.make_async_update_step(spec, cfg, correction="vtrace")

    def consume(block):
        arrays = {k: jnp.array(v) for k, v in block.items()}
        return update(
            params, opt_state, *(arrays[k] for k in _BLOCK_ORDER), key
        )

    out = consume(_ppo_block(cfg, seed))  # warm
    jax.block_until_ready(out)
    per_block = []
    for i in range(blocks):
        block = _ppo_block(cfg, seed + 1 + i)
        with measure() as c:
            out = consume(block)
            jax.block_until_ready(out)
        per_block.append(c)
    worst = worst_of(per_block)
    return {"program": "ppo_update_host", "blocks": blocks,
            "counters": worst, "per_block": per_block}


def exercise_ppo_update_device(
    blocks: int = 3, seed: int = 0, revert: Optional[str] = None
) -> dict:
    """The device-plane twin: actors enqueue int8-encoded blocks into
    the HBM ring (enqueue bytes measured separately — they are the
    actor's cost, off the learner's critical path); the learner's
    measured scope runs under transfer_guard("disallow") and must
    dispatch ONE program transferring only the explicitly staged slot
    scalar. `revert="host-gather"` re-introduces the pre-PR-13 host
    gather inside the learner scope — caught on every run."""
    import jax
    import jax.numpy as jnp

    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.data_plane import ring as dp_ring

    spec, cfg, params, opt_state, key = _ppo_fixture()
    block_spec = ppo.async_block_spec(spec, cfg, 1, "vtrace")
    ring = dp_ring.DeviceTrajRing(
        depth=2, block_spec=block_spec, codec="int8",
        register_gauge=False,
    )
    try:
        update = ppo.make_device_update_step(
            spec, cfg, ring.codecs, correction="vtrace"
        )

        def learner_consume(lease, c_slot):
            return ring.run(
                lambda state: update(
                    params, opt_state, state, c_slot, key
                )
            )

        # warm both halves
        ring.put(_ppo_block(cfg, seed), version=0)
        lease = ring.get(timeout=5.0)
        out = learner_consume(lease, jax.device_put(np.int32(lease.slot)))
        jax.block_until_ready(out)
        ring.release(lease)

        enqueue_counters, consume_counters = [], []
        for i in range(blocks):
            block = _ppo_block(cfg, seed + 1 + i)
            with measure() as ce:
                ring.put(block, version=i + 1)
            enqueue_counters.append(ce)
            lease = ring.get(timeout=5.0)
            if revert == "host-gather":
                try:
                    with measure(guard="disallow") as cc:
                        # The pre-PR-13 learner: gather the consumed
                        # slot to HOST and re-upload it — one
                        # device_get + nine jnp.array transfers per
                        # block, exactly what the device ring removed.
                        host = {
                            k: jax.device_get(v[lease.slot])
                            for k, v in ring._state.storage.items()
                        }
                        arrays = {
                            k: jnp.array(v) for k, v in host.items()
                        }
                        jax.block_until_ready(arrays)
                except PerfSanError:
                    raise
                except Exception as e:
                    # An implicit crossing tripping the transfer guard
                    # IS the detection (deterministic per program
                    # structure, like the counter path below).
                    raise PerfSanError(
                        "REVERTED MODE DETECTED: the pre-PR-13 host "
                        "gather crossed the transfer guard inside the "
                        f"device-plane learner scope ({type(e).__name__})"
                    ) from e
            else:
                slot_dev = None
                with measure(guard="disallow") as cc:
                    # The ONE sanctioned transfer: the slot index,
                    # staged explicitly so the meter sees its 4 bytes
                    # (the production driver ships the same scalar
                    # implicitly on the dispatch).
                    slot_dev = jax.device_put(np.int32(lease.slot))
                    out = learner_consume(lease, slot_dev)
                    jax.block_until_ready(out)
            ring.release(lease)
            consume_counters.append(cc)
        worst = worst_of(consume_counters)
        return {
            "program": "ppo_update_device",
            "blocks": blocks,
            "counters": worst,
            "per_block": consume_counters,
            "enqueue": worst_of(enqueue_counters),
            "enqueue_bytes_per_block": ring.bytes_per_block(),
            "host_bytes_per_block": ring.raw_bytes_per_block(),
        }
    finally:
        ring.close()


def exercise_ppo_update_fused(
    blocks: int = 3, seed: int = 0, revert: Optional[str] = None
) -> dict:
    """ISSUE 19's fused consume: gather + decode + ADVANTAGES (the
    `common.gae_targets` seam lowering through the Pallas layer) +
    update as ONE program under `correction="none"` — the same budget
    shape as ppo_update_device, now with the advantage scan inside the
    measured dispatch. `revert="unfused"` splits the advantage
    computation back out into its own jitted dispatch per block (the
    pre-ISSUE-19 two-program consume) — 2 dispatches against a budget
    of 1, caught on every run."""
    import jax
    import jax.numpy as jnp

    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.algos.common import gae_targets
    from actor_critic_tpu.data_plane import ring as dp_ring

    spec, cfg, params, opt_state, key = _ppo_fixture()
    block_spec = ppo.async_block_spec(spec, cfg, 1, "none")
    ring = dp_ring.DeviceTrajRing(
        depth=2, block_spec=block_spec, codec="fp32",
        register_gauge=False,
    )
    try:
        update = ppo.make_device_update_step(
            spec, cfg, ring.codecs, correction="none"
        )

        @jax.jit
        def advantages_only(state, c_slot):
            # The split-out advantage program the fused path removed:
            # its existence per consumed block IS the regression.
            block = dp_ring.gather_block(state, c_slot, ring.codecs)
            return gae_targets(
                block["reward"], block["value"], block["done"],
                block["bootstrap_value"], cfg.gamma, cfg.gae_lambda,
            )

        def block_for(i):
            rng = np.random.default_rng(seed + i)
            block = _ppo_block(cfg, seed + i)
            T, E = cfg.rollout_steps, cfg.num_envs
            block["final_values"] = rng.normal(size=(T, E)).astype(
                np.float32
            )
            block["bootstrap_value"] = rng.normal(size=(E,)).astype(
                np.float32
            )
            return block

        # warm both programs (the dispatch meter fires on cache hits)
        ring.put(block_for(0), version=0)
        lease = ring.get(timeout=5.0)
        slot_dev = jax.device_put(np.int32(lease.slot))
        if revert == "unfused":
            adv = ring.run(lambda s: advantages_only(s, slot_dev))
            jax.block_until_ready(adv)
        out = ring.run(
            lambda s: update(params, opt_state, s, slot_dev, key)
        )
        jax.block_until_ready(out)
        ring.release(lease)

        per_block = []
        for i in range(blocks):
            ring.put(block_for(i + 1), version=i + 1)
            lease = ring.get(timeout=5.0)
            with measure(guard="disallow") as c:
                # jaxlint: disable=transfer-discipline (the ONE
                # sanctioned transfer — the staged slot scalar, priced
                # by the meter: this IS the measurement)
                slot_dev = jax.device_put(np.int32(lease.slot))
                if revert == "unfused":
                    adv = ring.run(
                        lambda s: advantages_only(s, slot_dev)
                    )
                    # jaxlint: disable=transfer-discipline (the
                    # reverted two-dispatch shape under test — its
                    # extra fence is the regression being metered)
                    jax.block_until_ready(adv)
                out = ring.run(
                    lambda s: update(params, opt_state, s, slot_dev, key)
                )
                # jaxlint: disable=transfer-discipline (measurement
                # fence: the counter window must close on a finished
                # block, not an enqueued one)
                jax.block_until_ready(out)
            ring.release(lease)
            per_block.append(c)
        worst = worst_of(per_block)
        return {
            "program": "ppo_update_fused",
            "blocks": blocks,
            "counters": worst,
            "per_block": per_block,
        }
    finally:
        ring.close()


def exercise_offpolicy_ingest(blocks: int = 3, seed: int = 0) -> dict:
    """DDPG's fused device-plane ingest: gather + decode + scatter into
    the donated replay ring + the whole update loop, ONE program per
    consumed block (device_replay.make_device_ingest_update)."""
    import jax

    from actor_critic_tpu.algos import ddpg
    from actor_critic_tpu.data_plane import codecs as np_codecs
    from actor_critic_tpu.data_plane import device_replay
    from actor_critic_tpu.data_plane import ring as dp_ring
    from actor_critic_tpu.envs.jax_env import EnvSpec

    spec = EnvSpec(
        obs_shape=(3,), action_dim=1, discrete=False,
        obs_dtype=np.float32, can_truncate=True,
    )
    cfg = ddpg.DDPGConfig(
        num_envs=2, steps_per_iter=4, batch_size=8, warmup_steps=0,
        buffer_capacity=256, updates_per_iter=1,
    )
    block_spec = device_replay.offpolicy_block_spec(spec, cfg, 1)
    kinds = np_codecs.traj_codecs("int8", block_spec)
    ring = dp_ring.DeviceTrajRing(
        depth=2, block_spec=block_spec, codec="int8",
        register_gauge=False,
    )
    try:
        ingest = device_replay.make_device_ingest_update(
            ddpg.make_update_loop, spec.action_dim, cfg, kinds,
            max(cfg.batch_size, cfg.nstep),
        )
        learner = ddpg.init_learner((3,), 1, cfg, jax.random.key(seed))
        rng = np.random.default_rng(seed)

        def block_for(i):
            K, E = cfg.steps_per_iter, cfg.num_envs
            obs = rng.normal(size=(K, E, 3)).astype(np.float32)
            return {
                "obs": obs,
                "action": rng.uniform(-1, 1, (K, E, 1)).astype(np.float32),
                "reward": np.ones((K, E), np.float32),
                "done": np.zeros((K, E), np.float32),
                "terminated": np.zeros((K, E), np.float32),
                "final_obs": obs.copy(),
                "last_obs": obs[0].copy(),
            }

        ring.put(block_for(0), version=0)
        lease = ring.get(timeout=5.0)
        staged = jax.device_put(
            (np.int32(lease.slot), np.int32(cfg.steps_per_iter))
        )
        learner, _ = ring.run(
            lambda s: ingest(learner, s, staged[0], staged[1])
        )
        jax.block_until_ready(learner)
        ring.release(lease)

        per_block = []
        env_steps = cfg.steps_per_iter
        for i in range(blocks):
            ring.put(block_for(i + 1), version=i + 1)
            lease = ring.get(timeout=5.0)
            env_steps += cfg.steps_per_iter
            with measure(guard="disallow") as c:
                # jaxlint: disable=transfer-discipline (the sanctioned
                # slot/env-steps scalars, staged explicitly so the
                # meter prices them — this IS the measurement)
                staged = jax.device_put(
                    (np.int32(lease.slot), np.int32(env_steps))
                )
                learner, metrics = ring.run(
                    lambda s: ingest(learner, s, staged[0], staged[1])
                )
                # jaxlint: disable=transfer-discipline (measurement
                # fence: the counter window must close on a finished
                # block, not an enqueued one)
                jax.block_until_ready(learner)
            ring.release(lease)
            per_block.append(c)
        worst = worst_of(per_block)
        return {"program": "offpolicy_ingest", "blocks": blocks,
                "counters": worst, "per_block": per_block}
    finally:
        ring.close()


def exercise_serving_dispatch(
    acts: int = 4, seed: int = 0, revert: Optional[str] = None
) -> dict:
    """PolicyEngine.act on warmed buckets, including a mid-stream
    hot-swap: the budget pins dispatches/transfers/bytes per act and
    ZERO recompiles across the swap (prepare_params routes the install
    through checkpoint.uncommit). `revert="uncommit"` installs an
    orbax-restored COMMITTED tree with prepare=False — the dropped
    uncommit — and the next dispatch's recompile is caught on every
    run."""
    import tempfile

    from actor_critic_tpu.serving import engine as serving_engine
    from actor_critic_tpu.serving.policy_store import PolicyStore

    spec, cfg, _, _, _ = _ppo_fixture()
    engine = serving_engine.PolicyEngine(
        spec, cfg, algo="ppo", buckets=(1, 4), seed=seed
    )
    params = serving_engine.init_params(spec, cfg, "ppo", seed=seed)
    store = PolicyStore()
    store.register("default", engine, params, version=1)
    engine.warm(store.get("default").params)

    rng = np.random.default_rng(seed)
    sizes = [1, 4, 1, 4][:acts] or [1]

    per_act = []
    for n in sizes:
        obs = rng.normal(size=(n, 4)).astype(np.float32)
        handle = store.get("default")
        with measure(guard="disallow") as c:
            out = engine.act(handle.params, obs)
        assert out.shape[0] == n
        per_act.append(c)

    # Mid-stream hot-swap through a REAL orbax checkpoint: restore ->
    # prepare_params (uncommit) -> swap -> act, still zero recompiles.
    swap_params = serving_engine.init_params(spec, cfg, "ppo", seed=seed + 1)
    with tempfile.TemporaryDirectory(prefix="perfsan_") as root:
        from actor_critic_tpu.utils.checkpoint import Checkpointer

        with Checkpointer(root, max_to_keep=1) as ck:
            ck.save(0, {"params": swap_params}, force=True)
            ck.wait()
            restored = ck.restore({"params": params}, 0)["params"]
        store.swap(
            "default", restored,
            prepare=(revert != "uncommit"),
        )
        obs = rng.normal(size=(1, 4)).astype(np.float32)
        handle = store.get("default")
        with measure(guard="disallow") as c_swap:
            out = engine.act(handle.params, obs)
        per_act.append(c_swap)
    worst = worst_of(per_act)
    return {"program": "serving_dispatch", "acts": len(per_act),
            "counters": worst, "per_act": per_act}


def exercise_serving_overlap(acts: int = 4, seed: int = 0) -> dict:
    """The overlapped-dispatch act path (ISSUE 17 leg c): the SAME
    per-act budget as serving_dispatch, measured through a RUNNING
    `MicroBatcher` with `max_inflight=2` — packing, the 1-deep flight
    handoff, shed checks and SLO accounting are all pure host work, so
    the overlap machinery must add zero device work per act.

    Requests are serialized (one outstanding at a time), so each
    measured window holds exactly one single-row flush — the counters
    stay structural/deterministic. The dispatch runs on a FLIGHT
    thread: `jax.transfer_guard` scopes are thread-local, so the
    disallow guard is applied process-globally for the measured windows
    (explicit put/get stay sanctioned; an implicit coercion on the
    flight thread raises there and surfaces as the request's error)."""
    import jax

    from actor_critic_tpu.serving import engine as serving_engine
    from actor_critic_tpu.serving.batcher import MicroBatcher
    from actor_critic_tpu.serving.policy_store import PolicyStore

    spec, cfg, _, _, _ = _ppo_fixture()
    engine = serving_engine.PolicyEngine(
        spec, cfg, algo="ppo", buckets=(1, 4), seed=seed
    )
    params = serving_engine.init_params(spec, cfg, "ppo", seed=seed)
    store = PolicyStore()
    store.register("default", engine, params, version=1)
    engine.warm(store.get("default").params)
    batcher = MicroBatcher(store, max_wait_us=200.0, max_inflight=2)

    rng = np.random.default_rng(seed)
    per_act = []
    jax.config.update("jax_transfer_guard", "disallow")
    try:
        for _ in range(max(acts, 1)):
            obs = rng.normal(size=(1, 4)).astype(np.float32)
            with measure() as c:
                req = batcher.submit(obs, "default")
                if not req.done.wait(timeout=30.0):
                    raise PerfSanError(
                        "serving_overlap: flight dispatch never "
                        "completed (overlap machinery wedged)"
                    )
                if req.error is not None:
                    raise req.error
            per_act.append(c)
    finally:
        jax.config.update("jax_transfer_guard", "allow")
        batcher.close()
    worst = worst_of(per_act)
    return {"program": "serving_overlap", "acts": len(per_act),
            "counters": worst, "per_act": per_act}


def exercise_serving_proxy_hop(relays: int = 4, seed: int = 0) -> dict:
    """One FleetProxy relay to a single stub-engine replica gateway,
    over real HTTP on loopback: the budget is ALL-ZERO — the fronting
    proxy carries no device state, so a dispatch, transfer, or
    recompile showing up in a relay window means device work leaked
    into the scale-out hop (the whole point of fronting with a dumb
    relay instead of a second engine)."""
    import http.client
    import json as _json

    from actor_critic_tpu.serving.fleet_proxy import FleetProxy
    from actor_critic_tpu.serving.gateway import ServeGateway
    from actor_critic_tpu.serving.policy_store import PolicyStore

    class _StubEngine:
        max_rows = 8

        def prepare_params(self, params):
            return params

        def act(self, params, obs):
            return np.asarray(obs)[:, 0]

    store = PolicyStore()
    store.register("default", _StubEngine(), {"w": np.ones((1,), np.float32)})
    gateway = ServeGateway(store, port=0)
    proxy = FleetProxy([gateway.url], port=0, probe=False)
    rng = np.random.default_rng(seed)
    per_relay = []
    try:
        conn = http.client.HTTPConnection(proxy.host, proxy.port, timeout=10)
        body0 = _json.dumps(
            {"obs": rng.normal(size=(1, 4)).astype(np.float32).tolist()}
        )
        # Unmetered warm relay: first contact pays connection setup on
        # both hops; steady-state is what the budget prices.
        conn.request("POST", "/v1/act", body0,
                     {"Content-Type": "application/json"})
        conn.getresponse().read()
        for _ in range(max(relays, 1)):
            body = _json.dumps(
                {"obs": rng.normal(size=(1, 4)).astype(np.float32).tolist()}
            )
            with measure() as c:
                conn.request("POST", "/v1/act", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                payload = _json.loads(resp.read())
            if resp.status != 200:
                raise PerfSanError(
                    f"serving_proxy_hop: relay answered {resp.status}: "
                    f"{payload}"
                )
            per_relay.append(c)
        conn.close()
    finally:
        proxy.close()
        gateway.close()
    worst = worst_of(per_relay)
    return {"program": "serving_proxy_hop", "relays": len(per_relay),
            "counters": worst, "per_relay": per_relay}


def exercise_mixture_fleet_step(
    calls: int = 3, seed: int = 0, iters_per_call: int = 4
) -> dict:
    """The heterogeneous mixture fleet's fused scan block (ISSUE 11's
    one-XLA-program contract): the whole train state stays device-
    resident and donated — one dispatch per call, zero transfers, under
    transfer_guard("disallow")."""
    from functools import partial

    import jax

    from actor_critic_tpu.algos import a2c
    from actor_critic_tpu.envs import make_mixture

    env = make_mixture("cartpole,pendulum")
    cfg = a2c.A2CConfig(num_envs=8, rollout_steps=4)
    state = a2c.init_state(env, cfg, jax.random.key(seed))
    train_step = a2c.make_train_step(env, cfg)

    @partial(jax.jit, donate_argnums=0)
    def block(s):
        def body(carry, _):
            carry, _m = train_step(carry)
            return carry, None

        s, _ = jax.lax.scan(body, s, None, length=iters_per_call)
        return s

    state = block(state)  # warm
    jax.block_until_ready(state)
    per_call = []
    for _ in range(calls):
        with measure(guard="disallow") as c:
            state = block(state)
            # jaxlint: disable=transfer-discipline (measurement fence:
            # the counter window must close on a finished block)
            jax.block_until_ready(state)
        per_call.append(c)
    worst = worst_of(per_call)
    return {"program": "mixture_fleet_step", "calls": calls,
            "counters": worst, "per_call": per_call}


# ---------------------------------------------------------------------------
# the budgeted sweep + reverted modes
# ---------------------------------------------------------------------------

_EXERCISERS = {
    "ppo_update_host": exercise_ppo_update_host,
    "ppo_update_device": exercise_ppo_update_device,
    "ppo_update_fused": exercise_ppo_update_fused,
    "offpolicy_ingest": exercise_offpolicy_ingest,
    "serving_dispatch": exercise_serving_dispatch,
    "serving_overlap": exercise_serving_overlap,
    "serving_proxy_hop": exercise_serving_proxy_hop,
    "mixture_fleet_step": exercise_mixture_fleet_step,
}


def run_program(
    name: str, budgets: dict, seed: int = 0
) -> dict:
    """One program end to end: exercise, then gate on its budget. The
    device-plane program additionally gates its actor-side enqueue
    bytes (`ppo_update_device.enqueue` manifest entry)."""
    report = _EXERCISERS[name](seed=seed)
    check_budget(name, report["counters"], budgets)
    if name == "ppo_update_device" and "ppo_update_device.enqueue" in budgets:
        check_budget(
            "ppo_update_device.enqueue", report["enqueue"], budgets
        )
    return report


def quick_profile(
    manifest_path: Optional[str] = None,
    seed: int = 0,
    programs: Iterable[str] = PROGRAMS,
) -> dict:
    """The tier-1 sweep: every steady-state program measured against
    the committed manifest. Counters are structural (fixed shapes,
    fixed programs), so the actuals are bit-identical run to run — a
    violation names the program, the counter, and the per-program
    dispatch breakdown."""
    budgets = load_manifest(
        manifest_path or default_manifest_path()
    )
    out: dict = {"programs": {}, "violations": 0}
    for name in programs:
        report = run_program(name, budgets, seed=seed)
        entry = {
            "actuals": report["counters"].as_dict(),
            "budget": budgets.get(name, {}),
        }
        if "enqueue" in report:
            entry["enqueue_actuals"] = report["enqueue"].as_dict()
            entry["enqueue_bytes_per_block"] = report[
                "enqueue_bytes_per_block"
            ]
            entry["host_bytes_per_block"] = report[
                "host_bytes_per_block"
            ]
        out["programs"][name] = entry
    return out


def run_reverted(mode: str, manifest_path: Optional[str] = None) -> None:
    """Reverted-regression modes — each must raise PerfSanError on
    EVERY run (the deterministic detection the ISSUE requires):

    - "host-gather": the pre-PR-13 per-block host gather inside the
      device-plane learner scope → transfer-budget violation;
    - "unfused": the pre-ISSUE-19 two-program consume (advantage scan
      dispatched separately from the update) → dispatch-budget
      violation;
    - "uncommit": a gateway swap installing a committed orbax restore
      with prepare=False → recompile-budget violation.
    """
    budgets = load_manifest(manifest_path or default_manifest_path())
    if mode == "host-gather":
        report = exercise_ppo_update_device(revert="host-gather")
        check_budget("ppo_update_device", report["counters"], budgets)
        raise PerfSanError(
            "host-gather revert escaped the transfer budget — the "
            "meter is blind"
        )
    if mode == "unfused":
        report = exercise_ppo_update_fused(revert="unfused")
        check_budget("ppo_update_fused", report["counters"], budgets)
        raise PerfSanError(
            "unfused revert escaped the dispatch budget — the "
            "meter is blind"
        )
    if mode == "uncommit":
        report = exercise_serving_dispatch(revert="uncommit")
        check_budget("serving_dispatch", report["counters"], budgets)
        raise PerfSanError(
            "uncommit revert escaped the recompile budget — the "
            "meter is blind"
        )
    raise PerfSanError(f"unknown reverted mode {mode!r}")
