"""tracer-leak: Python control flow on traced values inside jit.

Inside a jit-compiled function the arguments are tracers; `if x > 0:`,
`while x < n:`, `assert x.all()` or `bool(x)` on a value that flows
from a parameter forces concretization and raises
`TracerBoolConversionError` at trace time — or worse, silently bakes
one branch in when the value happens to be concrete during tracing
(weak constants, closed-over arrays). The fix is `lax.cond`/`jnp.where`
or hoisting the value to a `static_argnums` argument.

What does NOT flag (the near-misses that make this check usable):

- `.shape` / `.ndim` / `.dtype` / `.size` derivations — static under
  tracing; branching on them is the standard shape-specialization
  idiom (`if B % cfg.num_minibatches != 0: raise ...`).
- `len(x)`, `isinstance`, `hasattr`, `type` — concrete under tracing.
- `x is None` / `x is not None` — Python-level presence checks on
  optional arguments, resolved at trace time.
- Parameters named in the site's `static_argnums`/`static_argnames`.

Scope: defs detected as jit targets by analysis/jitinfo.py (decorated,
wrap-assigned, or anonymous `jax.jit(f)`), parameters tainted, taint
propagated through assignments in the def (nested defs included — a
scan body defined inside a jitted def traces its closure too).
"""

from __future__ import annotations

import ast

from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
    target_names,
)
from actor_critic_tpu.analysis.jitinfo import collect_jit_sites

CHECK = "tracer-leak"

# Attribute accesses that yield static (non-traced) values.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
# Builtin calls whose result is concrete even on tracer arguments.
_STATIC_CALLS = {"len", "isinstance", "hasattr", "type", "getattr", "callable"}


def _is_none(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


class _Tainter:
    """Taint = "flows from a traced parameter". Assignment-ordered by
    line number within one jitted def (nested defs share the space —
    their bodies trace with the enclosing jit)."""

    def __init__(self, mod: ModuleInfo, fn: ast.AST, tainted: set[str]):
        self.mod = mod
        self.tainted = set(tainted)
        assigns = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    assigns.append((node.lineno, tgt, node.value))
            elif (
                isinstance(node, (ast.AnnAssign, ast.AugAssign))
                and node.value is not None
            ):
                assigns.append((node.lineno, node.target, node.value))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                # loop target over a tainted iterable is tainted
                assigns.append((node.lineno, node.target, node.iter))
        for _, tgt, value in sorted(assigns, key=lambda a: a[0]):
            names = target_names(tgt)
            if self.expr_tainted(value):
                self.tainted.update(names)
            else:
                self.tainted.difference_update(names)

    def expr_tainted(self, expr: ast.AST) -> bool:
        """Whether the expression carries taint after sanitization."""
        if expr is None:
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False  # static metadata of a traced value
            return self.expr_tainted(expr.value)
        if isinstance(expr, ast.Call):
            name = self.mod.dotted(expr.func)
            if name in _STATIC_CALLS:
                return False
            # a call's output is tainted if any input is (conservative
            # for jnp math, which is exactly the point)
            return any(
                self.expr_tainted(a)
                for a in [
                    *expr.args,
                    *[kw.value for kw in expr.keywords],
                ]
            ) or self.expr_tainted(expr.func)
        if isinstance(expr, ast.Compare):
            ops_are_is = all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops
            )
            comparators_none = all(
                _is_none(c) for c in expr.comparators
            ) or _is_none(expr.left)
            if ops_are_is and comparators_none:
                return False  # `x is None` — trace-time presence check
            return self.expr_tainted(expr.left) or any(
                self.expr_tainted(c) for c in expr.comparators
            )
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_tainted(v) for v in expr.values)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_tainted(expr.operand)
        if isinstance(expr, ast.BinOp):
            return self.expr_tainted(expr.left) or self.expr_tainted(
                expr.right
            )
        if isinstance(expr, ast.Subscript):
            return self.expr_tainted(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_tainted(e) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return any(
                self.expr_tainted(e)
                for e in (expr.test, expr.body, expr.orelse)
            )
        if isinstance(expr, ast.Starred):
            return self.expr_tainted(expr.value)
        return False


def _jitted_defs(mod: ModuleInfo):
    """(def_node, tainted_param_names) for each jit-compiled def whose
    body we can see."""
    out = []
    seen: set[ast.AST] = set()
    for site in collect_jit_sites(mod):
        fn = site.func_def
        if fn is None or not isinstance(
            fn, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        if fn in seen:
            continue
        seen.add(fn)
        params = list(site.params())
        static = set(site.static_positions())
        static_names = set(site.static_argnames)
        tainted = {
            p
            for i, p in enumerate(params)
            if i not in static and p not in static_names
        }
        out.append((fn, tainted))
    return out


@register_check(
    CHECK,
    "Python if/while/assert/bool() on values traced by jax.jit "
    "(concretization error or silently baked branch)",
)
def check_tracer_leak(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for fn, tainted in _jitted_defs(mod):
        t = _Tainter(mod, fn, tainted)
        context = mod.enclosing_function(fn)

        def flag(node: ast.AST, what: str) -> None:
            findings.append(
                Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    f"{what} on a value traced by jit-compiled "
                    f"`{fn.name}` — use jax.lax.cond/jnp.where, or mark "
                    "the driving argument static_argnums",
                    context,
                )
            )

        for node in ast.walk(fn):
            if isinstance(node, ast.If) and t.expr_tainted(node.test):
                flag(node, "Python `if`")
            elif isinstance(node, ast.While) and t.expr_tainted(node.test):
                flag(node, "Python `while`")
            elif isinstance(node, ast.Assert) and t.expr_tainted(node.test):
                flag(node, "`assert`")
            elif (
                isinstance(node, ast.Call)
                and mod.dotted(node.func) == "bool"
                and node.args
                and t.expr_tainted(node.args[0])
            ):
                flag(node, "`bool()`")
            elif isinstance(node, ast.IfExp) and t.expr_tainted(node.test):
                flag(node, "conditional expression")
    return findings
