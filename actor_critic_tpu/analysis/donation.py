"""donation-aliasing: donated jit arguments fed buffers that are not
jit's to free — the PR 4 heap-corruption class.

Two hazards, both found at donating call sites (`donate_argnums` /
`donate_argnames` wrap or decoration, resolved by analysis/jitinfo.py):

1. **Restored buffers**: the argument flows from a checkpoint restore
   (`*.restore(...)`, `resume_or_init`, `host_resume`) without being
   re-placed (`uncommit` / `jnp.copy` / `device_put`). Donating a
   restore-aliased buffer into a deserialized executable corrupted the
   glibc heap in PR 4 (`checkpoint.uncommit` is the fix; this check
   keeps the class from coming back at a NEW call site).
2. **Use after donation**: the donated name is read again after the
   donating call without being rebound by it — including the
   loop-carried form (`for ...: metrics = step(state)` with `state`
   never rebound, so iteration 2 donates a freed buffer). The donated
   buffer is freed (or worse, aliased by the output) — classic
   use-after-free that only crashes under real memory pressure.

Dataflow is per top-level function, statement-ordered by line number:
restore-taint enters at restore-like assignments, propagates through
name/subscript/attribute aliasing, and is cleared by any other
rebinding (so `state = uncommit(state)` cleans the name).
"""

from __future__ import annotations

import ast
from typing import Optional

from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
    target_names,
)
from actor_critic_tpu.analysis.jitinfo import named_jit_sites

CHECK = "donation-aliasing"

# Call names that yield restore-aliased buffers. Taint is cleared by
# rebinding from ANY other call's result (a call output is a fresh
# value — `state = uncommit(state)` cleans the name, and so does any
# transform of it); only name/subscript/attribute aliasing propagates.
_RESTORE_FUNCS = {"resume_or_init", "host_resume", "restore"}


def _root_name(node: ast.AST) -> Optional[str]:
    """The base Name of a Name/Subscript/Attribute chain."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _last_attr(mod: ModuleInfo, func: ast.AST) -> Optional[str]:
    dotted = mod.dotted(func)
    return dotted.rsplit(".", 1)[-1] if dotted else None


class _TaintScope:
    """Restore-taint of names within one top-level function, queried by
    line number (assignments before the line decide)."""

    def __init__(self, mod: ModuleInfo, scope: ast.AST):
        self.mod = mod
        # name -> [(lineno, restored_bool)] in line order
        self.history: dict[str, list[tuple[int, bool]]] = {}
        assigns: list[tuple[int, ast.AST, ast.AST]] = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    assigns.append((node.lineno, tgt, node.value))
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                if node.value is not None:
                    assigns.append((node.lineno, node.target, node.value))
        for lineno, tgt, value in sorted(assigns, key=lambda a: a[0]):
            restored = self._value_restored(value, lineno)
            for name in target_names(tgt, roots=True):
                self.history.setdefault(name, []).append((lineno, restored))

    def _value_restored(self, value: ast.AST, lineno: int) -> bool:
        if isinstance(value, ast.Call):
            attr = _last_attr(self.mod, value.func)
            if attr in _RESTORE_FUNCS:
                return True
            return False  # any other call output is a fresh value
        root = _root_name(value)
        if root is not None:
            return self.restored(root, lineno + 1)
        return False

    def restored(self, name: str, before_line: int) -> bool:
        state = False
        for lineno, restored in self.history.get(name, ()):
            if lineno < before_line:
                state = restored
            else:
                break
        return state


def _assign_targets_of_call(mod: ModuleInfo, call: ast.Call) -> set[str]:
    """Names the enclosing statement rebinds to this call's result."""
    parent = mod.parent(call)
    # tolerate  `a = b = f(x)`  and  `a, b = f(x)`  one level up
    if isinstance(parent, ast.Assign):
        return {
            n for tgt in parent.targets for n in target_names(tgt)
        }
    if isinstance(parent, (ast.AnnAssign, ast.AugAssign)) and isinstance(
        parent.target, ast.Name
    ):
        return {parent.target.id}
    return set()


def _reused_after(
    mod: ModuleInfo, scope: ast.AST, name: str, call: ast.Call
) -> Optional[int]:
    """First line after the donating call where `name` is read on a
    path that can follow it. Excluded: reads INSIDE the call itself (a
    multiline call's own argument sits on a later physical line) and
    reads in an exclusive sibling `if` arm (alternatives, not
    use-after-free)."""
    own = {id(n) for n in ast.walk(call)}
    best: Optional[int] = None
    for node in ast.walk(scope):
        if (
            isinstance(node, ast.Name)
            and node.id == name
            and isinstance(node.ctx, ast.Load)
            and id(node) not in own
            and node.lineno > call.lineno
            and not mod.exclusive_branches(call, node)
        ):
            best = node.lineno if best is None else min(best, node.lineno)
    return best


def _loop_without_rebind(
    mod: ModuleInfo, call: ast.Call, name: str, scope: ast.AST
) -> Optional[ast.AST]:
    """The innermost for/while around the donating call in which `name`
    is never (re)bound — iteration 2 would donate a freed buffer. None
    when no such loop exists."""
    loop = None
    for anc in mod.ancestors(call):
        if anc is scope:
            break
        if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
            loop = anc
            break
    if loop is None:
        return None
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign):
            if any(name in target_names(t) for t in node.targets):
                return None
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if name in target_names(node.target):
                return None
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if name in target_names(node.target):
                return None
    return loop


@register_check(
    CHECK,
    "donated jit args fed checkpoint-restored or still-live buffers "
    "(PR 4 heap-corruption class)",
)
def check_donation_aliasing(mod: ModuleInfo) -> list[Finding]:
    sites = {n: s for n, s in named_jit_sites(mod).items() if s.donates}
    if not sites:
        return []
    findings: list[Finding] = []
    taints: dict[ast.AST, _TaintScope] = {}

    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call) or not isinstance(
            call.func, ast.Name
        ):
            continue
        site = sites.get(call.func.id)
        if site is None:
            continue
        positions = site.donated_positions()
        if not positions and site.donates:
            positions = (0,)  # jax's overwhelmingly common convention
        donated_args: list[ast.AST] = [
            call.args[p]
            for p in positions
            if p < len(call.args)
            and not isinstance(call.args[p], ast.Starred)
        ]
        donated_args += [
            kw.value for kw in call.keywords if kw.arg in site.donate_argnames
        ]
        if not donated_args:
            continue

        scope = mod.scope_of(call)
        if scope not in taints:
            taints[scope] = _TaintScope(mod, scope)
        taint = taints[scope]
        rebound = _assign_targets_of_call(mod, call)
        context = mod.enclosing_function(call)

        for arg in donated_args:
            # direct `f(ckpt.restore(t))`
            if (
                isinstance(arg, ast.Call)
                and _last_attr(mod, arg.func) in _RESTORE_FUNCS
            ):
                findings.append(
                    Finding(
                        CHECK, mod.relpath, arg.lineno, arg.col_offset,
                        f"donating the result of a checkpoint restore into "
                        f"jitted `{call.func.id}` — restore-aliased buffers "
                        "must be re-placed first (checkpoint.uncommit / "
                        "jnp.copy)",
                        context,
                    )
                )
                continue
            name = _root_name(arg)
            if name is None:
                continue
            if taint.restored(name, call.lineno):
                findings.append(
                    Finding(
                        CHECK, mod.relpath, arg.lineno, arg.col_offset,
                        f"`{name}` flows from a checkpoint restore and is "
                        f"donated into jitted `{call.func.id}` — donating a "
                        "restore-aliased buffer into a deserialized "
                        "executable corrupts the heap (PR 4); re-place it "
                        "(checkpoint.uncommit / jnp.copy) first",
                        context,
                    )
                )
            if isinstance(mod.parent(call), ast.Return):
                # a donating call in a `return` ends its path; a read on
                # a LATER line is a sibling branch, not a use-after-free
                continue
            loop = _loop_without_rebind(mod, call, name, scope)
            if loop is not None:
                # the canonical PR 4 shape: iteration 2 donates the
                # buffer iteration 1 already freed
                findings.append(
                    Finding(
                        CHECK, mod.relpath, call.lineno, call.col_offset,
                        f"`{name}` is donated into jitted "
                        f"`{call.func.id}` inside the loop at line "
                        f"{loop.lineno} but never rebound in it — the "
                        "next iteration donates an already-freed buffer; "
                        "rebind the result (`out = "
                        f"{call.func.id}(...)`) or drop the donation",
                        context,
                    )
                )
                continue
            if name not in rebound:
                reuse_line = _reused_after(mod, scope, name, call)
                if reuse_line is not None:
                    findings.append(
                        Finding(
                            CHECK, mod.relpath, call.lineno, call.col_offset,
                            f"`{name}` is donated into jitted "
                            f"`{call.func.id}` but read again at line "
                            f"{reuse_line} — a donated buffer is freed by "
                            "the call; rebind the result or drop the "
                            "donation",
                            context,
                        )
                    )
    return findings
