"""recompile-hazard: call patterns that defeat the compile-once
contract (the storms PR 3 could only observe and PR 4 prevents).

Two sub-patterns, both visible from source:

1. **jit construction inside a loop** — `jax.jit(...)` (or
   `partial(jax.jit, ...)` application, or a jit-decorated def)
   evaluated in a `for`/`while`/comprehension body builds a FRESH
   callable per iteration. Each fresh callable has an empty dispatch
   cache, so every call re-traces (and, for closures over loop
   variables — the f-string/`.shape`-captured closure case — compiles a
   distinct program per iteration). Hoist the jit out of the loop.

2. **shape-churning scalar arguments** — a known-jitted callable fed a
   `len(...)`- or `.shape`-derived Python value (directly or through a
   local name) that is not covered by `static_argnums`/
   `static_argnames`. Used as a shape inside the program it either
   fails to trace or gets marked static — and then every distinct value
   is its own XLA program (the chunked-tail storm
   `compile_cache.make_chunked_step` exists to fix). Pass a padded
   bucket (`pad_to_bucket`) or pin it dynamic with
   `jnp.asarray(x, dtype)`.

Near-misses that stay clean: args already wrapped in
`jnp.asarray`/`np.asarray`/`jnp.array` (dynamic, dtype-pinned), and
positions the wrap explicitly lists in `static_argnums` (the author
opted into per-value compilation deliberately).
"""

from __future__ import annotations

import ast
from typing import Optional

from actor_critic_tpu.analysis.core import Finding, ModuleInfo, register_check
from actor_critic_tpu.analysis.jitinfo import (
    is_jax_jit_expr,
    named_jit_sites,
)

CHECK = "recompile-hazard"

_LOOPS = (
    ast.For, ast.AsyncFor, ast.While,
    ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
)
_ASARRAY = {
    "jax.numpy.asarray", "jax.numpy.array", "numpy.asarray", "numpy.array",
    "jnp.asarray", "jnp.array",
}


def _in_loop(mod: ModuleInfo, node: ast.AST) -> bool:
    return any(isinstance(a, _LOOPS) for a in mod.ancestors(node))


def _is_shape_derived(mod: ModuleInfo, expr: ast.AST) -> Optional[str]:
    """A human-readable description when `expr` is len()- or
    .shape-derived, else None."""
    if isinstance(expr, ast.Call) and mod.dotted(expr.func) == "len":
        return "a len(...) value"
    if isinstance(expr, ast.Attribute) and expr.attr == "shape":
        return "a .shape tuple"
    if (
        isinstance(expr, ast.Subscript)
        and isinstance(expr.value, ast.Attribute)
        and expr.value.attr == "shape"
    ):
        return "a .shape[i] value"
    if isinstance(expr, ast.BinOp):
        return _is_shape_derived(mod, expr.left) or _is_shape_derived(
            mod, expr.right
        )
    return None


def _latest_assignment(
    mod: ModuleInfo, scope: ast.AST, name: str, before: int
) -> Optional[ast.AST]:
    best_line = -1
    best_value: Optional[ast.AST] = None
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and node.lineno < before:
            for tgt in node.targets:
                targets = (
                    [tgt] if isinstance(tgt, ast.Name) else (
                        tgt.elts if isinstance(tgt, (ast.Tuple, ast.List))
                        else []
                    )
                )
                for i, t in enumerate(targets):
                    if isinstance(t, ast.Name) and t.id == name:
                        if node.lineno > best_line:
                            best_line = node.lineno
                            # tuple-unpack of `x.shape` marks every
                            # target shape-derived
                            best_value = node.value
    return best_value


@register_check(
    CHECK,
    "jit built inside a loop, or shape-/len()-derived scalars fed to "
    "jitted calls (re-trace per iteration / per value)",
)
def check_recompile_hazard(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []

    # -- 1. jit construction inside a loop --------------------------------
    for node in ast.walk(mod.tree):
        is_wrap = isinstance(node, ast.Call) and (
            mod.dotted(node.func) == "jax.jit"
            or is_jax_jit_expr(mod, node.func)
        )
        is_dec = isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)
        ) and any(is_jax_jit_expr(mod, d) for d in node.decorator_list)
        if (is_wrap or is_dec) and _in_loop(mod, node):
            findings.append(
                Finding(
                    CHECK, mod.relpath, node.lineno, node.col_offset,
                    "jax.jit evaluated inside a loop — every iteration "
                    "builds a fresh callable with an empty dispatch cache "
                    "(re-trace per iteration); hoist the jit out of the "
                    "loop",
                    mod.enclosing_function(node),
                )
            )

    # -- 2. shape-churning scalar args at jitted call sites ----------------
    sites = named_jit_sites(mod)
    if not sites:
        return findings
    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call) or not isinstance(
            call.func, ast.Name
        ):
            continue
        site = sites.get(call.func.id)
        if site is None:
            continue
        static_pos = set(site.static_positions())
        static_names = set(site.static_argnames)
        scope = None
        for i, arg in enumerate(call.args):
            if i in static_pos or isinstance(arg, ast.Starred):
                continue
            self_desc = _describe_hazard(mod, call, arg)
            if self_desc is None and isinstance(arg, ast.Name):
                if scope is None:
                    scope = mod.scope_of(call)
                value = _latest_assignment(mod, scope, arg.id, call.lineno)
                if value is not None:
                    derived = _is_shape_derived(mod, value)
                    if derived is not None:
                        self_desc = f"`{arg.id}` ({derived})"
            if self_desc is not None:
                findings.append(
                    Finding(
                        CHECK, mod.relpath, arg.lineno, arg.col_offset,
                        f"jitted `{call.func.id}` is fed {self_desc} — a "
                        "data-dependent Python scalar either fails to "
                        "trace or (marked static) compiles one program "
                        "per distinct value; pad to a bucket "
                        "(compile_cache.pad_to_bucket) or pin it dynamic "
                        "with jnp.asarray(x, dtype)",
                        mod.enclosing_function(call),
                    )
                )
        for kw in call.keywords:
            if kw.arg in static_names or kw.arg is None:
                continue
            desc = _describe_hazard(mod, call, kw.value)
            if desc is not None:
                findings.append(
                    Finding(
                        CHECK, mod.relpath, kw.value.lineno,
                        kw.value.col_offset,
                        f"jitted `{call.func.id}` is fed {desc} via "
                        f"`{kw.arg}=` — each distinct value re-traces; "
                        "mark it static deliberately or pin it dynamic "
                        "with jnp.asarray(x, dtype)",
                        mod.enclosing_function(call),
                    )
                )
    return findings


def _describe_hazard(
    mod: ModuleInfo, call: ast.Call, arg: ast.AST
) -> Optional[str]:
    if isinstance(arg, ast.Call) and mod.dotted(arg.func) in _ASARRAY:
        return None  # dtype-pinned dynamic array: the sanctioned form
    return _is_shape_derived(mod, arg)
