"""Numerics passes: precision-discipline, nonfinite-hazard, sink-guard
(ISSUE 14 tentpole, static half).

The fourth analysis dimension (JAX correctness → threads → processes →
NUMERICS), gating the ROADMAP's bf16/Pallas kernel direction: low-
precision compute paths only land safely once the repo can prove where
precision changes, where non-finites can be born, and where they would
escape into durable/visible state. Each pass is grounded in a failure
class this codebase hit or is one edit away from:

- **precision-discipline** — silent dtype changes. (a) float64 on the
  device namespace (CPU-silent, TPU-fatal: jax demotes or errors, and
  an x64 path doubles every buffer). (b) bf16/f16 × f32 arithmetic
  without an explicit astype: promotion silently discards the
  low-precision intent (the bf16 path quietly computes in f32, so the
  measured speedup is noise) or, reversed, quietly truncates. (c)
  reductions over bf16/f16 operands without an fp32 accumulator
  (`dtype=jnp.float32`): `jnp.sum` accumulates IN the operand dtype,
  and a [4096]-element bf16 sum has ~8 bits of mantissa left — the
  bf16-accumulator revert class. (d) codec decode paths whose output
  dtype forks on the codec kind (measured through `jax.eval_shape` when
  the live package is importable) — callers must normalize or every
  downstream op's dtype depends on a config string.
- **nonfinite-hazard** — where NaN/Inf are born. `log`/`sqrt`/
  `arctanh`/division at sites whose operands are not provably guarded
  (the model recognizes this repo's eps-add, `clip`, `maximum`-floor,
  `where`-select and `_EPS` idioms and non-negative producers);
  `exp` of an unbounded log-ratio (the PPO/V-trace importance-ratio
  shape — behavior/target drift overflows it to inf, and inf × 0
  advantage is NaN); and fresh `scale` seeds from bare constants (the
  PR 8 class: a `1.0` seed destroys int8 resolution, a `0.0` seed
  divides by zero — the `_EPS`-floor seed is the sanctioned idiom).
- **sink-guard** — where non-finites escape. `json.dumps(...,
  allow_nan=False)` raises on the first NaN and the writer drops the
  row (the telemetry crash class — route through
  `utils.numguard.safe_json_row`); commit-point defs (`write_params`,
  `publish`, `swap`, `save` taking a params/state tree) must carry a
  finiteness gate (`numguard.check_finite`) so a poisoned tree is
  refused before it becomes durable (checkpoint), fleet-visible
  (mailbox), or client-visible (gateway swap).

Runtime companion: `analysis/numsan.py` poisons real trees through the
REAL update/codec/publish/checkpoint objects and asserts the guards
these passes require statically actually fire (`scripts/numsan.py`,
tier-1's quick profile between fleetsan and pytest).
"""

from __future__ import annotations

import ast
from typing import Optional

from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
)
from actor_critic_tpu.analysis.dtype_model import (
    LOW_PRECISION,
    DtypeModel,
    _call_name,
    codec_fork_evidence,
    dumps_sites,
    dtype_token,
    iter_scopes,
    sink_defs,
)

PRECISION_DISCIPLINE = "precision-discipline"
NONFINITE_HAZARD = "nonfinite-hazard"
SINK_GUARD = "sink-guard"

# Single-entry shared-model cache (the concurrency/distributed passes'
# `_SHARED` idiom): three registered checks, one DtypeModel per run.
_SHARED: dict = {}


def _shared_model(modules: list[ModuleInfo]) -> DtypeModel:
    key = tuple(id(m) for m in modules)
    entry = _SHARED.get("entry")
    if entry is not None and entry[0] == key:
        return entry[1]
    model = DtypeModel(modules)
    _SHARED["entry"] = (key, model, list(modules))
    return model


_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.MatMult, ast.Pow)
_ACCUMULATING = {"sum", "mean", "var", "std", "prod", "dot", "matmul"}
# Reductions that hit exactly zero on degenerate input (a constant
# batch, an all-false mask, zeroed weights). max/min and wall-clock
# differences are deliberately absent — host timing quotients are not
# this hazard class.
_REDUCERS = {"sum", "mean", "var", "std", "norm", "count_nonzero"}
_LOG_CALLS = {"log", "log2", "log10"}


def _bare_names(expr: ast.AST) -> set[str]:
    """Bare (non-attribute-base) Name loads in an expression: `x` in
    `f(x)` counts, `cfg` in `cfg.init_alpha` does not — attribute reads
    are out-of-scope provenance the model never resolves (assumption
    shared with the thread model)."""
    attr_bases = {
        id(sub.value)
        for sub in ast.walk(expr)
        if isinstance(sub, ast.Attribute)
    }
    return {
        sub.id
        for sub in ast.walk(expr)
        if isinstance(sub, ast.Name)
        and isinstance(sub.ctx, ast.Load)
        and id(sub) not in attr_bases
    }


def _opaque(mod: ModuleInfo, scope: ast.AST, expr: ast.AST) -> bool:
    """Attribute/constant-only provenance: nothing in the expression is
    a locally-visible value, so guardedness cannot be judged here —
    stay silent (the flagging passes only fire on in-scope evidence)."""
    return not _bare_names(expr)


# ---------------------------------------------------------------------------
# precision-discipline
# ---------------------------------------------------------------------------


@register_check(
    PRECISION_DISCIPLINE,
    "device float64; silent bf16/f16-with-f32 arithmetic; reductions "
    "over low-precision operands without an fp32 accumulator; codec "
    "decode dtypes forking on the codec kind",
    scope="repo",
)
def check_precision_discipline(
    modules: list[ModuleInfo],
) -> list[Finding]:
    model = _shared_model(modules)
    findings: list[Finding] = []
    for mod in modules:
        findings.extend(_f64_findings(mod))
        for scope in iter_scopes(mod):
            env = model.env(mod, scope)
            for node in ast.walk(scope):
                if isinstance(node, ast.BinOp) and isinstance(
                    node.op, _ARITH_OPS
                ):
                    left = env.expr_dtype(node.left)
                    right = env.expr_dtype(node.right)
                    pair = {left, right}
                    if pair & set(LOW_PRECISION) and pair & {"f32", "f64"}:
                        findings.append(
                            Finding(
                                PRECISION_DISCIPLINE, mod.relpath,
                                node.lineno, node.col_offset,
                                f"mixed-precision arithmetic: {left} "
                                f"with {right} promotes silently — the "
                                "low-precision side either upcasts "
                                "(the bf16 compute path quietly runs "
                                "in f32 and the measured speedup is "
                                "noise) or the result truncates on the "
                                "next narrow store; make the intent "
                                "explicit with .astype at this site",
                                mod.enclosing_function(node),
                            )
                        )
                if isinstance(node, ast.Call):
                    findings.extend(
                        _accumulator_findings(mod, env, node)
                    )
            findings.extend(_fork_findings(mod, model, scope))
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


def _f64_findings(mod: ModuleInfo) -> list[Finding]:
    """Device-namespace float64: jnp constructors with a float64 dtype
    and .astype(jnp.float64). Host-side numpy float64 (the env pools'
    Welford normalizers, gymnasium-native obs) is deliberate and out of
    scope."""
    out: list[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        flagged = False
        name = _call_name(node)
        if name == "astype" and node.args:
            if mod.dotted(node.args[0]) == "jax.numpy.float64":
                flagged = True
        elif isinstance(node.func, ast.Attribute):
            base = mod.dotted(node.func.value)
            if base == "jax.numpy":
                for kw in node.keywords:
                    if kw.arg == "dtype" and dtype_token(
                        mod, kw.value
                    ) == "f64":
                        flagged = True
                from actor_critic_tpu.analysis.dtype_model import (
                    _CONSTRUCTORS,
                )

                pos = _CONSTRUCTORS.get(name or "")
                if pos is not None and len(node.args) > pos and (
                    dtype_token(mod, node.args[pos]) == "f64"
                ):
                    flagged = True
        if flagged:
            out.append(
                Finding(
                    PRECISION_DISCIPLINE, mod.relpath,
                    node.lineno, node.col_offset,
                    "float64 on the device namespace: without "
                    "jax_enable_x64 this silently demotes to f32 (the "
                    "annotation lies), and WITH it every touched "
                    "buffer doubles and TPUs fall off the fast path — "
                    "keep f64 on host numpy (the Welford-normalizer "
                    "idiom) and device arrays at f32 or below",
                    mod.enclosing_function(node),
                )
            )
    return out


def _accumulator_findings(
    mod: ModuleInfo, env, node: ast.Call
) -> list[Finding]:
    name = _call_name(node)
    if name not in _ACCUMULATING:
        return []
    if any(kw.arg == "dtype" for kw in node.keywords):
        return []  # explicit accumulator: the sanctioned idiom
    operand: Optional[ast.AST] = None
    if node.args:
        operand = node.args[0]
    elif isinstance(node.func, ast.Attribute):
        operand = node.func.value  # x.sum() method spelling
    if operand is None:
        return []
    token = env.expr_dtype(operand)
    if token not in LOW_PRECISION:
        return []
    return [
        Finding(
            PRECISION_DISCIPLINE, mod.relpath,
            node.lineno, node.col_offset,
            f"`{name}` accumulates IN its {token} operand dtype: a "
            "long reduction leaves ~8 mantissa bits by the end (the "
            "bf16-accumulator class) and the loss/advantage built on "
            "it is quantization noise; pass dtype=jnp.float32 (XLA "
            "still reads the narrow operand — the accumulator is the "
            "only thing widened)",
            mod.enclosing_function(node),
        )
    ]


_FORK_PARAMS = {"kind", "codec", "mode"}


def _fork_findings(
    mod: ModuleInfo, model: DtypeModel, scope: ast.AST
) -> list[Finding]:
    if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return []
    args = scope.args
    params = {
        a.arg for a in args.posonlyargs + args.args + args.kwonlyargs
    }
    if not (params & _FORK_PARAMS):
        return []
    env = model.env(mod, scope)
    known: set[str] = set()
    passthrough = False
    returns = [
        n for n in ast.walk(scope)
        if isinstance(n, ast.Return) and n.value is not None
    ]
    for ret in returns:
        value = ret.value
        if isinstance(value, ast.Call) and _call_name(value) in (
            "asarray", "array"
        ) and value.args and not value.keywords and len(value.args) == 1:
            value = value.args[0]  # dtype-preserving wrapper
        if isinstance(value, ast.Name) and value.id in params:
            passthrough = True
            continue
        token = env.expr_dtype(ret.value)
        if token is not None and token not in ("pyfloat", "pyint"):
            known.add(token)
    fork = len(known) > 1 or (known and passthrough)
    if not fork:
        return []
    evidence = codec_fork_evidence(f"quantize.{scope.name}")
    detail = f" ({evidence})" if evidence else ""
    kinds = ", ".join(sorted(known)) + (
        " + kind-dependent passthrough" if passthrough else ""
    )
    return [
        Finding(
            PRECISION_DISCIPLINE, mod.relpath,
            scope.lineno, scope.col_offset,
            f"`{scope.name}`'s return dtype forks on its codec/kind "
            f"argument ({kinds}){detail}: every downstream op's dtype "
            "now depends on a config string — callers must normalize "
            "the decode output (or the fork must be documented and "
            "audited at this def)",
            scope.name,
        )
    ]


# ---------------------------------------------------------------------------
# nonfinite-hazard
# ---------------------------------------------------------------------------


@register_check(
    NONFINITE_HAZARD,
    "unguarded log/sqrt/arctanh/division operands, exp of unbounded "
    "log-ratios (the PPO/V-trace surrogate), and scale seeds from bare "
    "constants instead of the _EPS floor (the PR 8 class)",
    scope="repo",
)
def check_nonfinite_hazard(modules: list[ModuleInfo]) -> list[Finding]:
    model = _shared_model(modules)
    findings: list[Finding] = []
    for mod in modules:
        for scope in iter_scopes(mod):
            guards = model.guards(mod, scope)
            for node in ast.walk(scope):
                if isinstance(node, ast.Call):
                    findings.extend(_op_findings(mod, scope, guards, node))
                elif isinstance(node, ast.BinOp) and isinstance(
                    node.op, ast.Div
                ):
                    findings.extend(
                        _division_findings(mod, scope, guards, node)
                    )
        findings.extend(_scale_seed_findings(mod))
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


_MATH_NAMESPACES = ("jax.numpy", "numpy", "math", "jax.nn", "jax.lax")


def _math_call(mod: ModuleInfo, node: ast.Call) -> Optional[str]:
    if not isinstance(node.func, ast.Attribute):
        return None
    base = mod.dotted(node.func.value)
    if base in _MATH_NAMESPACES or (base or "").endswith(".numpy"):
        return node.func.attr
    return None


def _op_findings(mod, scope, guards, node: ast.Call) -> list[Finding]:
    name = _math_call(mod, node)
    if name is None or not node.args:
        return []
    arg = node.args[0]
    ctx = mod.enclosing_function(node)
    if name in _LOG_CALLS:
        if guards.positive_floored(arg) or _opaque(mod, scope, arg):
            return []
        return [Finding(
            NONFINITE_HAZARD, mod.relpath, node.lineno, node.col_offset,
            f"`{name}` of an operand not provably floored away from "
            "zero: one zero/negative element is -inf/nan in the loss "
            "and every guard downstream of it dies at once; floor the "
            "operand (`+ _EPS`, `clip(lo=eps)`, `maximum(x, eps)` — "
            "the repo idioms this pass recognizes)",
            ctx,
        )]
    if name == "sqrt":
        if guards.nonnegative(arg) or _opaque(mod, scope, arg):
            return []
        return [Finding(
            NONFINITE_HAZARD, mod.relpath, node.lineno, node.col_offset,
            "`sqrt` of an operand not provably non-negative: one "
            "negative element (a variance estimate gone slightly "
            "below zero in low precision) is nan; produce it from "
            "`var`/`square`/`abs` or floor it (`maximum(x, 0.0)`)",
            ctx,
        )]
    if name in ("arctanh", "atanh"):
        if guards.bounded(arg) or _opaque(mod, scope, arg):
            return []
        return [Finding(
            NONFINITE_HAZARD, mod.relpath, node.lineno, node.col_offset,
            "`arctanh` of an unclipped operand: a squashed action "
            "stored at exactly ±1 (f32 rounding of tanh at modest "
            "pre-activations does this) evaluates to ±inf and the "
            "log_prob of that sample poisons the whole batch — clip "
            "to ±(1 - 1e-6) first (the TanhGaussian.log_prob idiom)",
            ctx,
        )]
    if name == "exp":
        if guards.log_diff(arg) and not guards.bounded(arg):
            return [Finding(
                NONFINITE_HAZARD, mod.relpath,
                node.lineno, node.col_offset,
                "`exp` of an unbounded log-ratio (the importance-"
                "ratio shape): when behavior and target policies "
                "drift, the ratio overflows to inf and inf × 0 "
                "advantage is nan — cap the log-ratio first "
                "(`jnp.minimum(log_ratio, CAP)`; clipping the RATIO "
                "after exp is too late, the inf already happened)",
                ctx,
            )]
    return []


def _division_findings(mod, scope, guards, node: ast.BinOp) -> list[Finding]:
    denom = node.right
    resolved = guards._resolve(denom, 1)
    risky = isinstance(resolved, ast.Call) and (
        _call_name(resolved) in _REDUCERS
    )
    if not risky or guards.positive_floored(resolved):
        return []
    if _conditionally_guarded(mod, node, denom):
        return []
    return [Finding(
        NONFINITE_HAZARD, mod.relpath, node.lineno, node.col_offset,
        "division by an unfloored reduction/difference: a constant "
        "batch (or an empty mask) makes the denominator exactly zero "
        "and the quotient inf/nan; floor it (`+ _EPS` or "
        "`maximum(d, eps)` — the normalize_advantages idiom)",
        mod.enclosing_function(node),
    )]


def _conditionally_guarded(
    mod: ModuleInfo, node: ast.AST, denom: ast.AST
) -> bool:
    """Whether the division sits inside an `if`/ternary whose test
    mentions its denominator — the host-side `x / w if w > 0 else 0.0`
    idiom (the in-jit equivalent is the `where`-select the guard facts
    already recognize)."""
    names = _bare_names(denom)
    if not names:
        return False
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.IfExp, ast.If)):
            if names & _bare_names(anc.test):
                return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
    return False


_SCALE_CTORS = {"zeros", "ones", "full", "zeros_like", "ones_like",
                "full_like"}


def _bad_scale_seed(mod: ModuleInfo, value: ast.AST) -> Optional[str]:
    """Why a scale-seed expression is hazardous, or None when it is
    fine (the `_EPS`-floor fill, a non-constructor value)."""
    if not isinstance(value, ast.Call):
        return None
    name = _call_name(value)
    if name not in _SCALE_CTORS:
        return None
    if name in ("zeros", "zeros_like"):
        return "a 0.0 seed divides the first encode by zero"
    if name in ("ones", "ones_like"):
        return (
            "a 1.0 seed permanently floors the quantization step at "
            "1/127 (the running max only grows) — the PR 8 bug"
        )
    fill = None
    if name == "full" and len(value.args) >= 2:
        fill = value.args[1]
    elif name == "full_like" and len(value.args) >= 2:
        fill = value.args[1]
    for kw in value.keywords:
        if kw.arg == "fill_value":
            fill = kw.value
    if fill is None:
        return None
    from actor_critic_tpu.analysis.dtype_model import _is_eps_name

    if _is_eps_name(fill):
        return None  # the sanctioned _EPS-floor seed
    if isinstance(fill, ast.Constant) and isinstance(
        fill.value, (int, float)
    ) and not isinstance(fill.value, bool):
        v = float(fill.value)
        if v == 0.0:
            return "a 0.0 seed divides the first encode by zero"
        if v >= 1e-3:
            return (
                f"a {v!r} seed permanently floors the quantization "
                "step (the running max only grows) — the PR 8 bug"
            )
    return None


def _scale_seed_findings(mod: ModuleInfo) -> list[Finding]:
    out: list[Finding] = []

    def scaleish(name: str) -> bool:
        low = name.lower()
        return "scale" in low or low.endswith("std")

    for node in ast.walk(mod.tree):
        sites: list[tuple[str, ast.AST, ast.AST]] = []
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                for name in (
                    [tgt.id] if isinstance(tgt, ast.Name) else []
                ):
                    if scaleish(name):
                        sites.append((name, node.value, node))
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg and scaleish(kw.arg):
                    sites.append((kw.arg, kw.value, kw.value))
        for name, value, anchor in sites:
            why = _bad_scale_seed(mod, value)
            if why is None:
                continue
            lineno = getattr(anchor, "lineno", node.lineno)
            col = getattr(anchor, "col_offset", node.col_offset)
            out.append(Finding(
                NONFINITE_HAZARD, mod.relpath, lineno, col,
                f"`{name}` seeded from a bare constant: {why}; seed "
                "at the _EPS floor (`full(shape, _EPS)`) like "
                "quantize.init_stats",
                mod.enclosing_function(node),
            ))
    return out


# ---------------------------------------------------------------------------
# sink-guard
# ---------------------------------------------------------------------------


@register_check(
    SINK_GUARD,
    "json.dumps(allow_nan=False) writers (one NaN drops the row) and "
    "commit-point defs (write_params/publish/swap/save) without a "
    "finiteness gate — non-finite trees escaping into durable/"
    "fleet-visible/client-visible state",
    scope="repo",
)
def check_sink_guard(modules: list[ModuleInfo]) -> list[Finding]:
    findings: list[Finding] = []
    for mod in modules:
        for node in dumps_sites(mod):
            findings.append(Finding(
                SINK_GUARD, mod.relpath, node.lineno, node.col_offset,
                "json.dumps(allow_nan=False) raises on the first "
                "non-finite value and this writer drops the whole row "
                "— a NaN loss gauge silently ends telemetry for the "
                "rest of the run (the ISSUE 14 sampler crash class); "
                "route through utils.numguard.safe_json_row (non-"
                "finite → null, offending key reported once)",
                mod.enclosing_function(node),
            ))
        for def_node, gated in sink_defs(mod):
            if gated:
                continue
            findings.append(Finding(
                SINK_GUARD, mod.relpath,
                def_node.lineno, def_node.col_offset,
                f"commit point `{def_node.name}` has no finiteness "
                "gate: a nan/inf tree flowing through here becomes "
                "durable (checkpoint), fleet-visible (mailbox "
                "publish), or client-visible (gateway swap) — call "
                "utils.numguard.check_finite before the commit so "
                "the previous good snapshot stays in place",
                def_node.name,
            ))
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings
