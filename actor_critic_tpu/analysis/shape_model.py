"""shape_model: shape/padding/mask facts for the padding-discipline
passes (analysis/shapes.py) — the SHAPES sibling of thread_model /
process_model / dtype_model / perf_model.

The framework stabilizes shapes by padding everywhere the hardware
wants tiles: `pad_to_bucket` widens ragged serving/chunk batches to a
bucket ladder, `ops.pallas_scan._pad_lanes` lane-pads ragged env
batches to the 128-lane Mosaic tile ("compute junk, slice it away"),
and the mixture fleet zero-pads heterogeneous obs behind per-type
validity masks. Each producer has a DISCIPLINE that keeps the junk
lanes out of the math:

- a **mask** rides along (`padded, mask = pad_to_bucket(...)`) and
  every reduction over the widened axis multiplies/`where`s it in, or
- the consumer **slices back** to the valid prefix (`out[:n]`,
  `adv[:, :E]`) before anything observes the padded lanes.

This module inventories, per statement-ordered scope (the same units
dtype_model analyzes):

- **pad bindings** — names bound from a padding producer call
  (`pad_to_bucket` unpack, `_pad_lanes` unpack, `jnp.pad`/`np.pad`),
  each carrying the mask name bound alongside it (None when the mask
  was discarded with `_`), threaded through shape-preserving wrappers
  (`asarray`/`astype`/`device_put`/...) and CLEARED by a slice-back or
  any other rebind;
- **mask names** — the second `pad_to_bucket` unpack element plus any
  identifier that self-describes as a mask (`*mask*`, `*valid*`,
  `*count*`);
- **slice-back sites** — names that appear under a `Slice` subscript
  anywhere in the scope (`np.asarray(out)[:n]` counts for `out`): the
  evidence that a padded result is cut before it is observed.

Everything is pure `ast` (core.py's contract: scanned code is never
imported). Like the siblings, the model is deliberately name-local and
conservative: a binding is only "padded" when a producer call visibly
creates it in the same scope, so the passes built on top have the
precision to run with an EMPTY baseline.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from actor_critic_tpu.analysis.core import ModuleInfo, target_names

# ---------------------------------------------------------------------------
# Vocabulary
# ---------------------------------------------------------------------------

# Producer call suffixes (matched against core's alias-resolved dotted
# name): the batch-axis bucket pad, the Mosaic lane pad, and the raw
# jnp/np pad primitive.
BUCKET_PAD_SUFFIX = "pad_to_bucket"
LANE_PAD_SUFFIX = "_pad_lanes"
RAW_PAD_ROOTS = ("jax.numpy", "numpy", "jax")  # <root>.pad / <root>...pad

# Defs that ARE the producers (and their unit-sized helpers): the pad
# they construct is their contract, not a leak — the passes skip their
# bodies entirely.
PRODUCER_DEF_NAMES = {"pad_to_bucket", "_pad_lanes", "_pad"}

# Calls that preserve the padded axis (and therefore propagate the
# binding): staging/casting wrappers between the producer and the
# consumer seam.
_PRESERVING_SUFFIXES = (
    "asarray", "array", "device_put", "device_get", "block_until_ready",
    "astype", "copy", "stop_gradient",
)

# Reductions that collapse an axis — the calls pad-mask-discipline
# audits when their operand is a padded binding.
REDUCTION_NAMES = {
    "mean", "sum", "max", "min", "prod", "std", "var", "median",
    "average", "amax", "amin", "argmax", "argmin", "nanmean", "nansum",
    "logsumexp", "softmax", "log_softmax",
}

# Commit-point callees for slice-before-commit: once a padded buffer
# crosses one of these it is durable/visible (published params, a
# checkpoint, a data-plane slot, a serving response, a socket) and the
# junk lanes are someone else's wrong answer.
COMMIT_NAMES = {
    "publish", "save", "save_checkpoint", "swap", "write_params",
    "put", "put_nowait", "enqueue", "send", "sendall", "respond",
    "write", "wfile_write", "set_result",
}

# Identifier fragments that self-describe as pad-validity metadata: a
# call that passes one of these alongside the padded array is keeping
# the mask-propagation contract.
MASK_FRAGMENTS = ("mask", "valid", "count")

# Alias-resolved roots treated as library namespaces: elementwise
# library math preserves lanes (and its reductions are pad-mask-
# discipline's domain), so mask-propagation only audits USER seams.
_LIB_ROOTS = {
    "jax", "numpy", "math", "functools", "np", "jnp", "scipy",
}


# ---------------------------------------------------------------------------
# Small AST predicates shared by the passes
# ---------------------------------------------------------------------------


def call_name(mod: ModuleInfo, node: ast.Call) -> Optional[str]:
    """Alias-resolved dotted name of a call's callee; for curried calls
    (`pl.pallas_call(...)(args)`) the INNER callee's name — that is the
    namespace that decides library-vs-user."""
    fn = node.func
    while isinstance(fn, ast.Call):
        fn = fn.func
    return mod.dotted(fn)


def bare_names(expr: ast.AST) -> set[str]:
    """Bare Name loads in an expression, excluding attribute bases
    (`x.shape` uses `x` structurally, `jnp.mean` is a namespace) — the
    same notion numerics.py keys its models on."""
    out: set[str] = set()
    attr_bases: set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            attr_bases.add(id(node.value))
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and id(node) not in attr_bases:
            out.add(node.id)
    return out


def is_maskish(name: str) -> bool:
    low = name.lower()
    return any(frag in low for frag in MASK_FRAGMENTS)


def _is_lib_root(mod: ModuleInfo, dotted: Optional[str]) -> bool:
    if not dotted:
        return False
    root = dotted.split(".")[0]
    return root in _LIB_ROOTS


def is_raw_pad_call(mod: ModuleInfo, node: ast.Call) -> bool:
    """`jnp.pad(...)` / `np.pad(...)` (alias-resolved)."""
    dotted = mod.dotted(node.func)
    if not dotted or not dotted.endswith(".pad"):
        return False
    return _is_lib_root(mod, dotted)


def producer_kind(mod: ModuleInfo, node: ast.Call) -> Optional[str]:
    """"pad_to_bucket" | "_pad_lanes" | "pad" for producer calls."""
    dotted = mod.dotted(node.func)
    if dotted:
        if dotted.split(".")[-1] == BUCKET_PAD_SUFFIX:
            return "pad_to_bucket"
        if dotted.split(".")[-1] == LANE_PAD_SUFFIX:
            return "_pad_lanes"
    if is_raw_pad_call(mod, node):
        return "pad"
    return None


def is_preserving_call(mod: ModuleInfo, node: ast.Call) -> bool:
    dotted = mod.dotted(node.func)
    if dotted is None:
        return False
    return dotted.split(".")[-1] in _PRESERVING_SUFFIXES


def reduction_operand(
    mod: ModuleInfo, node: ast.Call
) -> Optional[ast.AST]:
    """The reduced expression when `node` is a reduction call, else
    None. Covers `jnp.mean(x)` (library function, first positional arg)
    and `x.mean()` (method form, the receiver)."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in REDUCTION_NAMES:
        dotted = mod.dotted(fn)
        if dotted and _is_lib_root(mod, dotted):
            return node.args[0] if node.args else None
        # method form: the receiver is the operand
        return fn.value
    if isinstance(fn, ast.Name):
        resolved = mod.aliases.get(fn.id, fn.id)
        if resolved.split(".")[-1] in REDUCTION_NAMES and _is_lib_root(
            mod, resolved
        ):
            return node.args[0] if node.args else None
    return None


def has_valid_slice(expr: ast.AST, names: set[str]) -> bool:
    """A `Slice` subscript over one of `names` inside `expr`
    (`x[:n]`, `adv[:, :E]`, `np.asarray(out)[:n]`)."""
    for node in ast.walk(expr):
        if not isinstance(node, ast.Subscript):
            continue
        if not _contains_slice(node.slice):
            continue
        if bare_names(node.value) & names:
            return True
    return False


def _contains_slice(node: ast.AST) -> bool:
    if isinstance(node, ast.Slice):
        return True
    if isinstance(node, ast.Tuple):
        return any(_contains_slice(e) for e in node.elts)
    return False


def has_mask_guard(
    mod: ModuleInfo, expr: ast.AST, masks: set[str]
) -> bool:
    """Whether `expr` applies a validity mask to what it reduces: a
    multiply whose other side is a mask binding/maskish name, or a
    `where(mask, ...)` select."""

    def maskish(e: ast.AST) -> bool:
        return any(n in masks or is_maskish(n) for n in bare_names(e))

    for node in ast.walk(expr):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            if maskish(node.left) or maskish(node.right):
                return True
        if isinstance(node, ast.Call):
            dotted = mod.dotted(node.func)
            if dotted and dotted.split(".")[-1] == "where" and node.args:
                if maskish(node.args[0]):
                    return True
    return False


# ---------------------------------------------------------------------------
# Per-scope flow model
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PadBinding:
    """One name currently carrying a padded array."""

    name: str
    producer: str  # "pad_to_bucket" | "_pad_lanes" | "pad"
    mask: Optional[str]  # mask bound alongside (None = discarded)
    lineno: int  # producer site


@dataclasses.dataclass
class ScopeFlow:
    """Statement-ordered padding facts for one scope."""

    scope: ast.AST
    stmts: list  # ordered ast.stmt list (nested blocks inlined)
    env_before: dict  # id(stmt) -> {name: PadBinding}
    masks: set  # mask names bound in this scope
    sliced: set  # names observed under a Slice subscript anywhere


def iter_scopes(mod: ModuleInfo) -> Iterable[ast.AST]:
    """Top-level functions plus methods of top-level classes, then the
    module itself — the same units dtype_model iterates."""
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub
    yield mod.tree


def _scope_stmts(mod: ModuleInfo, scope: ast.AST) -> list:
    """All statements belonging to `scope`, in source order. Function
    scopes include their nested defs' bodies (the closure IS the scope's
    dataflow — serving's `xla_once` pattern); the module scope owns only
    what no top-level def/method claims."""
    if isinstance(scope, ast.Module):
        claimed: set[int] = set()
        for fn in iter_scopes(mod):
            if isinstance(fn, ast.Module):
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.stmt):
                    claimed.add(id(node))
        stmts = [
            n
            for n in ast.walk(scope)
            if isinstance(n, ast.stmt) and id(n) not in claimed
        ]
    else:
        stmts = [
            n
            for n in ast.walk(scope)
            if isinstance(n, ast.stmt) and n is not scope
        ]
    stmts.sort(key=lambda n: (n.lineno, n.col_offset))
    return stmts


def _assign_parts(stmt: ast.stmt):
    """(targets, value) for the binding statements the flow threads."""
    if isinstance(stmt, ast.Assign):
        return stmt.targets, stmt.value
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return [stmt.target], stmt.value
    if isinstance(stmt, ast.AugAssign):
        return [stmt.target], stmt.value
    return None, None


def _unwrap_preserving(mod: ModuleInfo, expr: ast.AST) -> ast.AST:
    """Peel shape-preserving wrapper calls: `np.asarray(x)` -> `x`."""
    while isinstance(expr, ast.Call) and is_preserving_call(mod, expr):
        if len(expr.args) >= 1:
            expr = expr.args[0]
        else:
            break
    return expr


def _is_slice_of(mod: ModuleInfo, expr: ast.AST, names: set[str]) -> bool:
    """Whether `expr` IS (possibly wrapped) a Slice subscript of one of
    `names` — the slice-back that clears a padded binding."""
    expr = _unwrap_preserving(mod, expr)
    if isinstance(expr, ast.Subscript) and _contains_slice(expr.slice):
        return bool(bare_names(expr.value) & names)
    return False


def build_scope_flow(mod: ModuleInfo, scope: ast.AST) -> ScopeFlow:
    stmts = _scope_stmts(mod, scope)
    env: dict[str, PadBinding] = {}
    masks: set[str] = set()
    sliced: set[str] = set()
    env_before: dict[int, dict[str, PadBinding]] = {}

    # One up-front pass for slice-back evidence: consumers often slice
    # AFTER the seam the passes audit (`out = program(p, padded)` then
    # `return np.asarray(out)[:n]`), so this set is scope-global.
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Subscript) and _contains_slice(
                node.slice
            ):
                sliced |= bare_names(node.value)

    for stmt in stmts:
        env_before[id(stmt)] = dict(env)
        targets, value = _assign_parts(stmt)
        if targets is None:
            # for-loop / with-as targets rebind names opaquely
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                for n in target_names(stmt.target):
                    env.pop(n, None)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    if item.optional_vars is not None:
                        for n in target_names(item.optional_vars):
                            env.pop(n, None)
            continue
        names = [n for t in targets for n in target_names(t)]
        inner = _unwrap_preserving(mod, value)

        if isinstance(inner, ast.Call):
            kind = producer_kind(mod, inner)
        else:
            kind = None

        if kind == "pad_to_bucket":
            # `padded, mask = pad_to_bucket(...)`: first name padded,
            # second is its mask ("_" = discarded).
            tgt = targets[0]
            if isinstance(tgt, (ast.Tuple, ast.List)) and len(tgt.elts) == 2:
                pn = target_names(tgt.elts[0])
                mn = target_names(tgt.elts[1])
                mask = mn[0] if mn and mn[0] != "_" else None
                if mask:
                    masks.add(mask)
                for n in pn:
                    env[n] = PadBinding(n, kind, mask, stmt.lineno)
            else:
                for n in names:
                    env[n] = PadBinding(n, kind, None, stmt.lineno)
            continue
        if kind == "_pad_lanes":
            # every unpacked element is lane-padded; the discipline is
            # the downstream `[:, :E]` slice, not a mask.
            for n in names:
                if n != "_":
                    env[n] = PadBinding(n, kind, None, stmt.lineno)
            continue
        if kind == "pad":
            # raw jnp/np.pad — unless a mask multiply is applied in the
            # same expression (the mixture obs contract), the binding is
            # undisciplined padded data.
            if has_mask_guard(mod, value, masks):
                for n in names:
                    env.pop(n, None)
            else:
                for n in names:
                    env[n] = PadBinding(n, kind, None, stmt.lineno)
            continue

        padded_names = set(env)
        if padded_names and _is_slice_of(mod, value, padded_names):
            # slice-back: the target holds valid lanes only
            for n in names:
                env.pop(n, None)
            continue
        # propagation: alias or preserving wrapper of a padded name
        src = inner if isinstance(inner, ast.Name) else None
        if src is not None and src.id in env and len(names) == 1:
            env[names[0]] = dataclasses.replace(env[src.id], name=names[0])
            continue
        # any other rebind clears the padded fact (conservative)
        for n in names:
            env.pop(n, None)

    return ScopeFlow(
        scope=scope, stmts=stmts, env_before=env_before, masks=masks,
        sliced=sliced,
    )


# ---------------------------------------------------------------------------
# Per-module model (single-entry cache, the numerics _SHARED pattern)
# ---------------------------------------------------------------------------


_SHARED: dict = {}


def module_flows(mod: ModuleInfo) -> list[ScopeFlow]:
    """[ScopeFlow] for every scope in `mod`, cached per module so the
    three shapes passes build the model once."""
    key = id(mod)
    entry = _SHARED.get("entry")
    if entry is not None and entry[0] == key:
        return entry[1]
    flows = [build_scope_flow(mod, scope) for scope in iter_scopes(mod)]
    _SHARED["entry"] = (key, flows)
    return flows


def scope_name(scope: ast.AST) -> str:
    if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return scope.name
    return "<module>"


def is_producer_scope(scope: ast.AST) -> bool:
    """The producer defs themselves (pad_to_bucket, _pad_lanes, _pad):
    their bodies construct the pad on purpose."""
    return scope_name(scope) in PRODUCER_DEF_NAMES
