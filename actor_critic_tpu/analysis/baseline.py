"""jaxlint baseline: accepted findings with reasons, matched by
line-number-free fingerprint.

The baseline records findings the team has LOOKED AT and decided to
keep — every entry carries a `reason` string a reviewer can audit, the
same contract as `compile_cache.EXEMPT`. Tier-1 fails on findings that
are not in the baseline (`--error-on-new`, the default gate), so new
hazards surface immediately while accepted ones stay visible in
`--show-baselined` output instead of rotting as ignored noise.

Matching is by `Finding.fingerprint()` — check + path + enclosing
top-level function + stripped line text — so entries survive edits
elsewhere in the file. When the flagged LINE itself changes, the entry
goes stale (reported, never silently dropped) and the finding resurfaces
as new: a changed line deserves a fresh look, not a stale pardon.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

from actor_critic_tpu.analysis.core import AnalysisError, Finding

DEFAULT_BASENAME = "jaxlint_baseline.json"
_PLACEHOLDER_REASON = (
    "NEEDS-REASON: accepted by --write-baseline; replace with why this "
    "finding is deliberate"
)


def entry_fingerprint(entry: dict) -> str:
    return (
        f"{entry.get('check', '')}:{entry.get('path', '')}:"
        f"{entry.get('context', '')}:{entry.get('line_text', '')}"
    )


def load_baseline(path: str) -> list[dict]:
    """Baseline entries; [] when the file does not exist. A present but
    unreadable/malformed file is an AnalysisError (exit 2) — a corrupt
    baseline silently reading as empty would fail tier-1 with dozens of
    'new' findings and no hint why."""
    if not os.path.exists(path):
        return []
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise AnalysisError(f"baseline {path}: {e}") from e
    if not isinstance(data, dict) or not isinstance(
        data.get("entries"), list
    ):
        raise AnalysisError(
            f"baseline {path}: expected {{'version': 1, 'entries': [...]}}"
        )
    return list(data["entries"])


def save_baseline(path: str, entries: Iterable[dict]) -> None:
    entries = sorted(entries, key=entry_fingerprint)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2)
        f.write("\n")


def apply_baseline(
    findings: list[Finding], entries: list[dict]
) -> tuple[list[Finding], list[tuple[Finding, dict]], list[dict]]:
    """(new_findings, baselined (finding, entry) pairs, stale entries).
    One entry covers every finding sharing its fingerprint."""
    by_fp = {entry_fingerprint(e): e for e in entries}
    new: list[Finding] = []
    matched: list[tuple[Finding, dict]] = []
    used: set[str] = set()
    for f in findings:
        entry = by_fp.get(f.fingerprint())
        if entry is None:
            new.append(f)
        else:
            matched.append((f, entry))
            used.add(f.fingerprint())
    stale = [e for fp, e in by_fp.items() if fp not in used]
    return new, matched, stale


def regenerate(
    findings: list[Finding], old_entries: list[dict]
) -> list[dict]:
    """Baseline entries for the current findings, PRESERVING the reason
    of any entry whose fingerprint still matches; genuinely new entries
    get a loud placeholder reason that a reviewer must replace."""
    old_by_fp = {entry_fingerprint(e): e for e in old_entries}
    out: dict[str, dict] = {}
    for f in findings:
        fp = f.fingerprint()
        if fp in out:
            continue
        old = old_by_fp.get(fp)
        out[fp] = {
            "check": f.check,
            "path": f.path,
            "context": f.context,
            "line_text": f.line_text,
            "reason": old["reason"] if old else _PLACEHOLDER_REASON,
        }
    return list(out.values())


def default_baseline_path(repo_root: str) -> str:
    return os.path.join(repo_root, DEFAULT_BASENAME)


def find_reason(entries: list[dict], finding: Finding) -> Optional[str]:
    fp = finding.fingerprint()
    for e in entries:
        if entry_fingerprint(e) == fp:
            return e.get("reason")
    return None
