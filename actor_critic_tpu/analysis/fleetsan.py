"""fleetsan: deterministic multi-process chaos sanitizer for the
mailbox/gossip/gateway stack (ISSUE 12 runtime half).

racesan (ISSUE 7) made THREAD interleavings seeded and replayable;
this module lifts the same scheduler contract to PROCESS granularity.
A seeded `ChaosScheduler` steps a fleet of simulated hosts — each one
driving the REAL protocol objects: `write_params`/`read_params` file
transport, `FileMailboxWriter.poll_once` (the production consume
logic, thread never started), `ParamMailbox`, `gossip_peer`,
`mix_params`, and the serving `PolicyStore.swap` path — one atomic
action at a time, interleaving publishes at their crash points and
injecting faults from a seeded menu:

- **SIGKILL mid-publish** — the victim writes its tmp file and dies
  before the rename (the exact window `os.replace` protects);
- **restart-and-rejoin** — a dead rank comes back, resumes its version
  clock from its own published file, and must diffuse through the ring
  again within a bounded number of rounds (`time-to-recover`, measured
  per schedule in rounds — the process-level injector below measures
  it in seconds);
- **torn/truncated mailbox files** — a victim's published snapshot is
  truncated to a seeded byte count (fs loss / non-atomic writer):
  consumers must tolerate (read -> None, retry next poll) and the next
  publish must repair;
- **reordered delivery** — a stale complete snapshot is re-placed over
  a newer one (a delayed NFS write): per-peer version clocks must
  refuse to regress;
- **duplicate snapshots** — the same version re-delivered: latest-wins
  must hand it to the learner at most once.

Every parse of every mailbox file is checked at every interleave
point: payloads encode `(rank, version)` into a uniform fill
(`_encode`), so a torn-but-parsing file, a cross-rank tempfile
collision (rank A's path carrying rank B's payload), and a version
regression are all detected AT THE READ, deterministically, not by an
unlucky preemption. Reverted-snippet modes reproduce the bug classes:
`writer="direct"` (no tmp+rename — caught on EVERY schedule: the
checker reads the half-written file at the interleave point),
`writer="shared_tmp"` (a tmp name shared across ranks — the collision
interleaving is found within a few seeds and replays bit-identically),
and `poller="naive"` (consume without per-peer clocks — the reorder
injector regresses the gateway's resident policy on every schedule).

A given seed replays bit-identically (`report["trace"]` records the
scheduling decisions); `quick_profile` is the fixed-seed sweep
`scripts/tier1.sh` runs between racesan and pytest, and
`run_process_chaos` is the REAL-process injector (spawn a gossip
fleet, SIGKILL a rank mid-run, restart it, measure wall-clock
time-to-recover) that `multihost_scaling`'s fault-injection bench
block reuses as its driver.
"""

from __future__ import annotations

import io
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Iterable, Optional

import numpy as np

_SHAPE = (2, 2)


class FleetSanError(RuntimeError):
    """A detected protocol violation, or a schedule that failed to
    recover within its liveness bound."""


def _encode(rank: int, version: int) -> float:
    """The uniform fill value of rank's version-v snapshot: payloads
    are a FUNCTION of (rank, version), so any parse can be verified
    without side-channel state — a foreign payload (tempfile
    collision) or torn-but-parsing tree mismatches immediately."""
    return float(rank * 1000 + version)


def _payload(rank: int, version: int) -> dict:
    return {"w": np.full(_SHAPE, _encode(rank, version), np.float32)}


def _npz_bytes(version: int, payload: dict) -> bytes:
    buf = io.BytesIO()
    np.savez(
        buf,
        **{f"leaf{i}": v for i, v in enumerate(payload.values())},
        version=np.asarray(int(version), np.int64),
    )
    return buf.getvalue()


# ---------------------------------------------------------------------------
# simulated hosts (real protocol objects, scripted learner)
# ---------------------------------------------------------------------------


class _SimHost:
    """One rank of the simulated fleet: a scripted learner loop over
    the REAL mailbox objects. `actions()` yields one atomic action at a
    time; the scheduler interleaves hosts between actions — publishes
    are split at their crash/interleave points."""

    def __init__(
        self,
        rank: int,
        world: int,
        mailbox_dir: str,
        writer: str = "atomic",
        on_publish: Optional[Callable[["_SimHost"], None]] = None,
    ):
        self.on_publish = on_publish or (lambda host: None)
        from actor_critic_tpu.parallel.multihost import (
            FileMailboxWriter,
            ParamMailbox,
            read_params,
        )

        self.rank = int(rank)
        self.world = int(world)
        self.dir = mailbox_dir
        self.writer = writer
        self.template = _payload(rank, 0)
        self.mailbox = ParamMailbox()
        # Thread NEVER started: the scheduler drives poll_once directly
        # (racesan's contract lifted to the process level — the real
        # consume logic, deterministic schedule).
        self.poller = FileMailboxWriter(
            mailbox_dir, rank, world, template=self.template,
            mailbox=self.mailbox, stop=threading.Event(),
        )
        # Restart-and-rejoin: resume the version clock from our own
        # published file, exactly as a restarted process would.
        own = read_params(mailbox_dir, rank, self.template)
        self.version = own[0] if own is not None else 0
        self.taken: dict[int, int] = {}  # per-peer consume clock
        self.takes = 0
        self.deposits = 0

    # -- publish variants (each yields at its interleave points) ----------

    def _publish_atomic(self):
        from actor_critic_tpu.parallel.multihost import write_params

        write_params(self.dir, self.rank, self.version, _payload(
            self.rank, self.version
        ))
        self.on_publish(self)
        yield "publish"

    def _publish_direct(self):
        """REVERTED writer: the consumed path written in place, torn at
        the interleave point — the checker reads the half-written file
        there on every schedule."""
        from actor_critic_tpu.parallel.multihost import params_file

        path = params_file(self.dir, self.rank)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        data = _npz_bytes(self.version, _payload(self.rank, self.version))
        # jaxlint: disable=mailbox-protocol (deliberate: this IS the
        # reverted non-atomic writer under test — the checker must
        # catch it at the interleave point)
        with open(path, "wb") as f:
            f.write(data[: len(data) // 2])
        yield "publish:half"
        with open(path, "ab") as f:
            f.write(data[len(data) // 2:])
        self.on_publish(self)
        yield "publish:done"

    def _publish_shared_tmp(self):
        """REVERTED writer: one tmp name for the whole mailbox — two
        ranks publishing concurrently interleave into it and rename
        each other's payloads into place."""
        from actor_critic_tpu.parallel.multihost import params_file

        path = params_file(self.dir, self.rank)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = os.path.join(self.dir, "pending.tmp")
        data = _npz_bytes(self.version, _payload(self.rank, self.version))
        # jaxlint: disable=mailbox-protocol (deliberate: the shared —
        # non-process-unique — tmp name IS the collision under test)
        with open(tmp, "wb") as f:
            f.write(data)
        yield "publish:tmp"
        try:
            os.replace(tmp, path)
        except FileNotFoundError:
            # The OTHER manifestation of the collision: a concurrent
            # rank renamed our shared tmp into ITS path — our payload
            # is now published under a foreign rank.
            raise FleetSanError(
                f"rank {self.rank}: shared tmp vanished mid-publish — "
                "a concurrent rank renamed it into its own path "
                "(tempfile collision: tmp names must be "
                "process-unique)"
            )
        self.on_publish(self)
        yield "publish:done"

    def publish_kill(self):
        """SIGKILL mid-publish: the tmp lands, the rename never runs —
        the stale tmp must be harmless and the published file must
        still hold the previous complete snapshot."""
        from actor_critic_tpu.parallel.multihost import params_file

        path = params_file(self.dir, self.rank)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        # jaxlint: disable=mailbox-protocol (deliberate: SIGKILL lands
        # here in the simulation — no fsync/rename ever runs)
        with open(tmp, "wb") as f:
            f.write(_npz_bytes(self.version, _payload(
                self.rank, self.version
            )))

    # -- one learner round -------------------------------------------------

    def actions(self, verify: Callable[["_SimHost", tuple], None]):
        self.version += 1
        if self.writer == "atomic":
            yield from self._publish_atomic()
        elif self.writer == "direct":
            yield from self._publish_direct()
        elif self.writer == "shared_tmp":
            yield from self._publish_shared_tmp()
        else:
            raise ValueError(f"unknown writer mode {self.writer!r}")
        self.poller.set_round(self.version)
        if self.poller.poll_once():
            self.deposits += 1
        yield "poll"
        out = self.mailbox.take()
        if out is not None:
            verify(self, out)
            self.takes += 1
        yield "take"


# ---------------------------------------------------------------------------
# the chaos scheduler
# ---------------------------------------------------------------------------


class ChaosScheduler:
    """Seeded process-granularity scheduler: per global round every
    live host contributes its action generator, the controller
    contributes fault actions, and the RNG picks who advances next —
    so a given seed replays its interleaving (and its faults)
    bit-identically. No wall clock anywhere: time-to-recover is
    measured in rounds."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.trace: list[tuple] = []

    def interleave(self, gens: dict[str, Any], round_: int) -> None:
        """Advance the named generators one action at a time in seeded
        order until all are exhausted. Operates on `gens` IN PLACE so a
        fault action can remove another participant mid-round (a
        SIGKILLed host must stop at its current action, not keep
        executing to generator exhaustion as a zombie)."""
        while gens:
            name = sorted(gens)[self.rng.randrange(len(gens))]
            try:
                tag = next(gens[name])
                self.trace.append((round_, name, tag))
            except StopIteration:
                gens.pop(name, None)


# ---------------------------------------------------------------------------
# fleet exerciser
# ---------------------------------------------------------------------------


def exercise_fleet(
    seed: int,
    world: int = 3,
    rounds: int = 10,
    writer: str = "atomic",
    faults: bool = True,
    recover_bound: int = 12,
) -> dict:
    """One seeded chaos schedule over a simulated gossip fleet of
    `world` ranks sharing a real on-disk mailbox. Detection raises
    FleetSanError; a clean schedule returns the report (trace included
    — bit-identical per seed)."""
    from actor_critic_tpu.parallel.multihost import params_file, read_params

    sched = ChaosScheduler(seed)
    report: dict = {
        "seed": seed, "world": world, "rounds": rounds, "writer": writer,
        "takes": 0, "deposits": 0, "faults": [], "kills": 0,
        "recover_rounds": [], "violations": 0,
    }
    with tempfile.TemporaryDirectory(prefix="fleetsan_") as mailbox:
        template = _payload(0, 0)
        # rank -> newest version fully published (set by the publish
        # actions themselves, so a file torn AFTER a publish can never
        # be re-marked complete by round bookkeeping); rank -> True
        # while an injected fault legitimately tore the file.
        complete: dict[int, int] = {}
        injector_torn: dict[int, bool] = {r: False for r in range(world)}

        def on_publish(host: "_SimHost") -> None:
            complete[host.rank] = host.version
            injector_torn[host.rank] = False

        hosts: dict[int, Optional[_SimHost]] = {
            r: _SimHost(r, world, mailbox, writer=writer,
                        on_publish=on_publish)
            for r in range(world)
        }
        # pending recoveries: rank -> (restart_round, version_at_kill)
        pending: dict[int, tuple[int, int]] = {}
        dead: dict[int, tuple[int, int]] = {}  # rank -> (revive_round, v)
        saved: dict[int, bytes] = {}  # reorder/duplicate ammunition

        def verify(host: _SimHost, out: tuple) -> None:
            version, peer, params = out
            if version <= host.taken.get(peer, -1):
                report["violations"] += 1
                raise FleetSanError(
                    f"seed {seed}: host {host.rank} took version "
                    f"{version} from peer {peer} after "
                    f"{host.taken[peer]} — per-peer monotonicity "
                    "violated (reordered/duplicate delivery reached "
                    "the learner)"
                )
            host.taken[peer] = version
            w = np.asarray(params["w"])
            uniform = bool(np.all(w == w.flat[0]))
            if not uniform or float(w.flat[0]) != _encode(peer, version):
                report["violations"] += 1
                raise FleetSanError(
                    f"seed {seed}: host {host.rank} took a corrupt "
                    f"snapshot claiming (peer={peer}, v={version}): "
                    f"uniform={uniform}, value={float(w.flat[0])!r}, "
                    f"expected {_encode(peer, version)} — torn write "
                    "or cross-rank tempfile collision"
                )
            # The mixing math itself must preserve uniformity.
            from actor_critic_tpu.parallel.multihost import mix_params

            mixed = mix_params(_payload(host.rank, host.version), params, 0.5)
            mw = np.asarray(mixed["w"])
            if not bool(np.all(mw == mw.flat[0])):
                report["violations"] += 1
                raise FleetSanError(
                    f"seed {seed}: mix_params broke uniformity"
                )
            # Recovery bookkeeping: fresh post-restart news from a
            # previously killed rank closes its pending window.
            if peer in pending and version > pending[peer][1]:
                restart_round, _ = pending.pop(peer)
                report["recover_rounds"].append(
                    max(round_now[0] - restart_round, 0)
                )

        def check_files() -> Iterable[str]:
            """The torn-publish detector, run at EVERY interleave
            point: a rank that completed a publish must always present
            a parseable snapshot whose payload matches its claimed
            (rank, version) — unless an injected fault (not the writer
            under test) tore the file."""
            for r in range(world):
                if injector_torn[r]:
                    continue
                if r not in complete:
                    continue
                out = read_params(mailbox, r, template)
                if out is None:
                    report["violations"] += 1
                    raise FleetSanError(
                        f"seed {seed}: rank {r}'s mailbox file is "
                        f"unreadable although version {complete[r]} "
                        "was fully published — the writer tore the "
                        "consumed path (atomic write→fsync→rename "
                        "violated)"
                    )
                version, tree = out
                w = np.asarray(tree["w"])
                if not bool(np.all(w == w.flat[0])) or float(
                    w.flat[0]
                ) != _encode(r, version):
                    report["violations"] += 1
                    raise FleetSanError(
                        f"seed {seed}: rank {r}'s mailbox file claims "
                        f"version {version} but carries value "
                        f"{float(w.flat[0])!r} (expected "
                        f"{_encode(r, version)}) — a foreign rank's "
                        "payload was renamed into place (tempfile "
                        "collision)"
                    )
            return ()

        def checked(gen):
            """Wrap a host generator so the file checker runs at every
            one of its interleave points."""
            for tag in gen:
                check_files()
                yield tag

        def chaos_actions(round_: int):
            """The controller's seeded faults for this round."""
            if not faults:
                return
            live = [r for r, h in hosts.items() if h is not None]
            roll = sched.rng.random()
            if roll < 0.25 and len(live) > 1 and not dead and not pending:
                victim = live[sched.rng.randrange(len(live))]
                host = hosts[victim]
                host.version += 1
                host.publish_kill()  # tmp written, rename never runs
                hosts[victim] = None
                # SIGKILL is immediate: the victim's action generator
                # must not keep running this round as a zombie (it
                # could complete a FULL publish after "dying", masking
                # stale-tmp/stuck-peer regressions and zeroing the
                # measured recovery window).
                round_gens.pop(f"host{victim}", None)
                dead[victim] = (
                    round_ + 1 + sched.rng.randrange(2),
                    host.version - 1,
                )
                report["kills"] += 1
                report["faults"].append((round_, "kill", victim))
                yield f"kill:host{victim}"
            elif roll < 0.45 and complete:
                ranks = sorted(complete)
                victim = ranks[sched.rng.randrange(len(ranks))]
                path = params_file(mailbox, victim)
                try:
                    size = os.path.getsize(path)
                    with open(path, "r+b") as f:
                        f.truncate(sched.rng.randrange(1, max(size, 2)))
                    injector_torn[victim] = True
                    complete.pop(victim, None)
                    report["faults"].append((round_, "torn", victim))
                    yield f"torn:host{victim}"
                except OSError:
                    pass
            elif roll < 0.60 and complete:
                # Save a complete snapshot now; re-placing it later is
                # the reorder/duplicate delivery fault.
                ranks = sorted(complete)
                victim = ranks[sched.rng.randrange(len(ranks))]
                path = params_file(mailbox, victim)
                try:
                    with open(path, "rb") as f:
                        saved[victim] = f.read()
                    report["faults"].append((round_, "save", victim))
                    yield f"save:host{victim}"
                except OSError:
                    pass
            elif roll < 0.80 and saved:
                ranks = sorted(saved)
                victim = ranks[sched.rng.randrange(len(ranks))]
                path = params_file(mailbox, victim)
                tmp = f"{path}.tmp.reorder"
                # jaxlint: disable=mailbox-protocol (deliberate fault
                # injection: re-placing a stale complete snapshot IS
                # the reordered-delivery fault, not a publish)
                with open(tmp, "wb") as f:
                    f.write(saved[victim])
                # jaxlint: disable=mailbox-protocol (injector rename)
                os.replace(tmp, path)
                report["faults"].append((round_, "replay", victim))
                yield f"replay:host{victim}"

        round_now = [0]
        # This round's interleave set — shared with chaos_actions so a
        # kill can remove the victim's generator mid-round.
        round_gens: dict[str, Any] = {}
        total_rounds = rounds + recover_bound
        for round_ in range(total_rounds):
            round_now[0] = round_
            # Revive due ranks: restart-and-rejoin. The version clock
            # resumes from the host's own published file, floored at
            # its pre-kill value (the consumed-block clock rides the
            # local checkpoint in production — a torn/stale mailbox
            # file must not rewind it below what peers already saw, or
            # their per-peer clocks mute the rejoiner).
            for r, (due, v_at_kill) in sorted(dead.items()):
                if round_ >= due:
                    h = _SimHost(r, world, mailbox, writer=writer,
                                 on_publish=on_publish)
                    h.version = max(h.version, v_at_kill)
                    hosts[r] = h
                    pending[r] = (round_, v_at_kill)
                    dead.pop(r)
                    sched.trace.append((round_, "chaos", f"restart:host{r}"))
            if round_ >= rounds and not pending and not dead:
                break  # drain phase over: every kill recovered
            round_gens.clear()
            round_gens.update({
                f"host{r}": checked(h.actions(verify))
                for r, h in hosts.items()
                if h is not None
            })
            if round_ < rounds:
                round_gens["chaos"] = chaos_actions(round_)
            sched.interleave(round_gens, round_)
        if pending:
            raise FleetSanError(
                f"seed {seed}: rank(s) {sorted(pending)} restarted but "
                f"their fresh snapshots never reached a peer within "
                f"{recover_bound} drain rounds — ring diffusion broken "
                "(time-to-recover unbounded)"
            )
        report["takes"] = sum(
            h.takes for h in hosts.values() if h is not None
        )
        report["deposits"] = sum(
            h.deposits for h in hosts.values() if h is not None
        )
    report["trace"] = list(sched.trace)
    report["trace_len"] = len(sched.trace)
    return report


# ---------------------------------------------------------------------------
# gateway swap exerciser
# ---------------------------------------------------------------------------


class _StubSwapEngine:
    """jax-free engine stand-in for the gateway swap path (racesan's
    _StubServingEngine shape): prepare_params snapshots + freezes, so
    the store's install contract matches production."""

    max_rows = 8

    def prepare_params(self, params: Any) -> Any:
        out = {k: np.array(v) for k, v in params.items()}
        for v in out.values():
            v.flags.writeable = False
        return out

    def act(self, params: Any, obs: np.ndarray) -> np.ndarray:
        return np.asarray(obs)[:, 0] * params["w"].flat[0]


def exercise_gateway(
    seed: int,
    versions: int = 8,
    poller: str = "guarded",
) -> dict:
    """One seeded chaos schedule over the serve-while-training swap
    path: a publisher rank publishes `(version, params)` snapshots
    through the real file mailbox; a gateway-side consumer polls them
    (through the REAL `FileMailboxWriter.poll_once` + `ParamMailbox`
    when `poller="guarded"`) and installs fresh versions into a real
    `PolicyStore` via `swap`. The controller injects torn files and
    reordered/duplicate deliveries. Invariants: the resident policy's
    version never regresses, and its params always match the version
    they claim. `poller="naive"` is the REVERTED consumer — raw
    read-then-swap with no per-peer clock — which the reorder injector
    regresses on every schedule."""
    from actor_critic_tpu.parallel.multihost import (
        FileMailboxWriter,
        ParamMailbox,
        params_file,
        read_params,
        write_params,
    )
    from actor_critic_tpu.serving.policy_store import PolicyStore

    if poller not in ("guarded", "naive"):
        raise ValueError(f"unknown poller mode {poller!r}")
    sched = ChaosScheduler(seed)
    report = {
        "seed": seed, "poller": poller, "swaps": 0, "published": 0,
        "faults": [], "violations": 0,
    }
    with tempfile.TemporaryDirectory(prefix="fleetsan_gw_") as mailbox:
        template = _payload(0, 0)
        store = PolicyStore()
        engine = _StubSwapEngine()
        store.register("default", engine, _payload(0, 0))
        pmailbox = ParamMailbox()
        consumer = FileMailboxWriter(
            mailbox, rank=1, world=2, template=template,
            mailbox=pmailbox, stop=threading.Event(),
        )
        saved: dict[int, bytes] = {}
        last_version = [0]

        def install(version: int, params: Any) -> None:
            handle = store.swap("default", params, version=version)
            if handle.version < last_version[0]:
                report["violations"] += 1
                raise FleetSanError(
                    f"seed {seed}: gateway swapped BACK from version "
                    f"{last_version[0]} to {handle.version} — a "
                    "reordered/duplicate snapshot regressed the "
                    "resident policy (per-peer version clock missing "
                    "at the consume site)"
                )
            w = np.asarray(handle.params["w"])
            if not bool(np.all(w == w.flat[0])) or float(
                w.flat[0]
            ) != _encode(0, version):
                report["violations"] += 1
                raise FleetSanError(
                    f"seed {seed}: resident policy at version "
                    f"{version} carries value {float(w.flat[0])!r}, "
                    f"expected {_encode(0, version)} — torn install"
                )
            last_version[0] = handle.version
            report["swaps"] += 1

        def poll_step() -> None:
            """ONE consumer poll — runs after EVERY scheduler action,
            so publishes, faults, and installs genuinely interleave."""
            if poller == "guarded":
                if consumer.poll_once():
                    out = pmailbox.take()
                    if out is not None:
                        version, _peer, params = out
                        install(version, params)
            else:
                # REVERTED consumer: no per-peer clock, no mailbox
                # dedupe — whatever the file says right now is swapped
                # in; a replayed stale snapshot regresses the store.
                out = read_params(mailbox, 0, template)
                if out is not None:
                    install(*out)

        def publisher():
            for v in range(1, versions + 1):
                write_params(mailbox, 0, v, _payload(0, v))
                report["published"] = v
                yield f"publish:{v}"

        def chaos():
            """Scripted fault sequence with seeded placement: save an
            early complete snapshot, optionally tear the live file
            mid-stream, then REPLAY the stale save after the final
            publish — so every schedule exercises the regression path
            (the guarded consumer refuses it; the naive one swaps it
            in and is caught)."""
            for _ in range(versions * 4):
                if report["published"] >= 2:
                    break
                yield "idle"
            path = params_file(mailbox, 0)
            with open(path, "rb") as f:
                saved[0] = f.read()
            report["faults"].append("save")
            yield "save"
            if sched.rng.random() < 0.5:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(sched.rng.randrange(1, max(size, 2)))
                report["faults"].append("torn")
                yield "torn"
            for _ in range(versions * 4):
                if report["published"] >= versions:
                    break
                yield "idle"
            tmp = f"{path}.tmp.reorder"
            # jaxlint: disable=mailbox-protocol (deliberate fault
            # injection: the reordered-delivery fault, not a publish)
            with open(tmp, "wb") as f:
                f.write(saved[0])
            # jaxlint: disable=mailbox-protocol (injector rename)
            os.replace(tmp, path)
            report["faults"].append("replay")
            yield "replay"
            # Duplicate delivery: the same stale bytes once more.
            # jaxlint: disable=mailbox-protocol (duplicate injector)
            with open(tmp, "wb") as f:
                f.write(saved[0])
            # jaxlint: disable=mailbox-protocol (injector rename)
            os.replace(tmp, path)
            report["faults"].append("duplicate")
            yield "duplicate"

        gens: dict[str, Any] = {"publisher": publisher(), "chaos": chaos()}
        live = dict(gens)
        while live:
            name = sorted(live)[sched.rng.randrange(len(live))]
            try:
                tag = next(live[name])
                sched.trace.append((0, name, tag))
            except StopIteration:
                del live[name]
                continue
            poll_step()
            sched.trace.append((0, "gateway", "poll"))
        # Drain: a torn/stale final file is repaired by re-publishing
        # the newest version (what the next training step would do),
        # bounded so a broken consumer cannot spin forever.
        for _ in range(versions * 20):
            if last_version[0] >= versions:
                break
            write_params(mailbox, 0, versions, _payload(0, versions))
            poll_step()
        if last_version[0] < versions:
            raise FleetSanError(
                f"seed {seed}: gateway never converged to version "
                f"{versions} (stuck at {last_version[0]}) — the swap "
                "path lost the newest snapshot"
            )
    report["trace"] = list(sched.trace)
    report["trace_len"] = len(sched.trace)
    return report


# ---------------------------------------------------------------------------
# replica-fleet swap exerciser (ISSUE 17 leg b)
# ---------------------------------------------------------------------------


def exercise_replica_fleet(
    seed: int,
    versions: int = 8,
    replicas: int = 3,
) -> dict:
    """One seeded replica-kill-mid-swap schedule over the horizontal
    scale-out propagation path: a trainer rank publishes `(version,
    params)` snapshots through the real file mailbox while N replica
    gateways consume them through the REAL
    `serving.fleet_proxy.MailboxPolicySyncer.poll_once` (sync thread
    never started — the scheduler owns every interleave point) into N
    real `PolicyStore`s. The controller injects the gateway exerciser's
    fault menu (torn live file, stale replay, duplicate delivery) PLUS
    a replica SIGKILL at a seeded point in the swap pipeline — possibly
    between a publish and the victim's consume of it — and a later
    restart with a cold store and a reset version clock (exactly what a
    respawned serve.py process has).

    Invariants, checked after EVERY scheduler action on the polled
    replica: (1) the resident policy's params always self-verify
    against the version they claim (`_encode` — a torn policy is never
    served), (2) each replica's resident version never regresses within
    one process lifetime (the syncer's per-publisher clock; a restart
    legitimately resets it), (3) every replica — including the
    killed-and-restarted one — converges to the final published
    version within the bounded drain."""
    from actor_critic_tpu.parallel.multihost import params_file, write_params
    from actor_critic_tpu.serving.fleet_proxy import MailboxPolicySyncer
    from actor_critic_tpu.serving.policy_store import PolicyStore

    sched = ChaosScheduler(seed)
    report = {
        "seed": seed, "replicas": replicas, "swaps": 0, "published": 0,
        "kills": 0, "faults": [], "violations": 0,
    }
    with tempfile.TemporaryDirectory(prefix="fleetsan_rf_") as mailbox:
        template = _payload(0, 0)

        def make_replica() -> dict:
            store = PolicyStore()
            store.register("default", _StubSwapEngine(), _payload(0, 0))
            return {
                "store": store,
                "syncer": MailboxPolicySyncer(
                    store, "default", mailbox, rank=0, template=template
                ),
                # Newest resident version THIS process lifetime: the
                # monotonicity witness (reset by a legitimate restart).
                "last": 0,
            }

        fleet = {i: make_replica() for i in range(replicas)}

        def check(idx: int, rep: dict) -> None:
            handle = rep["store"].get("default")
            if handle.version < rep["last"]:
                report["violations"] += 1
                raise FleetSanError(
                    f"seed {seed}: replica {idx} regressed from version "
                    f"{rep['last']} to {handle.version} — a reordered/"
                    "duplicate snapshot got past the syncer's version "
                    "clock"
                )
            w = np.asarray(handle.params["w"])
            if handle.version > 0 and (
                not bool(np.all(w == w.flat[0]))
                or float(w.flat[0]) != _encode(0, handle.version)
            ):
                report["violations"] += 1
                raise FleetSanError(
                    f"seed {seed}: replica {idx} serves version "
                    f"{handle.version} with value {float(w.flat[0])!r}, "
                    f"expected {_encode(0, handle.version)} — a torn "
                    "policy reached the store"
                )
            rep["last"] = handle.version

        def poll(idx: int) -> None:
            rep = fleet.get(idx)
            if rep is None:  # killed — nothing to poll
                return
            if rep["syncer"].poll_once():
                report["swaps"] += 1
            check(idx, rep)

        def publisher():
            for v in range(1, versions + 1):
                write_params(mailbox, 0, v, _payload(0, v))
                report["published"] = v
                yield f"publish:{v}"

        saved: dict[int, bytes] = {}

        def chaos():
            # Same seeded menu as exercise_gateway: save early, maybe
            # tear the live file, replay + duplicate the stale save
            # after the final publish.
            for _ in range(versions * 4):
                if report["published"] >= 2:
                    break
                yield "idle"
            path = params_file(mailbox, 0)
            with open(path, "rb") as f:
                saved[0] = f.read()
            report["faults"].append("save")
            yield "save"
            if sched.rng.random() < 0.5:
                size = os.path.getsize(path)
                with open(path, "r+b") as f:
                    f.truncate(sched.rng.randrange(1, max(size, 2)))
                report["faults"].append("torn")
                yield "torn"
            for _ in range(versions * 4):
                if report["published"] >= versions:
                    break
                yield "idle"
            tmp = f"{path}.tmp.reorder"
            # jaxlint: disable=mailbox-protocol (reorder injector)
            with open(tmp, "wb") as f:
                f.write(saved[0])
            # jaxlint: disable=mailbox-protocol (injector rename)
            os.replace(tmp, path)
            report["faults"].append("replay")
            yield "replay"

        def killer():
            """SIGKILL one replica at a seeded point mid-schedule and
            restart it a seeded number of rounds later: the restart is
            a COLD process (fresh store at version 0, syncer clock
            reset), so if the mailbox currently holds the chaos
            injector's stale replay, the rejoiner legitimately swaps it
            in — and must still converge to the newest version at
            drain."""
            victim = sched.rng.randrange(replicas)
            for _ in range(sched.rng.randrange(1, versions * 2)):
                yield "idle"
            fleet.pop(victim, None)
            report["kills"] += 1
            report["faults"].append(f"kill:{victim}")
            yield f"kill:{victim}"
            for _ in range(sched.rng.randrange(1, versions)):
                yield "idle"
            fleet[victim] = make_replica()
            report["faults"].append(f"restart:{victim}")
            yield f"restart:{victim}"

        gens: dict[str, Any] = {
            "publisher": publisher(), "chaos": chaos(), "killer": killer(),
        }
        live = dict(gens)
        while live:
            name = sorted(live)[sched.rng.randrange(len(live))]
            try:
                tag = next(live[name])
                sched.trace.append((0, name, tag))
            except StopIteration:
                del live[name]
                continue
            # ONE seeded replica polls per action — replica consumes
            # genuinely interleave with publishes, faults, and kills.
            idx = sched.rng.randrange(replicas)
            poll(idx)
            sched.trace.append((0, f"replica{idx}", "poll"))
        # Drain: repair the (possibly stale/torn) final file the way
        # the next training publish would, and poll every survivor
        # until the whole fleet converges — bounded.
        for _ in range(versions * 20):
            if all(r["last"] >= versions for r in fleet.values()):
                break
            write_params(mailbox, 0, versions, _payload(0, versions))
            for idx in sorted(fleet):
                poll(idx)
        laggards = {
            i: r["last"] for i, r in fleet.items() if r["last"] < versions
        }
        if laggards:
            raise FleetSanError(
                f"seed {seed}: replicas never converged to version "
                f"{versions}: {laggards} — the propagation path lost "
                "the newest snapshot"
            )
    report["trace"] = list(sched.trace)
    report["trace_len"] = len(sched.trace)
    return report


# ---------------------------------------------------------------------------
# sweep + the tier-1 quick profile
# ---------------------------------------------------------------------------


def exercise_sweep(
    seeds: Iterable[int], scenario: Callable[[int], dict]
) -> dict:
    reports = [scenario(seed) for seed in seeds]
    return {
        "schedules": len(reports),
        "takes": sum(r.get("takes", 0) for r in reports),
        "deposits": sum(r.get("deposits", 0) for r in reports),
        "swaps": sum(r.get("swaps", 0) for r in reports),
        "kills": sum(r.get("kills", 0) for r in reports),
        "faults": sum(len(r.get("faults", ())) for r in reports),
        "recover_rounds_max": max(
            (x for r in reports for x in r.get("recover_rounds", ())),
            default=0,
        ),
        "violations": sum(r.get("violations", 0) for r in reports),
    }


def quick_profile(schedules: int = 40, seed0: int = 0) -> dict:
    """The tier-1 fast profile: `schedules` seeded chaos schedules
    split between the gossip-fleet unit (atomic writer, full fault
    menu, recovery bounded) and the gateway swap unit (guarded poller)
    — every schedule must sweep clean. ~40 schedules run in a few
    seconds on one CPU core (tiny trees, tmpfs-speed files)."""
    half = max(schedules // 2, 1)
    fleet = exercise_sweep(
        range(seed0, seed0 + half),
        lambda s: exercise_fleet(s, writer="atomic", faults=True),
    )
    gateway = exercise_sweep(
        range(seed0, seed0 + (schedules - half)),
        lambda s: exercise_gateway(s, poller="guarded"),
    )
    return {
        "schedules": fleet["schedules"] + gateway["schedules"],
        "fleet": fleet,
        "gateway": gateway,
        "violations": fleet["violations"] + gateway["violations"],
    }


# ---------------------------------------------------------------------------
# the real-process injector (the bench driver)
# ---------------------------------------------------------------------------


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    try:
        from __graft_entry__ import disarm_axon

        disarm_axon(env)
    except ImportError:
        pass
    return env


def run_process_chaos(
    world: int = 2,
    duration_s: float = 8.0,
    kill_after_s: float = 3.0,
    restart_after_s: float = 0.5,
    kill_rank: int = 1,
    timeout_s: float = 180.0,
    seed: int = 0,
    telemetry_dir: Optional[str] = None,
) -> dict:
    """SIGKILL a REAL gossip worker mid-run and measure wall-clock
    time-to-recover: spawn `world` gossip-mode processes of
    `scripts/launch_multihost.py` against a shared mailbox, SIGKILL
    rank `kill_rank` at `kill_after_s` (mid-publish in expectation —
    gossip publishes every consumed block), restart it after
    `restart_after_s`, and time until its FIRST post-restart snapshot
    lands in the mailbox (the ring has fresh news from the killed rank
    again). This is the `multihost_scaling` bench's fault-injection
    driver; the simulated exercisers above cover the same protocol
    deterministically in tier-1."""
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    launcher = os.path.join(repo, "scripts", "launch_multihost.py")
    env = _worker_env()

    def spawn(rank: int, dur: float, mailbox: str):
        cmd = [
            sys.executable, launcher, "--worker",
            "--rank", str(rank), "--processes", str(world),
            "--mode", "gossip", "--mailbox-dir", mailbox,
            "--duration-s", str(dur), "--iterations", "0",
            "--rollout-steps", "8", "--num-envs", "2", "--actors", "1",
            "--sleep-s", "0.004", "--epochs", "1", "--minibatches", "1",
            "--seed", str(seed),
        ]
        if telemetry_dir:
            cmd += ["--telemetry-dir", telemetry_dir]
        return subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env,
        )

    mailbox = tempfile.mkdtemp(prefix="fleetsan_chaos_")
    record: dict = {
        "world": world, "killed_rank": kill_rank,
        "kill_after_s": kill_after_s, "restart_after_s": restart_after_s,
        "duration_s": duration_s,
    }
    procs = {}
    try:
        t0 = time.monotonic()
        for r in range(world):
            procs[r] = spawn(r, duration_s, mailbox)
        if telemetry_dir:
            # Start the kill clock only once the victim is actually
            # recording: worker startup (jax import + session
            # construction) can dwarf kill_after_s on a cold cache, and
            # SIGKILLing before the flight ring exists would prove
            # nothing about crash recording.
            from actor_critic_tpu.telemetry import flight

            ring = os.path.join(
                telemetry_dir, f"host{kill_rank}", flight.RING_FILENAME
            )
            ready_deadline = time.monotonic() + timeout_s
            while time.monotonic() < ready_deadline:
                if flight.harvest(ring):
                    break
                if procs[kill_rank].poll() is not None:
                    break  # died at startup; surfaced by harvest below
                time.sleep(0.05)
        time.sleep(kill_after_s)
        victim = procs[kill_rank]
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30)
        t_kill = time.monotonic()
        record["killed_at_s"] = round(t_kill - t0, 3)
        if telemetry_dir:
            # Post-mortem flight harvest (ISSUE 16) — BEFORE the
            # restart, which recreates (zeroes) the same rank's ring.
            # The victim got no chance to flush anything: every record
            # here survived SIGKILL purely via the mmap'd ring.
            from actor_critic_tpu.telemetry import flight

            ring = os.path.join(
                telemetry_dir, f"host{kill_rank}", flight.RING_FILENAME
            )
            flight_records = flight.harvest(ring)
            if not flight_records:
                raise FleetSanError(
                    f"SIGKILL'd rank {kill_rank} left no harvestable "
                    f"flight-ring records at {ring} — the crash "
                    "recorder lost the victim's final seconds"
                )
            record["flight_dump"] = flight.write_dump(
                os.path.join(
                    telemetry_dir, f"host{kill_rank}",
                    "flight_dump_sigkill_harvest.json",
                ),
                flight_records,
                reason="sigkill_harvest",
                meta={"rank": kill_rank, "seed": seed, "world": world},
            )
            record["flight_records"] = len(flight_records)
        time.sleep(restart_after_s)
        from actor_critic_tpu.parallel.multihost import params_file

        path = params_file(mailbox, kill_rank)
        try:
            mtime_before = os.stat(path).st_mtime
        except OSError:
            mtime_before = 0.0
        remaining = max(duration_s - (time.monotonic() - t0), 2.0)
        procs[kill_rank] = spawn(kill_rank, remaining, mailbox)
        t_rec = None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                if os.stat(path).st_mtime > mtime_before:
                    t_rec = time.monotonic()
                    break
            except OSError:
                pass
            time.sleep(0.05)
        if t_rec is None:
            raise FleetSanError(
                f"killed rank {kill_rank} never republished within "
                f"{timeout_s:.0f}s of restart — rejoin broken"
            )
        record["time_to_recover_s"] = round(t_rec - t_kill, 3)
        summaries = {}
        for r, p in sorted(procs.items()):
            try:
                out, err = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
                raise FleetSanError(
                    f"worker {r} hung past {timeout_s:.0f}s after the "
                    "chaos window"
                )
            line = next(
                (
                    ln
                    for ln in reversed(out.strip().splitlines())
                    if ln.startswith("{")
                ),
                None,
            )
            if p.returncode != 0 or line is None:
                tail = (err or out).strip().splitlines()[-8:]
                raise FleetSanError(
                    f"worker {r} failed rc={p.returncode}: "
                    + "\n".join(tail)
                )
            import json as _json

            summaries[str(r)] = _json.loads(line)
        record["survivor_gossip_mixes"] = sum(
            s.get("gossip_mixes", 0)
            for r, s in summaries.items()
            if int(r) != kill_rank
        )
        record["restarted_consumed_blocks"] = summaries[
            str(kill_rank)
        ].get("consumed_blocks", 0)
        record["ok"] = (
            record["survivor_gossip_mixes"] > 0
            and record["restarted_consumed_blocks"] > 0
        )
        return record
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        shutil.rmtree(mailbox, ignore_errors=True)
