"""prng-reuse: one PRNG key consumed by two `jax.random.*` calls.

Reusing a key gives correlated (identical) randomness — in this
codebase that means identical exploration noise across calls, identical
minibatch permutations across epochs, and silently broken statistics
rather than a crash. The contract is one consumption per key binding:
`split`/`fold_in` and rebind before the next use.

Mechanics (per top-level function, statement-ordered by line number):

- A name becomes a *tracked key* when it is ever bound from a producer
  (`jax.random.key/PRNGKey/split/fold_in/clone/wrap_key_data`),
  including tuple unpacking (`key, sub = jax.random.split(key)`).
- Every `jax.random.*` call consumes the tracked keys it takes as bare
  `Name` arguments (subscripted uses like `keys[i]` are per-element and
  not tracked). `split` consumes too — that is the idiom's point.
  `fold_in` consumes NOTHING: deriving per-step keys from one parent
  (`fold_in(key, i)`) deliberately keeps the parent live.
- Consumptions in mutually exclusive `if` arms are alternatives (at
  most one executes) and never pair into a reuse finding.
- Any assignment to the name resets its consumption count (same-line
  `key, sub = split(key)` consumes the old binding first, then
  rebinds).
- A second consumption of one binding flags. A consumption inside a
  `for`/`while` whose binding was made OUTSIDE the loop (and never
  rebound inside it) flags once per loop — every iteration reuses the
  same key.
"""

from __future__ import annotations

import ast
from typing import Optional

from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
    target_names as _target_names,
)

CHECK = "prng-reuse"

_PRODUCERS = {
    "key", "PRNGKey", "split", "fold_in", "clone", "wrap_key_data",
}


def _is_jax_random_call(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """The jax.random function name ("split", "normal", ...) or None."""
    dotted = mod.dotted(call.func)
    if dotted and dotted.startswith("jax.random."):
        return dotted.rsplit(".", 1)[-1]
    return None


def _scopes(mod: ModuleInfo):
    """Every function def (nested included) plus the module top level.
    Each def is its own scope: two sibling closures both naming their
    key `key` (the repo's idiom) are unrelated bindings, and analyzing
    them flat would count one's consumption against the other's."""
    yield mod.tree
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _walk_scope(scope: ast.AST, mod: ModuleInfo):
    """Walk `scope` WITHOUT descending into nested defs (their own
    scopes). Lambdas stay in the enclosing scope — they cannot rebind
    names, so their consumptions belong to the scope they close over."""
    if isinstance(
        scope, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef)
    ):
        # the scope's own statements, minus child defs (their own scopes)
        stack = [
            n
            for n in scope.body
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
    else:
        stack = [scope]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


@register_check(
    CHECK,
    "a PRNG key consumed by two jax.random.* calls without an "
    "intervening split/fold_in (correlated randomness)",
)
def check_prng_reuse(mod: ModuleInfo) -> list[Finding]:
    findings: list[Finding] = []
    for scope in _scopes(mod):
        # ---- gather events -------------------------------------------
        binds: list[tuple[int, str, bool]] = []  # (line, name, from_producer)
        consumes: list[tuple[int, str, ast.Call]] = []
        loops: list[ast.AST] = []
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # parameters bind at the def line (so a key param consumed
            # inside a loop without rebinding reads as loop-carried)
            a = scope.args
            binds.extend(
                (scope.lineno, p.arg, False)
                for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]
            )
        for node in _walk_scope(scope, mod):
            if isinstance(node, (ast.For, ast.While)):
                loops.append(node)
                if isinstance(node, ast.For):
                    for n in _target_names(node.target):
                        binds.append((node.lineno, n, False))
            if isinstance(node, ast.Assign):
                from_prod = (
                    isinstance(node.value, ast.Call)
                    and _is_jax_random_call(mod, node.value) in _PRODUCERS
                )
                for tgt in node.targets:
                    for n in _target_names(tgt):
                        binds.append((node.lineno, n, from_prod))
            elif (
                isinstance(node, (ast.AnnAssign, ast.AugAssign))
                and node.value is not None
            ):
                from_prod = (
                    isinstance(node.value, ast.Call)
                    and _is_jax_random_call(mod, node.value) in _PRODUCERS
                )
                for n in _target_names(node.target):
                    binds.append((node.lineno, n, from_prod))
            if isinstance(node, ast.Call):
                fn = _is_jax_random_call(mod, node)
                # fold_in never counts as consumption: deriving
                # per-step keys from one parent (`fold_in(key, i)`) is
                # the sanctioned loop idiom, and the parent deliberately
                # stays live across derivations.
                if fn is not None and fn != "fold_in":
                    for arg in [
                        *node.args,
                        *[kw.value for kw in node.keywords],
                    ]:
                        if isinstance(arg, ast.Name):
                            consumes.append((node.lineno, arg.id, node))

        tracked = {n for _, n, p in binds if p}
        # A def's key-like parameters are keys by convention even though
        # no producer call binds them in this scope (`def reset(key):`).
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = scope.args
            for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
                if "key" in p.arg.lower() or "rng" in p.arg.lower():
                    tracked.add(p.arg)
        if not tracked:
            continue

        # ---- linear replay -------------------------------------------
        # Same-line order: consumptions read the OLD binding, then the
        # assignment rebinds (the `key, sub = split(key)` idiom).
        events = sorted(
            [(ln, 0, n, node) for ln, n, node in consumes if n in tracked]
            + [(ln, 1, n, None) for ln, n, _p in binds if n in tracked],
            key=lambda e: (e[0], e[1]),
        )
        since_bind: dict[str, list[ast.Call]] = {}
        for ln, kind, name, node in events:
            if kind == 1:
                since_bind[name] = []
                continue
            prev = since_bind.setdefault(name, [])
            # Consumptions in mutually exclusive `if` arms are
            # alternatives, not reuse — only pair path-compatible uses.
            clash = [
                p for p in prev if not mod.exclusive_branches(p, node)
            ]
            if clash:
                findings.append(
                    Finding(
                        CHECK, mod.relpath, ln, node.col_offset,
                        f"PRNG key `{name}` is consumed again (previous "
                        f"consumption at line {clash[-1].lineno}) without "
                        "an intervening split — reused keys repeat their "
                        "randomness; split and rebind first",
                        mod.enclosing_function(node),
                    )
                )
            prev.append(node)

        # ---- loop-carried reuse --------------------------------------
        flagged: set[tuple[str, int]] = set()
        for ln, name, node in consumes:
            if name not in tracked:
                continue
            loop = _innermost_loop(loops, ln)
            if loop is None or (name, loop.lineno) in flagged:
                continue
            bound_before = max(
                (bl for bl, n, _p in binds if n == name and bl < loop.lineno),
                default=None,
            )
            bound_inside = any(
                n == name and loop.lineno <= bl <= (loop.end_lineno or bl)
                for bl, n, _p in binds
            )
            if bound_before is not None and not bound_inside:
                flagged.add((name, loop.lineno))
                findings.append(
                    Finding(
                        CHECK, mod.relpath, ln, node.col_offset,
                        f"PRNG key `{name}` is consumed inside a loop but "
                        "bound outside it — every iteration reuses the "
                        "same key; split per iteration (`key, sub = "
                        "jax.random.split(key)`)",
                        mod.enclosing_function(node),
                    )
                )
    return findings


def _innermost_loop(loops: list[ast.AST], lineno: int) -> Optional[ast.AST]:
    best = None
    for lp in loops:
        end = lp.end_lineno or lp.lineno
        if lp.lineno < lineno <= end:
            if best is None or lp.lineno > best.lineno:
                best = lp
    return best
