"""Whole-repo dtype-flow model for the numerics passes (ISSUE 14).

PRs 5/7/12 each gave the analysis layer a dimension (JAX correctness →
threads → processes) by pairing a derived MODEL with the passes that
consult it; this module is the numerics dimension's model, the sibling
of `thread_model.py`/`process_model.py`. From `ast` alone it derives:

- **Per-function dtype environments** — name → dtype token, propagated
  in statement order through `astype`, dtype-carrying constructors
  (`jnp.zeros(shape, jnp.bfloat16)`, `dtype=` kwargs), elementwise
  dtype-preserving calls (`clip`/`where`/`maximum`/reductions), binops
  under a jax-promotion lattice, and python float literals (WEAK-typed:
  `0.5 * bf16_x` stays bf16 — weak scalars must never read as f32
  mixing).
- **Guard facts** — whether an expression is provably guarded against
  the non-finite producing classes: positive-floored for `log`/division
  (eps-add, `clip(lo>0)`, `maximum(·, eps)`, `_EPS`-named floors, the
  `where`-select idiom), non-negative for `sqrt` (`var`/`square`/`x*x`
  producers), bounded for `exp`/`arctanh` (`clip`/`minimum` caps),
  resolved one assignment hop through the local environment.
- **Sink inventory** — `json.dumps(..., allow_nan=False)` call sites
  and the known-fragile commit-point defs (`write_params`, `publish`,
  `swap`, `save` taking a params/state tree) together with whether a
  finiteness gate (`check_finite`/`isfinite`/`nonfinite*`) is present
  in the body — the facts the `sink-guard` pass consumes.

**eval_shape grounding** (`grounded_return_dtypes`): the one
non-AST-only fact source, mirroring the warmup-registry pass's
exception: when the scanned tree is the live repo, the model probes the
REAL codec/return-math functions with canonical abstract arg trees
through `jax.eval_shape` (trace-only — no compile, milliseconds) and
records their measured output dtypes, e.g. that `quantize.decode`
returns float32 for every codec kind EXCEPT `raw` (which passes the
storage dtype through). The precision pass uses this to report codec
dtype forks as measured facts rather than AST guesses; import/probe
failures degrade to AST-only silently (the lint must run anywhere).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from actor_critic_tpu.analysis.core import ModuleInfo, target_names

# ---------------------------------------------------------------------------
# dtype lattice
# ---------------------------------------------------------------------------

FLOAT_TOKENS = ("f64", "f32", "bf16", "f16")
LOW_PRECISION = ("bf16", "f16")

_TOKEN_BY_NAME = {
    "float64": "f64", "double": "f64",
    "float32": "f32", "single": "f32",
    "bfloat16": "bf16",
    "float16": "f16", "half": "f16",
    "int8": "i8", "int16": "i16", "int32": "i32", "int64": "i64",
    "uint8": "u8", "uint32": "u32",
    "bool_": "bool", "bool": "bool",
}

_ARRAY_MODULES = ("numpy", "jax.numpy", "ml_dtypes")

# Constructors whose result dtype is the dtype argument (positional
# index of the dtype arg, when it has one).
_CONSTRUCTORS = {
    "zeros": 1, "ones": 1, "empty": 1, "arange": None,
    "array": 1, "asarray": 1, "full": 2,
    "zeros_like": None, "ones_like": None, "full_like": None,
}

# Elementwise/reshaping calls that preserve their first array operand's
# dtype (reductions included — `jnp.sum` of a bf16 operand ACCUMULATES
# in bf16 unless dtype= overrides, which is exactly the hazard the
# precision pass flags).
_PRESERVING = {
    "clip", "abs", "maximum", "minimum", "round", "nan_to_num",
    "negative", "transpose", "reshape", "squeeze", "expand_dims",
    "sum", "mean", "max", "min", "prod", "var", "std",
    "dot", "matmul", "tanh_like",
}

# where(cond, x, y): result promotes x and y.
_SELECTS = {"where"}


def promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Jax-style promotion over the token lattice (weak python scalars
    preserve the array operand's dtype). None = not statically known."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    weak = {"pyfloat", "pyint"}
    if a in weak and b in weak:
        return "pyfloat" if "pyfloat" in (a, b) else "pyint"
    if a in weak:
        return b if (a == "pyint" or b in FLOAT_TOKENS) else None
    if b in weak:
        return a if (b == "pyint" or a in FLOAT_TOKENS) else None
    if a in FLOAT_TOKENS and b in FLOAT_TOKENS:
        if "f64" in (a, b):
            return "f64"
        if "f32" in (a, b):
            return "f32"
        # bf16 × f16 promotes to f32 (neither is a superset)
        return "f32" if {a, b} == {"bf16", "f16"} else a
    if a in FLOAT_TOKENS:
        return a
    if b in FLOAT_TOKENS:
        return b
    return None  # int×int details are irrelevant to these passes


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def dtype_token(mod: ModuleInfo, expr: Optional[ast.AST]) -> Optional[str]:
    """The dtype a dtype-position expression denotes: `jnp.bfloat16`,
    `np.float32`, `"bfloat16"`, a module constant bound to either, or a
    `jnp.dtype(...)` wrapper. None when not statically resolvable (a
    parameter, an IfExp — the repo's `bf16_compute` selection is
    DELIBERATELY unresolvable: both arms are possible)."""
    if expr is None:
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return _TOKEN_BY_NAME.get(expr.value)
    if isinstance(expr, ast.Attribute):
        dotted = mod.dotted(expr)
        if dotted is None:
            return None
        head, _, tail = dotted.rpartition(".")
        if head in _ARRAY_MODULES or head.endswith(".numpy"):
            return _TOKEN_BY_NAME.get(tail)
        return None
    if isinstance(expr, ast.Call) and _call_name(expr) == "dtype":
        return dtype_token(mod, expr.args[0]) if expr.args else None
    if isinstance(expr, ast.Name):
        binding = _module_const(mod, expr.id)
        if binding is not None:
            return dtype_token(mod, binding)
    return None


def _module_const(mod: ModuleInfo, name: str) -> Optional[ast.AST]:
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign):
            if any(name in target_names(t) for t in stmt.targets):
                return stmt.value
    return None


def _dtype_arg(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """The resolved dtype= (kwarg or positional) of a constructor/
    reduction call, None when absent/unresolvable."""
    for kw in call.keywords:
        if kw.arg == "dtype":
            return dtype_token(mod, kw.value)
    name = _call_name(call)
    pos = _CONSTRUCTORS.get(name or "")
    if pos is not None and len(call.args) > pos:
        return dtype_token(mod, call.args[pos])
    return None


def _is_array_api(mod: ModuleInfo, call: ast.Call) -> bool:
    """Whether the call targets the numpy / jax.numpy namespace (either
    directly or via the jnp/np aliases)."""
    if not isinstance(call.func, ast.Attribute):
        return False
    base = call.func.value
    dotted = mod.dotted(base) if not isinstance(base, ast.Call) else None
    return dotted in ("numpy", "jax.numpy") or (
        dotted is not None and dotted.endswith(".numpy")
    )


class DtypeEnv:
    """One scope's name → dtype-token environment, in statement order
    to fixpoint (2 passes cover the chains these passes flag)."""

    def __init__(self, mod: ModuleInfo, scope: ast.AST):
        self.mod = mod
        self.scope = scope
        self.names: dict[str, Optional[str]] = {}
        for _ in range(2):
            for node in ast.walk(scope):
                if isinstance(node, ast.Assign):
                    token = self.expr_dtype(node.value)
                    if token is None:
                        continue
                    for tgt in node.targets:
                        for name in target_names(tgt):
                            self.names[name] = token
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    token = self.expr_dtype(node.value)
                    if token is not None and isinstance(node.target, ast.Name):
                        self.names[node.target.id] = token

    def expr_dtype(self, expr: ast.AST) -> Optional[str]:
        """Statically-known dtype token of an expression, else None."""
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, bool):
                return "bool"
            if isinstance(expr.value, float):
                return "pyfloat"
            if isinstance(expr.value, int):
                return "pyint"
            return None
        if isinstance(expr, ast.Name):
            return self.names.get(expr.id)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_dtype(expr.operand)
        if isinstance(expr, ast.BinOp):
            return promote(
                self.expr_dtype(expr.left), self.expr_dtype(expr.right)
            )
        if isinstance(expr, ast.Call):
            name = _call_name(expr)
            if name == "astype":
                return dtype_token(
                    self.mod, expr.args[0] if expr.args else None
                )
            explicit = _dtype_arg(self.mod, expr)
            if explicit is not None:
                return explicit
            if name in _CONSTRUCTORS and _is_array_api(self.mod, expr):
                return None  # dtype defaulted/unresolved: unknown
            if name in _SELECTS and len(expr.args) >= 3:
                return promote(
                    self.expr_dtype(expr.args[1]),
                    self.expr_dtype(expr.args[2]),
                )
            if name in _PRESERVING and expr.args:
                return self.expr_dtype(expr.args[0])
            return None
        return None


# ---------------------------------------------------------------------------
# guard facts (nonfinite-hazard's provability layer)
# ---------------------------------------------------------------------------

def _is_eps_name(node: ast.AST) -> bool:
    name = None
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    return name is not None and "eps" in name.lower()


def _small_positive_const(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ) and not isinstance(node.value, bool):
        return 0 < float(node.value) <= 1.0
    return False


def _positive_const(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant) and isinstance(
        node.value, (int, float)
    ) and not isinstance(node.value, bool):
        return float(node.value) > 0
    return False


# Calls whose result is non-negative by construction (sqrt guards).
_NONNEG_CALLS = {"var", "square", "abs", "softplus", "relu", "exp"}
# Calls that bound their operand (exp/arctanh guards).
_BOUNDING_CALLS = {"clip", "minimum", "maximum", "tanh", "log_softmax",
                   "log_sigmoid", "nan_to_num"}


class GuardFacts:
    """Per-scope guard analysis: which expressions are provably safe
    operands for log / sqrt / exp / arctanh / division."""

    def __init__(self, mod: ModuleInfo, scope: ast.AST):
        self.mod = mod
        self.scope = scope

    def _latest_binding(
        self, name: str, before: int
    ) -> Optional[ast.AST]:
        latest, latest_line = None, -1
        for node in ast.walk(self.scope):
            if not isinstance(node, ast.Assign):
                continue
            if node.lineno >= before:
                continue
            if any(name in target_names(t) for t in node.targets):
                if node.lineno > latest_line:
                    latest, latest_line = node.value, node.lineno
        return latest

    def _resolve(self, expr: ast.AST, depth: int) -> ast.AST:
        if depth > 0 and isinstance(expr, ast.Name):
            bound = self._latest_binding(expr.id, expr.lineno)
            if bound is not None:
                return bound
        return expr

    def positive_floored(self, expr: ast.AST, depth: int = 2) -> bool:
        """Provably bounded away from 0/negative: `x + eps`,
        `clip(x, lo>0, ...)`, `maximum(x, eps)`, an eps-name, a positive
        constant, or a name assigned from one of those."""
        expr = self._resolve(expr, depth)
        if _positive_const(expr) or _is_eps_name(expr):
            return True
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            return any(
                _is_eps_name(s) or _small_positive_const(s)
                for s in (expr.left, expr.right)
            ) or any(
                self.positive_floored(s, depth - 1)
                for s in (expr.left, expr.right)
            )
        if isinstance(expr, ast.Call):
            name = _call_name(expr)
            if name == "clip" and len(expr.args) >= 2:
                lo = expr.args[1]
                return _positive_const(lo) or _is_eps_name(lo)
            if name in ("sum", "mean"):
                # log-sum-exp: a sum/mean OVER exp terms is positive
                # (the max-shifted spelling guarantees a 1.0 term).
                operand = expr.args[0] if expr.args else (
                    expr.func.value
                    if isinstance(expr.func, ast.Attribute)
                    else None
                )
                if isinstance(operand, ast.Call) and _call_name(
                    operand
                ) == "exp":
                    return True
            if name in ("maximum", "max") and len(expr.args) >= 2:
                return any(
                    _positive_const(a) or _is_eps_name(a)
                    or self.positive_floored(a, depth - 1)
                    for a in expr.args[:2]
                )
            if name in ("softplus", "exp"):
                return True  # strictly positive by construction
            if name in ("asarray", "array", "float32", "float64",
                        "abs", "nan_to_num"):
                # wrappers: look through to the payload (abs alone does
                # NOT floor away from zero — only counts when its
                # operand does, e.g. abs(x) + eps handled above)
                if name == "abs":
                    return False
                return bool(expr.args) and self.positive_floored(
                    expr.args[0], depth - 1
                )
        if isinstance(expr, ast.IfExp):
            return self.positive_floored(
                expr.body, depth - 1
            ) and self.positive_floored(expr.orelse, depth - 1)
        return False

    def nonnegative(self, expr: ast.AST, depth: int = 2) -> bool:
        """Provably >= 0 (the sqrt contract): var/square/abs/x**2/x*x
        producers, non-negative constants, or floored expressions."""
        expr = self._resolve(expr, depth)
        if self.positive_floored(expr, 0):
            return True
        if isinstance(expr, ast.Constant) and isinstance(
            expr.value, (int, float)
        ) and not isinstance(expr.value, bool):
            return float(expr.value) >= 0
        if isinstance(expr, ast.BinOp):
            if isinstance(expr.op, ast.Pow) and isinstance(
                expr.right, ast.Constant
            ) and expr.right.value == 2:
                return True
            if isinstance(expr.op, ast.Mult) and ast.dump(
                expr.left
            ) == ast.dump(expr.right):
                return True
            if isinstance(expr.op, ast.Add):
                return all(
                    self.nonnegative(s, depth - 1)
                    for s in (expr.left, expr.right)
                )
        if isinstance(expr, ast.Call):
            name = _call_name(expr)
            if name in _NONNEG_CALLS:
                return True
            if name in ("maximum",) and expr.args:
                return any(
                    self.nonnegative(a, depth - 1) for a in expr.args[:2]
                )
            if name == "clip" and len(expr.args) >= 2:
                lo = expr.args[1]
                return isinstance(lo, ast.Constant) and isinstance(
                    lo.value, (int, float)
                ) and float(lo.value) >= 0
        return False

    def bounded(self, expr: ast.AST, depth: int = 2) -> bool:
        """Provably range-bounded (the exp/arctanh contract): wrapped in
        clip/minimum (or tanh for arctanh's inverse), or a name assigned
        from one."""
        expr = self._resolve(expr, depth)
        if isinstance(expr, ast.Call):
            name = _call_name(expr)
            if name in _BOUNDING_CALLS:
                return True
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Sub):
            # The max-shift idiom: `x - x.max(...)` is bounded above by
            # zero — the stable softmax/logsumexp prelude.
            right = expr.right
            if isinstance(right, ast.Call) and _call_name(right) in (
                "max", "amax"
            ):
                return True
        if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.Mult, ast.Add, ast.Sub)
        ):
            # A scaled/shifted bounded value stays bounded when the
            # non-constant side is.
            sides = [expr.left, expr.right]
            consts = [s for s in sides if isinstance(s, ast.Constant)]
            if consts:
                other = sides[0] if sides[1] in consts else sides[1]
                return self.bounded(other, depth - 1)
        return False

    def log_diff(self, expr: ast.AST, depth: int = 2) -> bool:
        """Whether the expression is an (unbounded) log-ratio: a
        subtraction either side of which is `log`-named — the PPO /
        V-trace importance-ratio shape whose exp overflows when the
        behavior and target policies drift apart."""
        expr = self._resolve(expr, depth)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Sub):
            def mentions_log(side: ast.AST) -> bool:
                for sub in ast.walk(side):
                    name = None
                    if isinstance(sub, ast.Name):
                        name = sub.id
                    elif isinstance(sub, ast.Attribute):
                        name = sub.attr
                    if name is None:
                        continue
                    low = name.lower()
                    # "logits" are NOT log-probs: exp(x - x.max()) of a
                    # logit shift is the stable-softmax idiom.
                    if "log" in low and "logit" not in low:
                        return True
                return False

            return mentions_log(expr.left) or mentions_log(expr.right)
        return False


# ---------------------------------------------------------------------------
# sink inventory (sink-guard's facts)
# ---------------------------------------------------------------------------

SINK_DEF_NAMES = {"write_params", "publish", "swap", "save"}
_TREE_PARAM_NAMES = {"params", "state", "snapshot", "tree", "payload"}
_GATE_FRAGMENTS = ("check_finite", "isfinite", "nonfinite",
                   "assert_finite")


def dumps_sites(mod: ModuleInfo) -> list[ast.Call]:
    """`json.dumps(..., allow_nan=False)` calls — the writer shape that
    raises (and silently drops the row) on the first non-finite value."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if mod.dotted(node.func) != "json.dumps":
            continue
        for kw in node.keywords:
            if kw.arg == "allow_nan" and isinstance(
                kw.value, ast.Constant
            ) and kw.value.value is False:
                out.append(node)
    return out


def sink_defs(mod: ModuleInfo) -> list[tuple[ast.AST, bool]]:
    """(def node, has_finiteness_gate) for every module-level function /
    method named like a fragile commit point (`write_params`, `publish`,
    `swap`, `save`) that takes a params/state tree. Nested defs are
    excluded (racesan/fleetsan build scripted stand-ins inline — those
    are exercisers, not commit points)."""
    out: list[tuple[ast.AST, bool]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in SINK_DEF_NAMES:
            continue
        parent = mod.parent(node)
        if isinstance(parent, ast.ClassDef):
            if not isinstance(mod.parent(parent), ast.Module):
                continue
        elif not isinstance(parent, ast.Module):
            continue
        args = node.args
        names = {
            a.arg
            for a in args.posonlyargs + args.args + args.kwonlyargs
        }
        if not (names & _TREE_PARAM_NAMES):
            continue
        gated = any(
            isinstance(sub, ast.Call)
            and any(
                frag in (_call_name(sub) or "")
                for frag in _GATE_FRAGMENTS
            )
            for sub in ast.walk(node)
        )
        out.append((node, gated))
    return out


# ---------------------------------------------------------------------------
# the repo-wide model
# ---------------------------------------------------------------------------

class DtypeModel:
    """Derived once per lint run (the `_SHARED` idiom the concurrency /
    distributed passes use); the three numerics checks consult it."""

    def __init__(self, modules: list[ModuleInfo]):
        self._modules = modules
        self._envs: dict[int, DtypeEnv] = {}
        self._guards: dict[int, GuardFacts] = {}

    def env(self, mod: ModuleInfo, scope: ast.AST) -> DtypeEnv:
        key = id(scope)
        if key not in self._envs:
            self._envs[key] = DtypeEnv(mod, scope)
        return self._envs[key]

    def guards(self, mod: ModuleInfo, scope: ast.AST) -> GuardFacts:
        key = id(scope)
        if key not in self._guards:
            self._guards[key] = GuardFacts(mod, scope)
        return self._guards[key]


# ---------------------------------------------------------------------------
# eval_shape grounding (lazy; tolerated to fail anywhere)
# ---------------------------------------------------------------------------

_GROUNDED: Optional[dict[str, str]] = None


def _token_of(dtype) -> Optional[str]:
    return _TOKEN_BY_NAME.get(str(dtype))


def grounded_return_dtypes() -> dict[str, str]:
    """Measured output dtypes of the live codec/return-math functions,
    probed with canonical abstract arg trees through `jax.eval_shape`
    (trace-only; no compile, no device). Keys are
    '<module>.<function>[<variant>]'. Empty when jax or the live package
    is unavailable — callers must degrade to AST-only facts. Cached per
    process (one grounding per lint run)."""
    global _GROUNDED
    if _GROUNDED is not None:
        return _GROUNDED
    out: dict[str, str] = {}
    try:
        import jax
        import jax.numpy as jnp

        from actor_critic_tpu.replay import quantize

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype)

        for kind in quantize.KINDS:
            # `raw` probes with the uint8 pixel-obs storage — the case
            # that makes the decode dtype genuinely fork on the codec
            # (every non-raw kind decodes to float32; raw passes the
            # storage dtype through untouched).
            store = quantize.storage_dtype(
                kind, jnp.uint8 if kind == "raw" else jnp.float32
            )
            stats = quantize.QuantStats(
                mean=sds((), jnp.float32),
                scale=sds((), jnp.float32),
                count=sds((), jnp.int32),
            )
            try:
                dec = jax.eval_shape(
                    lambda s, q, k=kind: quantize.decode(k, s, q),
                    stats, sds((4,), store),
                )
                token = _token_of(dec.dtype)
                if token:
                    out[f"quantize.decode[{kind}]"] = token
                enc = jax.eval_shape(
                    lambda s, x, k=kind, d=store: quantize.encode(
                        k, s, x, d
                    ),
                    stats, sds((4,), jnp.float32),
                )
                token = _token_of(enc.dtype)
                if token:
                    out[f"quantize.encode[{kind}]"] = token
            except Exception:
                continue  # one probe failing must not lose the rest
        try:
            from actor_critic_tpu.ops import returns as _returns

            adv = jax.eval_shape(
                lambda r, v, d, b: _returns.gae(r, v, d, b, 0.99, 0.95),
                sds((8, 2), jnp.float32), sds((8, 2), jnp.float32),
                sds((8, 2), jnp.float32), sds((2,), jnp.float32),
            )
            leaves = jax.tree.leaves(adv)
            if leaves:
                token = _token_of(leaves[0].dtype)
                if token:
                    out["returns.gae"] = token
        except Exception:
            pass
    except Exception:
        out = {}
    _GROUNDED = out
    return out


def codec_fork_evidence(fn_name: str) -> Optional[str]:
    """When grounding is available and the named codec function's
    measured output dtypes genuinely fork across kinds, a short
    evidence string for the finding message; None otherwise."""
    grounded = grounded_return_dtypes()
    seen = {
        key.split("[", 1)[1].rstrip("]"): tok
        for key, tok in grounded.items()
        if key.startswith(f"{fn_name}[")
    }
    if len(set(seen.values())) > 1:
        pairs = ", ".join(f"{k}→{v}" for k, v in sorted(seen.items()))
        return f"measured via jax.eval_shape: {pairs}"
    return None


def iter_scopes(mod: ModuleInfo) -> Iterable[ast.AST]:
    """Top-level functions plus methods of top-level classes — the
    statement-ordered units the numerics passes analyze."""
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub
