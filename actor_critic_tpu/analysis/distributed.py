"""Distributed-protocol passes: collective-discipline,
mailbox-protocol, rank-affinity (ISSUE 12 tentpole, static half).

Each is grounded in a failure class the PR 9/10 fleet stack either hit
or is one edit away from:

- **collective-discipline** — the fleet-desync class. (a) A collective
  reducing over an axis name no mesh declares lowers wrong or not at
  all; axis names are strings, so a typo ("dq" for "dp") is invisible
  until a pod run. (b) A collective reachable inside a branch keyed on
  a PROCESS-LOCAL value (rank, wall clock, pid, queue depth) executes
  on some hosts and not others — the hosts that entered sit in the
  all-reduce forever (the exact hazard the stop-vote in
  `train_multihost` exists to avoid: the deadline check rides INTO the
  collective instead of gating it). (c) A collective inside a `try`
  whose handler swallows the error diverges the collective ORDER: the
  host that caught skips an exchange the rest of the fleet executes,
  and the fleet deadlocks one collective later.
- **mailbox-protocol** — the gossip-mailbox file discipline
  (`write_params`/`read_params`, arxiv 1906.04585's exchange made
  crash-tolerant). Producers must write→fsync→rename: a direct write
  to the consumed path is torn under SIGKILL; a rename without fsync
  can publish a zero-length file after a crash (data blocks not yet
  ordered before the metadata); a tmp name without a process-unique
  discriminator collides when two ranks share a mailbox directory.
  Consumers must tolerate torn/partial files (for `.npz` that means
  `zipfile.BadZipFile`/`EOFError`, which are NOT `OSError`s — the
  reverted PR 12 reader died on exactly this) and must track peer
  version clocks PER PEER (a global newest-seen scalar permanently
  mutes every host slower than the fastest, the PR 9 review bug).
- **rank-affinity** — shared-artifact paths written from a per-rank
  scope (a `rank` parameter, `jax.process_index()`, a
  `--distributed` flag read) must be parameterized by the process
  identity, or every host clobbers the same file: telemetry sessions,
  metrics jsonl, checkpoints. (train.py's `--distributed` telemetry
  and metrics paths were exactly this until this PR.)

All three are repo-scope: they consult the whole-repo `ProcessModel`
(`analysis/process_model.py`, the rank-granularity sibling of PR 7's
thread model). Runtime companion: `analysis/fleetsan.py` exercises the
same protocol under seeded multi-process chaos schedules.
"""

from __future__ import annotations

import ast
from typing import Optional

from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
)
from actor_critic_tpu.analysis.process_model import (
    TORN_EXC_JSON,
    TORN_EXC_NPZ,
    ProcessModel,
    rank_parameterized,
)

COLLECTIVE_DISCIPLINE = "collective-discipline"
MAILBOX_PROTOCOL = "mailbox-protocol"
RANK_AFFINITY = "rank-affinity"

# Shared-artifact sinks for rank-affinity (terminal callable names):
# each takes a directory/path its process will WRITE under.
_PATH_SINKS = {"TelemetrySession", "JsonlLogger", "Checkpointer"}

# Single-entry cache (the concurrency passes' `_SHARED` idiom): three
# registered checks, one ProcessModel derivation per lint run. The
# modules list is held strongly so the id()-keyed entry can never alias
# a collected ModuleInfo.
_SHARED: dict = {}


def _shared_model(modules: list[ModuleInfo]) -> ProcessModel:
    key = tuple(id(m) for m in modules)
    entry = _SHARED.get("entry")
    if entry is not None and entry[0] == key:
        return entry[1]
    model = ProcessModel(modules)
    _SHARED["entry"] = (key, model, list(modules))
    return model


def _branch_ancestors(mod: ModuleInfo, node: ast.AST):
    """(if/while ancestor, child-on-path) pairs between `node` and its
    nearest enclosing function def — branches OUTSIDE the def gate the
    definition, not the collective's execution."""
    child = node
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(anc, (ast.If, ast.While)):
            yield anc, child
        child = anc


def _nearest_function(mod: ModuleInfo, node: ast.AST) -> Optional[ast.AST]:
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


# ---------------------------------------------------------------------------
# collective-discipline
# ---------------------------------------------------------------------------


@register_check(
    COLLECTIVE_DISCIPLINE,
    "axis names no mesh declares; collectives gated on process-local "
    "values (rank/wall-clock/queue depth) or inside exception-swallowing "
    "try blocks — both desync the fleet into a deadlock",
    scope="repo",
)
def check_collective_discipline(
    modules: list[ModuleInfo],
) -> list[Finding]:
    model = _shared_model(modules)
    findings: list[Finding] = []
    declared = model.axes.declared
    for mod in modules:
        taint_cache: dict[int, set[str]] = {}
        for site in model.collective_sites[mod.relpath]:
            node = site.node
            # (a) axis-name consistency, prim sites with a resolvable
            # constant axis only (parameterized axes are checked where
            # a constant is bound).
            if site.kind == "prim" and site.axis_arg is not None and declared:
                resolved = model.axes.resolve(mod, site.axis_arg)
                names = (
                    (resolved,) if isinstance(resolved, str)
                    else resolved if isinstance(resolved, tuple) else ()
                )
                for name in names:
                    if name not in declared:
                        findings.append(
                            Finding(
                                COLLECTIVE_DISCIPLINE, mod.relpath,
                                node.lineno, node.col_offset,
                                f"`{site.desc}` reduces over axis "
                                f"{name!r}, but no mesh in the scanned "
                                "tree declares that axis (declared: "
                                f"{sorted(declared)}) — axis names are "
                                "bare strings, so a typo lowers to the "
                                "wrong reduction or fails only on the "
                                "pod; use the shared *_AXIS constant",
                                mod.enclosing_function(node),
                            )
                        )
            # (b) process-local gating.
            fn = _nearest_function(mod, node)
            for branch, _child in _branch_ancestors(mod, node):
                if fn is None:
                    break
                if id(fn) not in taint_cache:
                    taint_cache[id(fn)] = model.process_local_names(mod, fn)
                if model.expr_process_local(
                    mod, branch.test, taint_cache[id(fn)]
                ):
                    kw = "if" if isinstance(branch, ast.If) else "while"
                    findings.append(
                        Finding(
                            COLLECTIVE_DISCIPLINE, mod.relpath,
                            node.lineno, node.col_offset,
                            f"collective `{site.desc}` sits inside a "
                            f"`{kw}` (line {branch.lineno}) keyed on a "
                            "process-local value (rank / wall clock / "
                            "pid / queue depth) — hosts whose predicate "
                            "differs skip the exchange and the rest of "
                            "the fleet deadlocks in it; hoist the "
                            "collective out, or make the decision "
                            "fleet-uniform first (all-reduce a vote, "
                            "as train_multihost's stop path does)",
                            mod.enclosing_function(node),
                        )
                    )
                    break
            # (c) order divergence through a swallowed exception.
            if site.kind in ("prim", "derived"):
                swallowing = _swallowing_try(
                    mod, node, model.collective_sites[mod.relpath]
                )
                if swallowing is not None:
                    findings.append(
                        Finding(
                            COLLECTIVE_DISCIPLINE, mod.relpath,
                            node.lineno, node.col_offset,
                            f"collective `{site.desc}` runs inside a "
                            "`try` whose handler (line "
                            f"{swallowing.lineno}) swallows the error — "
                            "the host that catches skips this exchange "
                            "while the rest of the fleet executes it, "
                            "diverging the collective order into a "
                            "deadlock one exchange later; re-raise (a "
                            "dead host must take its whole fleet slot "
                            "down), or move the fallible work out of "
                            "the collective region",
                            mod.enclosing_function(node),
                        )
                    )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


def _swallowing_try(
    mod: ModuleInfo, node: ast.AST, sites
) -> Optional[ast.excepthandler]:
    """The first exception handler that would swallow an error raised
    at `node`: no `raise` in its body AND no collective of its own (a
    handler performing the equivalent exchange — mesh.axis_size's
    psum-fallback compat shim — keeps the fleet's collective count in
    step). Only `try` bodies between the node and its enclosing def
    count."""
    site_nodes = [s.node for s in sites]
    child = node
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        if isinstance(anc, ast.Try) and any(
            child is stmt or _in(stmt, child) for stmt in anc.body
        ):
            for handler in anc.handlers:
                if any(
                    isinstance(sub, ast.Raise)
                    for sub in ast.walk(handler)
                ):
                    continue
                if any(_in(handler, sn) for sn in site_nodes):
                    continue
                return handler
        child = anc
    return None


def _in(root: ast.AST, target: ast.AST) -> bool:
    return any(sub is target for sub in ast.walk(root))


# ---------------------------------------------------------------------------
# mailbox-protocol
# ---------------------------------------------------------------------------


@register_check(
    MAILBOX_PROTOCOL,
    "file-mailbox discipline: write→fsync→rename at producers "
    "(process-unique tmp names), torn-read tolerance and per-peer "
    "version clocks at consumers (the gossip exchange's crash contract)",
    scope="repo",
)
def check_mailbox_protocol(modules: list[ModuleInfo]) -> list[Finding]:
    model = _shared_model(modules)
    findings: list[Finding] = []
    for mod in modules:
        for site in model.producers[mod.relpath]:
            node = site.open_call
            ctx = mod.enclosing_function(node)
            if site.replace_call is not None:
                if not site.has_fsync:
                    findings.append(
                        Finding(
                            MAILBOX_PROTOCOL, mod.relpath,
                            node.lineno, node.col_offset,
                            "atomic publish without fsync: this scope "
                            "renames a written file into place (line "
                            f"{site.replace_call.lineno}) but never "
                            "fsyncs it first — after a crash the "
                            "rename can be durable while the data "
                            "blocks are not, publishing a zero-length/"
                            "partial file; `f.flush(); "
                            "os.fsync(f.fileno())` before the replace",
                            ctx,
                        )
                    )
                tmp_expr = (
                    site.replace_call.args[0]
                    if site.replace_call.args
                    else None
                )
                if tmp_expr is not None and not rank_parameterized(
                    mod, site.scope, tmp_expr
                ):
                    findings.append(
                        Finding(
                            MAILBOX_PROTOCOL, mod.relpath,
                            site.replace_call.lineno,
                            site.replace_call.col_offset,
                            "tempfile name carries no process-unique "
                            "discriminator — two ranks publishing into "
                            "a shared directory interleave their "
                            "writes into the same tmp file and rename "
                            "each other's torn payloads into place; "
                            "suffix the tmp with `os.getpid()` (or "
                            "rank/uuid) the way "
                            "`multihost.write_params` does",
                            mod.enclosing_function(site.replace_call),
                        )
                    )
            elif site.writes_builder_path:
                findings.append(
                    Finding(
                        MAILBOX_PROTOCOL, mod.relpath,
                        node.lineno, node.col_offset,
                        "non-atomic publish: this writes the CONSUMED "
                        "protocol path directly (a shared path-builder "
                        "names it), so a concurrent reader — or a "
                        "reader after a mid-write SIGKILL — sees a "
                        "torn file instead of the previous complete "
                        "snapshot; write a same-directory tmp and "
                        "`os.replace` it into place",
                        ctx,
                    )
                )
        for site in model.consumers[mod.relpath]:
            node = site.call
            if not _consumes_builder_path(mod, model, node):
                continue
            torn = TORN_EXC_NPZ if site.kind == "npz" else TORN_EXC_JSON
            if site.handler_names is None:
                findings.append(
                    Finding(
                        MAILBOX_PROTOCOL, mod.relpath,
                        node.lineno, node.col_offset,
                        "unguarded parse of a shared snapshot file — a "
                        "torn/partial/absent file (crash mid-publish, "
                        "fs hiccup) raises out of the consume loop and "
                        "takes the poller down; wrap in try/except "
                        "returning None (the mailbox contract: torn "
                        "reads are retried next poll)",
                        mod.enclosing_function(node),
                    )
                )
            elif not (site.handler_names & torn):
                need = (
                    "zipfile.BadZipFile/EOFError"
                    if site.kind == "npz"
                    else "json.JSONDecodeError"
                )
                findings.append(
                    Finding(
                        MAILBOX_PROTOCOL, mod.relpath,
                        node.lineno, node.col_offset,
                        "torn-read intolerance: the enclosing handler "
                        f"catches {sorted(site.handler_names)} but a "
                        f"truncated file raises {need}, which is none "
                        "of those — the poller thread dies on the "
                        "first torn snapshot instead of retrying "
                        "(the PR 12 mailbox-writer class)",
                        mod.enclosing_function(node),
                    )
                )
        findings.extend(_monotonicity_findings(mod))
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


def _consumes_builder_path(
    mod: ModuleInfo, model: ProcessModel, call: ast.Call
) -> bool:
    """Whether the parse call's source is a shared-builder path: its
    first arg is (or is a name last assigned from) a path-builder call.
    Keeps the rule off np.load/json.load of private files."""
    from actor_critic_tpu.analysis.process_model import _expr_from_builder

    if not call.args:
        return False
    builders: set[str] = set()
    for names in model.path_builders.values():
        builders |= names
    if not builders:
        return False
    return _expr_from_builder(
        mod, mod.scope_of(call), call.args[0], builders
    )


def _numeric_const(expr: ast.AST) -> bool:
    """A numeric literal, including the `-1` spelling (a UnaryOp over
    a Constant, not a Constant)."""
    if isinstance(expr, ast.UnaryOp) and isinstance(
        expr.op, (ast.USub, ast.UAdd)
    ):
        expr = expr.operand
    return (
        isinstance(expr, ast.Constant)
        and isinstance(expr.value, (int, float))
        and not isinstance(expr.value, bool)
    )


def _monotonicity_findings(mod: ModuleInfo) -> list[Finding]:
    """Per-peer version clocks: in a scope that distinguishes peers
    (reads a `peer`-named value or calls a `*_peer` schedule), a
    version comparison against a plain scalar initialized from a
    constant is a GLOBAL newest-seen clock — it permanently mutes every
    peer slower than the fastest ever seen (the PR 9 review bug); the
    clock must be a per-peer mapping (`seen.get(peer, -1)`)."""
    findings: list[Finding] = []
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_peer = any(
            (isinstance(n, ast.Name) and n.id == "peer")
            or (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id.endswith("_peer")
            )
            for n in ast.walk(fn)
        )
        if not has_peer:
            continue
        scalar_inits = {
            name
            for stmt in ast.walk(fn)
            if isinstance(stmt, ast.Assign)
            and _numeric_const(stmt.value)
            for tgt in stmt.targets
            if isinstance(tgt, ast.Name)
            for name in [tgt.id]
        }
        if not scalar_inits:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left, *node.comparators]
            version_side = any(
                isinstance(s, ast.Name) and "version" in s.id
                for s in sides
            )
            clock = next(
                (
                    s
                    for s in sides
                    if isinstance(s, ast.Name) and s.id in scalar_inits
                ),
                None,
            )
            if version_side and clock is not None:
                findings.append(
                    Finding(
                        MAILBOX_PROTOCOL, mod.relpath,
                        node.lineno, node.col_offset,
                        f"`{clock.id}` is a single scalar version "
                        "clock in a scope that consumes from multiple "
                        "peers — versions are per-peer consumption "
                        "counters and are NOT comparable across peers, "
                        "so one fast peer permanently mutes every "
                        "slower one (ring diffusion broken at "
                        "world>=3); track the newest seen PER RANK "
                        "(`seen: dict`, `seen.get(peer, -1)`)",
                        mod.enclosing_function(node),
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# rank-affinity
# ---------------------------------------------------------------------------


@register_check(
    RANK_AFFINITY,
    "shared artifact paths (telemetry/metrics/checkpoint/file writes) "
    "not parameterized by process identity in per-rank scopes — every "
    "host clobbers the same file",
    scope="repo",
)
def check_rank_affinity(modules: list[ModuleInfo]) -> list[Finding]:
    model = _shared_model(modules)
    findings: list[Finding] = []
    for mod in modules:
        scope_cache: dict[int, bool] = {}

        def is_distributed(scope: ast.AST) -> bool:
            if id(scope) not in scope_cache:
                scope_cache[id(scope)] = model.distributed_scope(mod, scope)
            return scope_cache[id(scope)]

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute):
                name = node.func.attr
            if name not in _PATH_SINKS:
                continue
            scope = mod.scope_of(node)
            if isinstance(scope, ast.Module) or not is_distributed(scope):
                continue
            path_expr = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg in ("directory", "dir", "path"):
                    path_expr = kw.value
            if path_expr is None:
                continue
            if rank_parameterized(mod, scope, path_expr):
                continue
            findings.append(
                Finding(
                    RANK_AFFINITY, mod.relpath,
                    node.lineno, node.col_offset,
                    f"`{name}(...)` writes a shared artifact from a "
                    "per-rank scope, but its path is not parameterized "
                    "by the process identity — every host of the fleet "
                    "appends/clobbers the SAME file (interleaved jsonl "
                    "lines, racing checkpoint commits); suffix the "
                    "path with the rank (`host<rank>/`, the "
                    "launch_multihost convention)",
                    mod.enclosing_function(node),
                )
            )
        # open-for-write producers in per-rank scopes ride the same rule.
        for site in model.producers[mod.relpath]:
            scope = site.scope
            if isinstance(scope, ast.Module) or not is_distributed(scope):
                continue
            if rank_parameterized(mod, scope, site.path_expr):
                continue
            node = site.open_call
            findings.append(
                Finding(
                    RANK_AFFINITY, mod.relpath,
                    node.lineno, node.col_offset,
                    "file written from a per-rank scope at a path no "
                    "process identity reaches — ranks sharing a "
                    "filesystem overwrite each other's bytes; fold the "
                    "rank (or pid) into the path",
                    mod.enclosing_function(node),
                )
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings
