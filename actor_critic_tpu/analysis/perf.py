"""Performance passes: transfer-discipline, donation-discipline,
dispatch-granularity (ISSUE 15 tentpole, static half).

The fifth analysis dimension (JAX correctness → threads → processes →
numerics → PERFORMANCE). The repo's perf claims are contracts — PR 13's
device plane promises "steady-state consumption transfers zero bytes",
PR 10's gateway promises "a swap never recompiles" — and accelerated
deep-RL stacks live or die on keeping the hot loop on-accelerator
(arxiv 1803.02811; HEPPO-GAE, arxiv 2501.12703, shows the next wins are
pipeline/memory discipline). Each pass names one way those contracts
silently rot:

- **transfer-discipline** — host↔device crossings paid per step.
  Generalizes and ABSORBS ISSUE 5's host-sync pass (its check name
  remains resolvable as an alias; annotations and baseline fingerprints
  migrated): the device→host syncs it always matched (`.item()`,
  `np.asarray`, `block_until_ready`, `float()`/`int()` coercions) plus
  `jax.device_get` and the host→device upload family (`jnp.array` /
  `jnp.asarray` / `jax.device_put`), flagged inside any loop of a hot
  module and inside detected step loops (loops dispatching a compiled
  program) of every other module. One stray crossing in a steady-state
  body serializes the async pipeline or re-pays the tunnel per block —
  exactly the regression class the PR 13 A/B measured at 1.5×.

- **donation-discipline** — donate-eligible buffers the program copies
  instead. (a) A compiled-program call site that REBINDS one of its own
  argument names (`state = step(state, ...)` — the recycled-buffer
  shape) through a program with NO donation: XLA must allocate a second
  buffer for the output and copy-preserve the input it could have
  reused, doubling live HBM for that state (the replay/ring/params
  family this repo recycles every iteration). (b) Donated-then-read
  NEAR-MISSES the donation-aliasing pass cannot see: a VIEW/alias bound
  from the donated tree before the donating call and read after it —
  the alias points into a buffer XLA already reused even though the
  donated name itself was properly rebound.

- **dispatch-granularity** — work that belongs inside ONE fused program
  dispatched as many. Python-level reductions (`sum`/`min`/`max`) over
  device values inside a step loop (one tiny dispatch per element plus
  a sync at the end), eager device-namespace math in a step-loop body
  outside any jit (each call is its own XLA program every iteration),
  and ≥2 distinct compiled programs dispatched in one loop body (the
  gather/update split the device plane exists to fuse).

Runtime companion: `analysis/perfsan.py` counts dispatches / transfers
/ transferred bytes / recompiles on the REAL steady-state programs
against the committed `perf_budgets.json` (scripts/perfsan.py, tier-1's
quick profile between numsan and pytest).
"""

from __future__ import annotations

import ast
from typing import Optional

from actor_critic_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    register_check,
    target_names,
)
from actor_critic_tpu.analysis import perf_model
from actor_critic_tpu.analysis.perf_model import (
    BUFFER_NAME_RE,
    ProgramInfo,
    crossing_kind,
    eager_device_call,
    factory_programs,
    in_loop,
    in_step_loop,
    inside_traced_def,
    is_hot_module,
    jit_traced_defs,
    program_bindings,
    step_loops,
)

TRANSFER_DISCIPLINE = "transfer-discipline"
DONATION_DISCIPLINE = "donation-discipline"
DISPATCH_GRANULARITY = "dispatch-granularity"

# Single-entry shared-model cache (the concurrency/distributed/numerics
# passes' `_SHARED` idiom): three registered checks, one factory table —
# plus per-module step loops and per-scope program bindings, which every
# pass re-needs — computed once per run.
_SHARED: dict = {}


def _shared_state(modules: list[ModuleInfo]) -> dict:
    key = tuple(id(m) for m in modules)
    entry = _SHARED.get("entry")
    if entry is not None and entry[0] == key:
        return entry[1]
    state = {
        "factories": factory_programs(modules),
        "loops": {},      # id(mod) -> step loops
        "bindings": {},   # (id(mod), id(scope)) -> program bindings
        "modules": list(modules),  # keep ids alive for the cache key
    }
    _SHARED["entry"] = (key, state)
    return state


def _loops_for(state: dict, mod: ModuleInfo) -> list:
    loops = state["loops"].get(id(mod))
    if loops is None:
        loops = step_loops(mod, state["factories"])
        state["loops"][id(mod)] = loops
    return loops


def _bindings_for(state: dict, mod: ModuleInfo, scope) -> dict:
    key = (id(mod), id(scope))
    bindings = state["bindings"].get(key)
    if bindings is None:
        bindings = program_bindings(mod, scope, state["factories"])
        state["bindings"][key] = bindings
    return bindings


# ---------------------------------------------------------------------------
# transfer-discipline
# ---------------------------------------------------------------------------


@register_check(
    TRANSFER_DISCIPLINE,
    "host<->device crossings (.item()/np.asarray/block_until_ready/"
    "float()/device_get syncs; jnp.array/device_put uploads) inside "
    "steady-state loop bodies — absorbs host-sync",
    scope="repo",
)
def check_transfer_discipline(modules: list[ModuleInfo]) -> list[Finding]:
    state = _shared_state(modules)
    findings: list[Finding] = []
    for mod in modules:
        hot = is_hot_module(mod)
        loops = _loops_for(state, mod)
        traced = jit_traced_defs(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            # Hot modules keep host-sync's scope (any loop); elsewhere
            # only detected step loops flag — straight-line setup code
            # crosses once, not per step.
            if hot:
                if in_loop(mod, node) is None:
                    continue
            elif not in_step_loop(mod, node, loops):
                continue
            # Jit-traced bodies execute as ONE compiled program: an
            # upload spelling there runs once at trace time, not per
            # iteration (the dispatch-granularity pass's filter).
            if inside_traced_def(mod, node, traced):
                continue
            kind = crossing_kind(mod, node)
            if kind is None:
                continue
            desc, direction = kind
            if direction == "d2h":
                msg = (
                    f"{desc} inside a steady-state loop blocks the host "
                    "on the device every iteration, serializing the "
                    "async dispatch pipeline — hoist it to the log "
                    "cadence, keep the value on device, or suppress "
                    "with the reason if the sync is deliberate"
                )
            else:
                msg = (
                    f"{desc} inside a steady-state loop re-pays the "
                    "host->device transfer every iteration (the PR 13 "
                    "device plane exists to remove exactly this class "
                    "— its A/B measured the relocation at 1.5x); keep "
                    "the buffer device-resident, or suppress with the "
                    "reason if this upload IS the data plane (and then "
                    "it must carry a perfsan transfer budget)"
                )
            findings.append(
                Finding(
                    TRANSFER_DISCIPLINE, mod.relpath,
                    node.lineno, node.col_offset, msg,
                    mod.enclosing_function(node),
                )
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


# ---------------------------------------------------------------------------
# donation-discipline
# ---------------------------------------------------------------------------


def _rebound_names(mod: ModuleInfo, call: ast.Call) -> set[str]:
    """Names (and dotted attribute paths) the enclosing statement
    rebinds to this call's result."""
    parent = mod.parent(call)
    out: set[str] = set()
    if isinstance(parent, ast.Assign):
        for tgt in parent.targets:
            out |= set(target_names(tgt))
            path = _attr_path(tgt)
            if path:
                out.add(path)
    elif isinstance(parent, (ast.AnnAssign, ast.AugAssign)):
        out |= set(target_names(parent.target))
        path = _attr_path(parent.target)
        if path:
            out.add(path)
    return out


def _attr_path(node: ast.AST) -> Optional[str]:
    """Dotted path of a Name/Attribute chain ("self._state"), or None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _arg_root(arg: ast.AST) -> Optional[str]:
    while isinstance(arg, (ast.Subscript, ast.Attribute)):
        arg = arg.value
    return arg.id if isinstance(arg, ast.Name) else None


def _undonated_findings(
    mod: ModuleInfo,
    bindings: dict[str, ProgramInfo],
    call: ast.Call,
) -> list[Finding]:
    """Shape (a): a program with NO donation whose call site rebinds
    one of its own argument names — the recycled-buffer family."""
    info = bindings.get(
        call.func.id if isinstance(call.func, ast.Name) else ""
    )
    if info is None or info.donates:
        return []
    rebound = _rebound_names(mod, call)
    if not rebound:
        return []
    recycled = []
    for arg in call.args:
        name = _arg_root(arg)
        if name is not None and name in rebound:
            recycled.append(name)
    if not recycled:
        return []
    looped = in_loop(mod, call) is not None
    bufferish = any(BUFFER_NAME_RE.search(n) for n in recycled)
    if not (looped or bufferish):
        return []
    names = ", ".join(f"`{n}`" for n in sorted(set(recycled)))
    return [
        Finding(
            DONATION_DISCIPLINE, mod.relpath,
            call.lineno, call.col_offset,
            f"{names} is recycled through compiled program "
            f"`{call.func.id}` (result rebinds the argument) with no "
            "donation: XLA allocates a fresh output buffer and "
            "copy-preserves an input nothing will read again — for a "
            "ring/replay/params-sized tree that doubles its live HBM "
            "every iteration; add donate_argnums (uncommit restored "
            "states first — the donation-aliasing contract), or "
            "suppress with the reason the copy is load-bearing",
            mod.enclosing_function(call),
        )
    ]


def _alias_read_findings(
    mod: ModuleInfo,
    bindings: dict[str, ProgramInfo],
    call: ast.Call,
    scope: ast.AST,
) -> list[Finding]:
    """Shape (b): the donated-then-read near-miss donation-aliasing
    cannot see — an alias/view bound FROM the donated tree before the
    donating call, read after it. The donated name itself may be
    properly rebound (so the aliasing pass stays quiet), but the alias
    still points into the reused buffer."""
    info = bindings.get(
        call.func.id if isinstance(call.func, ast.Name) else ""
    )
    if info is None or not info.donates:
        return []
    positions = info.donated_positions or (0,)
    donated_roots = {
        r
        for p in positions
        if p < len(call.args)
        for r in [_arg_root(call.args[p])]
        if r is not None
    }
    if not donated_roots:
        return []
    # aliases: `view = root` / `view = root[...]` / `view = root.attr`
    # bound BEFORE the call in the same scope
    aliases: dict[str, int] = {}
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign) or node.lineno >= call.lineno:
            continue
        value = node.value
        root = _arg_root(value) if not isinstance(value, ast.Call) else None
        if root in donated_roots:
            for tgt in node.targets:
                for name in target_names(tgt):
                    if name not in donated_roots:
                        aliases[name] = node.lineno
    if not aliases:
        return []
    # reads of an alias after the donating call, not rebound BETWEEN
    # the call and the read (a rebind after the read does not unpoison
    # the earlier dereference)
    out: list[Finding] = []
    rebind_lines: dict[str, list[int]] = {}
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and node.lineno > call.lineno:
            for tgt in node.targets:
                for name in target_names(tgt):
                    rebind_lines.setdefault(name, []).append(node.lineno)
    own = {id(n) for n in ast.walk(call)}
    for node in ast.walk(scope):
        if (
            isinstance(node, ast.Name)
            and node.id in aliases
            and isinstance(node.ctx, ast.Load)
            and id(node) not in own
            and node.lineno > call.lineno
            and not any(
                call.lineno < ln <= node.lineno
                for ln in rebind_lines.get(node.id, ())
            )
            and not mod.exclusive_branches(call, node)
        ):
            out.append(
                Finding(
                    DONATION_DISCIPLINE, mod.relpath,
                    node.lineno, node.col_offset,
                    f"`{node.id}` aliases `{'/'.join(sorted(donated_roots))}`"
                    f" (bound at line {aliases[node.id]}) which was "
                    f"donated into `{call.func.id}` at line "
                    f"{call.lineno} — the donated name may be rebound, "
                    "but this view still points into a buffer XLA "
                    "already reused (the near-miss the donation-"
                    "aliasing pass cannot see); re-derive it from the "
                    "call's result",
                    mod.enclosing_function(node),
                )
            )
            break  # one finding per donating call names the class
    return out


@register_check(
    DONATION_DISCIPLINE,
    "recycled ring/replay/params buffers donate-eligible but undonated "
    "at compiled-program call sites; donated-then-read alias near-"
    "misses the donation-aliasing pass cannot see",
    scope="repo",
)
def check_donation_discipline(modules: list[ModuleInfo]) -> list[Finding]:
    state = _shared_state(modules)
    findings: list[Finding] = []
    for mod in modules:
        for call in ast.walk(mod.tree):
            if not isinstance(call, ast.Call) or not isinstance(
                call.func, ast.Name
            ):
                continue
            scope = mod.scope_of(call)
            bindings = _bindings_for(state, mod, scope)
            findings.extend(_undonated_findings(mod, bindings, call))
            findings.extend(
                _alias_read_findings(mod, bindings, call, scope)
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings


# ---------------------------------------------------------------------------
# dispatch-granularity
# ---------------------------------------------------------------------------

_PY_REDUCERS = {"sum", "min", "max"}


def _gated_in_loop(mod: ModuleInfo, node: ast.AST, loop: ast.AST) -> bool:
    """Whether `node` sits inside a nested def/lambda or under an `if`
    BETWEEN itself and `loop` — conditional/cadence-gated work, not the
    unconditional per-iteration chain."""
    for anc in mod.ancestors(node):
        if anc is loop:
            return False
        if isinstance(
            anc, (ast.If, ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return True
    return False


def _reduction_over_device(
    mod: ModuleInfo,
    bindings: dict[str, ProgramInfo],
    call: ast.Call,
) -> bool:
    """Builtin sum/min/max whose iterable mentions a compiled-program
    dispatch or a device-namespace call — a Python loop of tiny
    dispatches plus a final sync."""
    if not isinstance(call.func, ast.Name):
        return False
    if call.func.id not in _PY_REDUCERS or not call.args:
        return False
    for sub in ast.walk(call.args[0]):
        if not isinstance(sub, ast.Call):
            continue
        if eager_device_call(mod, sub) is not None:
            return True
        if isinstance(sub.func, ast.Name) and sub.func.id in bindings:
            return True
    return False


@register_check(
    DISPATCH_GRANULARITY,
    "Python-level reductions over device values, eager device-"
    "namespace math, and multi-program dispatch chains inside "
    "per-step loops — work that belongs in one fused program",
    scope="repo",
)
def check_dispatch_granularity(modules: list[ModuleInfo]) -> list[Finding]:
    state = _shared_state(modules)
    findings: list[Finding] = []
    for mod in modules:
        loops = _loops_for(state, mod)
        if not loops:
            continue
        traced = jit_traced_defs(mod)

        def bindings_for(node):
            return _bindings_for(state, mod, mod.scope_of(node))

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not in_step_loop(mod, node, loops):
                continue
            if inside_traced_def(mod, node, traced):
                continue
            bindings = bindings_for(node)
            if _reduction_over_device(mod, bindings, node):
                findings.append(
                    Finding(
                        DISPATCH_GRANULARITY, mod.relpath,
                        node.lineno, node.col_offset,
                        f"Python `{node.func.id}()` over device values "
                        "inside a step loop dispatches one tiny program "
                        "per element and syncs at the end, every "
                        "iteration — fold the reduction into the "
                        "compiled program (jnp.sum/min/max inside the "
                        "jit) or hoist it to the log cadence",
                        mod.enclosing_function(node),
                    )
                )
                continue
            op = eager_device_call(mod, node)
            if op is not None:
                findings.append(
                    Finding(
                        DISPATCH_GRANULARITY, mod.relpath,
                        node.lineno, node.col_offset,
                        f"eager `jnp.{op}` inside a step loop is its "
                        "own XLA program dispatched every iteration — "
                        "move it inside the step's jitted program (one "
                        "fused dispatch per block is the contract the "
                        "update-wall bench prices), or suppress with "
                        "the reason if this site is cold",
                        mod.enclosing_function(node),
                    )
                )
        # multi-program chains: >= 2 DISTINCT compiled programs
        # dispatched unconditionally in one step-loop body. Calls
        # inside nested defs/lambdas (helper closures host_collect
        # drives), under an `if` (cadence-gated work — eval every N),
        # or in exclusive branch arms (mode selection, not a chain)
        # don't count: the finding is the straight-line gather/update
        # split one fused program would absorb.
        for loop in loops:
            body_calls: dict[str, ast.Call] = {}
            for sub in ast.walk(loop):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and not inside_traced_def(mod, sub, traced)
                    and not _gated_in_loop(mod, sub, loop)
                ):
                    bindings = bindings_for(sub)
                    if sub.func.id in bindings:
                        body_calls.setdefault(sub.func.id, sub)
            chain = [
                c
                for c in body_calls.values()
                if not any(
                    mod.exclusive_branches(c, o)
                    for o in body_calls.values()
                    if o is not c
                )
            ]
            if len(chain) >= 2:
                chain.sort(key=lambda c: (c.lineno, c.col_offset))
                first = chain[0]
                names = sorted(c.func.id for c in chain)
                findings.append(
                    Finding(
                        DISPATCH_GRANULARITY, mod.relpath,
                        first.lineno, first.col_offset,
                        f"step loop dispatches {len(names)} distinct "
                        f"compiled programs per iteration "
                        f"({', '.join(f'`{n}`' for n in names)}) — "
                        "the gather/update split the device plane "
                        "fuses into ONE program (ppo.make_device_"
                        "update_step's shape); fuse them or suppress "
                        "with the reason the split is load-bearing",
                        mod.enclosing_function(first),
                    )
                )
    findings.sort(key=lambda f: (f.path, f.line, f.col))
    return findings
