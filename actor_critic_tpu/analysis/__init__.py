"""jaxlint: repo-wide JAX correctness analyzer (ISSUE 5, extended with
concurrency passes + racesan in ISSUE 7, distributed passes + fleetsan
in ISSUE 12, numerics passes + numsan in ISSUE 14, performance passes
+ perfsan in ISSUE 15, and shape/padding passes + padsan in ISSUE 20).

AST-based static analysis over this repo's JAX code — pure stdlib
`ast`, no new dependencies, and (except the `warmup-registry` pass,
which validates against the live registry, and the numerics passes'
optional `jax.eval_shape` grounding) no imports of the code it scans.
Twenty-one registered passes, each grounded in a failure this codebase
actually hit or observes at runtime:

    donation-aliasing     donated jit args fed restore-aliased/still-
                          live buffers (the PR 4 glibc heap corruption)
    tracer-leak           Python if/while/assert/bool() on traced values
    prng-reuse            one PRNG key consumed twice without split
    recompile-hazard      jit built in loops; shape-/len()-derived
                          scalars at jitted call sites
    warmup-registry       jax.jit entry points without AOT warmup
                          planners (ISSUE 4's lint, folded in)
    lock-discipline       compound writes to cross-thread shared state
                          outside a lock (thread_model.py)
    publish-aliasing      ndarray views of recycled slots crossing
                          thread channels / aliased past release
    check-then-act        unlocked read-test-write windows on shared
                          flags/counters
    collective-discipline undeclared axis names; collectives gated on
                          process-local state (process_model.py)
    mailbox-protocol      gossip-mailbox write→fsync→rename discipline,
                          torn-read tolerance, per-peer clocks
    rank-affinity         shared artifact paths unparameterized by
                          process identity in per-rank scopes
    precision-discipline  device float64; silent bf16/f32 mixing;
                          low-precision reductions without an fp32
                          accumulator; codec decode dtype forks
                          (dtype_model.py)
    nonfinite-hazard      unguarded log/sqrt/arctanh/division, exp of
                          unbounded log-ratios, bare-constant scale
                          seeds (the PR 8 class)
    sink-guard            json.dumps(allow_nan=False) writers and
                          commit points (checkpoint/mailbox/publish/
                          swap) without a finiteness gate
    transfer-discipline   host<->device crossings inside steady-state
                          loop bodies (ABSORBS ISSUE 5's host-sync —
                          the old name stays resolvable as an alias;
                          perf_model.py)
    donation-discipline   recycled ring/replay/params buffers donate-
                          eligible but undonated; donated-then-read
                          alias near-misses
    dispatch-granularity  Python reductions over device values, eager
                          device math, and multi-program chains inside
                          per-step loops — one fused program's work
    pad-mask-discipline   reductions over a padding-widened axis with
                          neither a mask multiply/where nor a
                          valid-slice (shape_model.py)
    mask-propagation      padded arrays crossing function/jit seams
                          without their mask riding along or a
                          downstream slice-back
    slice-before-commit   padded buffers reaching commit points
                          (publish/save/enqueue/serving response)
                          with their junk lanes intact

Runtime companions, each gating tier-1 under its own timeout:
`analysis/racesan.py` (seeded cooperative-schedule race exerciser),
`analysis/fleetsan.py` (seeded multi-process chaos),
`analysis/numsan.py` (seeded NaN/Inf/saturation fault injection over
the real update/codec/publish/checkpoint objects),
`analysis/perfsan.py` (dispatch/transfer/recompile budget metering of
the real steady-state programs against `perf_budgets.json`), and
`analysis/padsan.py` (seeded padding-lane poisoner asserting valid-lane
outputs of the real padded programs are bitwise pad-invariant).

CLI: `python scripts/jaxlint.py` (tier-1-gated via
tests/test_jaxlint.py and scripts/tier1.sh). Per-line suppression:
`# jaxlint: disable=<check>` with the reason in the same comment;
audited single-writer state: `# jaxlint: thread-owned=<role>`.
Accepted findings live in `jaxlint_baseline.json` with reason strings.
"""

from actor_critic_tpu.analysis.baseline import (
    apply_baseline,
    default_baseline_path,
    load_baseline,
    regenerate,
    save_baseline,
)
from actor_critic_tpu.analysis.core import (
    AnalysisError,
    Check,
    Finding,
    ModuleInfo,
    analyze_paths,
    load_modules,
    register_check,
    registered_checks,
    run_checks,
)

__all__ = [
    "AnalysisError",
    "Check",
    "Finding",
    "ModuleInfo",
    "analyze_paths",
    "apply_baseline",
    "default_baseline_path",
    "load_baseline",
    "load_modules",
    "regenerate",
    "register_check",
    "registered_checks",
    "run_checks",
    "save_baseline",
]
