"""jaxlint: repo-wide JAX correctness analyzer (ISSUE 5, extended with
concurrency passes + the racesan runtime sanitizer in ISSUE 7).

AST-based static analysis over this repo's JAX code — pure stdlib
`ast`, no new dependencies, and (except the `warmup-registry` pass,
which validates against the live registry) no imports of the code it
scans. Nine registered passes, each grounded in a failure this codebase
actually hit or observes at runtime:

    donation-aliasing   donated jit args fed restore-aliased/still-live
                        buffers (the PR 4 glibc heap corruption)
    tracer-leak         Python if/while/assert/bool() on traced values
    prng-reuse          one PRNG key consumed twice without split
    recompile-hazard    jit built in loops; shape-/len()-derived scalars
                        at jitted call sites (the PR 3 recompile storms)
    host-sync           device syncs inside hot collection loops
    warmup-registry     jax.jit entry points without AOT warmup planners
                        (ISSUE 4's lint, folded in)
    lock-discipline     compound writes to cross-thread shared state
                        outside a lock (the PR 6 span-stack corruption;
                        thread model in analysis/thread_model.py)
    publish-aliasing    ndarray views of recycled slots crossing thread
                        channels / aliased past release (the PR 6
                        zero-copy queue race)
    check-then-act      unlocked read-test-write windows on shared
                        flags/counters

Runtime companion: `analysis/racesan.py` — seeded cooperative-schedule
exerciser + write-after-publish poisoner (`scripts/racesan.py`,
tier-1's quick profile).

CLI: `python scripts/jaxlint.py` (tier-1-gated via
tests/test_jaxlint.py and scripts/tier1.sh). Per-line suppression:
`# jaxlint: disable=<check>` with the reason in the same comment;
audited single-writer state: `# jaxlint: thread-owned=<role>`.
Accepted findings live in `jaxlint_baseline.json` with reason strings.
"""

from actor_critic_tpu.analysis.baseline import (
    apply_baseline,
    default_baseline_path,
    load_baseline,
    regenerate,
    save_baseline,
)
from actor_critic_tpu.analysis.core import (
    AnalysisError,
    Check,
    Finding,
    ModuleInfo,
    analyze_paths,
    load_modules,
    register_check,
    registered_checks,
    run_checks,
)

__all__ = [
    "AnalysisError",
    "Check",
    "Finding",
    "ModuleInfo",
    "analyze_paths",
    "apply_baseline",
    "default_baseline_path",
    "load_baseline",
    "load_modules",
    "regenerate",
    "register_check",
    "registered_checks",
    "run_checks",
    "save_baseline",
]
