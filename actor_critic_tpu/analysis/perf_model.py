"""Whole-repo performance model for the perf passes (ISSUE 15).

Sibling of `thread_model.py` (threads), `process_model.py` (ranks) and
`dtype_model.py` (numerics): pure-`ast` facts the three performance
passes in `analysis/perf.py` share, extracted once per run. The repo's
headline perf claims are CONTRACTS — "steady-state consumption
transfers zero bytes" (PR 13), "a swap never recompiles" (PR 10) — and
this model names the source regions those contracts live in:

- **Hot regions.** A module is hot when its basename is in
  `HOT_BASENAMES` (the step-loop owners ISSUE 5 named) or it carries a
  `# jaxlint: hot-module` pragma. Within ANY module, `step_loops`
  additionally resolves the loops that dispatch a compiled program each
  iteration — the steady-state bodies where a host↔device crossing is
  paid per step, not once.

- **Program bindings.** `named_jit_sites` (jitinfo.py) only sees direct
  `jax.jit` wraps, but this codebase overwhelmingly builds its programs
  through FACTORIES (`update = ppo.make_async_update_step(...)`): the
  jit lives inside the factory, the dispatch loop lives in the caller,
  and no single-module pass can connect them. `factory_programs` scans
  every module for factory defs whose return value is a jit-wrapped
  callable (direct `return jax.jit(f)`, a returned `@jax.jit`/
  `@partial(jax.jit, ...)`-decorated inner def, or a returned local jit
  wrap), recording the donation configuration; `program_bindings` then
  resolves `name = factory(...)` assignments per scope, so the passes
  know that `update(...)` at a call site dispatches a compiled program
  — and whether that program donates.

- **Crossing classification.** `crossing_kind` names host↔device
  crossing expressions: the device→host syncs host-sync always matched
  (`.item()`, `np.asarray`, `block_until_ready`, `float()`/`int()`
  coercions) plus `jax.device_get` and the host→device upload family
  (`jnp.array`/`jnp.asarray`/`jax.device_put`) — each a transfer paid
  per iteration when it sits in a steady-state loop.

The runtime companion is `analysis/perfsan.py`, which counts the same
quantities (dispatches, transfers, transferred bytes, recompiles) on
the REAL programs against `perf_budgets.json`.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterable, Optional

from actor_critic_tpu.analysis.core import ModuleInfo, target_names
from actor_critic_tpu.analysis.jitinfo import (
    JitSite,
    collect_jit_sites,
    is_jax_jit_expr,
    named_jit_sites,
)

# The step-loop owners (ISSUE 5's host-sync scope, inherited verbatim).
# Other modules opt in via the `# jaxlint: hot-module` pragma.
HOT_BASENAMES = {"host_loop.py", "ppo.py", "compile_cache.py"}

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
_SYNC_FREE_CALLS = {"len", "round", "abs"}  # cheap host-side builtins

# Factory names that return compiled programs follow one convention in
# this repo: make_<something about stepping/updating the system>.
_FACTORY_RE = re.compile(
    r"^make_\w*(update|step|train|ingest|enqueue|act|eval|rollout)\w*$"
)

# Argument names that denote large recycled device state — the
# donate-eligible family donation-discipline prices.
BUFFER_NAME_RE = re.compile(
    r"(state|ring|replay|buffer|storage|learner|params|opt)", re.I
)


def is_hot_module(mod: ModuleInfo) -> bool:
    basename = mod.relpath.rsplit("/", 1)[-1]
    return basename in HOT_BASENAMES or mod.hot_module


def in_loop(mod: ModuleInfo, node: ast.AST) -> Optional[ast.AST]:
    """The innermost real loop ancestor (comprehensions alone do not
    count — a lone dict-comp runs once per CALL, not per step), or
    None."""
    for anc in mod.ancestors(node):
        if isinstance(anc, _LOOPS):
            return anc
    return None


# ---------------------------------------------------------------------------
# factory programs: jit-wrapped callables returned by make_* factories
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ProgramInfo:
    """One compiled-program source: a factory (or direct jit wrap)
    whose result is dispatched at call sites. `key` is the last-two-
    component dotted name call sites resolve against
    ("ppo.make_async_update_step")."""

    key: str
    relpath: str
    lineno: int
    donates: bool
    donated_positions: tuple[int, ...]


def _returned_jit_site(
    mod: ModuleInfo, fn: ast.AST
) -> Optional[JitSite]:
    """The JitSite a factory def returns, or None. Recognizes
    `return jax.jit(f, ...)`, `return <name>` where <name> is a local
    jit wrap or a jit-decorated inner def, and `return partial-jit`
    spellings — the shapes the repo's make_* factories actually use."""
    local_sites = {
        s.name: s
        for s in collect_jit_sites(mod)
        if s.name and _contains(fn, s.lineno)
    }
    for node in ast.walk(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        value = node.value
        if isinstance(value, ast.Call) and is_jax_jit_expr(mod, value.func):
            for s in collect_jit_sites(mod):
                if s.lineno == value.lineno and not s.name:
                    return s
            site = JitSite("", value.lineno)
            return site
        if isinstance(value, ast.Name) and value.id in local_sites:
            return local_sites[value.id]
    return None


def _contains(fn: ast.AST, lineno: int) -> bool:
    return (
        getattr(fn, "lineno", 0)
        <= lineno
        <= (getattr(fn, "end_lineno", 0) or 0)
    )


def factory_programs(modules: Iterable[ModuleInfo]) -> dict[str, ProgramInfo]:
    """key ("<module stem>.<factory name>") → ProgramInfo for every
    factory def in the repo whose return value is a compiled program.
    Bare factory names are registered too, for same-module call sites
    (`update = make_host_update_step(...)`)."""
    out: dict[str, ProgramInfo] = {}
    for mod in modules:
        stem = mod.relpath.rsplit("/", 1)[-1].removesuffix(".py")
        for node in mod.tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _FACTORY_RE.match(node.name):
                continue
            site = _returned_jit_site(mod, node)
            if site is None:
                continue
            info = ProgramInfo(
                key=f"{stem}.{node.name}",
                relpath=mod.relpath,
                lineno=node.lineno,
                donates=site.donates,
                donated_positions=site.donated_positions(),
            )
            out[info.key] = info
    return out


def program_bindings(
    mod: ModuleInfo,
    scope: ast.AST,
    factories: dict[str, ProgramInfo],
) -> dict[str, ProgramInfo]:
    """name → ProgramInfo for names bound in `scope` from a factory
    call (`update = ppo.make_async_update_step(...)`) or a direct local
    jit wrap (folded in as ProgramInfo so the passes see one shape)."""
    out: dict[str, ProgramInfo] = {}
    stem = mod.relpath.rsplit("/", 1)[-1].removesuffix(".py")
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign) or not isinstance(
            node.value, ast.Call
        ):
            continue
        dotted = mod.dotted(node.value.func)
        if dotted is None:
            continue
        # Dotted call sites resolve by their own last-two components;
        # BARE names resolve only against THIS module's factories — a
        # bare `make_train_step(...)` in module B must never inherit
        # module A's donation config just because the names collide
        # (the repo has five make_train_step defs).
        if "." in dotted:
            info = factories.get(".".join(dotted.split(".")[-2:]))
        else:
            info = factories.get(f"{stem}.{dotted}")
        if info is None:
            continue
        for tgt in node.targets:
            for name in target_names(tgt):
                out[name] = info
    # Named jit wraps resolve scope-aware: a site bound INSIDE this
    # scope wins over a module-level one of the same name, and a site
    # local to a DIFFERENT function never leaks in (two functions may
    # each bind `run = jax.jit(...)` with different donation configs —
    # bench/suite.py does).
    top_defs = [
        n for n in mod.tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for name, site in _scoped_jit_sites(mod, scope, top_defs).items():
        out[name] = ProgramInfo(
            key=name,
            relpath=mod.relpath,
            lineno=site.lineno,
            donates=site.donates,
            donated_positions=site.donated_positions() or (
                (0,) if site.donates else ()
            ),
        )
    return out


def _scoped_jit_sites(
    mod: ModuleInfo, scope: ast.AST, top_defs: list[ast.AST]
) -> dict[str, JitSite]:
    module_level: dict[str, JitSite] = {}
    in_scope: dict[str, JitSite] = {}
    for site in sorted(collect_jit_sites(mod), key=lambda s: s.lineno):
        if not site.name:
            continue
        if not isinstance(scope, ast.Module) and _contains(
            scope, site.lineno
        ):
            in_scope[site.name] = site
        elif not any(_contains(d, site.lineno) for d in top_defs):
            module_level[site.name] = site
    return {**module_level, **in_scope}


# ---------------------------------------------------------------------------
# step loops: the steady-state dispatch bodies
# ---------------------------------------------------------------------------


def step_loops(
    mod: ModuleInfo, factories: dict[str, ProgramInfo]
) -> list[ast.AST]:
    """Loops whose body dispatches a compiled program (a program
    binding or local jit site) — the per-step regions where a crossing
    or a stray dispatch is paid every iteration. Resolution is
    name-based within the enclosing top-level scope, so a loop calling
    a program received as an opaque parameter stays out (no evidence)."""
    out: list[ast.AST] = []
    bindings_by_scope: dict[int, dict[str, ProgramInfo]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, _LOOPS):
            continue
        scope = mod.scope_of(node)
        key = id(scope)
        if key not in bindings_by_scope:
            bindings_by_scope[key] = program_bindings(mod, scope, factories)
        bindings = bindings_by_scope[key]
        if not bindings:
            continue
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id in bindings
            ):
                out.append(node)
                break
    return out


def in_step_loop(
    mod: ModuleInfo, node: ast.AST, loops: list[ast.AST]
) -> bool:
    ids = {id(l) for l in loops}
    return any(id(anc) in ids for anc in mod.ancestors(node))


# ---------------------------------------------------------------------------
# crossing classification (host-sync's taxonomy + uploads + device_get)
# ---------------------------------------------------------------------------


def crossing_kind(
    mod: ModuleInfo, call: ast.Call
) -> Optional[tuple[str, str]]:
    """(description, direction) of the host↔device crossing this call
    performs, or None. direction is "d2h" (a sync: the host blocks on
    the device) or "h2d" (an upload: bytes cross per iteration)."""
    dotted = mod.dotted(call.func)
    if isinstance(call.func, ast.Attribute):
        if call.func.attr == "item" and not call.args:
            return "`.item()`", "d2h"
        if call.func.attr == "block_until_ready":
            return "`block_until_ready`", "d2h"
    if dotted == "jax.block_until_ready":
        return "`jax.block_until_ready`", "d2h"
    if dotted == "jax.device_get":
        return "`jax.device_get`", "d2h"
    if dotted in ("numpy.asarray", "numpy.array"):
        return f"`{dotted.replace('numpy', 'np')}`", "d2h"
    if dotted == "jax.device_put":
        return "`jax.device_put`", "h2d"
    if dotted in ("jax.numpy.array", "jax.numpy.asarray"):
        return f"`jnp.{dotted.rsplit('.', 1)[-1]}`", "h2d"
    if dotted in ("float", "int") and call.args:
        arg = call.args[0]
        if isinstance(arg, ast.Constant):
            return None
        if isinstance(arg, ast.Call):
            inner = mod.dotted(arg.func) or ""
            if (
                inner.startswith("numpy.")
                or inner.startswith("math.")
                or inner in _SYNC_FREE_CALLS
            ):
                return None  # numpy/host math — no device involved
        return f"`{dotted}()`", "d2h"
    return None


# ---------------------------------------------------------------------------
# eager device ops (dispatch-granularity's raw material)
# ---------------------------------------------------------------------------

_DEVICE_NAMESPACES = ("jax.numpy", "jax.nn", "jax.lax")
# The upload/constructor family transfer-discipline already owns — the
# granularity pass must not double-report it.
_TRANSFER_ATTRS = {"array", "asarray", "device_put"}


def eager_device_call(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """The op name when `call` is a device-namespace math call
    dispatched EAGERLY (one tiny XLA program per evaluation), or None.
    Upload spellings are excluded (transfer-discipline's class)."""
    if not isinstance(call.func, ast.Attribute):
        return None
    base = mod.dotted(call.func.value)
    if base not in _DEVICE_NAMESPACES:
        return None
    if call.func.attr in _TRANSFER_ATTRS:
        return None
    return call.func.attr


def jit_traced_defs(mod: ModuleInfo) -> set[int]:
    """id()s of def nodes that are jit-traced (the wrapped def of any
    jit site) — eager-op findings must skip code that actually runs
    inside a program."""
    out: set[int] = set()
    for site in collect_jit_sites(mod):
        if site.func_def is not None:
            out.add(id(site.func_def))
    return out


def inside_traced_def(
    mod: ModuleInfo, node: ast.AST, traced: set[int]
) -> bool:
    if id(node) in traced:
        return True
    return any(id(anc) in traced for anc in mod.ancestors(node))
