"""Shared jit-site resolution for the AST passes.

Three passes need the same fact: "which local names are jit-compiled
callables in this module, and with what donate/static argument
configuration?" — donation-aliasing (donated positions), tracer-leak
(which defs trace their params), recompile-hazard (static positions at
call sites). This module extracts it once, recognizing the three forms
the codebase actually writes (the same set
scripts/check_warmup_registry.py always matched):

    @jax.jit / @partial(jax.jit, ...)        decorated defs
    name = jax.jit(fn, ...)                  wrap assignments
    jax.jit(fn, ...)                         anonymous wraps (call sites
                                             only, no name to track)

Keyword literals (donate_argnums/donate_argnames/static_argnums/
static_argnames) are parsed when they are int/str constants or tuples/
lists thereof; non-literal values are treated as unknown (empty), never
guessed.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from actor_critic_tpu.analysis.core import ModuleInfo

_PARTIAL = {"functools.partial", "partial"}


@dataclasses.dataclass
class JitSite:
    """One jit-compiled callable and its argument configuration."""

    name: str  # local name it is callable under ("" when anonymous)
    lineno: int
    donate_argnums: tuple[int, ...] = ()
    donate_argnames: tuple[str, ...] = ()
    static_argnums: tuple[int, ...] = ()
    static_argnames: tuple[str, ...] = ()
    func_def: Optional[ast.AST] = None  # wrapped/decorated def if resolvable
    donates_unknown: bool = False  # donate_* present but not a literal

    @property
    def donates(self) -> bool:
        return bool(
            self.donate_argnums or self.donate_argnames or self.donates_unknown
        )

    def params(self) -> tuple[str, ...]:
        if self.func_def is None or not isinstance(
            self.func_def, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return ()
        a = self.func_def.args
        return tuple(
            p.arg for p in [*a.posonlyargs, *a.args, *a.kwonlyargs]
        )

    def donated_positions(self) -> tuple[int, ...]:
        """Donated positional indices, argnames resolved through the
        wrapped def's signature when known."""
        pos = set(self.donate_argnums)
        params = self.params()
        for n in self.donate_argnames:
            if n in params:
                pos.add(params.index(n))
        return tuple(sorted(pos))

    def static_positions(self) -> tuple[int, ...]:
        pos = set(self.static_argnums)
        params = self.params()
        for n in self.static_argnames:
            if n in params:
                pos.add(params.index(n))
        return tuple(sorted(pos))


def _literal_ints(node: ast.AST) -> tuple[tuple[int, ...], bool]:
    """(values, is_literal) for an int-or-int-tuple keyword value."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,), True
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                vals.append(elt.value)
            else:
                return (), False
        return tuple(vals), True
    return (), False


def _literal_strs(node: ast.AST) -> tuple[tuple[str, ...], bool]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,), True
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                vals.append(elt.value)
            else:
                return (), False
        return tuple(vals), True
    return (), False


def _apply_keywords(site: JitSite, keywords: list[ast.keyword]) -> None:
    for kw in keywords:
        if kw.arg == "donate_argnums":
            vals, lit = _literal_ints(kw.value)
            site.donate_argnums = vals
            site.donates_unknown |= not lit
        elif kw.arg == "donate_argnames":
            vals, lit = _literal_strs(kw.value)
            site.donate_argnames = vals
            site.donates_unknown |= not lit
        elif kw.arg == "static_argnums":
            site.static_argnums, _ = _literal_ints(kw.value)
        elif kw.arg == "static_argnames":
            site.static_argnames, _ = _literal_strs(kw.value)


def is_jax_jit_expr(mod: ModuleInfo, node: ast.AST) -> bool:
    """Whether `node` denotes the `jax.jit` transform itself: the bare
    attribute, or `partial(jax.jit, ...)`."""
    if mod.dotted(node) == "jax.jit":
        return True
    return (
        isinstance(node, ast.Call)
        and mod.dotted(node.func) in _PARTIAL
        and bool(node.args)
        and mod.dotted(node.args[0]) == "jax.jit"
    )


def _jit_call_keywords(mod: ModuleInfo, call: ast.Call) -> Optional[list]:
    """keywords when `call` invokes jax.jit (directly or through a
    partial(jax.jit, ...) callee); None when it does not."""
    if mod.dotted(call.func) == "jax.jit":
        return list(call.keywords)
    if is_jax_jit_expr(mod, call.func) and isinstance(call.func, ast.Call):
        return list(call.func.keywords) + list(call.keywords)
    return None


def _local_defs(mod: ModuleInfo) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def collect_jit_sites(mod: ModuleInfo) -> list[JitSite]:
    """Every jit-compiled callable in the module. Named entries (bound
    via assignment or decoration) are callable-by-name at call sites;
    anonymous wraps still appear (name="") for passes that only care
    about where jit is invoked."""
    defs = _local_defs(mod)
    sites: list[JitSite] = []

    for node in ast.walk(mod.tree):
        # -- decorated defs ------------------------------------------------
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                site = None
                if mod.dotted(dec) == "jax.jit":
                    site = JitSite(node.name, node.lineno, func_def=node)
                elif isinstance(dec, ast.Call):
                    kws = None
                    if mod.dotted(dec.func) == "jax.jit":
                        kws = list(dec.keywords)
                    elif (
                        mod.dotted(dec.func) in _PARTIAL
                        and dec.args
                        and mod.dotted(dec.args[0]) == "jax.jit"
                    ):
                        kws = list(dec.keywords)
                    if kws is not None:
                        site = JitSite(node.name, node.lineno, func_def=node)
                        _apply_keywords(site, kws)
                if site is not None:
                    sites.append(site)
                    break
        # -- wrap calls ----------------------------------------------------
        elif isinstance(node, ast.Call):
            kws = _jit_call_keywords(mod, node)
            if kws is None:
                continue
            target = node.args[0] if node.args else None
            func_def = None
            if isinstance(target, ast.Name):
                func_def = defs.get(target.id)
            elif isinstance(target, ast.Lambda):
                func_def = target
            name = ""
            parent = mod.parent(node)
            if isinstance(parent, ast.Assign):
                tgt = parent.targets[0]
                if isinstance(tgt, ast.Name):
                    name = tgt.id
            site = JitSite(name, node.lineno, func_def=func_def)
            _apply_keywords(site, kws)
            sites.append(site)

    return sites


def named_jit_sites(mod: ModuleInfo) -> dict[str, JitSite]:
    """name -> JitSite for the callable-by-name entries (last binding
    wins, matching runtime shadowing)."""
    out: dict[str, JitSite] = {}
    for site in sorted(collect_jit_sites(mod), key=lambda s: s.lineno):
        if site.name:
            out[site.name] = site
    return out
