"""Whole-repo thread model for the concurrency passes (ISSUE 7).

PR 6 made the host pipeline genuinely multi-threaded (actor services, a
learner drain loop, the telemetry sampler, the AOT warmup thread) and
the single-threaded passes were blind to both bugs it hit. This module
derives, from `ast` alone, the facts the concurrency checks in
`analysis/concurrency.py` need:

- **Thread entry points** — every `threading.Thread(target=...)` spawn
  site, with the target resolved to a method of the enclosing class
  (`target=self._run`), a module-level function (`target=loop`), or a
  method of a locally constructed repo class (`svc = Service(...);
  Thread(target=svc.run)`). The role name comes from the `name=` kwarg
  when it is a readable constant/f-string head, else the target name.
- **Per-class roles** — for each class that spawns a thread onto one of
  its own methods: the transitive intra-class closure of each target
  method runs on that thread's role; every other method runs on the
  "caller" role (whatever thread holds the instance — for the classes
  this repo grew in PR 6, the learner/main thread).
- **Lock inventory** — attributes assigned from
  `threading.Lock/RLock/Condition/Semaphore/BoundedSemaphore`, plus
  module-level locks, so checks can decide whether an access happens
  under a held `with self._lock:` context.
- **Threaded modules** — any module importing `threading`: the scope of
  the module-global discipline (a module that reaches for threads is
  declaring its globals may be shared).
- **`# jaxlint: thread-owned=<role>` annotations** — the audited escape
  hatch: an attribute (or module global) whose compound mutations are
  all issued by one role, with readers that tolerate staleness, carries
  the annotation on a line that assigns it; the checks then skip it.
  Like suppressions, the why belongs in the same comment.

Documented model assumptions (README "Static analysis"):

1. Plain reference assignment (`self.x = value`, `GLOBAL = value`) and
   plain reads are treated as GIL-atomic and never flagged; the hazard
   class is COMPOUND mutation — `+=`, container method mutation
   (`append`/`pop`/`update`/...), and subscript stores — whose
   read-modify-write window interleaves.
2. Attribute accesses are analyzed within the owning class; mutation of
   `obj.attr` from outside the class is out of model (the passes are
   AST-only and do not infer types across call boundaries).
3. A method reached from no thread-target closure runs on the "caller"
   role. `__init__` is pre-publication (happens-before `Thread.start`)
   and exempt.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from actor_critic_tpu.analysis.core import ModuleInfo, target_names

CALLER_ROLE = "caller"

_LOCK_CONSTRUCTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}

# Container-mutating method names: calling one of these on an attribute
# or module-global is a compound write to the container.
MUTATING_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort", "reverse",
}


@dataclasses.dataclass
class SpawnSite:
    """One `threading.Thread(target=...)` call."""

    module: str           # relpath of the spawning module
    lineno: int
    role: str             # thread name head or target name
    target_class: Optional[str] = None  # class whose method is the target
    target_method: Optional[str] = None
    target_function: Optional[str] = None  # module-level function target


@dataclasses.dataclass
class ClassModel:
    """Concurrency-relevant facts about one class."""

    name: str
    module: str           # relpath
    node: ast.ClassDef
    lock_attrs: set[str] = dataclasses.field(default_factory=set)
    # method name -> set of self-method names it calls (intra-class)
    calls: dict[str, set[str]] = dataclasses.field(default_factory=dict)
    # role -> set of method names running under it (thread closures)
    thread_methods: dict[str, set[str]] = dataclasses.field(
        default_factory=dict
    )
    # attr name -> owning role, from thread-owned annotations
    owned_attrs: dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def threaded(self) -> bool:
        return bool(self.thread_methods)

    def methods(self) -> dict[str, ast.AST]:
        return {
            n.name: n
            for n in self.node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

    def roles_of(self, method: str) -> set[str]:
        """Roles that can execute `method`: each thread whose target
        closure contains it, plus the caller role for anything public
        or outside every closure (a private closure-only helper runs
        exclusively on its thread)."""
        roles = {
            role
            for role, members in self.thread_methods.items()
            if method in members
        }
        if not roles or not method.startswith("_"):
            roles.add(CALLER_ROLE)
        return roles


class ThreadModel:
    """The repo-wide model: spawn sites, per-class facts, threaded
    modules, module-level locks, and thread-owned globals."""

    def __init__(self, modules: list[ModuleInfo]):
        self.spawns: list[SpawnSite] = []
        self.classes: dict[tuple[str, str], ClassModel] = {}
        self.threaded_modules: set[str] = set()
        # relpath -> set of module-global lock names
        self.module_locks: dict[str, set[str]] = {}
        # (relpath, global name) -> owning role
        self.owned_globals: dict[tuple[str, str], str] = {}
        for mod in modules:
            self._scan_module(mod)
        self._resolve_spawns(modules)

    # -- per-module facts --------------------------------------------------

    def _scan_module(self, mod: ModuleInfo) -> None:
        if any(
            root == "threading"
            for root in (
                v.split(".")[0] for v in mod.aliases.values()
            )
        ):
            self.threaded_modules.add(mod.relpath)
        locks = {
            name
            for stmt in mod.tree.body
            if isinstance(stmt, ast.Assign)
            and _is_lock_call(mod, stmt.value)
            for tgt in stmt.targets
            for name in target_names(tgt)
        }
        if locks:
            self.module_locks[mod.relpath] = locks
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                self.classes[(mod.relpath, node.name)] = self._scan_class(
                    mod, node
                )
        self._scan_owned(mod)

    def _scan_class(self, mod: ModuleInfo, node: ast.ClassDef) -> ClassModel:
        cm = ClassModel(name=node.name, module=mod.relpath, node=node)
        for name, fn in cm.methods().items():
            callees: set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) and _is_lock_call(
                    mod, sub.value
                ):
                    for tgt in sub.targets:
                        attr = self_attr(tgt)
                        if attr:
                            cm.lock_attrs.add(attr)
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "self"
                ):
                    callees.add(sub.func.attr)
            cm.calls[name] = callees
        return cm

    def _scan_owned(self, mod: ModuleInfo) -> None:
        """Resolve thread-owned annotation lines (collected by
        ModuleInfo) to class attributes / module globals: the annotated
        line's statement (or, for a standalone comment, the next
        statement) must assign the attribute or global it covers."""
        for lineno, role in mod.thread_owned.items():
            stmt = _stmt_at(mod, lineno)
            if stmt is None:
                continue
            targets: list[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                targets = [stmt.target]
            for tgt in targets:
                attr = self_attr(tgt)
                if attr is not None:
                    cls = self._enclosing_class(mod, stmt)
                    if cls is not None:
                        cls.owned_attrs[attr] = role
                    continue
                base = tgt
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name):
                    scope = mod.scope_of(stmt)
                    if isinstance(scope, ast.Module) or _declared_global(
                        scope, base.id
                    ):
                        self.owned_globals[(mod.relpath, base.id)] = role

    def _enclosing_class(
        self, mod: ModuleInfo, node: ast.AST
    ) -> Optional[ClassModel]:
        for anc in mod.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return self.classes.get((mod.relpath, anc.name))
        return None

    # -- spawn resolution --------------------------------------------------

    def _resolve_spawns(self, modules: list[ModuleInfo]) -> None:
        for mod in modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                if mod.dotted(node.func) != "threading.Thread":
                    continue
                target = next(
                    (k.value for k in node.keywords if k.arg == "target"),
                    None,
                )
                if target is None:
                    continue
                site = SpawnSite(
                    module=mod.relpath,
                    lineno=node.lineno,
                    role=_role_name(node, target),
                )
                attr = self_attr(target)
                if attr is not None:
                    cls = self._enclosing_class(mod, node)
                    if cls is not None:
                        site.target_class = cls.name
                        site.target_method = attr
                        cls.thread_methods[site.role] = _closure(cls, attr)
                elif isinstance(target, ast.Name):
                    site.target_function = target.id
                elif isinstance(target, ast.Attribute) and isinstance(
                    target.value, ast.Name
                ):
                    # Thread(target=svc.run) on a locally constructed
                    # repo class: resolve svc through the enclosing
                    # scope's assignments.
                    cls = self._local_instance_class(
                        mod, node, target.value.id
                    )
                    if cls is not None:
                        site.target_class = cls.name
                        site.target_method = target.attr
                        cls.thread_methods[site.role] = _closure(
                            cls, target.attr
                        )
                self.spawns.append(site)

    def _local_instance_class(
        self, mod: ModuleInfo, at: ast.AST, name: str
    ) -> Optional[ClassModel]:
        scope = mod.scope_of(at)
        cls: Optional[ClassModel] = None
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            if not any(name in target_names(t) for t in node.targets):
                continue
            if isinstance(node.value, ast.Call) and isinstance(
                node.value.func, ast.Name
            ):
                cls = self.classes.get((mod.relpath, node.value.func.id))
        return cls

    # -- queries the checks use --------------------------------------------

    def class_model(
        self, mod: ModuleInfo, node: ast.AST
    ) -> Optional[ClassModel]:
        return self._enclosing_class(mod, node)

    def is_threaded_module(self, mod: ModuleInfo) -> bool:
        return mod.relpath in self.threaded_modules


def _closure(cls: ClassModel, method: str) -> set[str]:
    """`method` plus every self-method transitively called from it."""
    seen: set[str] = set()
    frontier = [method]
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        frontier.extend(cls.calls.get(m, ()))
    return seen


def _is_lock_call(mod: ModuleInfo, value: ast.AST) -> bool:
    return (
        isinstance(value, ast.Call)
        and mod.dotted(value.func) in _LOCK_CONSTRUCTORS
    )


def self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _declared_global(scope: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Global) and name in n.names
        for n in ast.walk(scope)
    )


_COMPOUND_STMTS = (
    ast.If, ast.For, ast.AsyncFor, ast.While, ast.With, ast.AsyncWith,
    ast.Try, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
)


def _stmt_at(mod: ModuleInfo, lineno: int) -> Optional[ast.stmt]:
    """The SIMPLE statement whose line span covers `lineno` (a trailing
    annotation), or — when the line is a standalone comment, which every
    enclosing compound statement's span covers but no simple one does —
    the next simple statement after it (mirrors the
    standalone-suppression rule in core.ModuleInfo)."""
    best: Optional[ast.stmt] = None
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.stmt) or isinstance(
            node, _COMPOUND_STMTS
        ):
            continue
        if node.lineno <= lineno <= (node.end_lineno or node.lineno):
            if best is None or node.lineno >= best.lineno:
                best = node
    if best is not None:
        return best
    after = [
        n
        for n in ast.walk(mod.tree)
        if isinstance(n, ast.stmt)
        and not isinstance(n, _COMPOUND_STMTS)
        and n.lineno > lineno
    ]
    return min(after, key=lambda n: n.lineno) if after else None


def _role_name(call: ast.Call, target: ast.AST) -> str:
    """Thread role: the `name=` kwarg's readable head (constant, or the
    leading literal of an f-string like f"actor-{i}") when present, else
    the target's terminal name."""
    for k in call.keywords:
        if k.arg != "name":
            continue
        v = k.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return _trim_role(v.value)
        if isinstance(v, ast.JoinedStr) and v.values:
            head = v.values[0]
            if isinstance(head, ast.Constant) and isinstance(
                head.value, str
            ):
                return _trim_role(head.value)
    if isinstance(target, ast.Attribute):
        return target.attr
    if isinstance(target, ast.Name):
        return target.id
    return "thread"


def _trim_role(name: str) -> str:
    return name.strip().strip("-_ ") or "thread"
