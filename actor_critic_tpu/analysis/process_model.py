"""Whole-repo process model for the distributed passes (ISSUE 12).

PR 9 made the repo multi-PROCESS (sync shard_map collectives + a
gossip file mailbox) and PR 10 made it outward-facing, but the analysis
layer still reasoned about one process: the thread model
(`analysis/thread_model.py`) resolves `threading.Thread` spawns, not
ranks. This module derives, from `ast` alone, the facts the
distributed checks in `analysis/distributed.py` need:

- **Collective sites** — every call that the WHOLE fleet must reach
  together: the in-program collective primitives (`jax.lax.psum`/
  `pmean`/`pmax`/`pmin`/`all_gather`/`ppermute`/...), the host-side
  cross-process staging ops (`jax.make_array_from_process_local_data`,
  `multihost_utils.*`, `jax.distributed.initialize`), and calls to repo
  functions whose bodies transitively contain either (resolved through
  imports and through locals assigned from collective-building
  factories, so `check = make_consistency_check(mesh); ...; check(v)`
  counts at the `check(v)` call site).
- **Axis inventory** — mesh-axis names DECLARED by `jax.make_mesh`/
  `Mesh` axis tuples (module string constants resolved, e.g.
  `DP_AXIS = "dp"`), versus names USED at collective call sites and in
  `PartitionSpec(...)` entries. A used name no declaration covers is a
  lowering error at best and a silently wrong reduction at worst.
- **Process-local taint** — per-scope name sets whose values differ
  across ranks: parameters named `rank`/`process_id`/..., reads of
  rank-named attributes (`args.process_id`, `self._rank`), wall-clock
  and pid calls (`time.monotonic`, `os.getpid`, `jax.process_index`),
  and queue-depth probes — propagated through assignments to fixpoint.
  A collective inside a branch keyed on tainted state desyncs the
  fleet into a deadlock (rank 3 skips the psum the others sit in).
- **Mailbox shapes** — path-builder functions (a module-level def whose
  return is a pure `os.path.join`/f-string of its args), the producer
  sites that open builder paths for writing, the `os.replace` publish
  sites, and the consumer sites (`np.load`/`json.load`/read-mode
  `open`) with their enclosing `try` handler exception lists — the
  facts the atomic write→fsync→rename and torn-read rules consume.
- **Distributed scopes** — functions that demonstrably run per-rank: a
  `rank`/`process_id` parameter, a `jax.process_index()` read, a
  `distributed_init` call, or a read of a `.distributed` flag. Shared
  artifact paths written from such a scope must be parameterized by the
  rank or every host clobbers the same file.

Like the thread model, everything here is stdlib `ast` over source
text — nothing scanned is imported, so the passes stay tier-1-cheap.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Optional

from actor_critic_tpu.analysis.core import ModuleInfo, target_names

# In-program collective primitives: every mapped process/device must
# execute these in the same order or the program deadlocks.
COLLECTIVE_PRIMS = {
    "jax.lax.psum",
    "jax.lax.pmean",
    "jax.lax.pmax",
    "jax.lax.pmin",
    "jax.lax.all_gather",
    "jax.lax.ppermute",
    "jax.lax.all_to_all",
    "jax.lax.psum_scatter",
}

# Host-side cross-process operations: multi-controller jax requires all
# processes to reach these together (they stage/commit global arrays or
# join the cluster), so they join the process-local-gating rule — but
# NOT the try-divergence rule, where designed single-process fallbacks
# (mesh.multihost_init's compat path) are legitimate.
CROSS_PROCESS_OPS = {
    "jax.make_array_from_process_local_data",
    "jax.experimental.multihost_utils.host_local_array_to_global_array",
    "jax.experimental.multihost_utils.global_array_to_host_local_array",
    "jax.experimental.multihost_utils.process_allgather",
    "jax.experimental.multihost_utils.sync_global_devices",
    "jax.distributed.initialize",
}

# Mesh/axis declaration constructors: their axis-names argument DECLARES
# the names collectives may reduce over.
_MESH_CALLS = {"jax.make_mesh", "jax.sharding.Mesh", "Mesh"}

# Parameter/attribute names whose VALUE differs per process.
RANK_NAMES = {
    "rank", "process_id", "process_index", "local_rank", "host_id",
}

# Calls whose result is process-local (wall clock, pid, rank).
PROCESS_LOCAL_CALLS = {
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "time.process_time",
    "os.getpid",
    "jax.process_index",
    "socket.gethostname",
}

# Zero-arg methods probing process-local runtime state (queue depth).
PROCESS_LOCAL_METHODS = {"qsize", "queue_depth"}

# Torn/partial-file exception classes per consumer kind: a handler that
# names none of these (nor a bare/blanket Exception) dies on the first
# torn read instead of tolerating it.
TORN_EXC_NPZ = {"BadZipFile", "EOFError", "Exception", "BaseException"}
TORN_EXC_JSON = {
    "JSONDecodeError", "ValueError", "Exception", "BaseException",
}


def _call_name(node: ast.Call) -> Optional[str]:
    """Terminal callable name: `TelemetrySession(...)` -> that, also for
    attribute calls (`telemetry.TelemetrySession(...)`)."""
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


# ---------------------------------------------------------------------------
# axis inventory
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AxisUse:
    """One axis name consumed at a collective / PartitionSpec site."""

    module: str
    node: ast.AST
    name: str
    where: str  # "collective" | "spec"


class AxisInventory:
    def __init__(self) -> None:
        self.declared: set[str] = set()
        # bare constant name -> string value, repo-wide ("DP_AXIS"->"dp")
        self.consts: dict[str, str] = {}
        self.uses: list[AxisUse] = []

    def resolve(self, mod: ModuleInfo, expr: ast.AST):
        """Axis-name expression -> str | tuple[str, ...] | None (None =
        not statically resolvable: a parameter, a computed name)."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            return self.consts.get(expr.id)
        if isinstance(expr, ast.Attribute):
            return self.consts.get(expr.attr)
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = tuple(self.resolve(mod, e) for e in expr.elts)
            if all(isinstance(v, str) for v in out):
                return out
            return None
        return None


# ---------------------------------------------------------------------------
# collective sites + the performing-function closure
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CollectiveSite:
    """One call the whole fleet must reach together."""

    module: str
    node: ast.Call
    desc: str  # human-readable ("jax.lax.psum", "check (collective-performing)")
    kind: str  # "prim" | "cross-process" | "derived"
    axis_arg: Optional[ast.AST] = None  # prim sites: the axis expression


def _axis_expr(call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg in ("axis_name", "axis_names"):
            return kw.value
    if len(call.args) >= 2:
        return call.args[1]
    return None


# ---------------------------------------------------------------------------
# mailbox shapes
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ProducerSite:
    """One open-for-write of a file later published (or not) in scope."""

    module: str
    open_call: ast.Call
    path_expr: ast.AST
    scope: ast.AST
    replace_call: Optional[ast.Call] = None  # os.replace/os.rename in scope
    has_fsync: bool = False
    writes_builder_path: bool = False  # final (consumed) path written directly


@dataclasses.dataclass
class ConsumerSite:
    """One parse of a shared file (np.load / json.load / read-open)."""

    module: str
    call: ast.Call
    kind: str  # "npz" | "json"
    handler_names: Optional[set[str]] = None  # None = not inside a try


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------


class ProcessModel:
    """The repo-wide model the distributed checks consult."""

    def __init__(self, modules: list[ModuleInfo]):
        self.axes = AxisInventory()
        # Executor defs EXECUTE a collective when called: a prim /
        # cross-process call (or a call to another executor) sits in
        # their own body, outside any nested def. Factory defs only
        # BUILD collective programs (the prims live in nested defs /
        # called factories): calling a factory communicates nothing,
        # but calling the object a factory returned does — that is the
        # `check = make_consistency_check(mesh); ...; check(v)` shape.
        # Cross-module resolution works on terminal names (unique
        # enough at repo scale).
        self._executor_names: set[str] = set()
        self._factory_names: set[str] = set()
        self.collective_sites: dict[str, list[CollectiveSite]] = {}
        self.producers: dict[str, list[ProducerSite]] = {}
        self.consumers: dict[str, list[ConsumerSite]] = {}
        # relpath -> path-builder function names defined there
        self.path_builders: dict[str, set[str]] = {}
        self._modules = modules
        self._scan_consts(modules)
        self._scan_axes(modules)
        self._close_performing(modules)
        for mod in modules:
            self.collective_sites[mod.relpath] = self._sites_in(mod)
            self.producers[mod.relpath] = self._producers_in(mod)
            self.consumers[mod.relpath] = self._consumers_in(mod)

    # -- constants + axis declarations --------------------------------------

    def _scan_consts(self, modules: list[ModuleInfo]) -> None:
        for mod in modules:
            for stmt in mod.tree.body:
                if not isinstance(stmt, ast.Assign):
                    continue
                if not (
                    isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)
                ):
                    continue
                for tgt in stmt.targets:
                    for name in target_names(tgt):
                        self.axes.consts[name] = stmt.value.value

    def _scan_axes(self, modules: list[ModuleInfo]) -> None:
        for mod in modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = mod.dotted(node.func)
                name = _call_name(node)
                if dotted in _MESH_CALLS or name == "Mesh" or (
                    name == "make_mesh"
                ):
                    arg = None
                    for kw in node.keywords:
                        if kw.arg == "axis_names":
                            arg = kw.value
                    if arg is None and len(node.args) >= 2:
                        arg = node.args[1]
                    if arg is not None:
                        resolved = self.axes.resolve(mod, arg)
                        if isinstance(resolved, str):
                            self.axes.declared.add(resolved)
                        elif isinstance(resolved, tuple):
                            self.axes.declared.update(resolved)
                elif name in ("PartitionSpec", "P"):
                    for arg in node.args:
                        resolved = self.axes.resolve(mod, arg)
                        if isinstance(resolved, str):
                            self.axes.uses.append(
                                AxisUse(mod.relpath, arg, resolved, "spec")
                            )
                        elif isinstance(resolved, tuple):
                            for v in resolved:
                                self.axes.uses.append(
                                    AxisUse(mod.relpath, arg, v, "spec")
                                )

    # -- performing closure --------------------------------------------------

    def _direct_collective(self, mod: ModuleInfo, call: ast.Call) -> bool:
        dotted = mod.dotted(call.func)
        return dotted in COLLECTIVE_PRIMS or dotted in CROSS_PROCESS_OPS

    def _close_performing(self, modules: list[ModuleInfo]) -> None:
        """Split the repo's module-level defs into collective EXECUTORS
        and collective FACTORIES (class docstring), each closed to
        fixpoint over terminal-name call resolution (`from x import f`
        and `mod.f(...)` both reach an `f` defined anywhere in the scan
        set)."""
        defs: dict[tuple[str, str], ast.AST] = {}
        for mod in modules:
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defs[(mod.relpath, node.name)] = node
        by_mod = {m.relpath: m for m in modules}

        def direct_calls(fn: ast.AST):
            """Calls in fn's own body, nested defs excluded."""
            nested = [
                n
                for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            ]
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                if any(_contains(inner, sub) for inner in nested):
                    continue
                yield sub

        changed = True
        while changed:
            changed = False
            for (relpath, fname), fn in defs.items():
                mod = by_mod[relpath]
                if fname not in self._executor_names:
                    hit = any(
                        self._direct_collective(mod, sub)
                        or _call_name(sub) in self._executor_names
                        for sub in direct_calls(fn)
                    )
                    if hit:
                        self._executor_names.add(fname)
                        changed = True
                if fname not in self._factory_names and (
                    fname not in self._executor_names
                ):
                    hit = any(
                        isinstance(sub, ast.Call)
                        and (
                            self._direct_collective(mod, sub)
                            or _call_name(sub) in self._executor_names
                            or _call_name(sub) in self._factory_names
                        )
                        for sub in ast.walk(fn)
                    )
                    if hit:
                        self._factory_names.add(fname)
                        changed = True

    # -- collective sites ----------------------------------------------------

    def _sites_in(self, mod: ModuleInfo) -> list[CollectiveSite]:
        # locals assigned from a call to a collective FACTORY: calling
        # the local is a collective site (check = make_consistency_
        # check(mesh); check(v)) — calling the factory itself is not.
        derived: dict[ast.AST, set[str]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Call)
                and _call_name(node.value) in self._factory_names
            ):
                continue
            scope = mod.scope_of(node)
            for tgt in node.targets:
                derived.setdefault(scope, set()).update(target_names(tgt))
        sites: list[CollectiveSite] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func)
            if dotted in COLLECTIVE_PRIMS:
                sites.append(
                    CollectiveSite(
                        mod.relpath, node, dotted, "prim",
                        axis_arg=_axis_expr(node),
                    )
                )
                continue
            if dotted in CROSS_PROCESS_OPS:
                sites.append(
                    CollectiveSite(mod.relpath, node, dotted, "cross-process")
                )
                continue
            cname = _call_name(node)
            if cname in self._executor_names:
                sites.append(
                    CollectiveSite(
                        mod.relpath, node,
                        f"{cname} (collective-performing)", "derived",
                    )
                )
            elif isinstance(node.func, ast.Name) and node.func.id in (
                derived.get(mod.scope_of(node), set())
            ):
                sites.append(
                    CollectiveSite(
                        mod.relpath, node,
                        f"{node.func.id} (built by a collective factory)",
                        "derived",
                    )
                )
        return sites

    # -- process-local taint -------------------------------------------------

    def process_local_names(self, mod: ModuleInfo, scope: ast.AST) -> set[str]:
        """Names in `scope` carrying per-process values, to fixpoint
        through plain assignments (2 passes cover the chains flagged)."""
        tainted: set[str] = set()
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = scope.args
            for a in (
                args.posonlyargs + args.args + args.kwonlyargs
                + ([args.vararg] if args.vararg else [])
                + ([args.kwarg] if args.kwarg else [])
            ):
                if a.arg in RANK_NAMES:
                    tainted.add(a.arg)
        for _ in range(2):
            for node in ast.walk(scope):
                if not isinstance(node, ast.Assign):
                    continue
                if self.expr_process_local(mod, node.value, tainted):
                    for tgt in node.targets:
                        tainted.update(target_names(tgt))
        return tainted

    def expr_process_local(
        self, mod: ModuleInfo, expr: ast.AST, tainted: Iterable[str]
    ) -> bool:
        """Whether evaluating `expr` reads per-process state."""
        tainted = set(tainted)
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
            if isinstance(sub, ast.Attribute) and (
                sub.attr in RANK_NAMES or sub.attr.lstrip("_") in RANK_NAMES
            ):
                return True
            if isinstance(sub, ast.Call):
                if mod.dotted(sub.func) in PROCESS_LOCAL_CALLS:
                    return True
                if (
                    isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in PROCESS_LOCAL_METHODS
                ):
                    return True
        return False

    # -- mailbox shapes ------------------------------------------------------

    def _builders_in(self, mod: ModuleInfo) -> set[str]:
        """Module-level defs whose every return is a pure path
        construction (os.path.join / f-string / str concat) — the shared
        protocol-path builders producers and consumers both call."""
        out: set[str] = set()
        for node in mod.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            returns = [
                s for s in ast.walk(node) if isinstance(s, ast.Return)
            ]
            if not returns:
                continue
            if all(
                r.value is not None and _is_path_expr(mod, r.value)
                for r in returns
            ):
                out.add(node.name)
        return out

    def _producers_in(self, mod: ModuleInfo) -> list[ProducerSite]:
        builders = self.path_builders.setdefault(
            mod.relpath, self._builders_in(mod)
        )
        all_builders = set(builders)
        for names in self.path_builders.values():
            all_builders |= names
        sites: list[ProducerSite] = []
        per_scope: dict[ast.AST, list[ProducerSite]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (
                isinstance(node.func, ast.Name) and node.func.id == "open"
                or mod.dotted(node.func) == "builtins.open"
            ):
                continue
            mode = None
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if not (isinstance(mode, str) and ("w" in mode or "x" in mode)):
                continue
            path_expr = node.args[0] if node.args else None
            if path_expr is None:
                continue
            scope = mod.scope_of(node)
            site = ProducerSite(mod.relpath, node, path_expr, scope)
            site.writes_builder_path = _expr_from_builder(
                mod, scope, path_expr, all_builders
            )
            sites.append(site)
            per_scope.setdefault(scope, []).append(site)
        for scope, scoped in per_scope.items():
            replace = None
            fsync = False
            for node in ast.walk(scope):
                if isinstance(node, ast.Call):
                    dotted = mod.dotted(node.func)
                    if dotted in ("os.replace", "os.rename"):
                        replace = node
                    if dotted == "os.fsync" or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "fsync"
                    ):
                        fsync = True
            for site in scoped:
                site.replace_call = replace
                site.has_fsync = fsync
        return sites

    def _consumers_in(self, mod: ModuleInfo) -> list[ConsumerSite]:
        sites: list[ConsumerSite] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func)
            kind = None
            if dotted == "numpy.load":
                kind = "npz"
            elif dotted in ("json.load", "json.loads"):
                kind = "json"
            if kind is None:
                continue
            handler_names: Optional[set[str]] = None
            for anc in mod.ancestors(node):
                if isinstance(anc, ast.Try):
                    in_body = any(
                        _contains(stmt, node) for stmt in anc.body
                    )
                    if in_body and anc.handlers:
                        handler_names = set()
                        for h in anc.handlers:
                            handler_names |= _handler_exc_names(h)
                        break
            sites.append(
                ConsumerSite(mod.relpath, node, kind, handler_names)
            )
        return sites

    # -- distributed scopes --------------------------------------------------

    def distributed_scope(self, mod: ModuleInfo, scope: ast.AST) -> bool:
        """Whether `scope` demonstrably runs once PER RANK of a fleet: a
        rank-named parameter, a `jax.process_index()` read, a
        `distributed_init`/`jax.distributed.initialize` call, or a read
        of a `.distributed` flag (train.py's `args.distributed`)."""
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = scope.args
            for a in args.posonlyargs + args.args + args.kwonlyargs:
                if a.arg in RANK_NAMES:
                    return True
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                dotted = mod.dotted(node.func)
                if dotted in (
                    "jax.process_index", "jax.distributed.initialize"
                ):
                    return True
                if _call_name(node) in (
                    "distributed_init", "multihost_init"
                ):
                    return True
            if isinstance(node, ast.Attribute) and node.attr == "distributed":
                return True
        return False


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(sub is target for sub in ast.walk(root))


def _handler_exc_names(handler: ast.ExceptHandler) -> set[str]:
    """Exception class terminal names a handler catches; a bare
    `except:` reads as catching everything."""
    if handler.type is None:
        return {"BaseException"}
    out: set[str] = set()
    types = (
        handler.type.elts
        if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, ast.Attribute):
            out.add(t.attr)
    return out


def _is_path_expr(mod: ModuleInfo, expr: ast.AST) -> bool:
    if isinstance(expr, ast.Call):
        return mod.dotted(expr.func) in (
            "os.path.join", "pathlib.Path", "os.path.abspath",
        )
    if isinstance(expr, ast.JoinedStr):
        return True
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _is_path_expr(mod, expr.left) or _is_path_expr(mod, expr.right)
    return False


def _expr_from_builder(
    mod: ModuleInfo, scope: ast.AST, expr: ast.AST, builders: set[str]
) -> bool:
    """Whether `expr` IS (or is a name last assigned from) a call to a
    shared path-builder — i.e. the final consumed path, not a tmp."""
    if isinstance(expr, ast.Call) and _call_name(expr) in builders:
        return True
    if isinstance(expr, ast.Name):
        latest: Optional[ast.AST] = None
        latest_line = -1
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            if node.lineno >= expr.lineno:
                continue
            if any(expr.id in target_names(t) for t in node.targets):
                if node.lineno > latest_line:
                    latest, latest_line = node.value, node.lineno
        if latest is not None:
            return isinstance(latest, ast.Call) and (
                _call_name(latest) in builders
            )
    return False


def rank_parameterized(
    mod: ModuleInfo, scope: ast.AST, expr: ast.AST, depth: int = 2
) -> bool:
    """Whether a path expression is parameterized by the process
    identity: the expression (resolving Names through their latest
    in-scope assignment, `depth` hops) mentions a rank-named
    name/attribute, `os.getpid()`, or passes a rank-named value into a
    builder call (`params_file(dir, rank)`)."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and (
            sub.id in RANK_NAMES or sub.id.lstrip("_") in RANK_NAMES
        ):
            return True
        if isinstance(sub, ast.Attribute) and (
            sub.attr in RANK_NAMES or sub.attr.lstrip("_") in RANK_NAMES
        ):
            return True
        if isinstance(sub, ast.Call) and mod.dotted(sub.func) in (
            "os.getpid", "uuid.uuid4", "tempfile.mkstemp",
            "tempfile.mkdtemp",
        ):
            return True
    if depth <= 0:
        return False
    # Resolve Name (and attribute, e.g. the `args.telemetry_dir`
    # rebind train.py's --distributed path does) reads one hop through
    # their latest prior in-scope assignment.
    for sub in ast.walk(expr):
        matches = None
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            matches = lambda t, s=sub: s.id in target_names(t)  # noqa: E731
        elif isinstance(sub, ast.Attribute) and isinstance(
            sub.value, ast.Name
        ):
            matches = lambda t, s=sub: (  # noqa: E731
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == s.value.id
                and t.attr == s.attr
            )
        if matches is None:
            continue
        latest: Optional[ast.AST] = None
        latest_line = -1
        for node in ast.walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            if node.lineno >= expr.lineno:
                continue
            if any(matches(t) for t in node.targets):
                if node.lineno > latest_line:
                    latest, latest_line = node.value, node.lineno
        if latest is not None and rank_parameterized(
            mod, scope, latest, depth - 1
        ):
            return True
    return False
