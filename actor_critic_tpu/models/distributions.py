"""Action distributions as lightweight pytrees.

Covers the reference's policy heads (BASELINE.json:7-11; reference mount
empty at survey, SURVEY.md §0): categorical for discrete control (A2C
CartPole, IMPALA Pong), diagonal Gaussian for continuous control (PPO
HalfCheetah), and tanh-squashed Gaussian for SAC.

Design notes (TPU-first):
- Each distribution is a NamedTuple → automatically a JAX pytree, so it
  flows through `jit` / `vmap` / `lax.scan` carries without wrappers.
- All math is elementwise + reductions over the event axis: XLA fuses it
  into the surrounding matmuls; nothing here warrants a Pallas kernel.
- Tanh-Gaussian log-probs use the softplus-stable change-of-variables
  (no `log(1 - tanh(x)^2)`), and `log_prob(action)` clips the pre-atanh
  action away from ±1 (SURVEY.md §7.2 item 5).
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

_LOG_2PI = math.log(2.0 * math.pi)
# Clip log-std into a sane range (SAC-style) so exp() never over/underflows.
LOG_STD_MIN = -20.0
LOG_STD_MAX = 2.0


class Categorical(NamedTuple):
    """Categorical distribution over discrete actions, parameterised by logits.

    `logits` has shape [..., num_actions]; the trailing axis is the event axis.
    """

    logits: jax.Array

    @property
    def log_probs(self) -> jax.Array:
        return jax.nn.log_softmax(self.logits, axis=-1)

    def sample(self, key: jax.Array) -> jax.Array:
        return jax.random.categorical(key, self.logits, axis=-1)

    def log_prob(self, action: jax.Array) -> jax.Array:
        lp = self.log_probs
        return jnp.take_along_axis(lp, action[..., None].astype(jnp.int32), axis=-1)[
            ..., 0
        ]

    def entropy(self) -> jax.Array:
        lp = self.log_probs
        p = jnp.exp(lp)
        return -jnp.sum(p * lp, axis=-1)

    def mode(self) -> jax.Array:
        return jnp.argmax(self.logits, axis=-1)

    def kl(self, other: "Categorical") -> jax.Array:
        lp, lq = self.log_probs, other.log_probs
        return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)


class DiagGaussian(NamedTuple):
    """Diagonal Gaussian over continuous actions.

    `mean` and `log_std` have shape [..., action_dim]; log-prob / entropy
    reduce over the trailing event axis.
    """

    mean: jax.Array
    log_std: jax.Array

    @property
    def std(self) -> jax.Array:
        return jnp.exp(self.log_std)

    def sample(self, key: jax.Array) -> jax.Array:
        eps = jax.random.normal(key, self.mean.shape, self.mean.dtype)
        return self.mean + self.std * eps

    def log_prob(self, action: jax.Array) -> jax.Array:
        z = (action - self.mean) / self.std
        per_dim = -0.5 * (z * z + _LOG_2PI) - self.log_std
        return jnp.sum(per_dim, axis=-1)

    def entropy(self) -> jax.Array:
        return jnp.sum(self.log_std + 0.5 * (_LOG_2PI + 1.0), axis=-1)

    def mode(self) -> jax.Array:
        return self.mean

    def kl(self, other: "DiagGaussian") -> jax.Array:
        var, ovar = jnp.exp(2 * self.log_std), jnp.exp(2 * other.log_std)
        per_dim = (
            other.log_std
            - self.log_std
            + (var + (self.mean - other.mean) ** 2) / (2.0 * ovar)
            - 0.5
        )
        return jnp.sum(per_dim, axis=-1)


def _tanh_log_det_jacobian(pre_tanh: jax.Array) -> jax.Array:
    """log |d tanh(x)/dx| = log(1 - tanh(x)^2), computed stably.

    Uses the identity log(1 - tanh(x)^2) = 2*(log 2 - x - softplus(-2x)),
    which never evaluates log(0) for large |x|.
    """
    return 2.0 * (math.log(2.0) - pre_tanh - jax.nn.softplus(-2.0 * pre_tanh))


class TanhGaussian(NamedTuple):
    """Tanh-squashed diagonal Gaussian (SAC actor; BASELINE.json:10).

    Actions live in (-1, 1)^d. `log_std` is clipped to
    [LOG_STD_MIN, LOG_STD_MAX] at construction time by `create`.
    """

    mean: jax.Array
    log_std: jax.Array

    @classmethod
    def create(cls, mean: jax.Array, log_std: jax.Array) -> "TanhGaussian":
        return cls(mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))

    @property
    def base(self) -> DiagGaussian:
        return DiagGaussian(self.mean, self.log_std)

    def sample_and_log_prob(self, key: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Reparameterised sample with its log-prob (the SAC hot path)."""
        pre = self.base.sample(key)
        action = jnp.tanh(pre)
        logp = self.base.log_prob(pre) - jnp.sum(
            _tanh_log_det_jacobian(pre), axis=-1
        )
        return action, logp

    def sample(self, key: jax.Array) -> jax.Array:
        return jnp.tanh(self.base.sample(key))

    def log_prob(
        self, action: jax.Array, pre_tanh: Optional[jax.Array] = None
    ) -> jax.Array:
        """Log-prob of a squashed action.

        Prefer passing `pre_tanh` when available (e.g. stored at sampling
        time); otherwise the action is clipped to ±(1-1e-6) before atanh
        for numerical safety (SURVEY.md §7.2 item 5).
        """
        if pre_tanh is None:
            clipped = jnp.clip(action, -1.0 + 1e-6, 1.0 - 1e-6)
            pre_tanh = jnp.arctanh(clipped)
        return self.base.log_prob(pre_tanh) - jnp.sum(
            _tanh_log_det_jacobian(pre_tanh), axis=-1
        )

    def mode(self) -> jax.Array:
        return jnp.tanh(self.mean)
