"""Flax linen encoders and actor-critic networks.

Covers the reference's "shared policy/value MLP-and-CNN encoders"
(BASELINE.json:5; reference mount empty at survey, SURVEY.md §0) and the
per-algorithm heads: categorical (BASELINE.json:7,11), diagonal Gaussian
(BASELINE.json:8), tanh-Gaussian + twin-Q (BASELINE.json:9-10).

TPU-first design notes:
- Parameters are created in float32; the `compute_dtype` field casts
  activations (bfloat16 on TPU keeps the MXU fed at 2× the flop rate while
  the optimizer state stays fp32). Distribution parameters (logits, mean,
  log_std) and values are cast back to float32 before any log/exp math.
- The CNN is Nature-DQN shaped (stride-4/2/1 convs): XLA lowers these to
  MXU convolutions when channel counts are padded-friendly; at Pong-like
  sizes this is already compute-dense enough without custom kernels.
- Everything is a pure `Module.apply`; no mutable state. Observation
  normalization lives outside the network (envs/normalize.py) so the same
  params work in the fused on-device rollout and the host-env path.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from actor_critic_tpu.models.distributions import (
    Categorical,
    DiagGaussian,
    TanhGaussian,
)

# Orthogonal init is the genre-standard for on-policy PG stability.
ortho = nn.initializers.orthogonal


class MLPTorso(nn.Module):
    """2-layer (default) MLP torso shared by actor & critic heads."""

    hidden: Sequence[int] = (64, 64)
    activation: Callable[[jax.Array], jax.Array] = nn.tanh
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(self.compute_dtype)
        for i, h in enumerate(self.hidden):
            x = nn.Dense(
                h,
                kernel_init=ortho(jnp.sqrt(2.0)),
                bias_init=nn.initializers.zeros,
                dtype=self.compute_dtype,
                name=f"dense_{i}",
            )(x)
            x = self.activation(x)
        return x


class NatureCNN(nn.Module):
    """Nature-DQN conv stack for pixel observations (BASELINE.json:11).

    Expects [..., H, W, C] uint8 or float; uint8 is scaled by 1/255.
    """

    channels: Sequence[int] = (32, 64, 64)
    kernels: Sequence[int] = (8, 4, 3)
    strides: Sequence[int] = (4, 2, 1)
    dense: int = 512
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if x.dtype == jnp.uint8:
            x = x.astype(self.compute_dtype) / 255.0
        else:
            x = x.astype(self.compute_dtype)
        for i, (c, k, s) in enumerate(zip(self.channels, self.kernels, self.strides)):
            x = nn.Conv(
                c,
                (k, k),
                strides=(s, s),
                padding="VALID",
                kernel_init=ortho(jnp.sqrt(2.0)),
                dtype=self.compute_dtype,
                name=f"conv_{i}",
            )(x)
            x = nn.relu(x)
        x = x.reshape(*x.shape[:-3], -1)
        x = nn.Dense(
            self.dense, kernel_init=ortho(jnp.sqrt(2.0)), dtype=self.compute_dtype
        )(x)
        return nn.relu(x)


def _head(out: int, scale: float, dtype, name: str) -> nn.Dense:
    return nn.Dense(
        out,
        kernel_init=ortho(scale),
        bias_init=nn.initializers.zeros,
        dtype=dtype,
        name=name,
    )


class ActorCriticDiscrete(nn.Module):
    """Shared-torso policy+value net for discrete actions (A2C/PPO/IMPALA).

    Returns (Categorical, value[...]) — the reference's shared policy/value
    encoder pattern (BASELINE.json:5,7).
    """

    num_actions: int
    hidden: Sequence[int] = (64, 64)
    pixel_obs: bool = False
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array) -> tuple[Categorical, jax.Array]:
        if self.pixel_obs:
            z = NatureCNN(compute_dtype=self.compute_dtype, name="torso")(obs)
        else:
            z = MLPTorso(self.hidden, compute_dtype=self.compute_dtype, name="torso")(
                obs
            )
        logits = _head(self.num_actions, 0.01, self.compute_dtype, "policy")(z)
        value = _head(1, 1.0, self.compute_dtype, "value")(z)
        return (
            Categorical(logits.astype(jnp.float32)),
            value[..., 0].astype(jnp.float32),
        )


class ActorCriticGaussian(nn.Module):
    """Policy+value net for continuous actions (PPO on MuJoCo).

    Separate torsos for actor and critic (standard for MuJoCo PPO; shared
    torso hurts there), state-independent learned log_std.
    """

    action_dim: int
    hidden: Sequence[int] = (64, 64)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array) -> tuple[DiagGaussian, jax.Array]:
        za = MLPTorso(self.hidden, compute_dtype=self.compute_dtype, name="pi_torso")(
            obs
        )
        zc = MLPTorso(self.hidden, compute_dtype=self.compute_dtype, name="vf_torso")(
            obs
        )
        mean = _head(self.action_dim, 0.01, self.compute_dtype, "policy")(za)
        log_std = self.param(
            "log_std", nn.initializers.zeros, (self.action_dim,), jnp.float32
        )
        value = _head(1, 1.0, self.compute_dtype, "value")(zc)
        mean = mean.astype(jnp.float32)
        return (
            DiagGaussian(mean, jnp.broadcast_to(log_std, mean.shape)),
            value[..., 0].astype(jnp.float32),
        )


class DeterministicActor(nn.Module):
    """DDPG/TD3 actor: tanh-bounded deterministic policy (BASELINE.json:9)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array) -> jax.Array:
        z = MLPTorso(
            self.hidden, activation=nn.relu, compute_dtype=self.compute_dtype,
            name="torso",
        )(obs)
        a = _head(self.action_dim, 0.01, self.compute_dtype, "action")(z)
        return jnp.tanh(a.astype(jnp.float32))


class SquashedGaussianActor(nn.Module):
    """SAC actor: tanh-Gaussian with state-dependent log_std (BASELINE.json:10)."""

    action_dim: int
    hidden: Sequence[int] = (256, 256)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array) -> TanhGaussian:
        z = MLPTorso(
            self.hidden, activation=nn.relu, compute_dtype=self.compute_dtype,
            name="torso",
        )(obs)
        mean = _head(self.action_dim, 0.01, self.compute_dtype, "mean")(z)
        log_std = _head(self.action_dim, 0.01, self.compute_dtype, "log_std")(z)
        return TanhGaussian.create(
            mean.astype(jnp.float32), log_std.astype(jnp.float32)
        )


class QFunction(nn.Module):
    """Q(s, a) critic for off-policy algorithms."""

    hidden: Sequence[int] = (256, 256)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, action], axis=-1)
        z = MLPTorso(
            self.hidden, activation=nn.relu, compute_dtype=self.compute_dtype,
            name="torso",
        )(x)
        q = _head(1, 1.0, self.compute_dtype, "q")(z)
        return q[..., 0].astype(jnp.float32)


class TwinQ(nn.Module):
    """Twin Q-heads (TD3/SAC; BASELINE.json:9-10). Returns (q1, q2)."""

    hidden: Sequence[int] = (256, 256)
    compute_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, obs: jax.Array, action: jax.Array) -> tuple[jax.Array, jax.Array]:
        q1 = QFunction(self.hidden, self.compute_dtype, name="q1")(obs, action)
        q2 = QFunction(self.hidden, self.compute_dtype, name="q2")(obs, action)
        return q1, q2
