from actor_critic_tpu.models.distributions import (
    Categorical,
    DiagGaussian,
    TanhGaussian,
)

__all__ = ["Categorical", "DiagGaussian", "TanhGaussian"]
