"""Numpy host-side actor mirrors (SURVEY.md §7.2 item 2).

The host-env trainers' wall-clock path is: step the (1-core) host pool,
round-trip the TPU tunnel for every batched `act`, then block on the
device update before the next rollout can start. These mirrors remove
both device dependencies from the collection loop:

- acting is a few small numpy matmuls on the host (the policies are
  2-layer MLPs — a tunnel round-trip costs more than the forward pass),
- the jitted update is dispatched asynchronously and computes on-device
  WHILE the host collects the next rollout, using acting params that are
  one update stale (fetched from the previous iteration's output, which
  is concrete by then — no wait). PPO's clipped importance ratio and the
  off-policy algorithms' replay make 1-update staleness semantically
  clean; IMPALA formalizes the same idea (algos/impala.py).

Mirrors cover the MLP-torso networks (the host-env configs:
BASELINE.json:8-10). CNN torsos are not mirrored — pixel pools keep the
device acting path (`supports_mirror` returns False).

Parity with the flax modules is tested in tests/test_host_actor.py
(logits/means/values allclose against `Module.apply`).
"""

from __future__ import annotations

import math
from typing import Any

import numpy as np

_LOG_2PI = math.log(2.0 * math.pi)
# Keep in sync with models/distributions.py (TanhGaussian.create clips).
_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0


def _dense(p: dict, x: np.ndarray) -> np.ndarray:
    return x @ np.asarray(p["kernel"]) + np.asarray(p["bias"])


def _mlp(torso: dict, x: np.ndarray, activation) -> np.ndarray:
    for i in range(len(torso)):
        x = activation(_dense(torso[f"dense_{i}"], x))
    return x


def _tanh(x):
    return np.tanh(x)


def _relu(x):
    return np.maximum(x, 0.0)


def _log_softmax(logits: np.ndarray) -> np.ndarray:
    z = logits - logits.max(axis=-1, keepdims=True)
    return z - np.log(np.exp(z).sum(axis=-1, keepdims=True))


def supports_mirror(params: Any) -> bool:
    """True if the param tree is an MLP-torso net this module can mirror
    (conv torsos — pixel obs — keep the device acting path)."""
    p = params.get("params", params)
    torsos = [v for k, v in p.items() if k.endswith("torso")]
    return bool(torsos) and all(
        all(k.startswith("dense_") for k in t) for t in torsos
    )


# -- PPO (models/networks.py ActorCriticDiscrete / ActorCriticGaussian) --


def make_ppo_host_policy(env_spec, cfg):
    """(np_params, obs, rng) → (action, log_prob, value), matching
    ppo.make_policy_step's sampling semantics in host numpy."""
    if env_spec.discrete:

        def policy(params, obs, rng: np.random.Generator):
            p = params["params"]
            z = _mlp(p["torso"], np.asarray(obs, np.float32), _tanh)
            logits = _dense(p["policy"], z)
            value = _dense(p["value"], z)[..., 0]
            # Gumbel-max sampling == jax.random.categorical semantics.
            g = rng.gumbel(size=logits.shape).astype(np.float32)
            action = np.argmax(logits + g, axis=-1)
            logp = np.take_along_axis(
                _log_softmax(logits), action[..., None], axis=-1
            )[..., 0]
            return action, logp.astype(np.float32), value.astype(np.float32)

        return policy

    def policy(params, obs, rng: np.random.Generator):
        p = params["params"]
        obs = np.asarray(obs, np.float32)
        za = _mlp(p["pi_torso"], obs, _tanh)
        zc = _mlp(p["vf_torso"], obs, _tanh)
        mean = _dense(p["policy"], za)
        value = _dense(p["value"], zc)[..., 0]
        log_std = np.broadcast_to(np.asarray(p["log_std"]), mean.shape)
        std = np.exp(log_std)
        action = mean + std * rng.standard_normal(mean.shape).astype(np.float32)
        zscore = (action - mean) / std
        logp = np.sum(-0.5 * (zscore * zscore + _LOG_2PI) - log_std, axis=-1)
        return (
            action.astype(np.float32),
            logp.astype(np.float32),
            value.astype(np.float32),
        )

    return policy


def make_ppo_host_value(env_spec, cfg):
    """(np_params, obs) → value: the critic head alone, for computing
    truncation-bootstrap values of final_obs and the rollout bootstrap
    with the SAME (stale) params that produced the recorded per-step
    values — overlap mode must not mix value baselines across parameter
    versions (GAE deltas and the value-clip anchor stay consistent)."""
    if env_spec.discrete:

        def value_fn(params, obs):
            p = params["params"]
            z = _mlp(p["torso"], np.asarray(obs, np.float32), _tanh)
            return _dense(p["value"], z)[..., 0].astype(np.float32)

        return value_fn

    def value_fn(params, obs):
        p = params["params"]
        zc = _mlp(p["vf_torso"], np.asarray(obs, np.float32), _tanh)
        return _dense(p["value"], zc)[..., 0].astype(np.float32)

    return value_fn


def make_ppo_host_greedy(env_spec, cfg):
    """(np_params, obs) → mode action; host mirror of the eval policy
    (`ppo.make_greedy_act`). Greedy host eval otherwise round-trips the
    device tunnel once per eval step (~26 ms each on the axon host —
    ~26 s per 1000-step eval sweep)."""
    if env_spec.discrete:

        def act(params, obs):
            p = params["params"]
            z = _mlp(p["torso"], np.asarray(obs, np.float32), _tanh)
            return np.argmax(_dense(p["policy"], z), axis=-1)

        return act

    def act(params, obs):
        p = params["params"]
        za = _mlp(p["pi_torso"], np.asarray(obs, np.float32), _tanh)
        return _dense(p["policy"], za).astype(np.float32)

    return act


# -- DDPG/TD3 (models/networks.py DeterministicActor) --------------------


def _ddpg_actor_fwd(p: dict, obs) -> np.ndarray:
    """Deterministic tanh actor forward — the ONE copy both the explore
    and greedy mirrors share (divergence here would split collection and
    eval policies)."""
    z = _mlp(p["torso"], np.asarray(obs, np.float32), _relu)
    return _tanh(_dense(p["action"], z))


def make_ddpg_host_explore(env_spec, cfg):
    """(np_params, obs, rng, env_steps) → action; mirrors
    ddpg.make_explore_fn (tanh actor + clipped Gaussian noise, uniform
    random during warmup)."""

    def act(params, obs, rng: np.random.Generator, env_steps: int):
        shape = (np.asarray(obs).shape[0], env_spec.action_dim)
        if env_steps < cfg.warmup_steps:
            return rng.uniform(-1.0, 1.0, shape).astype(np.float32)
        a = _ddpg_actor_fwd(params["params"], obs)
        a = a + cfg.exploration_noise * rng.standard_normal(shape).astype(
            np.float32
        )
        return np.clip(a, -1.0, 1.0).astype(np.float32)

    return act


def make_ddpg_host_greedy(env_spec, cfg):
    """(np_params, obs) → deterministic actor action (no noise); host
    mirror of ddpg.make_greedy_act."""

    def act(params, obs):
        return _ddpg_actor_fwd(params["params"], obs).astype(np.float32)

    return act


# -- SAC (models/networks.py SquashedGaussianActor) ----------------------


def _sac_mean_logstd(p: dict, obs) -> tuple[np.ndarray, np.ndarray]:
    """Squashed-Gaussian actor heads — shared by explore and greedy."""
    z = _mlp(p["torso"], np.asarray(obs, np.float32), _relu)
    mean = _dense(p["mean"], z)
    log_std = np.clip(_dense(p["log_std"], z), _LOG_STD_MIN, _LOG_STD_MAX)
    return mean, log_std


def make_sac_host_explore(env_spec, cfg):
    """(np_params, obs, rng, env_steps) → action; mirrors
    sac.make_explore_fn (tanh-Gaussian sample, uniform during warmup)."""

    def act(params, obs, rng: np.random.Generator, env_steps: int):
        shape = (np.asarray(obs).shape[0], env_spec.action_dim)
        if env_steps < cfg.warmup_steps:
            return rng.uniform(-1.0, 1.0, shape).astype(np.float32)
        mean, log_std = _sac_mean_logstd(params["params"], obs)
        pre = mean + np.exp(log_std) * rng.standard_normal(shape).astype(
            np.float32
        )
        return _tanh(pre).astype(np.float32)

    return act


def make_sac_host_greedy(env_spec, cfg):
    """(np_params, obs) → tanh(mean) action; host mirror of
    sac.make_greedy_act."""

    def act(params, obs):
        mean, _ = _sac_mean_logstd(params["params"], obs)
        return _tanh(mean).astype(np.float32)

    return act


# -- serving dispatch (ISSUE 10) -----------------------------------------

_GREEDY_MIRRORS = {
    "ppo": make_ppo_host_greedy,
    "ddpg": make_ddpg_host_greedy,
    "td3": make_ddpg_host_greedy,
    "sac": make_sac_host_greedy,
}


def greedy_mirror_for(env_spec, cfg, algo: str):
    """The greedy host mirror `(np_params, obs) -> action` for `algo`'s
    policy params, or ValueError when no mirror exists — the serving
    engine's `backend="mirror"` acting path (serving/engine.py): on a
    CPU-only serving host these few numpy matmuls beat a batch-1 XLA
    dispatch, exactly the trade the training loops already make.
    Callers must still gate on `supports_mirror(params)` (conv torsos
    keep the device path)."""
    try:
        maker = _GREEDY_MIRRORS[algo]
    except KeyError:
        raise ValueError(
            f"no greedy host mirror for algo {algo!r}; "
            f"mirrored: {sorted(_GREEDY_MIRRORS)}"
        ) from None
    return maker(env_spec, cfg)
