"""Unified training CLI (SURVEY.md §5.6).

One entry point for every algorithm/config the framework supports —
the TPU build's replacement for the reference genre's per-script
argparse mains (reference mount empty at survey, SURVEY.md §0):

    python train.py --preset a2c_cartpole
    python train.py --preset ppo_halfcheetah --set lr=1e-4 --iterations 200
    python train.py --algo sac --env jax:point_mass --set num_envs=16
    python train.py --preset impala_pong --ckpt-dir runs/pong --resume
    python train.py --list-presets

Environments: `jax:<name>` runs the fused on-device trainer (rollout +
update in one XLA program); `host:<gym id>` steps a gymnasium/MuJoCo
pool on the host with the learner on device. Metrics stream to a JSONL
file; checkpoints (orbax) make the run restart-idempotent.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

from actor_critic_tpu import telemetry


def build_env(spec: str, algo: str, cfg, seed: int, scale_actions=None,
              env_kwargs=None, workers: int = 1):
    """'jax:<name>' → (JaxEnv, fused=True); 'host:<id>' → (pool, False).

    scale_actions is tri-state: None keeps each env's own convention
    (host pools clip — the recorded-run behavior; jax:pendulum scales),
    True/False (--scale-actions / --no-scale-actions) forces it where
    the env supports the choice.

    env_kwargs (preset env_kwargs merged with --env-set) go to the env
    CONSTRUCTOR: the jax:* maker (e.g. pong's opp_skill/frame_skip/size)
    or gym.make for host pools. The native backend's envs take no
    construction knobs, so kwargs there are an error, not a silent drop."""
    kind, _, name = spec.partition(":")
    env_kwargs = dict(env_kwargs or {})
    if kind == "mixture":
        # 'mixture:cartpole*2,pendulum,acrobot,maze' — a heterogeneous
        # fleet of env TYPES stepping inside one fused program
        # (envs/mixture.py, ISSUE 11). The member list (with optional
        # per-type draw weights) is the spec; --env-set reaches the
        # mixture maker (randomize/action_bins/redraw_types/...).
        import inspect

        from actor_critic_tpu.envs import make_mixture

        valid = set(inspect.signature(make_mixture).parameters) - {"members"}
        unknown = sorted(set(env_kwargs) - valid)
        if unknown:
            raise SystemExit(
                f"bad --env-set for {spec}: unknown kwargs {unknown}; "
                f"valid: {sorted(valid)}"
            )
        try:
            return make_mixture(name, **env_kwargs), True
        except ValueError as e:
            raise SystemExit(f"bad mixture env {spec!r}: {e}") from e
    if kind == "jax":
        from actor_critic_tpu import envs as E

        makers = {
            "cartpole": E.make_cartpole,
            "pendulum": E.make_pendulum,
            "pong": E.make_pong,
            "two_state": E.make_two_state_mdp,
            "point_mass": E.make_point_mass,
            "bandit": E.make_bandit,
        }
        if name not in makers:
            raise SystemExit(f"unknown jax env {name!r}; valid: {sorted(makers)}")
        if name == "pendulum":
            # One resolution for behavior AND the resume-guard record:
            # CLI flag wins, then --env-set/preset kwarg, then the env
            # default (scale) — effective_scale_actions is that order.
            env_kwargs["scale_actions"] = effective_scale_actions(
                spec, scale_actions, env_kwargs
            )
        # Validate kwargs against the maker's signature UP FRONT so the
        # friendly exit fires only for genuinely unknown knobs — a
        # TypeError raised inside a maker must keep its real traceback.
        import inspect

        valid = set(inspect.signature(makers[name]).parameters)
        unknown = sorted(set(env_kwargs) - valid)
        if unknown:
            raise SystemExit(
                f"bad --env-set for jax:{name}: unknown kwargs {unknown}; "
                f"valid: {sorted(valid)}"
            )
        return makers[name](**env_kwargs), True
    if kind in ("host", "native"):
        from actor_critic_tpu.envs.host_pool import HostEnvPool

        # Off-policy TD targets want raw reward scale, and off-policy
        # REPLAY wants raw observations too: the pool normalizes with
        # RUNNING stats, so replayed transitions stored early are scaled
        # differently than fresh ones, and the critic bootstraps across
        # inconsistent frames. On high-dim envs this destabilizes Q
        # (observed: SAC Humanoid-v5 Q/alpha runaway with normalization
        # on; raw obs is also the standard SAC/TD3 setup). On-policy PPO
        # consumes each batch immediately, so drifting stats are safe
        # and obs/reward normalization helps it.
        # 'native:<id>' steps the batch in the C++ engine (one C call per
        # step) instead of the Python SyncVectorEnv loop.
        on_policy = algo == "ppo"
        if kind == "native" and env_kwargs:
            raise SystemExit(
                f"--env-set is not supported for native:{name} (the C++ "
                "engine replicates gymnasium defaults exactly)"
            )
        if kind == "native" and workers > 1:
            raise SystemExit(
                "--workers applies to host:<id> pools only (the native "
                "engine already steps the whole batch in one C call)"
            )
        try:
            return (
                HostEnvPool(
                    name,
                    num_envs=cfg.num_envs,
                    seed=seed,
                    normalize_obs=on_policy,
                    normalize_reward=on_policy,
                    backend="gym" if kind == "host" else "native",
                    scale_actions=bool(scale_actions),
                    env_kwargs=env_kwargs,
                    workers=workers,
                ),
                False,
            )
        except TypeError as e:
            # gym.make raises TypeError on unknown constructor kwargs —
            # same friendly exit as the jax: path's maker check. Only
            # claim --env-set is at fault when kwargs were given AND the
            # message blames a keyword; other TypeErrors keep their
            # traceback.
            if env_kwargs and "keyword" in str(e):
                raise SystemExit(f"bad --env-set for {spec}: {e}") from e
            raise
    raise SystemExit(
        f"env must be jax:<name>, mixture:<members>, host:<gym id>, or "
        f"native:<id>, got {spec!r}"
    )


def effective_scale_actions(env_spec: str, scale_actions, env_kwargs=None):
    """Resolve the tri-state CLI flag to the convention the env will
    actually use, so the resume guard compares BEHAVIOR, not flag
    spelling: `jax:pendulum` defaults to scaling (build_env maps
    None→True there), so None and True are the same convention and a
    resume that makes the default explicit must not warn. The explicit
    CLI flag wins; an `--env-set scale_actions=...` kwarg comes next
    (mirroring build_env's setdefault order); then the env default.
    Envs with no continuous-action convention resolve to None."""
    if env_spec == "jax:pendulum":
        if scale_actions is not None:
            return bool(scale_actions)
        kw = (env_kwargs or {}).get("scale_actions")
        return True if kw is None else bool(kw)
    if env_spec.startswith(("host:", "native:")):
        # Host pools clip unless the flag forces scaling (build_env
        # passes bool(scale_actions), so None means clip).
        return bool(scale_actions)
    return None


def check_env_convention(ckpt_dir, env_spec: str, scale_actions, resume: bool,
                         env_kwargs=None):
    """Fused-path twin of the host path's `_pool_scale_actions` resume
    guard (algos/host_loop.py): record the run's EFFECTIVE action
    convention AND env-constructor kwargs in a sidecar JSON next to the
    checkpoints, and warn when a resume flips either — the restored
    policy would silently execute under another action convention
    (e.g. jax:pendulum ±2-scaled vs raw torques) or inside a
    different-difficulty env (e.g. pong opp_skill), contaminating the
    run's curve. Tolerant of pre-existing checkpoint dirs without the
    sidecar; a fresh (non-resume) run overwrites any stale sidecar left
    by a previous run in the same dir."""
    if not ckpt_dir:
        return
    import os
    import warnings

    env_kwargs = dict(env_kwargs or {})
    resolved = effective_scale_actions(env_spec, scale_actions, env_kwargs)
    # scale_actions is compared via `resolved` (which folds in the CLI
    # flag); leaving it in the kwargs dict would warn spuriously when one
    # run spells the same convention via --env-set and the other via the
    # flag.
    env_kwargs.pop("scale_actions", None)
    path = os.path.join(ckpt_dir, "env_convention.json")
    current = {
        "env": env_spec, "scale_actions": resolved, "env_kwargs": env_kwargs,
    }
    if resume and os.path.exists(path):
        with open(path) as f:
            saved = json.load(f)
        # Old sidecars recorded the raw tri-state flag; resolve it the
        # same way so None-vs-True on a scaling-default env stays quiet.
        saved_kwargs = saved.get("env_kwargs")
        saved_resolved = effective_scale_actions(
            saved.get("env", env_spec), saved.get("scale_actions"),
            saved_kwargs,
        )
        if saved_kwargs is not None:
            saved_kwargs = dict(saved_kwargs)
            saved_kwargs.pop("scale_actions", None)
        saved_env = saved.get("env")
        if saved_env is not None and saved_env != env_spec:
            warnings.warn(
                f"--resume into {env_spec!r} but this checkpoint dir "
                f"belongs to a {saved_env!r} run — the restored policy "
                "trained on a different environment. Use a fresh "
                "--ckpt-dir or the original env.",
                stacklevel=2,
            )
            # The convention/kwargs comparisons are meaningless across
            # different envs (and would emit nonsense follow-up advice
            # like "relaunch with the original flag") — the env warning
            # already says everything.
            return
        # Host pools already guard the scale flag through the checkpoint
        # metrics (host_loop._pool_scale_actions) — warning here too
        # would double-report the same flip; the sidecar adds env/kwargs
        # coverage there, and full coverage for fused envs.
        host = env_spec.startswith(("host:", "native:"))
        if not host and saved_resolved != resolved:
            warnings.warn(
                f"--resume with scale_actions={resolved!r} but this "
                f"run started with {saved_resolved!r} — the "
                "restored policy trained under the other action "
                "convention. Relaunch with the original flag.",
                stacklevel=2,
            )
        # Pre-env-kwargs sidecars (no key) are tolerated like legacy
        # dirs; a recorded mismatch is a different env, so warn.
        if saved_kwargs is not None and saved_kwargs != env_kwargs:
            warnings.warn(
                f"--resume with env_kwargs={env_kwargs!r} but this run "
                f"started with {saved_kwargs!r} — the restored policy "
                "would continue in a different environment. Relaunch "
                "with the original --env-set/preset.",
                stacklevel=2,
            )
        return
    os.makedirs(ckpt_dir, exist_ok=True)
    with open(path, "w") as f:
        json.dump(current, f)


def fused_module(algo: str):
    from actor_critic_tpu.algos import a2c, ddpg, impala, ppo, sac

    return {
        "a2c": a2c, "ppo": ppo, "ddpg": ddpg, "td3": ddpg,
        "sac": sac, "impala": impala, "a3c": impala,
    }[algo]


def steps_per_iteration(algo: str, cfg) -> int:
    if hasattr(cfg, "rollout_steps"):
        return cfg.rollout_steps * cfg.num_envs
    return cfg.steps_per_iter * cfg.num_envs


def run_fused(env, preset, args, logger) -> dict:
    import jax
    import jax.numpy as jnp

    from actor_critic_tpu.envs import mixture
    from actor_critic_tpu.utils.checkpoint import Checkpointer, checkpointed_train

    mod = fused_module(preset.algo)
    cfg = preset.config
    state = mod.init_state(env, cfg, jax.random.key(args.seed))
    raw_step = mod.make_train_step(env, cfg)
    chunk = max(1, getattr(args, "chunk", 1))
    if chunk > 1:
        # Chunked dispatch: scan `k` train iterations inside ONE jitted
        # call, so per-dispatch overhead (dominant through the axon
        # tunnel: measured 39k steps/s per-iteration vs 152k steps/s
        # scanned on the same pong program) is paid once per chunk.
        # Metrics are the final iteration's slice — the same
        # point-in-time semantics a per-iteration loop logs at chunk
        # boundaries. Shape-stabilized (utils/compile_cache.py): full
        # chunks share one program and EVERY partial chunk (resume
        # realignment, end tail) shares a second, n_valid-masked one —
        # arbitrary k never compiles a fresh program.
        from actor_critic_tpu.utils.compile_cache import make_chunked_step

        step = make_chunked_step(raw_step, chunk)

        # Cadences fire only at chunk boundaries; snap them UP to chunk
        # multiples so "every N" keeps meaning what it says.
        def _snap(x):
            if x is None or x <= 0 or x % chunk == 0:
                return x
            return ((x + chunk - 1) // chunk) * chunk

        for name in ("log_every", "eval_every", "save_every"):
            old = getattr(args, name, 0)
            new = _snap(old)
            if new != old:
                print(f"--chunk {chunk}: {name} {old} -> {new}", flush=True)
                setattr(args, name, new)
    else:
        step = jax.jit(raw_step, donate_argnums=0)
    spi = steps_per_iteration(preset.algo, cfg)

    ckpt = Checkpointer(args.ckpt_dir) if args.ckpt_dir else None
    if ckpt is not None and args.resume and ckpt.latest_step() is not None:
        print(f"resumed from iteration {ckpt.latest_step()}", flush=True)

    from actor_critic_tpu.algos.host_loop import should_log

    eval_fn = None
    typed_eval = None
    eval_matrix: dict = {}
    if getattr(args, "eval_every", 0) > 0:
        eval_fn = jax.jit(mod.make_eval_fn(env, cfg), static_argnums=(2, 3))
        eval_key = jax.random.key(args.seed + 1)
        if isinstance(env, mixture.MixtureEnv):
            # Per-type eval matrix (ISSUE 11): one jitted program whose
            # fleet is pinned to a TRACED type id — every member type
            # evaluates through the same executable. Last results ride
            # the sampler registry into /metrics + resources.jsonl
            # (rendered by scripts/run_report.py).
            typed_eval = jax.jit(
                mixture.make_typed_eval(env, mod.make_network(env, cfg)),
                static_argnums=(3, 4),
            )

    # Curriculum (ISSUE 11): the controller advances on eval progress;
    # the new weights are installed into the fleet state between
    # dispatches (same shapes/dtypes — never a retrace) and ride the
    # checkpoint, so a resumed run continues the schedule.
    curriculum_ctl = None
    pending_weights: list = []
    if getattr(args, "curriculum", ""):
        curriculum_ctl = mixture.CurriculumController(
            mixture.parse_curriculum(args.curriculum, env.member_names)
        )

    def log_fn(it, metrics):
        # Eval cadence is INDEPENDENT of the logging cadence; an eval
        # iteration always emits a log row so the number is never lost.
        do_log = should_log(it, args.log_every, args.iterations)
        extra = {}
        if eval_fn is not None and (
            it % args.eval_every == 0 or it == args.iterations
        ):
            with telemetry.span("eval", it=it):
                extra["eval_return"] = float(eval_fn(state_box[0], eval_key))
                if typed_eval is not None:
                    for t, name in enumerate(env.member_names):
                        # jaxlint: disable=transfer-discipline (eval
                        # cadence: the per-type eval matrix runs
                        # |types| dispatches once per eval, not in the
                        # training step loop)
                        r = float(typed_eval(
                            state_box[0],
                            jax.random.fold_in(eval_key, t),
                            jnp.asarray(t, jnp.int32),
                        ))
                        extra[f"eval_return_{name}"] = round(r, 3)
                        eval_matrix.update(mixture.eval_matrix_row(name, r))
            if curriculum_ctl is not None:
                advanced = curriculum_ctl.update(extra["eval_return"])
                if advanced is not None:
                    stage, weights = advanced
                    pending_weights[:] = [(stage, weights)]
                    print(
                        f"curriculum: eval {extra['eval_return']:.1f} -> "
                        f"stage {stage}, weights {list(weights)}",
                        flush=True,
                    )
                extra["curriculum_stage"] = curriculum_ctl.stage
            do_log = True
        if do_log:
            # Health monitors see the materialized row — AFTER the eval
            # merge (so eval_return reaches the divergence detector) and
            # only on the log cadence: the float() coercions are the
            # loop's first device sync, and syncing every dispatch would
            # serialize host on device, the pipelining this loop exists
            # to preserve. Non-floatable values stringify, same tolerance
            # as JsonlLogger.log.
            row = {}
            for k, v in metrics.items():
                try:
                    row[k] = float(v)
                except (TypeError, ValueError):
                    row[k] = str(v)
            row.update(extra, env_steps=it * spi)
            telemetry.observe(it, row)
            logger.log(it, row)

    # log_fn needs the CURRENT state for eval; checkpointed_train owns the
    # loop, so expose it via a one-cell box updated by a wrapped step.
    state_box = [state]
    ctl_synced = [curriculum_ctl is None]

    def step_tracking(s, *k):
        if not ctl_synced[0]:
            # First dispatch after a (possible) restore: re-align the
            # host-side curriculum counter from the stage the restored
            # fleet state carries, so resume continues the schedule.
            curriculum_ctl.sync(mixture.fleet_stage(s.rollout.env_state))
            ctl_synced[0] = True
        if pending_weights:
            stage, weights = pending_weights.pop()
            s = s._replace(rollout=s.rollout._replace(
                env_state=mixture.set_fleet_weights(
                    s.rollout.env_state, weights, stage
                )
            ))
        # jax:* envs fuse the rollout INTO the update program, so the
        # env_step phase has no separable host duration — record it as a
        # Chrome-trace instant so traces still carry the phase.
        telemetry.instant("env_step", fused=True)
        out, m = step(s, *k)
        state_box[0] = out
        return out, m

    gauge_key = None
    if typed_eval is not None:
        from actor_critic_tpu.telemetry import sampler

        gauge_key = sampler.register_gauge(
            "mixture_eval", lambda: dict(eval_matrix)
        )
    try:
        state, metrics = checkpointed_train(
            step_tracking, state, args.iterations,
            ckpt=ckpt, save_every=args.save_every, log_fn=log_fn,
            resume=args.resume, stride=chunk,
        )
    finally:
        if gauge_key is not None:
            from actor_critic_tpu.telemetry import sampler

            sampler.unregister_gauge(gauge_key)
    if ckpt is not None:
        ckpt.close()
    return {k: float(v) for k, v in metrics.items()}


def build_actor_pools(preset, args, actors: int) -> list:
    """One HostEnvPool per async actor (E/A envs each, disjoint seeds,
    the worker fleet split across actors) — the fleet the ISSUE 6
    actor–learner services collect from."""
    from actor_critic_tpu.envs.host_pool import HostEnvPool

    kind, _, name = preset.env.partition(":")
    if kind not in ("host", "native"):
        raise SystemExit(
            "--async-actors decouples HOST collection from the learner; "
            "jax:* envs fuse rollouts into the update program and have "
            "nothing to decouple"
        )
    if preset.algo not in ("ppo", "ddpg", "td3", "sac"):
        raise SystemExit(
            f"--async-actors drives the host trainers (ppo/ddpg/td3/"
            f"sac); {preset.algo} has no host loop to decouple"
        )
    # Same normalization policy as the lockstep pools (build_env): PPO
    # wants running obs/reward normalization; the off-policy algos must
    # store RAW transitions (drifting stats re-scale replayed frames).
    on_policy = preset.algo == "ppo"
    cfg = preset.config
    if actors > cfg.num_envs or cfg.num_envs % actors != 0:
        raise SystemExit(
            f"num_envs={cfg.num_envs} must split evenly across "
            f"--async-actors={actors} (one fixed [K, E/A] block shape "
            "keeps the learner on a single compiled program)"
        )
    workers_each = max(1, args.workers // actors)
    # Under --distributed every HOST builds its own fleet from the same
    # --seed: without a rank stride the fleets would replay identical
    # env reset streams and the global sync batch would carry
    # cross-host duplicate trajectories (launch_multihost.py uses the
    # same (rank·A + i) stride).
    rank = args.process_id if args.distributed else 0
    return [
        HostEnvPool(
            name,
            num_envs=cfg.num_envs // actors,
            # Large per-actor seed stride: pools seed their envs
            # [seed .. seed+E), so adjacent offsets would duplicate
            # trajectories across actors.
            seed=args.seed + (rank * actors + i) * 100003,
            normalize_obs=on_policy,
            normalize_reward=on_policy,
            backend="gym" if kind == "host" else "native",
            scale_actions=bool(args.scale_actions),
            env_kwargs=preset.env_kwargs,
            workers=workers_each,
        )
        for i in range(actors)
    ]


def run_multihost(pools, preset, args, logger) -> dict:
    """One process of the distributed actor–learner fleet (ISSUE 9):
    local actor services feed the local queue; the learner either joins
    the global all-reduce (sync) or gossips params peer-to-peer
    (--gossip). Launch one such process per host — or use
    scripts/launch_multihost.py for a CPU local cluster."""
    import jax

    from actor_critic_tpu.parallel import multihost

    rank = jax.process_index() if args.coordinator else args.process_id
    world = args.num_processes
    multihost.host_lane(rank)
    last: dict = {}

    def log_fn(it, m):
        telemetry.observe(it, m)
        last.clear()
        last.update(m)
        logger.log(it, m)

    _, _, summary = multihost.train_multihost(
        pools, preset.config, args.iterations,
        rank=rank, world=world,
        mode="gossip" if args.gossip else "sync",
        seed=args.seed, log_every=args.log_every, log_fn=log_fn,
        queue_depth=args.queue_depth,
        max_staleness=resolve_staleness(args, "ppo"),
        updates_per_block=args.updates_per_block,
        correction=args.async_correction,
        gossip=multihost.GossipConfig(
            every=args.gossip_every, weight=args.gossip_weight,
        ),
        mailbox_dir=args.mailbox_dir or None,
    )
    last.update({f"multihost_{k}": v for k, v in summary.items()
                 if isinstance(v, (int, float, bool))})
    return last


def resolve_staleness(args, algo: str):
    """--max-staleness tri-state: explicit S >= 0 is a bound, -1 is
    unbounded, absent picks the per-algo default (8 for PPO, unbounded
    for the off-policy algos — replay absorbs staleness)."""
    if args.max_staleness is None:
        return 8 if algo == "ppo" else None
    return args.max_staleness if args.max_staleness >= 0 else None


def start_serving_sidecar(preset, spec, args):
    """Serve-while-training (ISSUE 17): a resident policy-serving
    gateway whose single 'learner' policy tracks the training run.

    Built BEFORE training starts so every act bucket is compiled while
    the env pools are still spawning — the publish hook then only ever
    hot-swaps params through the `checkpoint.uncommit` route (frozen
    host snapshot re-placed as uncommitted device buffers: same program,
    0 recompiles, perfsan's committed serving budget). Versioning:
    the init placeholder registers at version 0; block `it`'s publish
    swaps to version `it + 1`, so /v1/act's `version` field is strictly
    monotone and equals blocks-consumed + 1.

    Returns `(gateway, publish_hook)`; the caller owns gateway.close().
    """
    from actor_critic_tpu import serving

    buckets = tuple(
        int(b) for b in args.serve_buckets.split(",") if b.strip()
    )
    engine = serving.PolicyEngine(
        spec, preset.config, algo=preset.algo, buckets=buckets,
        seed=args.seed,
    )
    store = serving.PolicyStore()
    template = serving.init_params(
        spec, preset.config, preset.algo, seed=args.seed
    )
    store.register("learner", engine, template, default=True)
    n_warm = engine.warm(template)
    gateway = serving.ServeGateway(store, port=args.serve_port)
    print(
        f"serving learner on http://127.0.0.1:{gateway.port} "
        f"(warm: {n_warm} act buckets)",
        flush=True,
    )

    def publish_hook(it: int, np_params) -> None:
        # The publisher freezes its own copy, so handing the same tree
        # to the store is safe; swap numguards + re-places per policy.
        store.swap("learner", np_params, version=it + 1)

    return gateway, publish_hook


def run_host_async(pools, preset, args, logger) -> dict:
    from actor_critic_tpu.algos import ddpg, ppo, sac

    last: dict = {}

    def log_fn(it, m):
        telemetry.observe(it, m)
        last.clear()
        last.update(m)
        logger.log(it, m)

    from actor_critic_tpu.utils.checkpoint import Checkpointer

    ckpt = Checkpointer(args.ckpt_dir) if args.ckpt_dir else None
    if ckpt is not None and args.resume and ckpt.latest_step() is not None:
        print(f"resuming from block {ckpt.latest_step()}", flush=True)
    gateway, publish_hook = None, None
    if args.serve_port is not None:
        gateway, publish_hook = start_serving_sidecar(
            preset, pools[0].spec, args
        )
    try:
        if preset.algo == "ppo":
            ppo.train_host_async(
                pools, preset.config, num_iterations=args.iterations,
                seed=args.seed, log_every=args.log_every, log_fn=log_fn,
                eval_every=args.eval_every, eval_envs=args.eval_envs,
                eval_steps=args.eval_steps,
                updates_per_block=args.updates_per_block,
                queue_depth=args.queue_depth,
                max_staleness=resolve_staleness(args, "ppo"),
                correction=args.async_correction,
                data_plane=args.data_plane,
                plane_codec=args.data_plane_codec,
                ckpt=ckpt, save_every=args.save_every, resume=args.resume,
                publish_hook=publish_hook,
            )
        else:
            # Off-policy (ddpg/td3/sac): replay absorbs behavior
            # staleness, so there is no correction knob and the
            # staleness bound defaults OFF (-1 keeps it off; >= 0 sets
            # a bound anyway).
            mod = ddpg if preset.algo in ("ddpg", "td3") else sac
            mod.train_host_async(
                pools, preset.config, num_iterations=args.iterations,
                seed=args.seed, log_every=args.log_every, log_fn=log_fn,
                eval_every=args.eval_every, eval_envs=args.eval_envs,
                eval_steps=args.eval_steps,
                queue_depth=args.queue_depth,
                max_staleness=resolve_staleness(args, preset.algo),
                data_plane=args.data_plane,
                plane_codec=args.data_plane_codec,
                publish_hook=publish_hook,
            )
    finally:
        if gateway is not None:
            gateway.close()
        if ckpt is not None:
            ckpt.close()
    return last


def run_host(pool, preset, args, logger) -> dict:
    from actor_critic_tpu.algos import ddpg, ppo, sac
    from actor_critic_tpu.utils.checkpoint import Checkpointer

    last: dict = {}

    def log_fn(it, m):
        telemetry.observe(it, m)
        last.clear()
        last.update(m)
        logger.log(it, m)

    ckpt = Checkpointer(args.ckpt_dir) if args.ckpt_dir else None
    if ckpt is not None and args.resume and ckpt.latest_step() is not None:
        print(f"resuming from iteration {ckpt.latest_step()}", flush=True)
    common = dict(
        num_iterations=args.iterations, seed=args.seed,
        log_every=args.log_every, log_fn=log_fn,
        eval_every=getattr(args, "eval_every", 0),
        eval_envs=getattr(args, "eval_envs", 4),
        eval_steps=getattr(args, "eval_steps", 1000),
        ckpt=ckpt, save_every=args.save_every, resume=args.resume,
        overlap=not args.no_overlap,
    )
    offpolicy = dict(common, save_replay=not args.no_save_replay)
    try:
        if preset.algo == "ppo":
            ppo.train_host(pool, preset.config, **common)
        elif preset.algo in ("ddpg", "td3"):
            ddpg.train_host(pool, preset.config, **offpolicy)
        elif preset.algo == "sac":
            sac.train_host(pool, preset.config, **offpolicy)
        else:
            raise SystemExit(
                f"{preset.algo} needs a pure-JAX env (fused trainer); "
                "pick env jax:<name>"
            )
        if not last and ckpt is not None:
            # Resume found the run already complete: no iteration ran, so
            # no log row fired — recover the final metrics saved alongside
            # the checkpoint instead of returning an empty summary.
            # Underscore-prefixed keys are checkpoint-internal bookkeeping
            # (e.g. _pool_scale_actions), not metrics.
            last = {
                k: v for k, v in ckpt.restore_metrics().items()
                if not k.startswith("_")
            }
    finally:
        if ckpt is not None:
            ckpt.close()
    return last


def main(argv=None) -> int:
    # NB: when ADDING an option that takes a VALUE, also add it to
    # `takes_value()` in scripts/run_resumable.sh — the wrapper parses
    # this argv shape to tell its own --fresh flag from option values.
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    p.add_argument("--preset", help="named preset (see --list-presets)")
    p.add_argument("--algo", help="a2c|ppo|ddpg|td3|sac|impala|a3c")
    p.add_argument("--env", help="jax:<name> or host:<gym id>")
    p.add_argument("--iterations", type=int, help="train-step iterations")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="config override (repeatable), e.g. --set lr=1e-4 --set hidden=64,64",
    )
    p.add_argument(
        "--env-set", action="append", default=[], metavar="KEY=VALUE",
        help="env-constructor kwarg (repeatable), e.g. --env-set "
        "opp_skill=0.5 --env-set frame_skip=4; merges over the preset's "
        "env_kwargs",
    )
    p.add_argument(
        "--curriculum", default="", metavar="SPEC",
        help="mixture envs (fused, needs --eval-every): re-weight the "
        "type/scenario draw distribution as learner eval progress "
        "crosses thresholds — 'THR:w0,w1,..;THR:w0,w1,..', one stage "
        "per semicolon entry, weights in member order (envs/mixture.py "
        "grammar). Forces redraw_types=True on the mixture; the stage "
        "and weights ride the env state inside the checkpoint, so "
        "--resume continues the schedule.",
    )
    p.add_argument("--metrics", default="metrics.jsonl", help="JSONL output path")
    p.add_argument(
        "--telemetry-dir",
        help="unified run telemetry: write spans.jsonl (Chrome-trace "
        "phase events; render with scripts/run_report.py --trace or open "
        "in Perfetto), resources.jsonl (RSS / device memory / XLA "
        "recompiles), and events.jsonl (health + lifecycle events) under "
        "this directory. Phase instrumentation is always on and "
        "near-free; this flag only adds the file sinks + the resource "
        "sampler thread.",
    )
    p.add_argument(
        "--telemetry-port", type=int, default=None, metavar="PORT",
        help="live run introspection: serve GET /metrics (Prometheus "
        "text: RSS, device memory, XLA recompiles, sampler gauges, last "
        "training row, steps/s), /healthz (watchdog staleness + open "
        "span; 503 when stalled), and /profile?iters=N (arm an "
        "on-demand jax.profiler capture) on 127.0.0.1:PORT from a "
        "daemon thread (telemetry/exporter.py). 0 picks an ephemeral "
        "port (printed at startup). Requires --telemetry-dir (profile "
        "captures land there). SIGUSR2 also arms a capture.",
    )
    p.add_argument(
        "--telemetry-bind", default="127.0.0.1", metavar="HOST",
        help="bind address for the --telemetry-port exporter (default "
        "127.0.0.1). Non-loopback binds expose unauthenticated run "
        "internals, so they are refused unless --distributed (where "
        "the fleet aggregator scrapes peers over the network).",
    )
    p.add_argument(
        "--telemetry-sample-s", type=float, default=5.0, metavar="SECS",
        help="cadence of the telemetry resource sampler thread "
        "(resources.jsonl rows; default 5 s). Only meaningful with "
        "--telemetry-dir. NB: the shard pool's utilization gauge "
        "recomputes over windows of at least 1 s, so sub-second "
        "cadences repeat its previous value between recomputes.",
    )
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument(
        "--chunk", type=int, default=1,
        help="fused envs only: train iterations scanned per device "
        "dispatch (amortizes tunnel/dispatch overhead; log/eval/save "
        "cadences snap up to multiples of this). The watchdog sees one "
        "heartbeat per chunk, so --stall-timeout must comfortably "
        "exceed one chunk's wall time",
    )
    p.add_argument(
        "--eval-every", type=int, default=0,
        help="greedy-eval cadence in iterations (0 = off)",
    )
    p.add_argument(
        "--eval-envs", type=int, default=4,
        help="host trainers: env count of the frozen-stats eval pool",
    )
    p.add_argument(
        "--eval-steps", type=int, default=1000,
        help="host trainers: max steps per eval sweep (first episode only)",
    )
    p.add_argument(
        "--workers", type=int, default=1,
        help="host pools: worker processes the env batch shards across "
        "(envs/shard_pool.py; shared-memory step exchange, per-shard "
        "seeding identical to the in-process pool). 1 = in-process "
        "SyncVectorEnv, today's exact semantics; scaling measured by "
        "`bench/suite.py host_pool_scaling`",
    )
    p.add_argument(
        "--async-actors", type=int, default=0, metavar="A",
        help="host PPO only: decouple collection from the learner "
        "(algos/traj_queue.py) — A actor threads each drive their own "
        "pool of num_envs/A envs and push [K, E/A] blocks into a "
        "bounded trajectory queue; the learner drains continuously and "
        "corrects behavior-policy staleness per --async-correction. "
        "0 (default) = today's lockstep pipeline. Checkpointing is not "
        "yet supported in this mode.",
    )
    p.add_argument(
        "--updates-per-block", type=int, default=1, metavar="M",
        help="async mode: epoch/minibatch passes the learner reuses "
        "each consumed block for (IMPACT-style sample reuse; the "
        "clipped surrogate + V-trace targets keep reuse sound)",
    )
    p.add_argument(
        "--max-staleness", type=int, default=None, metavar="S",
        help="async mode: drop blocks whose behavior-policy version "
        "lags the learner by more than S at consumption (back-pressure "
        "drops the OLDEST data rather than blocking actors); -1 = "
        "unbounded. Default: 8 for PPO (on-policy freshness matters), "
        "unbounded for ddpg/td3/sac (replay absorbs staleness — a "
        "stale block is still valid off-policy experience)",
    )
    p.add_argument(
        "--queue-depth", type=int, default=4, metavar="D",
        help="async mode: trajectory-queue capacity in blocks (a full "
        "queue recycles its oldest block's slot for the incoming one)",
    )
    p.add_argument(
        "--data-plane", choices=("host", "device"), default="host",
        help="async mode: where trajectory blocks live between actor "
        "and learner (actor_critic_tpu/data_plane/). 'host' (default) "
        "is the PR 6 numpy TrajQueue — one host→device transfer per "
        "consumed block on the learner thread; 'device' stages encoded "
        "blocks in a donated HBM ring at collection time (actor-side "
        "put of already-encoded bytes) and the learner gathers+decodes "
        "INSIDE its jitted update — zero steady-state host→device "
        "transfers per consumed block. Never flip it on a resumed run "
        "(the save trees differ).",
    )
    p.add_argument(
        "--data-plane-codec", choices=("fp32", "f16", "int8"),
        default="fp32",
        help="device data plane: per-key block codec "
        "(data_plane/codecs.py). fp32 = raw (bitwise-equal to the host "
        "plane at depth 1); f16 halves observation bytes; int8 "
        "standardizes obs + rewards to calibrated int8 and packs the "
        "flags (~4x smaller enqueue on obs-dominated blocks). Behavior "
        "log-probs/values/actions always stay raw — quantizing them "
        "would bias the V-trace correction itself.",
    )
    p.add_argument(
        "--serve-port", type=int, default=None, metavar="PORT",
        help="async mode: serve-while-training — bind a resident "
        "policy-serving gateway (serving/) on PORT (0 = OS-assigned, "
        "printed) whose 'learner' policy hot-swaps to every published "
        "learner snapshot: /v1/act answers with the CURRENT training "
        "params, version = blocks consumed + 1. Swaps ride the "
        "checkpoint.uncommit route — steady-state serving never "
        "recompiles",
    )
    p.add_argument(
        "--serve-buckets", default="1,4,16", metavar="B,B,..",
        help="--serve-port: act bucket sizes for the resident gateway "
        "(default 1,4,16 — smaller than scripts/serve.py's ladder; the "
        "sidecar warms before training starts, so startup cost is "
        "on the training critical path)",
    )
    p.add_argument(
        "--async-correction", choices=("vtrace", "none"), default="vtrace",
        help="async mode: staleness correction — 'vtrace' (clipped "
        "importance-weighted targets under the learner's params, "
        "default) or 'none' (plain GAE under the recorded behavior "
        "values; tolerates small staleness, A3C-style)",
    )
    p.add_argument(
        "--distributed", action="store_true",
        help="multi-host learner (parallel/multihost.py): this process "
        "is one host of a jax.distributed fleet — its actor fleet "
        "(--async-actors, host PPO only) feeds a local queue and the "
        "learner data-shards update batches across the global device "
        "mesh (or gossips params with --gossip). Requires --coordinator "
        "+ --num-processes + --process-id (or --gossip with a shared "
        "--mailbox-dir). For a CPU local cluster use "
        "scripts/launch_multihost.py instead.",
    )
    p.add_argument(
        "--coordinator", metavar="HOST:PORT", default="",
        help="jax.distributed coordinator address (rank 0's host). "
        "Needed for the sync all-reduce mode; optional under --gossip "
        "(peer-to-peer exchange never enters a collective).",
    )
    p.add_argument("--num-processes", type=int, default=1,
                   help="fleet size under --distributed")
    p.add_argument("--process-id", type=int, default=0,
                   help="this host's rank under --distributed")
    p.add_argument(
        "--gossip", action="store_true",
        help="distributed mode: exchange parameters peer-to-peer on a "
        "rotating ring schedule (no global barrier — a straggler host "
        "degrades fleet throughput instead of stalling it) instead of "
        "the synchronous all-reduce learner",
    )
    p.add_argument("--gossip-every", type=int, default=1, metavar="N",
                   help="consumed blocks between gossip exchanges")
    p.add_argument("--gossip-weight", type=float, default=0.5, metavar="W",
                   help="peer mixing weight in [0, 1]: params <- "
                   "(1-W) own + W peer")
    p.add_argument("--mailbox-dir", default="",
                   help="shared directory for the gossip param mailbox "
                   "(required for --gossip with more than one host)")
    p.add_argument(
        "--replay-dtype", choices=("fp32", "mixed", "int8"), default=None,
        help="off-policy algos (ddpg/td3/sac): replay-ring storage codec "
        "(replay/quantize.py). 'mixed' stores obs/rewards as int8 behind "
        "running mean/scale standardization with actions kept fp32 "
        "(~3x transitions per HBM byte); 'int8' also quantizes the "
        "bounded actions (~4x, aggressive); default fp32. Equivalent to "
        "--set replay_dtype=...; never flip it on a resumed run whose "
        "checkpoint carries a full ring (the template dtype must match).",
    )
    p.add_argument(
        "--update-dtype", choices=("fp32", "bf16"), default=None,
        help="update-compute precision (ISSUE 19). 'bf16' runs the "
        "network torso/head matmuls in bfloat16 with params, optimizer "
        "state, and every loss reduction kept fp32 (explicit fp32 "
        "accumulators; the heads cast outputs up before the loss); "
        "default fp32. Equivalent to --set bf16_compute=true. Eval "
        "parity vs fp32 is gated per algo in tests/test_bf16.py.",
    )
    p.add_argument("--quiet", action="store_true", help="no stdout metric echo")
    p.add_argument(
        "--no-overlap", action="store_true",
        help="host envs: disable the numpy actor mirror / async device "
        "update overlap (A/B baseline; models/host_actor.py)",
    )
    p.add_argument(
        "--scale-actions", action=argparse.BooleanOptionalAction,
        default=None,
        help="continuous envs: affine-map policy actions from [-1,1] "
        "onto the env's action bounds instead of clipping — keeps "
        "replayed == executed actions on narrow-bound envs like "
        "Humanoid-v5 (±0.4). Default: each env's own convention (host "
        "pools clip; jax:pendulum scales). Never flip this on a resumed "
        "run: the restored networks trained under the other convention.",
    )
    p.add_argument(
        "--compile-cache-dir", default="auto", metavar="DIR",
        help="persistent XLA compilation cache (utils/compile_cache.py): "
        "compiled programs are written here and later processes (e.g. "
        "run_resumable.sh retry legs) deserialize instead of recompiling. "
        "'auto' (default) uses a <ckpt-dir>/xla_cache sidecar when "
        "--ckpt-dir is set, else disables; 'none' disables explicitly.",
    )
    p.add_argument(
        "--warmup", action=argparse.BooleanOptionalAction, default=True,
        help="AOT-compile every registered jitted entry point (abstract "
        "shapes from the env spec + config) on a background thread while "
        "the env pool spawns/resets and the checkpoint restores, so "
        "time-to-first-step hides compile instead of serializing on it "
        "(utils/compile_cache.py warmup registry).",
    )
    p.add_argument("--ckpt-dir", help="orbax checkpoint dir")
    p.add_argument("--save-every", type=int, default=100)
    p.add_argument(
        "--no-save-replay", action="store_true",
        help="off-policy host runs: exclude the replay ring from "
        "checkpoints (a Humanoid-scale ring is ~3 GB per save). Resuming "
        "such a checkpoint restarts with an EMPTY buffer: updates pause "
        "until it refills past one batch, then continue on fresh "
        "experience only.",
    )
    p.add_argument("--resume", action="store_true", help="resume from --ckpt-dir")
    p.add_argument(
        "--stall-timeout", type=float, default=0,
        help="seconds without training progress before the process exits "
        "42 (device tunnel presumed wedged) so a retry loop can --resume; "
        "0 = off. Pair with --ckpt-dir/--save-every.",
    )
    p.add_argument("--list-presets", action="store_true")
    args = p.parse_args(argv)
    if args.telemetry_port is not None and not args.telemetry_dir:
        raise SystemExit(
            "--telemetry-port requires --telemetry-dir (the exporter "
            "serves the session's sinks and /profile captures land in "
            "that directory)"
        )
    if args.telemetry_sample_s <= 0:
        raise SystemExit("--telemetry-sample-s must be > 0")
    from actor_critic_tpu.telemetry.exporter import validate_bind

    try:
        validate_bind(args.telemetry_bind, distributed=args.distributed)
    except ValueError as e:
        raise SystemExit(str(e))

    from actor_critic_tpu.config import (
        PRESETS, parse_env_set_args, parse_set_args, resolve,
    )
    from actor_critic_tpu.utils.cadence import finite_or_none
    from actor_critic_tpu.utils.logging import JsonlLogger

    if args.list_presets:
        for name, pre in PRESETS.items():
            print(f"{name:18s} {pre.algo:7s} {pre.env:22s} {pre.description}")
        return 0

    preset = resolve(
        args.preset, args.algo, args.env, parse_set_args(args.set),
        env_overrides=parse_env_set_args(args.env_set),
    )
    if args.replay_dtype is not None:
        if not hasattr(preset.config, "replay_dtype"):
            raise SystemExit(
                f"--replay-dtype applies to the off-policy algos "
                f"(ddpg/td3/sac) with an HBM replay ring; {preset.algo} "
                "has no replay storage"
            )
        preset = dataclasses.replace(
            preset,
            config=dataclasses.replace(
                preset.config, replay_dtype=args.replay_dtype
            ),
        )
    if args.update_dtype is not None:
        if not hasattr(preset.config, "bf16_compute"):
            raise SystemExit(
                f"--update-dtype has no effect on {preset.algo}: its "
                "config carries no bf16_compute switch"
            )
        preset = dataclasses.replace(
            preset,
            config=dataclasses.replace(
                preset.config, bf16_compute=(args.update_dtype == "bf16")
            ),
        )
    if args.iterations is None:
        args.iterations = preset.iterations

    if args.curriculum:
        # Every doomed --curriculum combination exits before any env or
        # device work: the schedule drives a fused mixture fleet and
        # advances on the eval cadence.
        if not preset.env.startswith("mixture:"):
            raise SystemExit(
                "--curriculum re-weights a mixture fleet's type draw "
                "(--env mixture:<members>); it has no effect on "
                f"{preset.env!r}"
            )
        if args.eval_every <= 0:
            raise SystemExit(
                "--curriculum advances on learner eval progress — pass "
                "--eval-every N"
            )
        from actor_critic_tpu.envs import mixture as _mixture
        from actor_critic_tpu.envs import parse_mixture_spec

        try:
            names = tuple(
                n for n, _ in
                parse_mixture_spec(preset.env.partition(":")[2])
            )
            _mixture.parse_curriculum(args.curriculum, names)
        except ValueError as e:
            raise SystemExit(f"bad --curriculum: {e}") from e
        # Type re-draws are what the weights act on; an explicit
        # --env-set redraw_types=false wins (and makes the schedule a
        # weights-recording no-op, which the user asked for).
        preset.env_kwargs.setdefault("redraw_types", True)

    if args.data_plane == "device":
        # The data plane is the actor→learner hand-off: without actor
        # services there is no queue to relocate, and the multi-host
        # learner shard_maps HOST arrays into the global batch — exit
        # with advice before any env or device work.
        if args.async_actors <= 0:
            raise SystemExit(
                "--data-plane device relocates the async actor–learner "
                "hand-off into HBM — pass --async-actors N (the lockstep "
                "pipeline has no trajectory queue to relocate)"
            )
        if args.distributed:
            raise SystemExit(
                "--data-plane device is single-host for now: the "
                "--distributed sync learner builds its global batch from "
                "host arrays (make_array_from_process_local_data) — drop "
                "--distributed or use --data-plane host"
            )

    if args.serve_port is not None:
        # Serve-while-training rides the async publish cadence: the
        # lockstep/fused paths have no PolicyPublisher to hook.
        if args.async_actors <= 0:
            raise SystemExit(
                "--serve-port hooks the async learner's per-block "
                "publish (PolicyPublisher) — pass --async-actors N"
            )
        if args.distributed:
            raise SystemExit(
                "--serve-port is single-host (the resident gateway "
                "swaps from THIS process's publish hook); a fleet "
                "serves through scripts/serve.py --distributed + "
                "scripts/serve_fleet.py instead"
            )

    if args.distributed:
        # Every doomed flag combination exits HERE, before the blocking
        # coordinator handshake below (a misconfigured fleet member
        # hanging at jax.distributed.initialize is far worse than a
        # SystemExit). Resolving the preset first costs only module
        # imports — the XLA backend stays uninitialized until pools /
        # params / warmup touch it, which all happen after.
        if args.async_actors <= 0:
            raise SystemExit(
                "--distributed drives the async actor–learner stack: "
                "each host runs its own actor fleet — pass "
                "--async-actors N (host PPO)"
            )
        if preset.algo != "ppo":
            raise SystemExit(
                "--distributed drives the PPO multi-host learner "
                "(parallel/multihost.py); the off-policy async drivers "
                "are single-host — drop --distributed or use --algo ppo"
            )
        if not args.gossip and not args.coordinator:
            raise SystemExit(
                "--distributed sync mode needs --coordinator HOST:PORT "
                "(+ --num-processes/--process-id); or pass --gossip for "
                "the peer-to-peer mode"
            )
        if not args.gossip and args.async_correction != "vtrace":
            raise SystemExit(
                "--distributed sync mode shard_maps the V-trace-"
                "corrected update; --async-correction none is not "
                "supported there (gossip mode and single-host async "
                "accept it)"
            )
        if args.gossip and args.num_processes > 1 and not args.mailbox_dir:
            raise SystemExit(
                "--gossip with more than one host needs a shared "
                "--mailbox-dir"
            )
        if args.coordinator:
            # BEFORE anything initializes the XLA backend (the warmup
            # thread, pool construction, param init all would).
            from actor_critic_tpu.parallel.multihost import distributed_init

            distributed_init(
                args.coordinator, args.num_processes, args.process_id
            )
        # Rank affinity for the shared artifact paths: every host of
        # the fleet runs this same main() with the same flags, so an
        # unsuffixed --telemetry-dir/--metrics would interleave N
        # hosts' appends into ONE spans.jsonl/metrics.jsonl (torn lines
        # on a shared filesystem; scrambled rows even locally). Same
        # host<rank>/ convention as scripts/launch_multihost.py.
        rank = args.process_id
        if args.telemetry_dir:
            args.telemetry_dir = os.path.join(
                args.telemetry_dir, f"host{rank}"
            )
        root, ext = os.path.splitext(args.metrics)
        args.metrics = f"{root}.host{rank}{ext}"

    print(
        f"algo={preset.algo} env={preset.env} iterations={args.iterations} "
        f"config={dataclasses.asdict(preset.config)} "
        f"env_kwargs={preset.env_kwargs}",
        flush=True,
    )
    from actor_critic_tpu.utils import compile_cache

    cache_dir = compile_cache.resolve_cache_dir(
        args.compile_cache_dir, args.ckpt_dir
    )
    if cache_dir is not None:
        # Before the first trace/compile of the process: every program —
        # including the warmup thread's — must land in (or hit) the
        # on-disk cache so resumed legs start near-instantly.
        compile_cache.enable_persistent_cache(cache_dir)
        print(f"compile cache: {cache_dir}", flush=True)
    pools = None
    if args.async_actors > 0:
        if (args.ckpt_dir or args.resume) and (
            preset.algo != "ppo" or args.distributed
        ):
            raise SystemExit(
                "--async-actors checkpointing is wired for single-host "
                "PPO only (the save tree carries every actor pool's "
                "normalizer state — ppo.train_host_async); off-policy "
                "async and --distributed runs don't support "
                "--ckpt-dir/--resume yet"
            )
        if args.no_overlap:
            print(
                "--no-overlap is meaningless with --async-actors (actors "
                "always act through the numpy mirror); ignored",
                flush=True,
            )
        pools = build_actor_pools(preset, args, args.async_actors)
        env, fused = pools[0], False
    else:
        env, fused = build_env(
            preset.env, preset.algo, preset.config, args.seed,
            scale_actions=args.scale_actions, env_kwargs=preset.env_kwargs,
            workers=args.workers,
        )
    if fused and args.workers > 1:
        print("--workers applies to host pools only; ignored for jax:* "
              "envs (their rollouts are fused on-device)", flush=True)
    # Host pools carry their ACTION convention in the checkpoint metrics
    # too (host_loop's _pool_scale_actions), but env_kwargs exist only
    # here — the sidecar guards both paths against resuming into a
    # different env (kwargs) or convention (fused envs).
    check_env_convention(
        args.ckpt_dir, preset.env, args.scale_actions, args.resume,
        env_kwargs=preset.env_kwargs,
    )

    telemetry_session = None
    if args.telemetry_dir:
        telemetry_session = telemetry.TelemetrySession(
            args.telemetry_dir,
            run_info={
                "algo": preset.algo,
                "env": preset.env,
                "iterations": args.iterations,
                "seed": args.seed,
                "config": dataclasses.asdict(preset.config),
            },
            resource_interval_s=args.telemetry_sample_s,
            serve_port=args.telemetry_port,
            serve_host=args.telemetry_bind,
        )
        telemetry.set_current(telemetry_session)
        if telemetry_session.exporter is not None:
            print(
                f"telemetry exporter: {telemetry_session.exporter.url}"
                "/metrics /healthz /profile?iters=N",
                flush=True,
            )
        # `kill -USR2 <pid>` arms an on-demand profile capture even when
        # no --telemetry-port was given.
        from actor_critic_tpu.telemetry.profiler import install_sigusr2

        install_sigusr2()

    if args.warmup and cache_dir is None:
        # AOT-compiled executables are never installed into the jit
        # dispatch cache (JAX AOT contract) — without the persistent
        # cache to carry them to the loop's own jit objects, warmup
        # would just compile everything twice on a contended host.
        print(
            "AOT warmup skipped: requires the persistent compile cache "
            "(--compile-cache-dir, or --ckpt-dir for the auto sidecar)",
            flush=True,
        )
    elif args.warmup:
        # Background AOT warmup: compile every registered entry point
        # (abstract arg shapes from spec + config) while the host side
        # resets pools / restores checkpoints. XLA compilation releases
        # the GIL, so this genuinely overlaps; each compile lands in the
        # persistent cache, so the loop's own first dispatch re-traces
        # and hits instead of compiling.
        ctx = compile_cache.WarmupContext(
            algo=preset.algo, fused=fused, spec=env.spec,
            cfg=preset.config, env=env if fused else None,
            chunk=max(1, args.chunk) if fused else 1,
            iterations=args.iterations, eval_every=args.eval_every,
            eval_envs=args.eval_envs, overlap=not args.no_overlap,
            resume=args.resume,
            async_actors=args.async_actors,
            async_correction=args.async_correction,
            data_plane=args.data_plane,
            plane_codec=args.data_plane_codec,
            queue_depth=args.queue_depth,
        )
        plan = compile_cache.plan_warmup(ctx)
        if plan:
            print(
                f"AOT warmup: {len(plan)} entry point(s) compiling in "
                "the background: " + ", ".join(n for n, _ in plan),
                flush=True,
            )
            compile_cache.WarmupRunner(plan).start()

    watchdog = None
    if args.stall_timeout > 0:
        from actor_critic_tpu.utils.watchdog import StallWatchdog

        if getattr(args, "chunk", 1) > 1:
            # One heartbeat per chunk: a timeout shorter than a chunk's
            # wall time would misread normal progress as a stall and
            # kill/resume in a loop that never clears the chunk.
            print(
                f"watchdog with --chunk {args.chunk}: --stall-timeout "
                f"{args.stall_timeout:g}s must exceed one chunk's wall "
                "time or the run will be killed mid-chunk", flush=True,
            )
        watchdog = StallWatchdog(args.stall_timeout).start()
    t0 = time.time()
    try:
        with JsonlLogger(args.metrics, echo=not args.quiet) as logger:
            if fused:
                final = run_fused(env, preset, args, logger)
            else:
                if getattr(args, "chunk", 1) > 1:
                    print("--chunk applies to fused (jax:*) envs only; "
                          "ignored for host pools", flush=True)
                if pools is not None and args.distributed:
                    final = run_multihost(pools, preset, args, logger)
                elif pools is not None:
                    final = run_host_async(pools, preset, args, logger)
                else:
                    final = run_host(env, preset, args, logger)
    finally:
        if watchdog is not None:
            watchdog.stop()
        if telemetry_session is not None:
            telemetry_session.close()
        if pools is not None:
            for p_ in pools:
                p_.close()
    wall = time.time() - t0
    print(
        json.dumps(
            {
                "algo": preset.algo,
                "env": preset.env,
                "iterations": args.iterations,
                # Async mode consumes [K, E/A] blocks: env_steps here is
                # what the LEARNER consumed (actor-side collection,
                # drops included, rides the metrics rows).
                "env_steps": args.iterations
                * steps_per_iteration(preset.algo, preset.config)
                // max(1, args.async_actors),
                "wall_s": round(wall, 2),
                # NaN/Inf → null: the summary line must stay strict JSON
                **{
                    k: (None if (f := finite_or_none(v)) is None else round(f, 5))
                    for k, v in final.items()
                },
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
