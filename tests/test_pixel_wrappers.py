"""Pixel preprocessing wrapper: shapes, stacking, reward clip, action
repeat — against a synthetic RGB env (ALE absent in this image,
SURVEY.md §7.0)."""

import gymnasium as gym
import numpy as np

from actor_critic_tpu.envs.pixel_wrappers import PixelPreprocess


class _SyntheticPixelEnv(gym.Env):
    """RGB frames whose uniform brightness encodes the step count
    (30 + 20t, resize-proof); reward 2.5 each step; terminates at step 10."""

    observation_space = gym.spaces.Box(0, 255, (60, 80, 3), np.uint8)
    action_space = gym.spaces.Discrete(2)

    def __init__(self):
        self.t = 0

    def _frame(self):
        return np.full((60, 80, 3), 30 + 20 * self.t, np.uint8)

    def reset(self, seed=None, options=None):
        self.t = 0
        return self._frame(), {}

    def step(self, action):
        self.t += 1
        return self._frame(), 2.5, self.t >= 10, False, {}


def test_obs_contract():
    env = PixelPreprocess(_SyntheticPixelEnv(), size=84, stack=4)
    obs, _ = env.reset()
    assert obs.shape == (84, 84, 4) and obs.dtype == np.uint8
    assert env.observation_space.shape == (84, 84, 4)
    # reset replicates the first frame across the stack
    assert (obs[:, :, 0] == obs[:, :, 3]).all()


def test_frame_stack_rolls():
    env = PixelPreprocess(_SyntheticPixelEnv(), size=60, stack=3)
    env.reset()
    obs, *_ = env.step(0)
    obs, *_ = env.step(0)
    # channels hold distinct history: brightness 30, 50, 70 for t=0,1,2
    means = [round(float(obs[:, :, c].mean())) for c in range(3)]
    assert means == [30, 50, 70], means


def test_reward_clip_and_action_repeat():
    env = PixelPreprocess(_SyntheticPixelEnv(), action_repeat=3, clip_reward=True)
    env.reset()
    _, r, term, trunc, _ = env.step(0)
    assert r == 1.0  # sign(3 * 2.5)
    env2 = PixelPreprocess(_SyntheticPixelEnv(), action_repeat=3, clip_reward=False)
    env2.reset()
    _, r2, *_ = env2.step(0)
    assert abs(r2 - 7.5) < 1e-6


def test_action_repeat_stops_at_termination():
    env = PixelPreprocess(_SyntheticPixelEnv(), action_repeat=4, clip_reward=False)
    env.reset()
    term = False
    steps = 0
    while not term:
        _, r, term, trunc, _ = env.step(0)
        steps += 1
        assert steps < 10
    # terminal step consumed <= action_repeat inner steps, none past done
    assert env.env.t == 10


def test_uint8_survives_host_pool():
    """With normalize_obs=False the pool must deliver uint8 pixels so the
    CNN encoder's /255 branch fires (regression: the pool used to
    float32-cast every obs)."""
    import gymnasium.envs.registration as reg

    from actor_critic_tpu.envs.host_pool import HostEnvPool

    if "SynthPx-v0" not in gym.registry:
        reg.register(id="SynthPx-v0", entry_point=_SyntheticPixelEnv)
    pool = HostEnvPool(
        "SynthPx-v0", num_envs=2, pixel_preprocess=True,
        normalize_obs=False, normalize_reward=False,
    )
    obs = pool.reset()
    assert obs.dtype == np.uint8 and obs.shape == (2, 84, 84, 4)
    assert pool.spec.obs_dtype == np.uint8
    out = pool.step(np.zeros(2, np.int64))
    assert out.obs.dtype == np.uint8
    assert out.final_obs.dtype == np.uint8


def test_gray_resize_known_values():
    env = PixelPreprocess(_SyntheticPixelEnv(), size=30, stack=2)
    obs, _ = env.reset()
    # uniform gray 30 everywhere except marker → mean close to 30
    assert abs(float(obs.mean()) - 30.0) < 1.0
