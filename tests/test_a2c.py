"""A2C CartPole learning test (SURVEY.md §4: 'CartPole-v1 A2C/PPO reach
reward >=195 within a step budget').

The flagship a2c_cartpole preset's annealed shape (lr and entropy →0
over the run — the flat-coefficient config oscillated at eval ≤429 and
never converged, round-2 verdict #1; round 4 doubled T to 64 and scaled
preset lr to 3e-3 with the E=4096 batch, reaching eval 491/500) at a
reduced CPU batch with the batch-appropriate lr=1e-3: calibrated greedy
eval 487/488/469/486 at iteration 400 (E=256, seeds 0–3,
scripts/a2c_anneal_sweep.py); the test floor of 400 doubles SURVEY's
≥195 bar while leaving seed/shape headroom.
"""

import jax
import pytest

from actor_critic_tpu.algos import a2c
from actor_critic_tpu.envs import make_cartpole


@pytest.mark.slow
def test_a2c_learns_cartpole_annealed():
    env = make_cartpole()
    cfg = a2c.A2CConfig(
        num_envs=256, rollout_steps=64, lr=1e-3,
        anneal_iters=400, lr_final=0.0,
        entropy_coef=0.01, entropy_coef_final=0.0,
    )
    # a2c.train with log_every=0 is the real entry path (the silent loop
    # scans on-device in O(1) dispatches).
    state, _ = a2c.train(env, cfg, num_iterations=400, seed=0)
    eval_fn = jax.jit(a2c.make_eval_fn(env, cfg), static_argnums=(2, 3))
    ev = float(eval_fn(state, jax.random.key(1), 32, 512))
    assert ev >= 400.0, f"annealed A2C failed CartPole: greedy eval {ev}"
