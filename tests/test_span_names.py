"""Static check: every phase-span name in the codebase is canonical.

`scripts/run_report.py`'s phase breakdown groups spans by NAME — a
typo'd `telemetry.span("updaet")` raises nowhere and simply grows a
one-off row that silently vanishes from every aggregate people actually
read. This test greps the source for every literal name passed to
`telemetry.span(...)` / `complete_span(...)` / `instant(...)` (and the
tracer-level `complete_foreign(...)` the shard-pool relay uses) and
asserts membership in `telemetry.CANONICAL_PHASES`. Add new phases to
that set (telemetry/spans.py) BEFORE instrumenting with them.
"""

import re
from pathlib import Path

from actor_critic_tpu import telemetry

REPO = Path(__file__).parent.parent

# Source that emits phase spans; tests are excluded on purpose — they
# exercise the tracer with synthetic names.
SCAN = ["actor_critic_tpu", "scripts", "train.py", "bench.py", "bench"]

_CALL = re.compile(
    r"""(?:telemetry|_session)\s*\.\s*
        (?:span|complete_span|instant)\s*\(\s*
        (['"])(?P<name>[^'"]+)\1
    """,
    re.VERBOSE,
)
_FOREIGN = re.compile(
    r"""\.\s*complete_foreign\s*\(\s*(['"])(?P<name>[^'"]+)\1""",
    re.VERBOSE,
)
# Phase names bound to a constant before use (e.g. the shard-pool
# relay's batched emission) declare themselves with a *_PHASE suffix.
_CONST = re.compile(
    r"""^\s*\w+_PHASE\s*=\s*(['"])(?P<name>[^'"]+)\1""",
    re.MULTILINE,
)


def _span_names() -> dict[str, set[str]]:
    """{span name: {files using it}} across the scanned source."""
    uses: dict[str, set[str]] = {}
    for root in SCAN:
        path = REPO / root
        files = [path] if path.is_file() else sorted(path.rglob("*.py"))
        for f in files:
            text = f.read_text()
            for pat in (_CALL, _FOREIGN, _CONST):
                for m in pat.finditer(text):
                    uses.setdefault(m.group("name"), set()).add(
                        str(f.relative_to(REPO))
                    )
    return uses


def test_every_span_name_is_canonical():
    uses = _span_names()
    assert uses, "scanner found no span call sites — regex rotted?"
    rogue = {
        name: sorted(files)
        for name, files in uses.items()
        if name not in telemetry.CANONICAL_PHASES
    }
    assert not rogue, (
        f"non-canonical span name(s) {rogue} — add to "
        "telemetry/spans.py CANONICAL_PHASES or fix the typo"
    )


def test_core_phases_are_instrumented():
    """The phases the run report's breakdown documents must actually be
    emitted somewhere (guards against an instrumentation refactor
    silently dropping one)."""
    uses = _span_names()
    for phase in ("iteration", "env_step", "update", "log", "checkpoint",
                  "eval", "host_to_device", "env_step_worker"):
        assert phase in uses, f"phase {phase!r} no longer instrumented"
