"""Stall watchdog (utils/watchdog.py, SURVEY.md §5.3 failure detection).

The firing path calls os._exit, so it must be exercised in a subprocess;
the keep-alive path runs in-process.
"""

import os
import subprocess
import sys
import time

from actor_critic_tpu.utils import watchdog


def _run(code: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=60, env=env,
    )


def test_fires_exit_42_on_stall():
    proc = _run(
        "import time\n"
        "from actor_critic_tpu.utils.watchdog import StallWatchdog\n"
        "StallWatchdog(1.0, startup_grace_s=0.0).start()\n"
        "time.sleep(30)\n"  # a 'wedged device call'; watchdog must kill us
        "print('unreachable')\n"
    )
    assert proc.returncode == watchdog.STALL_EXIT_CODE, (
        proc.returncode, proc.stderr,
    )
    assert "stall-watchdog" in proc.stderr
    assert "unreachable" not in proc.stdout


def test_beats_keep_it_alive_and_stop_disarms():
    # Generous timeout/beat ratio (15x): this watchdog is ARMED in the
    # pytest process, and a firing would os._exit the whole session —
    # the margin must absorb CI scheduler hiccups.
    w = watchdog.StallWatchdog(3.0, startup_grace_s=0.0).start()
    try:
        for _ in range(8):
            time.sleep(0.2)
            watchdog.beat()  # module-level beat reaches the armed instance
    finally:
        w.stop()
    assert w not in watchdog._ACTIVE
    time.sleep(0.5)  # disarmed: no exit even without beats


def test_cli_stall_timeout_clean_run(tmp_path):
    """--stall-timeout armed around a healthy run must not interfere."""
    proc = _run(
        "import sys\n"
        "sys.argv = ['train.py', '--algo', 'a2c', '--env', 'jax:two_state',\n"
        "            '--iterations', '3', '--quiet', '--log-every', '1',\n"
        f"            '--metrics', {str(tmp_path / 'm.jsonl')!r},\n"
        "            '--stall-timeout', '120']\n"
        "import train\n"
        "sys.exit(train.main())\n"
    )
    assert proc.returncode == 0, proc.stderr
