"""Stall watchdog (utils/watchdog.py, SURVEY.md §5.3 failure detection).

The firing path calls os._exit, so it must be exercised in a subprocess;
the keep-alive path runs in-process.
"""

import os
import subprocess
import sys
import time

from actor_critic_tpu.utils import watchdog


def _run(code: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=60, env=env,
    )


def test_fires_exit_42_on_stall():
    proc = _run(
        "import time\n"
        "from actor_critic_tpu.utils.watchdog import StallWatchdog\n"
        "StallWatchdog(1.0, startup_grace_s=0.0).start()\n"
        "time.sleep(30)\n"  # a 'wedged device call'; watchdog must kill us
        "print('unreachable')\n"
    )
    assert proc.returncode == watchdog.STALL_EXIT_CODE, (
        proc.returncode, proc.stderr,
    )
    assert "stall-watchdog" in proc.stderr
    assert "unreachable" not in proc.stdout


def test_beats_keep_it_alive_and_stop_disarms():
    # Generous timeout/beat ratio (15x): this watchdog is ARMED in the
    # pytest process, and a firing would os._exit the whole session —
    # the margin must absorb CI scheduler hiccups.
    w = watchdog.StallWatchdog(3.0, startup_grace_s=0.0).start()
    try:
        for _ in range(8):
            time.sleep(0.2)
            watchdog.beat()  # module-level beat reaches the armed instance
    finally:
        w.stop()
    assert w not in watchdog._ACTIVE
    time.sleep(0.5)  # disarmed: no exit even without beats


def test_cli_stall_timeout_clean_run(tmp_path):
    """--stall-timeout armed around a healthy run must not interfere."""
    proc = _run(
        "import sys\n"
        "sys.argv = ['train.py', '--algo', 'a2c', '--env', 'jax:two_state',\n"
        "            '--iterations', '3', '--quiet', '--log-every', '1',\n"
        f"            '--metrics', {str(tmp_path / 'm.jsonl')!r},\n"
        "            '--stall-timeout', '120']\n"
        "import train\n"
        "sys.exit(train.main())\n"
    )
    assert proc.returncode == 0, proc.stderr


def test_armed_and_ensure_timeout_at_least():
    """The chunk-wall auto-raise contract (ADVICE r4 #2): a completed
    chunk's measured wall time widens armed watchdogs, never narrows."""
    assert not watchdog.armed()
    w = watchdog.StallWatchdog(5.0, startup_grace_s=0.0).start()
    try:
        assert watchdog.armed()
        watchdog.ensure_timeout_at_least(2.0)   # below current: no-op
        assert w.timeout_s == 5.0
        watchdog.ensure_timeout_at_least(9.0)   # above: raises
        assert w.timeout_s == 9.0
        watchdog.ensure_timeout_at_least(9.0)   # equal: no-op
        assert w.timeout_s == 9.0
    finally:
        w.stop()
    assert not watchdog.armed()
    watchdog.ensure_timeout_at_least(99.0)      # disarmed: nothing to touch


def test_chunked_train_widens_watchdog_from_real_chunk_wall(monkeypatch):
    """End-to-end: checkpointed_train(stride>1) must measure the chunk
    BEHIND a block (a jitted call returns at enqueue time) and raise an
    armed watchdog to 3x the measured wall — from the SECOND dispatch on
    (the first is compile-inflated and skipped by design). Pinned to the
    HEURISTIC compile-detection path (telemetry listener off): these
    step fns fake compile latency with sleep, which the measured
    compile-event path correctly calls clean."""
    import jax.numpy as jnp

    from actor_critic_tpu.utils import checkpoint
    from actor_critic_tpu.utils.checkpoint import checkpointed_train

    monkeypatch.setattr(checkpoint, "_compile_probe", lambda: None)

    def slow_chunk(state, k):
        time.sleep(0.25)  # stand-in for real device wall time
        return state + k, {"loss": jnp.asarray(0.0)}

    # Default startup grace shields the FIRST chunk (in production it
    # shields first-call XLA compilation); the auto-raise must then widen
    # the armed 0.4s timeout past the 0.25s chunk wall before the grace
    # window would have expired. (An armed 0.1s/grace-0 variant of this
    # test correctly dies at the first chunk — that is the documented
    # pre-grace behavior, not a bug.)
    w = watchdog.StallWatchdog(0.4).start()
    try:
        state, _ = checkpointed_train(
            slow_chunk, jnp.asarray(0), num_iterations=4, stride=2,
        )
        assert int(state) == 4
        # 3 x ~0.25s measured wall (second dispatch): widened past 0.4.
        assert w.timeout_s >= 0.6, w.timeout_s
    finally:
        w.stop()


def test_chunked_train_first_dispatch_never_ratchets_and_wall_persists(
    tmp_path, monkeypatch
):
    """ISSUE 2 satellite: (a) the FIRST dispatch of a process — which in
    production carries full XLA compile — must not drive the auto-raise
    (it would bake compile time into 3x the stall timeout for the whole
    run); (b) the steady-state chunk wall persists to a ckpt-dir sidecar;
    (c) a resumed process widens its armed watchdog from the sidecar
    BEFORE its own (skipped) chunk 1. Heuristic detection path pinned
    (see test_chunked_train_widens_watchdog_from_real_chunk_wall)."""
    import json

    import jax.numpy as jnp

    from actor_critic_tpu.utils import checkpoint
    from actor_critic_tpu.utils.checkpoint import Checkpointer, checkpointed_train

    monkeypatch.setattr(checkpoint, "_compile_probe", lambda: None)

    calls = []

    def chunk(state, k):
        time.sleep(0.5 if not calls else 0.05)  # dispatch 1 "compiles"
        calls.append(k)
        return {"n": state["n"] + k}, {"loss": jnp.asarray(0.0)}

    init = {"n": jnp.asarray(0)}
    w = watchdog.StallWatchdog(0.4).start()  # default grace shields chunk 1
    try:
        with Checkpointer(tmp_path / "ck") as ck:
            state, _ = checkpointed_train(
                chunk, init, num_iterations=6, stride=2, ckpt=ck,
            )
        assert int(state["n"]) == 6 and len(calls) == 3
        # The 0.5s first dispatch did NOT ratchet (3 x 0.5 = 1.5 would
        # show); the 0.05s steady chunks ratchet 0.15 < 0.4 — a no-op.
        assert w.timeout_s == 0.4, w.timeout_s
    finally:
        w.stop()
    with open(tmp_path / "ck" / "chunk_wall.json") as f:
        wall = json.load(f)["chunk_wall_s"]
    assert 0 < wall < 0.3, wall  # steady wall, not the compile-inflated one

    # Resume leg: the persisted wall widens a narrower armed watchdog
    # before any dispatch runs (here: zero dispatches remain).
    w2 = watchdog.StallWatchdog(0.01).start()
    try:
        with Checkpointer(tmp_path / "ck") as ck:
            state, _ = checkpointed_train(
                chunk, init, num_iterations=6, stride=2, ckpt=ck,
            )
        assert int(state["n"]) == 6 and len(calls) == 3  # nothing re-ran
        assert w2.timeout_s >= 3.0 * wall - 1e-6, w2.timeout_s
    finally:
        w2.stop()


def test_chunked_train_ratchet_consumes_compile_events(monkeypatch):
    """ISSUE 4 satellite: with the telemetry compile listener installed,
    the ratchet decides "compile-inflated dispatch" from MEASURED compile
    events, not from per-k novelty — a recompile on a later same-k
    dispatch (the storm case the heuristic misreads as a clean wall)
    must extend grace instead of ratcheting its inflated wall into the
    permanent timeout."""
    import jax.numpy as jnp

    from actor_critic_tpu.utils import checkpoint
    from actor_critic_tpu.utils.checkpoint import checkpointed_train

    compile_count = [0]
    monkeypatch.setattr(
        checkpoint, "_compile_probe", lambda: (lambda: compile_count[0])
    )
    calls = []

    def chunk(state, k):
        calls.append(k)
        if len(calls) <= 2:
            compile_count[0] += 1  # dispatches 1 AND 2 "pay compile"
            time.sleep(0.3)       # compile-inflated wall
        else:
            time.sleep(0.05)      # steady-state wall
        return state + k, {"loss": jnp.asarray(0.0)}

    w = watchdog.StallWatchdog(0.4).start()
    try:
        state, _ = checkpointed_train(
            chunk, jnp.asarray(0), num_iterations=6, stride=2,
        )
        assert int(state) == 6 and len(calls) == 3
        # The k-novelty heuristic would have ratcheted dispatch 2
        # (same k as dispatch 1) to 3 x 0.3 = 0.9s; the event-driven
        # path shields it, and the clean 0.05s dispatch ratchets a
        # no-op 0.15 < 0.4.
        assert w.timeout_s == 0.4, w.timeout_s
    finally:
        w.stop()
