"""Scenario-fleet tests (ISSUE 8): deterministic per-instance physics
draws, per-episode re-randomization through auto_reset, range
configuration (fractional + per-param + --env-set string spellings),
default-env gymnasium-constant parity, and a domain-randomized fused
A2C smoke run stepping a heterogeneous fleet in one XLA program."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.envs import make_cartpole, make_pendulum
from actor_critic_tpu.envs import cartpole as cp
from actor_critic_tpu.envs.jax_env import (
    draw_scenario, is_randomized, scenario_ranges,
)


class TestRanges:
    def test_fractional_randomize(self):
        r = scenario_ranges({"mass": 2.0}, randomize=0.25)
        assert r["mass"] == (1.5, 2.5)
        assert is_randomized(r)

    def test_degenerate_without_randomize(self):
        r = scenario_ranges({"mass": 2.0})
        assert r["mass"] == (2.0, 2.0)
        assert not is_randomized(r)

    def test_override_spellings(self):
        """(lo, hi) tuples, '--env-set'-style 'lo,hi' strings, and bare
        numbers (pin) all resolve."""
        r = scenario_ranges(
            {"a": 1.0, "b": 1.0, "c": 1.0}, randomize=0.1,
            overrides={"a": (0.5, 2.0), "b": "0.25,4", "c": 3.0},
        )
        assert r["a"] == (0.5, 2.0)
        assert r["b"] == (0.25, 4.0)
        assert r["c"] == (3.0, 3.0)

    def test_unknown_param_raises(self):
        with pytest.raises(ValueError, match="unknown scenario parameter"):
            scenario_ranges({"mass": 1.0}, overrides={"masss": 2.0})

    def test_bad_range_raises(self):
        with pytest.raises(ValueError, match="lo,hi"):
            scenario_ranges({"mass": 1.0}, overrides={"mass": "1,2,3"})
        with pytest.raises(ValueError, match="randomize"):
            scenario_ranges({"mass": 1.0}, randomize=-0.5)

    def test_draw_determinism(self):
        """Same key ⇒ same randomized params; different keys differ —
        the scenario-fleet reproducibility contract."""
        r = scenario_ranges({"mass": 1.0, "g": 10.0}, randomize=0.5)
        a = draw_scenario(jax.random.key(7), r)
        b = draw_scenario(jax.random.key(7), r)
        c = draw_scenario(jax.random.key(8), r)
        for name in r:
            assert float(a[name]) == float(b[name])
        assert any(float(a[n]) != float(c[n]) for n in r)
        for name, (lo, hi) in r.items():
            assert lo <= float(a[name]) <= hi


class TestScenarioEnvs:
    def test_default_env_uses_exact_constants(self):
        """The non-randomized env must carry gymnasium's exact constants
        (the parity tests in test_envs.py compare dynamics against the
        installed gymnasium)."""
        env = make_cartpole()
        state, _ = env.reset(jax.random.key(0))
        sc = state.scenario
        assert float(sc.gravity) == np.float32(cp.GRAVITY)
        assert float(sc.masspole) == np.float32(cp.MASSPOLE)
        assert float(sc.force_mag) == np.float32(cp.FORCE_MAG)

    def test_fleet_is_heterogeneous_and_reproducible(self):
        env = make_cartpole(randomize=0.3)
        keys = jax.random.split(jax.random.key(0), 64)
        s1, _ = jax.vmap(env.reset)(keys)
        s2, _ = jax.vmap(env.reset)(keys)
        masses = np.asarray(s1.scenario.masspole)
        assert len(np.unique(masses)) > 32  # per-instance draws
        assert (masses >= cp.MASSPOLE * 0.7 - 1e-6).all()
        assert (masses <= cp.MASSPOLE * 1.3 + 1e-6).all()
        np.testing.assert_array_equal(
            masses, np.asarray(s2.scenario.masspole)
        )  # same keys ⇒ same fleet

    def test_autoreset_redraws_scenario(self):
        """An episode end re-randomizes the instance's physics (fresh
        draw from its own PRNG stream) while non-done instances keep
        theirs — per-episode domain randomization."""
        env = make_pendulum(randomize=0.4)
        keys = jax.random.split(jax.random.key(1), 4)
        state, obs = jax.vmap(env.reset)(keys)
        before = np.asarray(state.scenario.mass)
        # Pendulum truncates at MAX_STEPS; force it by setting t high.
        state = state._replace(
            t=jnp.full_like(state.t, 10_000),
        )
        out = jax.vmap(env.step)(state, jnp.zeros((4, 1), jnp.float32))
        assert (np.asarray(out.done) == 1.0).all()
        after = np.asarray(out.state.scenario.mass)
        assert (before != after).all()

    def test_scenario_changes_dynamics(self):
        """Heavier pole / stronger force actually alters the step output
        (the scenario is load-bearing, not decorative)."""
        heavy = make_cartpole(masspole=1.0)
        light = make_cartpole(masspole=0.05)
        sh, _ = heavy.reset(jax.random.key(3))
        sl, _ = light.reset(jax.random.key(3))
        # Same kinematic start, different physics.
        sl = sl._replace(scenario=sl.scenario)
        a = jnp.asarray(1, jnp.int32)
        oh = heavy.step(sh, a)
        ol = light.step(sl, a)
        assert float(oh.state.theta_dot) != float(ol.state.theta_dot)

    def test_env_set_string_ranges(self):
        """--env-set masspole=0.05,0.5 reaches the maker as a string and
        becomes a live per-instance range."""
        env = make_cartpole(masspole="0.05,0.5")
        keys = jax.random.split(jax.random.key(4), 32)
        s, _ = jax.vmap(env.reset)(keys)
        m = np.asarray(s.scenario.masspole)
        assert m.min() >= 0.05 and m.max() <= 0.5
        assert len(np.unique(m)) > 16


def test_randomized_fused_a2c_smoke():
    """ISSUE 8: a domain-randomized fleet steps and TRAINS inside one
    fused XLA program — A2C on scenario-randomized CartPole, finite
    metrics, episode accounting alive."""
    from actor_critic_tpu.algos import a2c

    env = make_cartpole(randomize=0.3)
    cfg = a2c.A2CConfig(num_envs=64, rollout_steps=16, hidden=(32,))
    state, metrics = a2c.train(env, cfg, num_iterations=3, seed=0)
    assert int(state.update_step) == 3
    for k, v in metrics.items():
        assert np.isfinite(float(v)), (k, v)
    # The trained fleet really is heterogeneous.
    masses = np.asarray(state.rollout.env_state.scenario.masspole)
    assert len(np.unique(masses)) > 32
