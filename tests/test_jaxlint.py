"""Tier-1 wiring for the jaxlint analyzer (ISSUE 5).

Three layers of guarantees:

1. **Fixture pairs** — per registered check, a `*_flag.py` fixture that
   MUST produce findings of exactly that check and a `*_ok.py` near
   miss that MUST stay completely clean, so a pass going blind (or
   over-flagging the sanctioned idiom) fails CI.
2. **Mechanics** — inline suppression comments (same line and
   standalone line), baseline round-trip (save → load → zero new,
   stale detection when the flagged line changes).
3. **The gate** — the real tree (`actor_critic_tpu train.py bench`)
   analyzes clean against the repo baseline, and the CLI's exit codes
   stay distinct: 0 clean / 1 findings / 2 crash-or-parse-error.

Everything runs AST-only (the analyzer never imports the files it
scans), so this module is JAX_PLATFORMS=cpu-safe and fast; only the
final gate test touches the live warmup registry (already imported by
the rest of tier-1).
"""

import importlib.util
import json
from pathlib import Path

import pytest

from actor_critic_tpu import analysis
from actor_critic_tpu.analysis import warmup

REPO = Path(__file__).parent.parent
FIXTURES = Path(__file__).parent / "jaxlint_fixtures"

# Every AST check rides the same fixture contract; warmup-registry is
# repo-scoped and has its own pair test below.
PAIRS = [
    ("donation-aliasing", "donation_aliasing"),
    ("tracer-leak", "tracer_leak"),
    ("prng-reuse", "prng_reuse"),
    ("recompile-hazard", "recompile_hazard"),
    ("transfer-discipline", "transfer_discipline"),
    ("donation-discipline", "donation_discipline"),
    ("dispatch-granularity", "dispatch_granularity"),
    ("lock-discipline", "lock_discipline"),
    ("publish-aliasing", "publish_aliasing"),
    ("check-then-act", "check_then_act"),
    ("collective-discipline", "collective_discipline"),
    ("mailbox-protocol", "mailbox_protocol"),
    ("rank-affinity", "rank_affinity"),
    ("precision-discipline", "precision_discipline"),
    ("nonfinite-hazard", "nonfinite_hazard"),
    ("sink-guard", "sink_guard"),
    ("pad-mask-discipline", "pad_mask_discipline"),
    ("mask-propagation", "mask_propagation"),
    ("slice-before-commit", "slice_before_commit"),
]


def _analyze(*names: str, checks=None):
    return analysis.analyze_paths(
        [str(FIXTURES / n) for n in names],
        str(REPO),
        checks=checks,
        skip=("warmup-registry",),
    )


def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "jaxlint_cli", REPO / "scripts" / "jaxlint.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# fixture pairs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("check,stem", PAIRS)
def test_flag_fixture_flags(check, stem):
    findings = _analyze(f"{stem}_flag.py")
    assert findings, f"{stem}_flag.py produced no findings"
    assert all(f.check == check for f in findings), (
        f"{stem}_flag.py leaked findings of other checks: "
        f"{[f.render() for f in findings if f.check != check]}"
    )


@pytest.mark.parametrize("check,stem", PAIRS)
def test_ok_fixture_stays_clean(check, stem):
    findings = _analyze(f"{stem}_ok.py")
    assert findings == [], (
        f"{stem}_ok.py must be clean, got: "
        f"{[f.render() for f in findings]}"
    )


def test_warmup_registry_fixture_pair():
    mods = analysis.load_modules(
        [
            str(FIXTURES / "warmup_registry_flag.py"),
            str(FIXTURES / "warmup_registry_ok.py"),
        ],
        str(REPO),
    )
    sites = warmup.sites_from_modules(
        mods, scan_dirs=("tests/jaxlint_fixtures",)
    )
    assert set(sites) == {
        "warmup_registry_flag.make_step",
        "warmup_registry_ok.make_step",
    }
    findings = warmup.site_findings(
        sites, registered={"warmup_registry_ok.make_step"}, exempt={}
    )
    assert [f.check for f in findings] == ["warmup-registry"]
    assert "warmup_registry_flag.make_step" in findings[0].message
    # near miss: fully covered registry -> clean
    assert (
        warmup.site_findings(
            sites,
            registered={
                "warmup_registry_flag.make_step",
                "warmup_registry_ok.make_step",
            },
            exempt={},
        )
        == []
    )
    # stale exemptions are findings too (refactors can't leave dead keys)
    stale = warmup.site_findings(
        sites,
        registered={
            "warmup_registry_flag.make_step",
            "warmup_registry_ok.make_step",
        },
        exempt={"gone.make_step": "reason"},
    )
    assert len(stale) == 1 and "stale exemption" in stale[0].message


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

_SNIPPET = (
    "import jax\n"
    "def f(seed):\n"
    "    key = jax.random.key(seed)\n"
    "    a = jax.random.normal(key, (2,))\n"
    "    b = jax.random.uniform(key, (2,)){pragma}\n"
    "    return a + b\n"
)


def _run_snippet(tmp_path, src):
    p = tmp_path / "snippet.py"
    p.write_text(src)
    return analysis.analyze_paths(
        [str(p)], str(REPO), skip=("warmup-registry",)
    )


def test_suppression_same_line(tmp_path):
    assert _run_snippet(tmp_path, _SNIPPET.format(pragma=""))
    suppressed = _run_snippet(
        tmp_path,
        _SNIPPET.format(
            pragma="  # jaxlint: disable=prng-reuse (fixture reason)"
        ),
    )
    assert suppressed == []


def test_suppression_standalone_line_covers_next_code_line(tmp_path):
    src = _SNIPPET.format(pragma="").replace(
        "    b = jax.random.uniform",
        "    # jaxlint: disable=prng-reuse (fixture reason)\n"
        "    b = jax.random.uniform",
    )
    assert _run_snippet(tmp_path, src) == []


def test_suppression_is_per_check(tmp_path):
    # Disabling a DIFFERENT check must not hide the finding.
    still = _run_snippet(
        tmp_path,
        _SNIPPET.format(pragma="  # jaxlint: disable=transfer-discipline"),
    )
    assert len(still) == 1 and still[0].check == "prng-reuse"
    assert (
        _run_snippet(
            tmp_path, _SNIPPET.format(pragma="  # jaxlint: disable=all")
        )
        == []
    )


# ---------------------------------------------------------------------------
# false-positive guards (reviewed hazards that must stay clean)
# ---------------------------------------------------------------------------


def test_fold_in_loop_idiom_is_clean(tmp_path):
    src = (
        "import jax\n"
        "def rollout(key, steps):\n"
        "    out = []\n"
        "    for i in range(steps):\n"
        "        sub = jax.random.fold_in(key, i)\n"
        "        out.append(jax.random.normal(sub, ()))\n"
        "    return out\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_exclusive_if_arms_are_not_reuse(tmp_path):
    src = (
        "import jax\n"
        "def sample(key, flag):\n"
        "    if flag:\n"
        "        a = jax.random.normal(key, (2,))\n"
        "    else:\n"
        "        a = jax.random.uniform(key, (2,))\n"
        "    return a\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_donation_read_in_sibling_branch_is_not_use_after_free(tmp_path):
    src = (
        "import jax\n"
        "def dispatch(state, fast, slow_fn):\n"
        "    step = jax.jit(lambda s: s, donate_argnums=0)\n"
        "    if fast:\n"
        "        metrics = step(state)\n"
        "    else:\n"
        "        metrics = slow_fn(state)\n"
        "    return metrics\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_hot_module_pragma_in_docstring_does_not_opt_in(tmp_path):
    body = (
        "import numpy as np\n"
        "def collect(act, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        obs = np.asarray(act(obs))\n"
        "    return obs\n"
    )
    doc = '"""Docs may MENTION `# jaxlint: hot-module` safely."""\n'
    assert _run_snippet(tmp_path, doc + body) == []
    # ... while a real comment pragma does opt in
    flagged = _run_snippet(tmp_path, "# jaxlint: hot-module\n" + body)
    assert [f.check for f in flagged] == ["transfer-discipline"]


def test_partial_scan_reports_no_stale_exemptions(capsys):
    """Scanning ONE algos file (against the repo baseline) must stay
    clean: neither the other modules' compile_cache.EXEMPT entries nor
    the unscanned files' baseline entries may read as stale."""
    cli = _load_cli()
    rc = cli.main(["actor_critic_tpu/algos/host_loop.py"])
    out = capsys.readouterr()
    assert rc == 0, f"{out.out}\n{out.err}"
    assert "stale" not in out.err


def test_write_baseline_scoped_run_keeps_out_of_scope_entries(
    tmp_path, capsys
):
    cli = _load_cli()
    bl = tmp_path / "bl.json"
    foreign = {
        "check": "host-sync",
        "path": "some/other/file.py",
        "context": "f",
        "line_text": "x = np.asarray(y)",
        "reason": "audited elsewhere",
    }
    analysis.save_baseline(str(bl), [foreign])
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--baseline", str(bl), "--write-baseline",
        ]
    )
    capsys.readouterr()
    assert rc == 0
    entries = analysis.load_baseline(str(bl))
    assert any(e.get("reason") == "audited elsewhere" for e in entries)
    assert any(e.get("check") == "prng-reuse" for e in entries)


def test_multiline_donating_call_is_not_self_reuse(tmp_path):
    src = (
        "import jax\n"
        "def run(state):\n"
        "    step = jax.jit(lambda s: s, donate_argnums=0)\n"
        "    out = step(\n"
        "        state,\n"
        "    )\n"
        "    return out\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_loop_carried_donation_without_rebind_flags(tmp_path):
    src = (
        "import jax\n"
        "def run(state, n):\n"
        "    step = jax.jit(lambda s: s, donate_argnums=0)\n"
        "    for _ in range(n):\n"
        "        metrics = step(state)\n"  # state freed on iteration 1
        "    return metrics\n"
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["donation-aliasing"]
    assert "never rebound" in flagged[0].message


def test_standalone_pragma_covers_multiline_statement(tmp_path):
    src = (
        "# jaxlint: hot-module\n"
        "import numpy as np\n"
        "def collect(act, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        # jaxlint: disable=transfer-discipline (fixture reason)\n"
        "        obs = (\n"
        "            np.asarray(act(obs))\n"  # finding anchors HERE
        "        )\n"
        "    return obs\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_standalone_pragma_does_not_disable_a_whole_block(tmp_path):
    src = (
        "# jaxlint: hot-module\n"
        "import numpy as np\n"
        "def collect(act, obs, steps, flag):\n"
        "    # jaxlint: disable=transfer-discipline (header only)\n"
        "    for _ in range(steps):\n"
        "        obs = np.asarray(act(obs))\n"
        "    return obs\n"
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["transfer-discipline"]


def test_quoted_pragma_in_comment_does_not_suppress(tmp_path):
    src = (
        "# jaxlint: hot-module\n"
        "import numpy as np\n"
        "def collect(act, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        # TODO: revisit the `# jaxlint: disable=host-sync` idea\n"
        "        obs = np.asarray(act(obs))\n"
        "    return obs\n"
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["transfer-discipline"]


def test_legacy_host_sync_pragma_still_suppresses(tmp_path):
    """The deprecation alias (ISSUE 15): annotations written against
    the absorbed host-sync name keep suppressing transfer-discipline
    at their sites."""
    src = (
        "# jaxlint: hot-module\n"
        "import numpy as np\n"
        "def collect(act, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        # jaxlint: disable=host-sync (legacy annotation)\n"
        "        obs = np.asarray(act(obs))\n"
        "    return obs\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_stale_warnings_are_check_scoped(capsys):
    """A --checks subset run must not call the deselected checks'
    baseline entries stale."""
    cli = _load_cli()
    rc = cli.main(["actor_critic_tpu", "--checks", "prng-reuse"])
    out = capsys.readouterr()
    assert rc == 0, f"{out.out}\n{out.err}"
    assert "stale" not in out.err


def test_write_baseline_refuses_no_baseline(tmp_path, capsys):
    cli = _load_cli()
    bl = tmp_path / "bl.json"
    analysis.save_baseline(
        str(bl),
        [{"check": "host-sync", "path": "p.py", "context": "f",
          "line_text": "x", "reason": "audited"}],
    )
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--baseline", str(bl), "--no-baseline", "--write-baseline",
        ]
    )
    capsys.readouterr()
    assert rc == 2
    assert analysis.load_baseline(str(bl))[0]["reason"] == "audited"


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    findings = _analyze("prng_reuse_flag.py")
    assert findings
    path = tmp_path / "baseline.json"
    analysis.save_baseline(
        str(path), analysis.regenerate(findings, [])
    )
    entries = analysis.load_baseline(str(path))
    new, matched, stale = analysis.apply_baseline(findings, entries)
    assert new == []
    assert len(matched) == len(findings)
    assert stale == []
    # regenerating preserves hand-written reasons by fingerprint
    entries[0]["reason"] = "audited: deliberate"
    regen = analysis.regenerate(findings, entries)
    assert any(e["reason"] == "audited: deliberate" for e in regen)


def test_baseline_goes_stale_when_the_line_changes(tmp_path):
    findings = _analyze("prng_reuse_flag.py")
    entries = analysis.regenerate(findings, [])
    entries[0]["line_text"] = "edited since the entry was written"
    new, _matched, stale = analysis.apply_baseline(findings, entries)
    # the finding resurfaces as new AND the dead entry is reported
    assert new and stale


def test_malformed_baseline_is_a_crash_not_a_clean_run(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text("{not json")
    with pytest.raises(analysis.AnalysisError):
        analysis.load_baseline(str(path))


# ---------------------------------------------------------------------------
# CLI: exit codes, --list-checks, --json
# ---------------------------------------------------------------------------


def test_cli_list_checks_names_all_twenty_one(capsys):
    cli = _load_cli()
    assert cli.main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for name in (
        "donation-aliasing", "tracer-leak", "prng-reuse",
        "recompile-hazard", "transfer-discipline", "warmup-registry",
        "lock-discipline", "publish-aliasing", "check-then-act",
        "collective-discipline", "mailbox-protocol", "rank-affinity",
        "precision-discipline", "nonfinite-hazard", "sink-guard",
        "donation-discipline", "dispatch-granularity",
        "pad-mask-discipline", "mask-propagation", "slice-before-commit",
    ):
        assert name in out
    # absorbed: no registered check is NAMED host-sync any more (the
    # docs column may still mention it as the absorbed predecessor)
    assert not any(
        line.startswith("host-sync") for line in out.splitlines()
    )


def test_select_host_sync_alias_resolves(capsys):
    """`--select host-sync` must run transfer-discipline (the
    deprecation alias), not crash as an unknown check."""
    cli = _load_cli()
    rc = cli.main(
        [
            str(FIXTURES / "transfer_discipline_flag.py"),
            "--no-baseline", "--select", "host-sync",
        ]
    )
    capsys.readouterr()
    assert rc == 1  # the flag fixture's findings surface through the alias
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--no-baseline", "--select", "host-sync",
        ]
    )
    capsys.readouterr()
    assert rc == 0  # alias selects ONLY the successor check


def test_cli_exit_codes_distinguish_findings_from_crashes(
    tmp_path, capsys
):
    cli = _load_cli()
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert cli.main([str(clean), "--no-baseline"]) == 0

    flag = str(FIXTURES / "prng_reuse_flag.py")
    assert cli.main([flag, "--no-baseline", "--error-on-new"]) == 1

    broken = tmp_path / "broken.py"
    broken.write_text("def (:\n")
    assert cli.main([str(broken), "--no-baseline"]) == 2
    assert cli.main([str(tmp_path / "missing.py"), "--no-baseline"]) == 2
    assert cli.main([flag, "--no-baseline", "--checks", "no-such"]) == 2
    capsys.readouterr()


def test_cli_json_mode(capsys):
    cli = _load_cli()
    rc = cli.main(
        [str(FIXTURES / "prng_reuse_flag.py"), "--no-baseline", "--json"]
    )
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"]["new"] >= 1
    assert all(f["check"] == "prng-reuse" for f in payload["new"])
    assert payload["counts"]["stale"] == 0


# ---------------------------------------------------------------------------
# the tier-1 gate: the real tree is clean against the repo baseline
# ---------------------------------------------------------------------------


def test_repo_tree_is_clean(capsys):
    """`python scripts/jaxlint.py actor_critic_tpu train.py bench` must
    exit 0: zero un-baselined findings (the ISSUE 5 acceptance
    criterion, enforced in-process so tier-1 fails with the report)."""
    cli = _load_cli()
    rc = cli.main(["actor_critic_tpu", "train.py", "bench", "--error-on-new"])
    out = capsys.readouterr()
    assert rc == 0, f"jaxlint found new findings:\n{out.out}\n{out.err}"


# ---------------------------------------------------------------------------
# --select / --prune-stale (ISSUE 7 satellites)
# ---------------------------------------------------------------------------


def test_select_runs_only_the_named_checks(capsys):
    cli = _load_cli()
    # prng_reuse_flag.py HAS prng findings, but a selection that
    # excludes the check must come back clean.
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--no-baseline", "--select", "host-sync,lock-discipline",
        ]
    )
    capsys.readouterr()
    assert rc == 0
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--no-baseline", "--select", "prng-reuse",
        ]
    )
    capsys.readouterr()
    assert rc == 1
    # a typo'd selection is a crash, not a clean run
    assert (
        cli.main(
            [str(FIXTURES / "prng_reuse_flag.py"), "--select", "no-such"]
        )
        == 2
    )
    capsys.readouterr()


def test_prune_stale_drops_only_in_scope_dead_entries(tmp_path, capsys):
    cli = _load_cli()
    bl = tmp_path / "bl.json"
    dead_in_scope = {
        "check": "prng-reuse",
        "path": "tests/jaxlint_fixtures/prng_reuse_flag.py",
        "context": "f",
        "line_text": "this line no longer exists",
        "reason": "went stale",
    }
    out_of_scope = {
        "check": "host-sync",
        "path": "some/other/file.py",
        "context": "g",
        "line_text": "x = np.asarray(y)",
        "reason": "audited elsewhere",
    }
    live = analysis.regenerate(_analyze("prng_reuse_flag.py"), [])
    for e in live:
        e["reason"] = "kept"
    analysis.save_baseline(
        str(bl), [dead_in_scope, out_of_scope, *live]
    )
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--baseline", str(bl), "--prune-stale",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 0 and "pruned 1" in out
    after = analysis.load_baseline(str(bl))
    reasons = {e["reason"] for e in after}
    # dead-in-scope gone; matched entries and out-of-scope retained
    assert "went stale" not in reasons
    assert "audited elsewhere" in reasons
    assert "kept" in reasons


def test_prune_stale_refuses_no_baseline(tmp_path, capsys):
    cli = _load_cli()
    bl = tmp_path / "bl.json"
    analysis.save_baseline(
        str(bl),
        [{"check": "host-sync", "path": "p.py", "context": "f",
          "line_text": "x", "reason": "audited"}],
    )
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--baseline", str(bl), "--no-baseline", "--prune-stale",
        ]
    )
    capsys.readouterr()
    assert rc == 2
    assert analysis.load_baseline(str(bl))[0]["reason"] == "audited"


# ---------------------------------------------------------------------------
# --diff mode (ISSUE 15 satellite): lint only files changed vs a ref
# ---------------------------------------------------------------------------


def _scratch_repo(tmp_path):
    """A throwaway git repo the CLI's REPO global is redirected into —
    the only way to make --diff deterministic regardless of the real
    working tree's state."""
    import subprocess

    root = tmp_path / "scratch"
    root.mkdir()
    git = ["git", "-C", str(root), "-c", "user.email=t@t",
           "-c", "user.name=t"]
    subprocess.run([*git[:3], "init", "-q"], check=True)
    (root / "clean.py").write_text("x = 1\n")
    (root / "hot.py").write_text("y = 2\n")
    subprocess.run([*git, "add", "-A"], check=True)
    subprocess.run([*git, "commit", "-qm", "seed"], check=True)
    return root


def test_diff_mode_lints_only_changed_files(tmp_path, capsys):
    cli = _load_cli()
    root = _scratch_repo(tmp_path)
    old_repo = cli.REPO
    cli.REPO = str(root)
    try:
        # nothing changed -> clean exit 0 without scanning anything
        rc = cli.main(["clean.py", "hot.py", "--no-baseline",
                       "--diff", "HEAD"])
        out = capsys.readouterr().out
        assert rc == 0 and "nothing to lint" in out
        # introduce a finding in ONE file: only it is linted
        (root / "hot.py").write_text(
            "import jax\n"
            "def f(seed):\n"
            "    key = jax.random.key(seed)\n"
            "    a = jax.random.normal(key, (2,))\n"
            "    b = jax.random.uniform(key, (2,))\n"
            "    return a + b\n"
        )
        rc = cli.main(["clean.py", "hot.py", "--no-baseline",
                       "--diff", "HEAD", "--json",
                       "--skip", "warmup-registry"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {f["path"] for f in payload["new"]} == {"hot.py"}
        # a changed file OUTSIDE the scanned paths stays out
        rc = cli.main(["clean.py", "--no-baseline", "--diff", "HEAD"])
        out = capsys.readouterr().out
        assert rc == 0 and "nothing to lint" in out
        # exit codes unchanged: a bad ref is a crash, not a clean run
        rc = cli.main(["clean.py", "--no-baseline",
                       "--diff", "no-such-ref"])
        capsys.readouterr()
        assert rc == 2
    finally:
        cli.REPO = old_repo


# ---------------------------------------------------------------------------
# --since mode (ISSUE 20 satellite): --diff + rev-parse + untracked +
# fixture-pair re-lint
# ---------------------------------------------------------------------------


def test_since_mode_includes_untracked_files(tmp_path, capsys):
    cli = _load_cli()
    root = _scratch_repo(tmp_path)
    old_repo = cli.REPO
    cli.REPO = str(root)
    try:
        # a brand-new (never-committed) module: invisible to --diff,
        # linted by --since
        (root / "fresh.py").write_text(
            "import jax\n"
            "def f(seed):\n"
            "    key = jax.random.key(seed)\n"
            "    a = jax.random.normal(key, (2,))\n"
            "    b = jax.random.uniform(key, (2,))\n"
            "    return a + b\n"
        )
        rc = cli.main(["fresh.py", "--no-baseline", "--diff", "HEAD"])
        out = capsys.readouterr().out
        assert rc == 0 and "nothing to lint" in out
        rc = cli.main(["fresh.py", "--no-baseline", "--since", "HEAD",
                       "--json", "--skip", "warmup-registry"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {f["path"] for f in payload["new"]} == {"fresh.py"}
    finally:
        cli.REPO = old_repo


def test_since_mode_resolves_revs_and_rejects_typos(tmp_path, capsys):
    cli = _load_cli()
    root = _scratch_repo(tmp_path)
    old_repo = cli.REPO
    cli.REPO = str(root)
    try:
        # a symbolic rev a plain `git diff` would also take — --since
        # resolves it through rev-parse first, same answer
        rc = cli.main(["clean.py", "--no-baseline", "--since", "HEAD"])
        out = capsys.readouterr().out
        assert rc == 0 and "nothing to lint" in out
        rc = cli.main(["clean.py", "--no-baseline",
                       "--since", "no-such-rev"])
        err = capsys.readouterr().err
        assert rc == 2 and "not a resolvable rev" in err
        # --diff and --since together is a usage error, not a merge
        rc = cli.main(["clean.py", "--no-baseline",
                       "--since", "HEAD", "--diff", "HEAD"])
        capsys.readouterr()
        assert rc == 2
    finally:
        cli.REPO = old_repo


def test_since_mode_fixture_pair_relints_the_pass_module(
    tmp_path, capsys
):
    """A change touching ONLY a check's fixture pair re-lints the
    module implementing that check: the fixture pins the pass's
    flag/ok contract, so editing one without re-examining the other is
    the drift --since exists to catch."""
    import sys as _sys
    import types

    cli = _load_cli()
    root = _scratch_repo(tmp_path)
    (root / "passmod.py").write_text("z = 3\n")
    import subprocess

    git = ["git", "-C", str(root), "-c", "user.email=t@t",
           "-c", "user.name=t"]
    subprocess.run([*git, "add", "-A"], check=True)
    subprocess.run([*git, "commit", "-qm", "pass module"], check=True)
    # a registered check whose implementing module file lives in the
    # scratch repo (the real registry's modules live outside it)
    modname = "jaxlint_scratch_pass"
    mod = types.ModuleType(modname)
    mod.__file__ = str(root / "passmod.py")
    _sys.modules[modname] = mod

    def scratch_check(mod_info):
        return []

    scratch_check.__module__ = modname
    analysis.core.register_check("scratch-pair", "test-only")(
        scratch_check
    )
    old_repo = cli.REPO
    cli.REPO = str(root)
    try:
        fixdir = root / "tests" / "jaxlint_fixtures"
        fixdir.mkdir(parents=True)
        (fixdir / "scratch_pair_flag.py").write_text("w = 4\n")
        rc = cli.main(["passmod.py", "--no-baseline",
                       "--since", "HEAD",
                       "--skip", "warmup-registry"])
        out = capsys.readouterr().out
        # the fixture itself is outside the scanned paths, but its
        # pass module was pulled in and linted (clean)
        assert rc == 0
        assert "nothing to lint" not in out
        assert "0 new finding(s)" in out
    finally:
        cli.REPO = old_repo
        analysis.core._CHECKS.pop("scratch-pair", None)
        _sys.modules.pop(modname, None)


# ---------------------------------------------------------------------------
# thread-owned annotation mechanics (ISSUE 7)
# ---------------------------------------------------------------------------

_COUNTER_SNIPPET = (
    "import threading\n"
    "class Svc:\n"
    "    def __init__(self):\n"
    "{anno}"
    "        self.blocks = 0\n"
    "        self._t = threading.Thread(target=self._run)\n"
    "    def _run(self):\n"
    "        while True:\n"
    "            self.blocks += 1\n"
)


def test_thread_owned_annotation_clears_the_attribute(tmp_path):
    flagged = _run_snippet(tmp_path, _COUNTER_SNIPPET.format(anno=""))
    assert [f.check for f in flagged] == ["lock-discipline"]
    clean = _run_snippet(
        tmp_path,
        _COUNTER_SNIPPET.format(
            anno="        # jaxlint: thread-owned=svc (fixture reason)\n"
        ),
    )
    assert clean == []


def test_cta_window_with_two_writes_flags_once(tmp_path):
    """Every unlocked write in a check-then-act window belongs to that
    finding: lock-discipline must not ALSO flag the second compound
    write (one defect, one finding)."""
    src = (
        "import threading\n"
        "class Reg:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._subs = []\n"
        "    def add(self, x):\n"
        "        if x in self._subs:\n"
        "            return\n"
        "        self._subs.append(x)\n"
        "        self._subs.sort()\n"
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["check-then-act"]


def test_thread_owned_in_docstring_does_not_annotate(tmp_path):
    # The pragma is anchored to comment starts; prose QUOTING it (as
    # this repo's docs do) must not silence the finding.
    doc = (
        '        """Docs may MENTION `# jaxlint: thread-owned=x`."""\n'
    )
    src = _COUNTER_SNIPPET.format(anno="").replace(
        "    def __init__(self):\n",
        "    def __init__(self):\n" + doc,
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["lock-discipline"]


# ---------------------------------------------------------------------------
# the two PR 6 bugs reproduce as findings (ISSUE 7 acceptance)
# ---------------------------------------------------------------------------

# telemetry/session.py as it was BEFORE the PR 6 per-thread span-stack
# fix: one module-global open-span list, pushed/popped from every
# thread that opens a span (actor services do). Reverting the fix must
# trip lock-discipline.
_PRE_FIX_SESSION = (
    "import threading\n"
    "import time\n"
    "_OPEN_SPANS = []\n"
    "class _Span:\n"
    "    def __init__(self, name):\n"
    "        self._name = name\n"
    "    def __enter__(self):\n"
    "        _OPEN_SPANS.append((self._name, time.perf_counter()))\n"
    "        return self\n"
    "    def __exit__(self, *exc):\n"
    "        _OPEN_SPANS.pop()\n"
    "def last_open_span():\n"
    "    return _OPEN_SPANS[-1] if _OPEN_SPANS else None\n"
)


def test_pr6_span_stack_revert_trips_lock_discipline(tmp_path):
    flagged = _run_snippet(tmp_path, _PRE_FIX_SESSION)
    assert {f.check for f in flagged} == {"lock-discipline"}
    lines = {f.line for f in flagged}
    assert len(lines) == 2  # the push AND the pop
    # ...and the FIXED session.py (per-thread stacks, registry lock)
    # sweeps clean: the finding is the revert, not the fix.
    assert (
        analysis.analyze_paths(
            ["actor_critic_tpu/telemetry/session.py"],
            str(REPO),
            checks=["lock-discipline"],
        )
        == []
    )


# ppo.train_host_async's transfer as it was BEFORE the PR 6
# copy-on-transfer fix: jnp.asarray may alias the slot's numpy buffer
# zero-copy, and the release below hands the slot back to the pool
# while the dispatched update still reads it.
_PRE_FIX_TRANSFER = (
    "import jax.numpy as jnp\n"
    "def learner(queue, update, params, opt_state, key):\n"
    "    while True:\n"
    "        block = queue.get()\n"
    "        arrays = {k: jnp.asarray(v) for k, v in "
    "block.arrays.items()}\n"
    "        queue.release(block)\n"
    "        params, opt_state = update(params, opt_state, arrays)\n"
)


def test_pr6_copy_on_transfer_revert_trips_publish_aliasing(tmp_path):
    flagged = _run_snippet(tmp_path, _PRE_FIX_TRANSFER)
    assert [f.check for f in flagged] == ["publish-aliasing"]
    assert "jnp.asarray" in flagged[0].message
    # the fixed consumer (jnp.array snapshots) stays clean
    fixed = _PRE_FIX_TRANSFER.replace("jnp.asarray", "jnp.array")
    assert _run_snippet(tmp_path, fixed) == []
    # ...and so does the real ppo.py this fixture mirrors
    assert (
        analysis.analyze_paths(
            ["actor_critic_tpu/algos/ppo.py"],
            str(REPO),
            checks=["publish-aliasing"],
        )
        == []
    )


# ---------------------------------------------------------------------------
# the PR 12 protocol bugs reproduce as findings (ISSUE 12 acceptance)
# ---------------------------------------------------------------------------

# multihost.read_params as it was BEFORE the PR 12 torn-read fix: the
# handler tuple misses zipfile.BadZipFile/EOFError, so the first torn
# snapshot (SIGKILL mid-publish on a non-atomic writer, fs hiccup)
# kills the mailbox writer thread. Reverting the fix must trip
# mailbox-protocol.
_PRE_FIX_READER = (
    "import os\n"
    "import numpy as np\n"
    "def params_file(mailbox_dir, rank):\n"
    "    return os.path.join(mailbox_dir, f'host{rank}', 'params.npz')\n"
    "def read_params(mailbox_dir, rank):\n"
    "    path = params_file(mailbox_dir, rank)\n"
    "    try:\n"
    "        with np.load(path) as z:\n"
    "            return {k: z[k] for k in z.files}\n"
    "    except (OSError, KeyError, ValueError):\n"
    "        return None\n"
)


def test_pr12_torn_reader_revert_trips_mailbox_protocol(tmp_path):
    flagged = _run_snippet(tmp_path, _PRE_FIX_READER)
    assert [f.check for f in flagged] == ["mailbox-protocol"]
    assert "BadZipFile" in flagged[0].message
    # the fixed multihost.py sweeps clean
    assert (
        analysis.analyze_paths(
            ["actor_critic_tpu/parallel/multihost.py"],
            str(REPO),
            checks=["mailbox-protocol"],
        )
        == []
    )


# train.py's --distributed telemetry wiring as it was BEFORE the PR 12
# rank-affinity fix: every host hands the SAME --telemetry-dir and
# metrics path to its session/logger — N hosts interleave one jsonl.
_PRE_FIX_TELEMETRY = (
    "class TelemetrySession:\n"
    "    def __init__(self, directory, **kw):\n"
    "        self.directory = directory\n"
    "class JsonlLogger:\n"
    "    def __init__(self, path, **kw):\n"
    "        self.path = path\n"
    "def main(args):\n"
    "    if args.distributed:\n"
    "        pass  # ranks join the fleet here\n"
    "    session = TelemetrySession(args.telemetry_dir)\n"
    "    logger = JsonlLogger(args.metrics)\n"
    "    return session, logger\n"
)


def test_pr12_telemetry_clobber_revert_trips_rank_affinity(tmp_path):
    flagged = _run_snippet(tmp_path, _PRE_FIX_TELEMETRY)
    assert {f.check for f in flagged} == {"rank-affinity"}
    assert len(flagged) == 2  # the session AND the logger
    # the fixed train.py (host<rank>-suffixed paths) sweeps clean
    assert (
        analysis.analyze_paths(
            ["train.py"], str(REPO), checks=["rank-affinity"]
        )
        == []
    )


# The PR 9 review bug as a snippet: a GLOBAL newest-seen version clock
# across peers permanently mutes every host slower than the fastest.
_GLOBAL_CLOCK_POLL = (
    "def poll(mailbox, schedule):\n"
    "    newest = -1\n"
    "    for peer in schedule:\n"
    "        out = mailbox.read(peer)\n"
    "        if out is None:\n"
    "            continue\n"
    "        version, params = out\n"
    "        if version > newest:\n"
    "            newest = version\n"
    "            mailbox.deposit(params, version, peer)\n"
)


def test_global_version_clock_trips_mailbox_protocol(tmp_path):
    flagged = _run_snippet(tmp_path, _GLOBAL_CLOCK_POLL)
    assert [f.check for f in flagged] == ["mailbox-protocol"]
    assert "per-peer" in flagged[0].message.lower() or (
        "PER RANK" in flagged[0].message
    )


# ---------------------------------------------------------------------------
# the ISSUE 14 bug classes reproduce as findings (numerics acceptance)
# ---------------------------------------------------------------------------

# replay/quantize.init_stats as it would read with the PR 8 bug
# re-introduced: the scale stats slot seeded at 1.0 instead of the
# _EPS floor (the running max only grows, so the 1.0 seed permanently
# floors the quantization step at 1/127). Reverting the fix must trip
# nonfinite-hazard.
_PRE_FIX_SCALE_SEED = (
    "import jax.numpy as jnp\n"
    "def init_stats(kind, example_leaf):\n"
    "    shape = jnp.shape(example_leaf)\n"
    "    mean = jnp.zeros(shape, jnp.float32)\n"
    "    scale = jnp.full(shape, 1.0, jnp.float32)\n"
    "    return {'mean': mean, 'scale': scale}\n"
)


def test_pr8_scale_seed_revert_trips_nonfinite_hazard(tmp_path):
    flagged = _run_snippet(tmp_path, _PRE_FIX_SCALE_SEED)
    assert [f.check for f in flagged] == ["nonfinite-hazard"]
    assert "PR 8" in flagged[0].message
    # the fixed quantize.py (the _EPS-floor seed) sweeps clean
    assert (
        analysis.analyze_paths(
            ["actor_critic_tpu/replay/quantize.py"],
            str(REPO),
            checks=["nonfinite-hazard"],
        )
        == []
    )


# A bf16 compute path whose loss reduction lost its fp32 accumulator —
# the revert the precision pass exists to catch before the ROADMAP's
# bf16/Pallas work lands.
_PRE_FIX_BF16_ACCUMULATOR = (
    "import jax.numpy as jnp\n"
    "def loss_terms(preds_f32, targets_f32):\n"
    "    preds = preds_f32.astype(jnp.bfloat16)\n"
    "    targets = targets_f32.astype(jnp.bfloat16)\n"
    "    err = preds - targets\n"
    "    return jnp.mean(err * err)\n"
)


def test_bf16_accumulator_revert_trips_precision_discipline(tmp_path):
    flagged = _run_snippet(tmp_path, _PRE_FIX_BF16_ACCUMULATOR)
    assert [f.check for f in flagged] == ["precision-discipline"]
    assert "accumulate" in flagged[0].message.lower()
    # the fp32-accumulator spelling is the near miss
    fixed = _PRE_FIX_BF16_ACCUMULATOR.replace(
        "jnp.mean(err * err)", "jnp.mean(err * err, dtype=jnp.float32)"
    )
    assert _run_snippet(tmp_path, fixed) == []


# The per-algo loss reductions exactly as they would read with
# ISSUE 19's fp32 accumulators dropped: under --update-dtype bf16 the
# activations reach every jnp.mean bare and the entropy/pg terms
# accumulate in bf16.
_PRE_FIX_UPDATE_LOSS = (
    "import jax.numpy as jnp\n"
    "def update_loss(log_probs_f32, ratio_f32, adv_f32):\n"
    "    log_probs = log_probs_f32.astype(jnp.bfloat16)\n"
    "    ratio = ratio_f32.astype(jnp.bfloat16)\n"
    "    adv = adv_f32.astype(jnp.bfloat16)\n"
    "    entropy = -jnp.mean(log_probs)\n"
    "    pg_loss = -jnp.mean(ratio * adv)\n"
    "    return pg_loss + entropy\n"
)


def test_update_loss_accumulator_revert_trips_precision_discipline(
    tmp_path,
):
    """ISSUE 19: dropping the explicit fp32 accumulators from the
    update-shaped loss reductions is caught per-site, and the LANDED
    per-algo loss modules (which spell every reduction with
    dtype=jnp.float32) sweep clean."""
    flagged = _run_snippet(tmp_path, _PRE_FIX_UPDATE_LOSS)
    assert flagged and all(
        f.check == "precision-discipline" for f in flagged
    )
    assert sum(
        "accumulate" in f.message.lower() for f in flagged
    ) == 2  # one finding per bare reduction: entropy AND pg_loss
    assert (
        analysis.analyze_paths(
            [
                "actor_critic_tpu/algos/ppo.py",
                "actor_critic_tpu/algos/a2c.py",
                "actor_critic_tpu/algos/impala.py",
            ],
            str(REPO),
            checks=["precision-discipline"],
        )
        == []
    )


# telemetry/sampler._emit as it was BEFORE the ISSUE 14 fix: the strict
# allow_nan=False dumps — one NaN gauge raises ValueError on every tick
# and resource sampling silently ends for the rest of the run.
_PRE_FIX_SAMPLER = (
    "import json\n"
    "def emit(fh, sample_row):\n"
    "    try:\n"
    "        fh.write(json.dumps(sample_row(), allow_nan=False) + '\\n')\n"
    "    except (OSError, ValueError):\n"
    "        pass\n"
)


def test_sampler_nan_crash_revert_trips_sink_guard(tmp_path):
    flagged = _run_snippet(tmp_path, _PRE_FIX_SAMPLER)
    assert [f.check for f in flagged] == ["sink-guard"]
    assert "safe_json_row" in flagged[0].message
    # the fixed telemetry writers sweep clean
    assert (
        analysis.analyze_paths(
            [
                "actor_critic_tpu/telemetry/sampler.py",
                "actor_critic_tpu/telemetry/spans.py",
                "actor_critic_tpu/telemetry/session.py",
                "actor_critic_tpu/utils/logging.py",
            ],
            str(REPO),
            checks=["sink-guard"],
        )
        == []
    )


# ---------------------------------------------------------------------------
# the ISSUE 15 regression classes reproduce as findings (perf acceptance)
# ---------------------------------------------------------------------------

# The async PPO learner's consume path as it was BEFORE PR 13's device
# data plane: every consumed block is gathered to host numpy and
# re-uploaded inside the steady-state loop — the per-block transfer the
# device ring removed. Re-introducing it must trip transfer-discipline.
_PRE_PR13_HOST_GATHER = (
    "# jaxlint: hot-module\n"
    "import jax\n"
    "import jax.numpy as jnp\n"
    "def learner(queue, update, params, opt_state, key, n):\n"
    "    for _ in range(n):\n"
    "        block = queue.get()\n"
    "        host = jax.device_get(block.arrays)\n"
    "        arrays = {k: jnp.array(v) for k, v in host.items()}\n"
    "        queue.release(block)\n"
    "        params, opt_state, _ = update(params, opt_state, arrays, key)\n"
    "    return params, opt_state\n"
)


def test_pre_pr13_host_gather_trips_transfer_discipline(tmp_path):
    flagged = _run_snippet(tmp_path, _PRE_PR13_HOST_GATHER)
    assert {f.check for f in flagged} == {"transfer-discipline"}
    lines = {f.line for f in flagged}
    assert len(lines) == 2  # the gather AND the re-upload
    # the fixed device-plane consume (ppo.train_host_async's device
    # branch) sweeps clean — audited annotations only
    assert (
        analysis.analyze_paths(
            ["actor_critic_tpu/algos/ppo.py"],
            str(REPO),
            checks=["transfer-discipline"],
        )
        == []
    )


# An undonated recycled device ring ingest — the donation gap the
# donation-discipline pass exists to price (the real ring's enqueue
# donates; a NEW consumer forgetting to would re-pay a full-state copy
# per block).
_UNDONATED_RING_INGEST = (
    "import jax\n"
    "def make_ingest_update(cfg):\n"
    "    def ingest(ring_state, block):\n"
    "        return ring_state\n"
    "    return jax.jit(ingest)\n"
    "def learner(cfg, ring_state, blocks):\n"
    "    ingest = make_ingest_update(cfg)\n"
    "    for block in blocks:\n"
    "        ring_state = ingest(ring_state, block)\n"
    "    return ring_state\n"
)


def test_undonated_ring_ingest_trips_donation_discipline(tmp_path):
    flagged = _run_snippet(tmp_path, _UNDONATED_RING_INGEST)
    assert [f.check for f in flagged] == ["donation-discipline"]
    assert "donate_argnums" in flagged[0].message
    # the donated spelling is the near miss
    fixed = _UNDONATED_RING_INGEST.replace(
        "jax.jit(ingest)", "jax.jit(ingest, donate_argnums=0)"
    )
    assert _run_snippet(tmp_path, fixed) == []
    # ...and the real device plane (donating enqueue/ingest) stays clean
    assert (
        analysis.analyze_paths(
            [
                "actor_critic_tpu/data_plane/ring.py",
                "actor_critic_tpu/data_plane/device_replay.py",
            ],
            str(REPO),
            checks=["donation-discipline"],
        )
        == []
    )


# A Python-level reduction over per-actor device metrics inside the
# step loop — one tiny dispatch per element plus a sync, every
# iteration; the dispatch-granularity class.
_PY_REDUCTION_IN_LOOP = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "step = jax.jit(lambda s, b: s, donate_argnums=0)\n"
    "def drive(state, blocks, shards):\n"
    "    for b in blocks:\n"
    "        total = sum(jnp.sum(s) for s in shards)\n"
    "        state = step(state, total)\n"
    "    return state\n"
)


def test_python_reduction_trips_dispatch_granularity(tmp_path):
    flagged = _run_snippet(tmp_path, _PY_REDUCTION_IN_LOOP)
    assert {f.check for f in flagged} == {"dispatch-granularity"}
    assert any("sum()" in f.message for f in flagged)
    # folding the reduction into the program is the near miss
    fixed = _PY_REDUCTION_IN_LOOP.replace(
        "        total = sum(jnp.sum(s) for s in shards)\n"
        "        state = step(state, total)\n",
        "        state = step(state, b)\n",
    )
    assert _run_snippet(tmp_path, fixed) == []
    # the real fused drivers (host_loop/mixture benches) stay clean
    assert (
        analysis.analyze_paths(
            ["actor_critic_tpu/algos/host_loop.py", "bench"],
            str(REPO),
            checks=["dispatch-granularity"],
        )
        == []
    )


def test_ungated_commit_points_trip_sink_guard(tmp_path):
    """Stripping the check_finite gate from a commit-point def (the
    numsan reverted-guard mode, in source form) must resurface as a
    sink-guard finding — and the real gated modules stay clean."""
    src = (
        "STORE = {}\n"
        "def write_params(mailbox_dir, rank, version, params):\n"
        "    STORE[(mailbox_dir, rank)] = (version, params)\n"
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["sink-guard"]
    assert (
        analysis.analyze_paths(
            [
                "actor_critic_tpu/parallel/multihost.py",
                "actor_critic_tpu/serving/policy_store.py",
                "actor_critic_tpu/algos/traj_queue.py",
                "actor_critic_tpu/utils/checkpoint.py",
            ],
            str(REPO),
            checks=["sink-guard"],
        )
        == []
    )
