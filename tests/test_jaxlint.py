"""Tier-1 wiring for the jaxlint analyzer (ISSUE 5).

Three layers of guarantees:

1. **Fixture pairs** — per registered check, a `*_flag.py` fixture that
   MUST produce findings of exactly that check and a `*_ok.py` near
   miss that MUST stay completely clean, so a pass going blind (or
   over-flagging the sanctioned idiom) fails CI.
2. **Mechanics** — inline suppression comments (same line and
   standalone line), baseline round-trip (save → load → zero new,
   stale detection when the flagged line changes).
3. **The gate** — the real tree (`actor_critic_tpu train.py bench`)
   analyzes clean against the repo baseline, and the CLI's exit codes
   stay distinct: 0 clean / 1 findings / 2 crash-or-parse-error.

Everything runs AST-only (the analyzer never imports the files it
scans), so this module is JAX_PLATFORMS=cpu-safe and fast; only the
final gate test touches the live warmup registry (already imported by
the rest of tier-1).
"""

import importlib.util
import json
from pathlib import Path

import pytest

from actor_critic_tpu import analysis
from actor_critic_tpu.analysis import warmup

REPO = Path(__file__).parent.parent
FIXTURES = Path(__file__).parent / "jaxlint_fixtures"

# Every AST check rides the same fixture contract; warmup-registry is
# repo-scoped and has its own pair test below.
PAIRS = [
    ("donation-aliasing", "donation_aliasing"),
    ("tracer-leak", "tracer_leak"),
    ("prng-reuse", "prng_reuse"),
    ("recompile-hazard", "recompile_hazard"),
    ("host-sync", "host_sync"),
]


def _analyze(*names: str, checks=None):
    return analysis.analyze_paths(
        [str(FIXTURES / n) for n in names],
        str(REPO),
        checks=checks,
        skip=("warmup-registry",),
    )


def _load_cli():
    spec = importlib.util.spec_from_file_location(
        "jaxlint_cli", REPO / "scripts" / "jaxlint.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# fixture pairs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("check,stem", PAIRS)
def test_flag_fixture_flags(check, stem):
    findings = _analyze(f"{stem}_flag.py")
    assert findings, f"{stem}_flag.py produced no findings"
    assert all(f.check == check for f in findings), (
        f"{stem}_flag.py leaked findings of other checks: "
        f"{[f.render() for f in findings if f.check != check]}"
    )


@pytest.mark.parametrize("check,stem", PAIRS)
def test_ok_fixture_stays_clean(check, stem):
    findings = _analyze(f"{stem}_ok.py")
    assert findings == [], (
        f"{stem}_ok.py must be clean, got: "
        f"{[f.render() for f in findings]}"
    )


def test_warmup_registry_fixture_pair():
    mods = analysis.load_modules(
        [
            str(FIXTURES / "warmup_registry_flag.py"),
            str(FIXTURES / "warmup_registry_ok.py"),
        ],
        str(REPO),
    )
    sites = warmup.sites_from_modules(
        mods, scan_dirs=("tests/jaxlint_fixtures",)
    )
    assert set(sites) == {
        "warmup_registry_flag.make_step",
        "warmup_registry_ok.make_step",
    }
    findings = warmup.site_findings(
        sites, registered={"warmup_registry_ok.make_step"}, exempt={}
    )
    assert [f.check for f in findings] == ["warmup-registry"]
    assert "warmup_registry_flag.make_step" in findings[0].message
    # near miss: fully covered registry -> clean
    assert (
        warmup.site_findings(
            sites,
            registered={
                "warmup_registry_flag.make_step",
                "warmup_registry_ok.make_step",
            },
            exempt={},
        )
        == []
    )
    # stale exemptions are findings too (refactors can't leave dead keys)
    stale = warmup.site_findings(
        sites,
        registered={
            "warmup_registry_flag.make_step",
            "warmup_registry_ok.make_step",
        },
        exempt={"gone.make_step": "reason"},
    )
    assert len(stale) == 1 and "stale exemption" in stale[0].message


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

_SNIPPET = (
    "import jax\n"
    "def f(seed):\n"
    "    key = jax.random.key(seed)\n"
    "    a = jax.random.normal(key, (2,))\n"
    "    b = jax.random.uniform(key, (2,)){pragma}\n"
    "    return a + b\n"
)


def _run_snippet(tmp_path, src):
    p = tmp_path / "snippet.py"
    p.write_text(src)
    return analysis.analyze_paths(
        [str(p)], str(REPO), skip=("warmup-registry",)
    )


def test_suppression_same_line(tmp_path):
    assert _run_snippet(tmp_path, _SNIPPET.format(pragma=""))
    suppressed = _run_snippet(
        tmp_path,
        _SNIPPET.format(
            pragma="  # jaxlint: disable=prng-reuse (fixture reason)"
        ),
    )
    assert suppressed == []


def test_suppression_standalone_line_covers_next_code_line(tmp_path):
    src = _SNIPPET.format(pragma="").replace(
        "    b = jax.random.uniform",
        "    # jaxlint: disable=prng-reuse (fixture reason)\n"
        "    b = jax.random.uniform",
    )
    assert _run_snippet(tmp_path, src) == []


def test_suppression_is_per_check(tmp_path):
    # Disabling a DIFFERENT check must not hide the finding.
    still = _run_snippet(
        tmp_path, _SNIPPET.format(pragma="  # jaxlint: disable=host-sync")
    )
    assert len(still) == 1 and still[0].check == "prng-reuse"
    assert (
        _run_snippet(
            tmp_path, _SNIPPET.format(pragma="  # jaxlint: disable=all")
        )
        == []
    )


# ---------------------------------------------------------------------------
# false-positive guards (reviewed hazards that must stay clean)
# ---------------------------------------------------------------------------


def test_fold_in_loop_idiom_is_clean(tmp_path):
    src = (
        "import jax\n"
        "def rollout(key, steps):\n"
        "    out = []\n"
        "    for i in range(steps):\n"
        "        sub = jax.random.fold_in(key, i)\n"
        "        out.append(jax.random.normal(sub, ()))\n"
        "    return out\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_exclusive_if_arms_are_not_reuse(tmp_path):
    src = (
        "import jax\n"
        "def sample(key, flag):\n"
        "    if flag:\n"
        "        a = jax.random.normal(key, (2,))\n"
        "    else:\n"
        "        a = jax.random.uniform(key, (2,))\n"
        "    return a\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_donation_read_in_sibling_branch_is_not_use_after_free(tmp_path):
    src = (
        "import jax\n"
        "def dispatch(state, fast, slow_fn):\n"
        "    step = jax.jit(lambda s: s, donate_argnums=0)\n"
        "    if fast:\n"
        "        metrics = step(state)\n"
        "    else:\n"
        "        metrics = slow_fn(state)\n"
        "    return metrics\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_hot_module_pragma_in_docstring_does_not_opt_in(tmp_path):
    body = (
        "import numpy as np\n"
        "def collect(act, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        obs = np.asarray(act(obs))\n"
        "    return obs\n"
    )
    doc = '"""Docs may MENTION `# jaxlint: hot-module` safely."""\n'
    assert _run_snippet(tmp_path, doc + body) == []
    # ... while a real comment pragma does opt in
    flagged = _run_snippet(tmp_path, "# jaxlint: hot-module\n" + body)
    assert [f.check for f in flagged] == ["host-sync"]


def test_partial_scan_reports_no_stale_exemptions(capsys):
    """Scanning ONE algos file (against the repo baseline) must stay
    clean: neither the other modules' compile_cache.EXEMPT entries nor
    the unscanned files' baseline entries may read as stale."""
    cli = _load_cli()
    rc = cli.main(["actor_critic_tpu/algos/host_loop.py"])
    out = capsys.readouterr()
    assert rc == 0, f"{out.out}\n{out.err}"
    assert "stale" not in out.err


def test_write_baseline_scoped_run_keeps_out_of_scope_entries(
    tmp_path, capsys
):
    cli = _load_cli()
    bl = tmp_path / "bl.json"
    foreign = {
        "check": "host-sync",
        "path": "some/other/file.py",
        "context": "f",
        "line_text": "x = np.asarray(y)",
        "reason": "audited elsewhere",
    }
    analysis.save_baseline(str(bl), [foreign])
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--baseline", str(bl), "--write-baseline",
        ]
    )
    capsys.readouterr()
    assert rc == 0
    entries = analysis.load_baseline(str(bl))
    assert any(e.get("reason") == "audited elsewhere" for e in entries)
    assert any(e.get("check") == "prng-reuse" for e in entries)


def test_multiline_donating_call_is_not_self_reuse(tmp_path):
    src = (
        "import jax\n"
        "def run(state):\n"
        "    step = jax.jit(lambda s: s, donate_argnums=0)\n"
        "    out = step(\n"
        "        state,\n"
        "    )\n"
        "    return out\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_loop_carried_donation_without_rebind_flags(tmp_path):
    src = (
        "import jax\n"
        "def run(state, n):\n"
        "    step = jax.jit(lambda s: s, donate_argnums=0)\n"
        "    for _ in range(n):\n"
        "        metrics = step(state)\n"  # state freed on iteration 1
        "    return metrics\n"
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["donation-aliasing"]
    assert "never rebound" in flagged[0].message


def test_standalone_pragma_covers_multiline_statement(tmp_path):
    src = (
        "# jaxlint: hot-module\n"
        "import numpy as np\n"
        "def collect(act, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        # jaxlint: disable=host-sync (fixture reason)\n"
        "        obs = (\n"
        "            np.asarray(act(obs))\n"  # finding anchors HERE
        "        )\n"
        "    return obs\n"
    )
    assert _run_snippet(tmp_path, src) == []


def test_standalone_pragma_does_not_disable_a_whole_block(tmp_path):
    src = (
        "# jaxlint: hot-module\n"
        "import numpy as np\n"
        "def collect(act, obs, steps, flag):\n"
        "    # jaxlint: disable=host-sync (must cover the header only)\n"
        "    for _ in range(steps):\n"
        "        obs = np.asarray(act(obs))\n"
        "    return obs\n"
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["host-sync"]


def test_quoted_pragma_in_comment_does_not_suppress(tmp_path):
    src = (
        "# jaxlint: hot-module\n"
        "import numpy as np\n"
        "def collect(act, obs, steps):\n"
        "    for _ in range(steps):\n"
        "        # TODO: revisit the `# jaxlint: disable=host-sync` idea\n"
        "        obs = np.asarray(act(obs))\n"
        "    return obs\n"
    )
    flagged = _run_snippet(tmp_path, src)
    assert [f.check for f in flagged] == ["host-sync"]


def test_stale_warnings_are_check_scoped(capsys):
    """A --checks subset run must not call the deselected checks'
    baseline entries stale."""
    cli = _load_cli()
    rc = cli.main(["actor_critic_tpu", "--checks", "prng-reuse"])
    out = capsys.readouterr()
    assert rc == 0, f"{out.out}\n{out.err}"
    assert "stale" not in out.err


def test_write_baseline_refuses_no_baseline(tmp_path, capsys):
    cli = _load_cli()
    bl = tmp_path / "bl.json"
    analysis.save_baseline(
        str(bl),
        [{"check": "host-sync", "path": "p.py", "context": "f",
          "line_text": "x", "reason": "audited"}],
    )
    rc = cli.main(
        [
            str(FIXTURES / "prng_reuse_flag.py"),
            "--baseline", str(bl), "--no-baseline", "--write-baseline",
        ]
    )
    capsys.readouterr()
    assert rc == 2
    assert analysis.load_baseline(str(bl))[0]["reason"] == "audited"


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip(tmp_path):
    findings = _analyze("prng_reuse_flag.py")
    assert findings
    path = tmp_path / "baseline.json"
    analysis.save_baseline(
        str(path), analysis.regenerate(findings, [])
    )
    entries = analysis.load_baseline(str(path))
    new, matched, stale = analysis.apply_baseline(findings, entries)
    assert new == []
    assert len(matched) == len(findings)
    assert stale == []
    # regenerating preserves hand-written reasons by fingerprint
    entries[0]["reason"] = "audited: deliberate"
    regen = analysis.regenerate(findings, entries)
    assert any(e["reason"] == "audited: deliberate" for e in regen)


def test_baseline_goes_stale_when_the_line_changes(tmp_path):
    findings = _analyze("prng_reuse_flag.py")
    entries = analysis.regenerate(findings, [])
    entries[0]["line_text"] = "edited since the entry was written"
    new, _matched, stale = analysis.apply_baseline(findings, entries)
    # the finding resurfaces as new AND the dead entry is reported
    assert new and stale


def test_malformed_baseline_is_a_crash_not_a_clean_run(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text("{not json")
    with pytest.raises(analysis.AnalysisError):
        analysis.load_baseline(str(path))


# ---------------------------------------------------------------------------
# CLI: exit codes, --list-checks, --json
# ---------------------------------------------------------------------------


def test_cli_list_checks_names_all_six(capsys):
    cli = _load_cli()
    assert cli.main(["--list-checks"]) == 0
    out = capsys.readouterr().out
    for name in (
        "donation-aliasing", "tracer-leak", "prng-reuse",
        "recompile-hazard", "host-sync", "warmup-registry",
    ):
        assert name in out


def test_cli_exit_codes_distinguish_findings_from_crashes(
    tmp_path, capsys
):
    cli = _load_cli()
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert cli.main([str(clean), "--no-baseline"]) == 0

    flag = str(FIXTURES / "prng_reuse_flag.py")
    assert cli.main([flag, "--no-baseline", "--error-on-new"]) == 1

    broken = tmp_path / "broken.py"
    broken.write_text("def (:\n")
    assert cli.main([str(broken), "--no-baseline"]) == 2
    assert cli.main([str(tmp_path / "missing.py"), "--no-baseline"]) == 2
    assert cli.main([flag, "--no-baseline", "--checks", "no-such"]) == 2
    capsys.readouterr()


def test_cli_json_mode(capsys):
    cli = _load_cli()
    rc = cli.main(
        [str(FIXTURES / "prng_reuse_flag.py"), "--no-baseline", "--json"]
    )
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"]["new"] >= 1
    assert all(f["check"] == "prng-reuse" for f in payload["new"])
    assert payload["counts"]["stale"] == 0


# ---------------------------------------------------------------------------
# the tier-1 gate: the real tree is clean against the repo baseline
# ---------------------------------------------------------------------------


def test_repo_tree_is_clean(capsys):
    """`python scripts/jaxlint.py actor_critic_tpu train.py bench` must
    exit 0: zero un-baselined findings (the ISSUE 5 acceptance
    criterion, enforced in-process so tier-1 fails with the report)."""
    cli = _load_cli()
    rc = cli.main(["actor_critic_tpu", "train.py", "bench", "--error-on-new"])
    out = capsys.readouterr()
    assert rc == 0, f"jaxlint found new findings:\n{out.out}\n{out.err}"
