"""Replay buffer tests: wraparound, sampling distribution, donation
(SURVEY.md §4 "Replay-buffer tests")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu import replay


def _example():
    return {
        "obs": jnp.zeros((3,), jnp.float32),
        "action": jnp.zeros((), jnp.int32),
        "reward": jnp.zeros((), jnp.float32),
    }


def _batch(values, b):
    """Batch whose obs rows encode the insert order for traceability."""
    v = jnp.asarray(values, jnp.float32)
    return {
        "obs": jnp.stack([v, v + 0.1, v + 0.2], axis=-1),
        "action": v.astype(jnp.int32),
        "reward": v,
    }


class TestInit:
    def test_shapes_dtypes(self):
        state = replay.init(_example(), capacity=16)
        assert state.storage["obs"].shape == (16, 3)
        assert state.storage["action"].dtype == jnp.int32
        assert int(state.size) == 0
        assert replay.capacity_of(state) == 16

    def test_add_grows_size(self):
        state = replay.init(_example(), capacity=8)
        state = replay.add_batch(state, _batch(np.arange(3), 3))
        assert int(state.size) == 3
        assert int(state.insert_pos) == 3
        np.testing.assert_allclose(
            np.asarray(state.storage["reward"][:3]), [0.0, 1.0, 2.0]
        )


class TestWraparound:
    def test_exact_wrap(self):
        state = replay.init(_example(), capacity=8)
        for start in range(0, 16, 4):
            state = replay.add_batch(state, _batch(np.arange(start, start + 4), 4))
        assert int(state.size) == 8
        assert int(state.insert_pos) == 0
        # Ring holds the newest 8 items in physical order 8..15.
        np.testing.assert_allclose(
            np.asarray(state.storage["reward"]), np.arange(8, 16, dtype=np.float32)
        )

    def test_straddling_batch(self):
        """A batch crossing the wrap point lands split across the ring."""
        state = replay.init(_example(), capacity=8)
        state = replay.add_batch(state, _batch(np.arange(6), 6))
        state = replay.add_batch(state, _batch(np.arange(6, 12), 6))
        assert int(state.size) == 8
        assert int(state.insert_pos) == 4
        # slots: [8, 9, 10, 11, 4, 5, 6, 7]
        np.testing.assert_allclose(
            np.asarray(state.storage["reward"]),
            [8.0, 9.0, 10.0, 11.0, 4.0, 5.0, 6.0, 7.0],
        )

    def test_batch_larger_runs(self):
        state = replay.init(_example(), capacity=4)
        state = replay.add_batch(state, _batch(np.arange(3), 3))
        state = replay.add_batch(state, _batch(np.arange(3, 6), 3))
        assert int(state.size) == 4

    def test_jit_add(self):
        add = jax.jit(replay.add_batch)
        state = replay.init(_example(), capacity=8)
        state = add(state, _batch(np.arange(5), 5))
        state = add(state, _batch(np.arange(5, 10), 5))
        assert int(state.size) == 8
        assert int(state.insert_pos) == 2


class TestSampling:
    def test_only_valid_entries(self):
        """Sampling never returns the zero-initialized (unwritten) tail."""
        state = replay.init(_example(), capacity=100)
        state = replay.add_batch(state, _batch(np.arange(1, 11), 10))
        out = replay.sample(state, jax.random.key(0), 256)
        r = np.asarray(out["reward"])
        assert r.min() >= 1.0 and r.max() <= 10.0
        assert out["obs"].shape == (256, 3)

    def test_roughly_uniform(self):
        state = replay.init(_example(), capacity=16)
        state = replay.add_batch(state, _batch(np.arange(16), 16))
        out = replay.sample(state, jax.random.key(1), 16 * 2000)
        counts = np.bincount(np.asarray(out["action"]), minlength=16)
        freq = counts / counts.sum()
        # Each slot ~1/16 ± generous tolerance.
        np.testing.assert_allclose(freq, np.full(16, 1 / 16), atol=0.01)

    def test_rows_internally_consistent(self):
        """Gather keeps (obs, action, reward) of one transition together."""
        state = replay.init(_example(), capacity=32)
        state = replay.add_batch(state, _batch(np.arange(32), 32))
        out = replay.sample(state, jax.random.key(2), 64)
        np.testing.assert_allclose(
            np.asarray(out["obs"][:, 0]), np.asarray(out["reward"])
        )

    def test_sample_sequences(self):
        state = replay.init(_example(), capacity=64)
        state = replay.add_batch(state, _batch(np.arange(40), 40))
        out = replay.sample_sequences(state, jax.random.key(3), 8, 5)
        r = np.asarray(out["reward"])
        assert r.shape == (8, 5)
        # Each row is consecutive inserts.
        np.testing.assert_allclose(np.diff(r, axis=1), np.ones((8, 4)))
        assert r.max() <= 39.0

    def test_sample_sequences_after_wrap(self):
        """Windows must never cross the write-cursor seam: a wrapped ring
        holds inserts [8..15] in physical order [8,9,10,11,4,5,6,7]*, and
        every sampled sequence must still be consecutive inserts."""
        state = replay.init(_example(), capacity=8)
        state = replay.add_batch(state, _batch(np.arange(6), 6))
        state = replay.add_batch(state, _batch(np.arange(6, 12), 6))
        # physical: [8, 9, 10, 11, 4, 5, 6, 7], insert_pos=4 (oldest=4)
        out = replay.sample_sequences(state, jax.random.key(0), 64, 3)
        r = np.asarray(out["reward"])
        np.testing.assert_allclose(np.diff(r, axis=1), np.ones((64, 2)))
        assert r.min() >= 4.0 and r.max() <= 11.0

    def test_sample_sequences_episode_boundary_contract(self):
        """Contract point 2 (ISSUE 13, pinned before the R2D2-style
        consumer builds on it): windows MAY span episode boundaries and
        are returned UNMODIFIED — the stored done flags arrive intact,
        and masking is the consumer's job (the shared alive-before-done
        convention: the done step is the last valid step of its
        episode)."""
        ex = {**_example(), "done": jnp.zeros((), jnp.float32)}
        state = replay.init(ex, capacity=32)
        done = np.zeros(16, np.float32)
        done[5] = 1.0  # an episode ends at insert 5
        b = _batch(np.arange(16), 16)
        b["done"] = jnp.asarray(done)
        state = replay.add_batch(state, b)
        out = replay.sample_sequences(state, jax.random.key(4), 128, 4)
        r = np.asarray(out["reward"])
        d = np.asarray(out["done"])
        # Windows are still consecutive inserts even when they contain
        # the boundary, and the done flag rides exactly where stored.
        np.testing.assert_allclose(np.diff(r, axis=1), np.ones((128, 3)))
        np.testing.assert_array_equal(d, (r == 5.0).astype(np.float32))
        # Some sampled window genuinely spans the boundary (done NOT in
        # the final slot), so the contract is exercised, not vacuous.
        spans = d[:, :-1].sum() > 0
        assert spans
        # The in-tree consumer convention cuts contributions after the
        # done: mask == alive-before-done (device_replay shares this
        # with ddpg.nstep_batch — tested against each other there).
        from actor_critic_tpu.data_plane import device_replay

        mask = np.asarray(
            device_replay.sequence_window_mask(jnp.asarray(d))
        )
        after_done = (np.cumsum(d, axis=1) - d) > 0
        np.testing.assert_array_equal(mask == 0.0, after_done)

    def test_sample_sequences_never_clamps_into_unwritten_slots(self):
        """Contract's caller obligation, enforced by construction for
        size >= seq_len: max_start keeps every window inside the valid
        region, so no sampled row reads a zero-initialized slot."""
        state = replay.init(_example(), capacity=64)
        state = replay.add_batch(state, _batch(np.arange(1, 9), 8))
        out = replay.sample_sequences(state, jax.random.key(5), 64, 8)
        r = np.asarray(out["reward"])
        assert r.min() >= 1.0  # zero-filled slots would read 0.0


class TestDonation:
    def test_inplace_update_under_donation(self):
        """Donated jitted add must reuse the storage buffer (no copy of the
        whole ring per insert — SURVEY §7.2 item 4)."""
        state = replay.init(_example(), capacity=1024)
        add = jax.jit(replay.add_batch, donate_argnums=0)
        state = add(state, _batch(np.arange(4), 4))  # compile
        before = state.storage["obs"].unsafe_buffer_pointer()
        state = add(state, _batch(np.arange(4, 8), 4))
        jax.block_until_ready(state)
        after = state.storage["obs"].unsafe_buffer_pointer()
        if before != after:
            pytest.skip("platform did not honor donation")
        assert int(state.size) == 8
