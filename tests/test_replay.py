"""Replay buffer tests: wraparound, sampling distribution, donation
(SURVEY.md §4 "Replay-buffer tests")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu import replay


def _example():
    return {
        "obs": jnp.zeros((3,), jnp.float32),
        "action": jnp.zeros((), jnp.int32),
        "reward": jnp.zeros((), jnp.float32),
    }


def _batch(values, b):
    """Batch whose obs rows encode the insert order for traceability."""
    v = jnp.asarray(values, jnp.float32)
    return {
        "obs": jnp.stack([v, v + 0.1, v + 0.2], axis=-1),
        "action": v.astype(jnp.int32),
        "reward": v,
    }


class TestInit:
    def test_shapes_dtypes(self):
        state = replay.init(_example(), capacity=16)
        assert state.storage["obs"].shape == (16, 3)
        assert state.storage["action"].dtype == jnp.int32
        assert int(state.size) == 0
        assert replay.capacity_of(state) == 16

    def test_add_grows_size(self):
        state = replay.init(_example(), capacity=8)
        state = replay.add_batch(state, _batch(np.arange(3), 3))
        assert int(state.size) == 3
        assert int(state.insert_pos) == 3
        np.testing.assert_allclose(
            np.asarray(state.storage["reward"][:3]), [0.0, 1.0, 2.0]
        )


class TestWraparound:
    def test_exact_wrap(self):
        state = replay.init(_example(), capacity=8)
        for start in range(0, 16, 4):
            state = replay.add_batch(state, _batch(np.arange(start, start + 4), 4))
        assert int(state.size) == 8
        assert int(state.insert_pos) == 0
        # Ring holds the newest 8 items in physical order 8..15.
        np.testing.assert_allclose(
            np.asarray(state.storage["reward"]), np.arange(8, 16, dtype=np.float32)
        )

    def test_straddling_batch(self):
        """A batch crossing the wrap point lands split across the ring."""
        state = replay.init(_example(), capacity=8)
        state = replay.add_batch(state, _batch(np.arange(6), 6))
        state = replay.add_batch(state, _batch(np.arange(6, 12), 6))
        assert int(state.size) == 8
        assert int(state.insert_pos) == 4
        # slots: [8, 9, 10, 11, 4, 5, 6, 7]
        np.testing.assert_allclose(
            np.asarray(state.storage["reward"]),
            [8.0, 9.0, 10.0, 11.0, 4.0, 5.0, 6.0, 7.0],
        )

    def test_batch_larger_runs(self):
        state = replay.init(_example(), capacity=4)
        state = replay.add_batch(state, _batch(np.arange(3), 3))
        state = replay.add_batch(state, _batch(np.arange(3, 6), 3))
        assert int(state.size) == 4

    def test_jit_add(self):
        add = jax.jit(replay.add_batch)
        state = replay.init(_example(), capacity=8)
        state = add(state, _batch(np.arange(5), 5))
        state = add(state, _batch(np.arange(5, 10), 5))
        assert int(state.size) == 8
        assert int(state.insert_pos) == 2


class TestSampling:
    def test_only_valid_entries(self):
        """Sampling never returns the zero-initialized (unwritten) tail."""
        state = replay.init(_example(), capacity=100)
        state = replay.add_batch(state, _batch(np.arange(1, 11), 10))
        out = replay.sample(state, jax.random.key(0), 256)
        r = np.asarray(out["reward"])
        assert r.min() >= 1.0 and r.max() <= 10.0
        assert out["obs"].shape == (256, 3)

    def test_roughly_uniform(self):
        state = replay.init(_example(), capacity=16)
        state = replay.add_batch(state, _batch(np.arange(16), 16))
        out = replay.sample(state, jax.random.key(1), 16 * 2000)
        counts = np.bincount(np.asarray(out["action"]), minlength=16)
        freq = counts / counts.sum()
        # Each slot ~1/16 ± generous tolerance.
        np.testing.assert_allclose(freq, np.full(16, 1 / 16), atol=0.01)

    def test_rows_internally_consistent(self):
        """Gather keeps (obs, action, reward) of one transition together."""
        state = replay.init(_example(), capacity=32)
        state = replay.add_batch(state, _batch(np.arange(32), 32))
        out = replay.sample(state, jax.random.key(2), 64)
        np.testing.assert_allclose(
            np.asarray(out["obs"][:, 0]), np.asarray(out["reward"])
        )

    def test_sample_sequences(self):
        state = replay.init(_example(), capacity=64)
        state = replay.add_batch(state, _batch(np.arange(40), 40))
        out = replay.sample_sequences(state, jax.random.key(3), 8, 5)
        r = np.asarray(out["reward"])
        assert r.shape == (8, 5)
        # Each row is consecutive inserts.
        np.testing.assert_allclose(np.diff(r, axis=1), np.ones((8, 4)))
        assert r.max() <= 39.0

    def test_sample_sequences_after_wrap(self):
        """Windows must never cross the write-cursor seam: a wrapped ring
        holds inserts [8..15] in physical order [8,9,10,11,4,5,6,7]*, and
        every sampled sequence must still be consecutive inserts."""
        state = replay.init(_example(), capacity=8)
        state = replay.add_batch(state, _batch(np.arange(6), 6))
        state = replay.add_batch(state, _batch(np.arange(6, 12), 6))
        # physical: [8, 9, 10, 11, 4, 5, 6, 7], insert_pos=4 (oldest=4)
        out = replay.sample_sequences(state, jax.random.key(0), 64, 3)
        r = np.asarray(out["reward"])
        np.testing.assert_allclose(np.diff(r, axis=1), np.ones((64, 2)))
        assert r.min() >= 4.0 and r.max() <= 11.0


class TestDonation:
    def test_inplace_update_under_donation(self):
        """Donated jitted add must reuse the storage buffer (no copy of the
        whole ring per insert — SURVEY §7.2 item 4)."""
        state = replay.init(_example(), capacity=1024)
        add = jax.jit(replay.add_batch, donate_argnums=0)
        state = add(state, _batch(np.arange(4), 4))  # compile
        before = state.storage["obs"].unsafe_buffer_pointer()
        state = add(state, _batch(np.arange(4, 8), 4))
        jax.block_until_ready(state)
        after = state.storage["obs"].unsafe_buffer_pointer()
        if before != after:
            pytest.skip("platform did not honor donation")
        assert int(state.size) == 8
