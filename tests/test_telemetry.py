"""Unified run telemetry (actor_critic_tpu/telemetry/, ISSUE 1).

Four contracts:
- the span tracer emits VALID Chrome-trace events whose phase spans nest
  inside their iteration span, from a real 3-iteration host-loop run;
- the resource sampler writes monotone-timestamp rows;
- the health monitors fire on synthetic regressions/divergence and stay
  quiet on clean runs;
- the stall watchdog's exit-42 diagnosis names the open span (and, with
  a session installed, writes a durable `stall` event first).

Plus `scripts/run_report.py` rendering the three sinks into markdown
with a per-phase breakdown — the acceptance-criteria path.
"""

import importlib.util
import json
import math
import os
import subprocess
import sys
import time
from pathlib import Path

from actor_critic_tpu import telemetry
from actor_critic_tpu.telemetry.health import (
    DivergenceMonitor,
    ThroughputMonitor,
)
from actor_critic_tpu.telemetry.sampler import ResourceSampler, sample_row

_spec = importlib.util.spec_from_file_location(
    "run_report", Path(__file__).parent.parent / "scripts" / "run_report.py"
)
run_report = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(run_report)


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------- spans


def test_spans_from_host_loop_are_valid_nested_chrome_trace(tmp_path):
    """A 3-iteration PPO host run under an installed session must leave a
    spans.jsonl whose every line is a Chrome Trace Event Format entry and
    whose phase spans (env_step / host_to_device / update / log) sit
    inside an iteration span by ts/dur containment — the property
    Perfetto uses to render nesting."""
    from actor_critic_tpu.algos import ppo
    from actor_critic_tpu.envs.host_pool import HostEnvPool

    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=8, epochs=1, num_minibatches=1, hidden=(16,)
    )
    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=0)
    with telemetry.TelemetrySession(tmp_path, sample_resources=False):
        ppo.train_host(pool, cfg, num_iterations=3, seed=0, log_every=1)
    pool.close()

    events = _read_jsonl(tmp_path / "spans.jsonl")
    assert events, "no span events written"
    for e in events:
        assert e["ph"] in ("M", "X", "i"), e
        assert "name" in e and "pid" in e and "tid" in e, e
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0, e
    # The line-per-event file wraps into the standard trace container.
    json.loads(json.dumps({"traceEvents": events}))

    complete = [e for e in events if e["ph"] == "X"]
    iters = [e for e in complete if e["name"] == "iteration"]
    assert len(iters) == 3, [e["name"] for e in complete]
    for phase in ("env_step", "host_to_device", "update", "log"):
        kids = [e for e in complete if e["name"] == phase]
        assert len(kids) == 3, (phase, [e["name"] for e in complete])
        for kid in kids:  # containment in SOME iteration span (±rounding)
            assert any(
                parent["ts"] - 1 <= kid["ts"]
                and kid["ts"] + kid["dur"] <= parent["ts"] + parent["dur"] + 1
                for parent in iters
            ), (phase, kid, iters)

    report = run_report.render(str(tmp_path))
    assert "| update |" in report and "| env_step |" in report, report
    run_report.write_trace(events, str(tmp_path / "trace.json"))
    assert json.load(open(tmp_path / "trace.json"))["traceEvents"]


def test_run_report_perf_budget_table():
    """ISSUE 15 satellite: with the committed perf_budgets.json present
    the report renders the budget table for every steady-state program
    (its committed max_* values), with actuals joined only when a
    perfsan_actuals.json report sits next to the manifest."""
    lines = run_report.perf_budget_table()
    assert lines, "committed manifest must render a table"
    body = "\n".join(lines)
    for program in (
        "ppo_update_host", "ppo_update_device", "offpolicy_ingest",
        "serving_dispatch", "mixture_fleet_step",
    ):
        assert f"`{program}`" in body
    # the device plane's metered contract is visible in the table
    assert "| `ppo_update_device` | 1 | 1 | 4 | 0 |" in body


def test_span_stack_tracked_without_session():
    """Spans must maintain the open-span stack with NO session installed
    (the watchdog reads it in runs launched without --telemetry-dir)."""
    assert telemetry.current() is None
    assert telemetry.open_spans() == []
    with telemetry.span("update", it=1):
        with telemetry.span("inner"):
            assert telemetry.open_spans() == ["update", "inner"]
            name, open_s = telemetry.last_open_span()
            assert name == "inner" and open_s >= 0
    assert telemetry.open_spans() == []
    telemetry.instant("env_step")  # no-op, must not raise
    telemetry.observe(1, {"loss": 0.0})


def test_span_stacks_are_per_thread():
    """Actor-service threads (algos/traj_queue.py, ISSUE 6) open spans
    concurrently with the learner: each thread gets its OWN stack (no
    stranded entries from interleaved pops), `open_spans` reports the
    calling thread only, and `last_open_span` — the watchdog's view —
    sees the most recently entered phase across all threads."""
    import threading
    import time as _time

    entered = threading.Event()
    release = threading.Event()
    seen_in_thread: list = []

    def worker():
        with telemetry.span("env_step", steps=1):
            seen_in_thread.append(telemetry.open_spans())
            entered.set()
            release.wait(5.0)

    t = threading.Thread(target=worker, daemon=True)
    with telemetry.span("update"):
        t.start()
        assert entered.wait(5.0)
        _time.sleep(0.01)
        assert telemetry.open_spans() == ["update"]  # this thread only
        assert seen_in_thread == [["env_step"]]
        # Cross-thread innermost: the worker's span opened later.
        assert telemetry.last_open_span()[0] == "env_step"
        release.set()
        t.join(5.0)
        assert telemetry.open_spans() == ["update"]
    assert telemetry.open_spans() == []
    assert telemetry.last_open_span() is None  # worker stack reclaimed


# -------------------------------------------------------------- sampler


def test_sampler_rows_are_monotone(tmp_path):
    path = tmp_path / "resources.jsonl"
    with open(path, "a", buffering=1) as fh:
        s = ResourceSampler(fh, interval_s=0.02).start()
        time.sleep(0.12)
        s.stop()
    rows = _read_jsonl(path)
    assert len(rows) >= 3  # start sample + >=1 tick + stop sample
    ts = [r["ts"] for r in rows]
    assert ts == sorted(ts)
    rec = [r["recompiles"] for r in rows]
    assert rec == sorted(rec) and all(isinstance(c, int) for c in rec)
    assert all(r["rss_bytes"] > 0 for r in rows if "rss_bytes" in r)


def test_session_plumbs_sampler_cadence(tmp_path):
    """`train.py --telemetry-sample-s` overrides the 5 s default via
    TelemetrySession(resource_interval_s=...)."""
    with telemetry.TelemetrySession(
        tmp_path, resource_interval_s=0.02
    ) as s:
        assert s.sampler is not None and s.sampler._interval == 0.02
        time.sleep(0.1)
    rows = _read_jsonl(tmp_path / "resources.jsonl")
    assert len(rows) >= 3  # the faster cadence actually ticked


def test_sample_row_shape():
    row = sample_row()
    assert set(row) >= {"ts", "recompiles"}
    for d in row.get("devices", []):
        assert "id" in d and "platform" in d
        # absent allocator stats must be ABSENT, never fake zeros
        assert d.get("live_bytes") != 0 or "live_bytes" not in d or d["live_bytes"] >= 0


# --------------------------------------------------------------- health


def test_throughput_monitor_confirms_fires_once_and_rearms():
    fired = []
    m = ThroughputMonitor(
        lambda kind, **f: fired.append((kind, f)),
        drop_threshold=0.5, warmup_observations=2,
    )
    t = 0.0
    for it in range(1, 8):  # steady 1 iter/s: quiet
        t += 1.0
        m.observe(it, {}, t)
    assert fired == []
    t += 10.0  # 0.1 iter/s — 90% below the ~1 EMA, but UNCONFIRMED
    m.observe(8, {}, t)
    assert fired == []
    t += 10.0  # second consecutive sub-floor window: fires once
    m.observe(9, {}, t)
    assert [k for k, _ in fired] == ["throughput_regression"]
    assert fired[0][1]["iters_per_s"] < fired[0][1]["ema_iters_per_s"]
    t += 10.0  # still slow: ALREADY tripped, no second event
    m.observe(10, {}, t)
    assert len(fired) == 1
    for it in range(11, 40):  # recovery re-arms...
        t += 1.0
        m.observe(it, {}, t)
    t += 30.0  # ...so a second CONFIRMED regression fires again
    m.observe(40, {}, t)
    t += 30.0
    m.observe(41, {}, t)
    assert [k for k, _ in fired] == ["throughput_regression"] * 2


def test_throughput_monitor_quiet_on_checkpoint_blips():
    """A healthy run's periodic one-window stalls (a checkpoint save or
    eval inside the observation interval inflates dt) must NOT fire —
    the confirm_observations=2 default makes isolated blips invisible."""
    fired = []
    m = ThroughputMonitor(
        lambda kind, **f: fired.append(kind),
        drop_threshold=0.5, warmup_observations=2,
    )
    t = 0.0
    for it in range(1, 30):
        t += 5.0 if it % 7 == 0 else 1.0  # save blip every 7th window
        m.observe(it, {}, t)
    assert fired == []


def test_throughput_monitor_threshold_boundary():
    """drop_threshold=0.5 means the floor is half the EMA: a sustained
    rate just ABOVE the floor must stay quiet, just BELOW must fire —
    the trigger/no-trigger edge the flag documents. ema_alpha=0 freezes
    the EMA at the baseline rate so the floor is exactly 0.5 iter/s
    (with the default alpha the EMA tracks a mild slowdown down and a
    45% rate stops counting as regressed — adaptive by design)."""
    for rate_frac, should_fire in ((0.55, False), (0.45, True)):
        fired = []
        m = ThroughputMonitor(
            lambda kind, **f: fired.append(kind),
            drop_threshold=0.5, warmup_observations=2, ema_alpha=0.0,
        )
        t = 0.0
        for it in range(1, 10):  # steady 1 iter/s baseline
            t += 1.0
            m.observe(it, {}, t)
        for it in range(10, 16):  # sustained slowdown at rate_frac
            t += 1.0 / rate_frac
            m.observe(it, {}, t)
        assert bool(fired) == should_fire, (rate_frac, fired)


def test_divergence_monitor_collapse_boundary():
    """collapse_frac=0.1 of best=100: 11 (above the line) must stay
    quiet, 9 (below) must fire."""
    for value, should_fire in ((11.0, False), (9.0, True)):
        fired = []
        m = DivergenceMonitor(
            lambda kind, **f: fired.append(kind), collapse_frac=0.1
        )
        m.observe(0, {"avg_return_ema": 100.0})
        m.observe(1, {"avg_return_ema": value})
        assert bool(fired) == should_fire, (value, fired)


def test_divergence_monitor_nonfinite_loss():
    fired = []
    m = DivergenceMonitor(lambda kind, **f: fired.append((kind, f)))
    for it in range(5):
        m.observe(it, {"loss": 0.5, "critic_loss": 0.1})
    assert fired == []
    m.observe(5, {"loss": float("nan")})
    m.observe(6, {"loss": math.inf})  # one event covers the run
    assert len(fired) == 1
    kind, f = fired[0]
    assert kind == "divergence" and f["reason"] == "non_finite_loss"


def test_divergence_monitor_return_collapse():
    fired = []
    m = DivergenceMonitor(
        lambda kind, **f: fired.append((kind, f)), collapse_frac=0.1
    )
    for it, r in enumerate([10.0, 120.0, 200.0, 190.0, 150.0]):
        m.observe(it, {"avg_return_ema": r})  # healthy wobble: quiet
    assert fired == []
    m.observe(5, {"avg_return_ema": 5.0})  # < 10% of best 200: collapse
    assert [k for k, _ in fired] == ["divergence"]
    assert fired[0][1]["reason"] == "return_collapse"
    m.observe(6, {"avg_return_ema": 4.0})  # still collapsed: no repeat
    assert len(fired) == 1


def test_divergence_monitor_quiet_below_progress_floor():
    """A run still at its random-policy floor has nothing to collapse
    from — near-zero watermarks must not trip the fraction test."""
    fired = []
    m = DivergenceMonitor(lambda kind, **f: fired.append(kind), min_progress=1.0)
    m.observe(0, {"avg_return_ema": 0.4})
    m.observe(1, {"avg_return_ema": 0.01})
    assert fired == []


def test_session_routes_observe_to_events(tmp_path):
    with telemetry.TelemetrySession(
        tmp_path, sample_resources=False
    ) as sess:
        sess.observe(1, {"loss": 1.0})
        sess.observe(2, {"loss": float("nan")})
    kinds = [r["kind"] for r in _read_jsonl(tmp_path / "events.jsonl")]
    assert kinds == ["session_start", "divergence", "session_end"]


# ------------------------------------------------------------- watchdog


def test_stall_report_names_open_span(tmp_path):
    with telemetry.TelemetrySession(tmp_path, sample_resources=False):
        with telemetry.span("update", it=7):
            msg = telemetry.stall_report(12.3)
    assert "update" in msg and "12.3" not in msg  # phase named, not the raw s
    rows = _read_jsonl(tmp_path / "events.jsonl")
    stall = [r for r in rows if r["kind"] == "stall"]
    assert len(stall) == 1
    assert stall[0]["phase"] == "update" and stall[0]["stalled_s"] == 12.3
    assert telemetry.stall_report() == ""  # no open span → empty clause


def test_stall_report_names_deepest_open_span(tmp_path):
    """Under nesting the diagnosis must name the INNERMOST open span —
    the phase actually executing when progress stopped — not the
    enclosing iteration."""
    with telemetry.TelemetrySession(tmp_path, sample_resources=False):
        with telemetry.span("iteration", it=3):
            with telemetry.span("env_step", steps=64):
                msg = telemetry.stall_report(7.0)
    assert "'env_step'" in msg and "'iteration'" not in msg, msg
    stall = [
        r for r in _read_jsonl(tmp_path / "events.jsonl")
        if r["kind"] == "stall"
    ]
    assert len(stall) == 1 and stall[0]["phase"] == "env_step"


def test_health_events_are_fsynced(tmp_path, monkeypatch):
    """A health event() must flush+fsync the sinks (SIGKILL durability):
    count fsync calls on the events file descriptor."""
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (synced.append(fd),
                                                 real_fsync(fd))[1])
    with telemetry.TelemetrySession(tmp_path, sample_resources=False) as s:
        s.event("session_note")  # lifecycle: no fsync required
        assert synced == []
        s.observe(1, {"loss": float("nan")})  # divergence → durable
    assert len(synced) >= 3  # all three sinks synced at least once


def test_watchdog_exit42_diagnosis_includes_open_span(tmp_path):
    """End-to-end: a process wedged INSIDE a span dies with exit 42, the
    stderr diagnosis names the span, and the stall event is durable in
    events.jsonl despite the os._exit teardown."""
    from actor_critic_tpu.utils import watchdog

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    proc = subprocess.run(
        [sys.executable, "-c", (
            "import time\n"
            "from actor_critic_tpu import telemetry\n"
            "from actor_critic_tpu.utils.watchdog import StallWatchdog\n"
            f"s = telemetry.TelemetrySession({str(tmp_path)!r}, "
            "sample_resources=False)\n"
            "telemetry.set_current(s)\n"
            "StallWatchdog(1.0, startup_grace_s=0.0).start()\n"
            "with telemetry.span('update', it=681):\n"
            "    time.sleep(30)\n"  # the wedged device call
        )],
        capture_output=True, text=True, timeout=60, env=env,
    )
    assert proc.returncode == watchdog.STALL_EXIT_CODE, (
        proc.returncode, proc.stderr,
    )
    assert "last open telemetry span: 'update'" in proc.stderr, proc.stderr
    stall = [
        r for r in _read_jsonl(tmp_path / "events.jsonl")
        if r["kind"] == "stall"
    ]
    assert len(stall) == 1 and stall[0]["phase"] == "update", stall


# ------------------------------------------------------------ reporting


def test_run_report_renders_health_and_resources(tmp_path):
    (tmp_path / "spans.jsonl").write_text(
        json.dumps({"name": "iteration", "ph": "X", "ts": 0.0, "dur": 100.0,
                    "pid": 1, "tid": 1}) + "\n"
        + json.dumps({"name": "update", "ph": "X", "ts": 10.0, "dur": 80.0,
                      "pid": 1, "tid": 1}) + "\n"
        + '{"torn'  # stall-kill mid-write: must not abort the report
    )
    (tmp_path / "resources.jsonl").write_text(
        json.dumps({"ts": 1.0, "recompiles": 2, "rss_bytes": 1 << 20}) + "\n"
        + json.dumps({"ts": 2.0, "recompiles": 2, "rss_bytes": 2 << 20}) + "\n"
    )
    (tmp_path / "events.jsonl").write_text(
        json.dumps({"ts": 1.0, "kind": "session_start", "algo": "sac"}) + "\n"
        + json.dumps({"ts": 2.0, "kind": "divergence",
                      "reason": "non_finite_loss"}) + "\n"
    )
    (tmp_path / "metrics.jsonl").write_text(
        json.dumps({"iter": 3, "wall_s": 2.0, "loss": 0.5,
                    "env_steps": 300, "eval_return": 21.0}) + "\n"
    )
    report = run_report.render(str(tmp_path))
    assert "divergence" in report
    assert "| update | 1 |" in report
    assert "80.0%" in report  # 80/100 of iteration wall
    assert "RSS" in report and "recompiles" in report.lower()
    assert "eval: best 21.0" in report


def test_run_report_stitches_resume_segments(tmp_path):
    """The sinks append across resume retries (exit-42 loop): the
    recompile counter resets per process (sum positive deltas, never
    endpoints), the report names the segment count, and --trace
    re-anchors each segment's perf_counter clock via its clock_sync
    epoch so Perfetto shows retries end to end."""
    (tmp_path / "resources.jsonl").write_text(
        "".join(
            json.dumps({"ts": ts, "recompiles": rec}) + "\n"
            for ts, rec in [(0, 0), (5, 40), (10, 47), (70, 0), (75, 30), (80, 31)]
        )
    )
    (tmp_path / "events.jsonl").write_text(
        json.dumps({"ts": 0.0, "kind": "session_start", "seed": 0}) + "\n"
        + json.dumps({"ts": 65.0, "kind": "stall", "phase": "update"}) + "\n"
        + json.dumps({"ts": 70.0, "kind": "session_start", "seed": 0}) + "\n"
    )
    seg = lambda epoch: json.dumps({  # noqa: E731
        "name": "clock_sync", "ph": "M", "pid": 1, "tid": 0,
        "args": {"unix_epoch_at_ts0": epoch},
    })
    upd = json.dumps({"name": "update", "ph": "X", "ts": 0.0, "dur": 10.0,
                      "pid": 1, "tid": 1})
    (tmp_path / "spans.jsonl").write_text(
        seg(1000.0) + "\n" + upd + "\n" + seg(1060.0) + "\n" + upd + "\n"
    )
    report = run_report.render(str(tmp_path))
    assert "2 session segments" in report
    assert "78 total" in report  # 47 + 31, NOT the raw endpoint 31
    assert "stall" in report
    run_report.write_trace(
        run_report.read_jsonl(str(tmp_path / "spans.jsonl")),
        str(tmp_path / "trace.json"),
    )
    ts = [e["ts"] for e in json.load(open(tmp_path / "trace.json"))["traceEvents"]
          if e["ph"] == "X"]
    assert ts == [0.0, 60.0 * 1e6]  # segment 2 shifted by the epoch gap


def test_read_jsonl_tolerates_torn_final_line(tmp_path, capsys):
    """A half-written final record (SIGKILL mid-write) must cost exactly
    that record, silently; undecodable INTERIOR lines are dropped too
    but announced on stderr (they mean real corruption, not a kill)."""
    p = tmp_path / "events.jsonl"
    p.write_text(
        json.dumps({"kind": "a"}) + "\n"
        + json.dumps({"kind": "b"}) + "\n"
        + '{"kind": "stall", "stalled_s": 3'  # torn: no close, no newline
    )
    rows = run_report.read_jsonl(str(p))
    assert [r["kind"] for r in rows] == ["a", "b"]
    assert capsys.readouterr().err == ""  # torn tail is expected, quiet

    p.write_text(
        json.dumps({"kind": "a"}) + "\n"
        + "{corrupt\n"
        + json.dumps({"kind": "c"}) + "\n"
    )
    rows = run_report.read_jsonl(str(p))
    assert [r["kind"] for r in rows] == ["a", "c"]
    assert "1 undecodable" in capsys.readouterr().err


def test_run_report_recompile_attribution_and_slowest_spans(tmp_path):
    """The report's new sections: compile events group into the
    attribution table naming distinct arg signatures, the slowest-spans
    table ranks raw durations, and profile_done events become links."""
    (tmp_path / "spans.jsonl").write_text(
        "".join(
            json.dumps({"name": n, "ph": "X", "ts": float(i), "dur": d,
                        "pid": 1, "tid": 1}) + "\n"
            for i, (n, d) in enumerate(
                [("update", 10.0), ("checkpoint", 4e7), ("update", 30.0)]
            )
        )
    )
    sig_a = "(tensor<8x3xf32>) -> tensor<8x8xf32>"
    sig_b = "(tensor<16x3xf32>) -> tensor<16x16xf32>"
    (tmp_path / "events.jsonl").write_text(
        "".join(
            json.dumps(r) + "\n"
            for r in [
                {"ts": 1.0, "kind": "session_start"},
                {"ts": 2.0, "kind": "compile", "name": "jit_update",
                 "compile_s": 2.0, "flops": 1e9, "signature": sig_a},
                {"ts": 3.0, "kind": "compile", "name": "jit_update",
                 "compile_s": 3.0, "flops": 4e9, "signature": sig_b},
                {"ts": 4.0, "kind": "profile_done",
                 "path": str(tmp_path / "profile_001"), "wall_s": 1.5},
            ]
        )
    )
    report = run_report.render(str(tmp_path))
    assert "## Recompile attribution" in report
    assert "| `jit_update` | 2 | 0 | 5.00s" in report, report
    assert "2 argument signatures" in report
    assert sig_a in report and sig_b in report
    assert "## Slowest spans" in report
    slow_sec = report.split("## Slowest spans")[1].split("##")[0]
    # checkpoint (40 s) outranks both updates
    assert slow_sec.splitlines()[4].startswith("| 1 | checkpoint | 40.00s")
    assert "## Profile captures" in report
    assert "profile_001" in report
    # compile/profile diagnostics must NOT flood the health table
    assert "| **compile**" not in report and "| **profile_done**" not in report


def test_phase_breakdown_separates_worker_lanes():
    """Relayed env_step_worker spans run in W processes CONCURRENT with
    the parent iteration wall: they must not enter the share table
    (workers=4 at ~90% busy would print a 360% row) — they get their
    own per-pid summary line instead."""
    spans = [
        {"name": "iteration", "ph": "X", "ts": 0.0, "dur": 100.0,
         "pid": 1, "tid": 1},
        {"name": "env_step", "ph": "X", "ts": 5.0, "dur": 90.0,
         "pid": 1, "tid": 1},
    ] + [
        {"name": "env_step_worker", "ph": "X", "ts": float(10 * i),
         "dur": 80.0, "pid": pid, "tid": 0, "args": {"worker": pid - 100}}
        for pid in (100, 101, 102, 103)
        for i in range(2)
    ]
    lines = "\n".join(run_report.phase_breakdown(spans))
    assert "| env_step_worker" not in lines
    assert "4 worker process(es)" in lines
    assert "pid 100: 2 steps" in lines
    # shares stay interpretable: the only table row is env_step at 90%
    assert "90.0%" in lines and "360" not in lines


def test_run_report_cli(tmp_path):
    d = tmp_path / "t"
    d.mkdir()
    (d / "spans.jsonl").write_text(
        json.dumps({"name": "update", "ph": "X", "ts": 0.0, "dur": 5.0,
                    "pid": 1, "tid": 1}) + "\n"
    )
    out = tmp_path / "report.md"
    assert run_report.main([str(d), "--trace", "-o", str(out)]) == 0
    assert "# Run report" in out.read_text()
    assert json.load(open(d / "trace.json"))["traceEvents"]


def test_checkpointed_train_emits_fused_loop_spans(tmp_path):
    """The fused-loop boundary (utils/checkpoint.checkpointed_train)
    must emit an update span per dispatch, a log span per log_fn call,
    and a checkpoint span at every should_save boundary EVEN with
    ckpt=None (args record saved=False) so checkpointed and
    checkpoint-free runs compare phase-for-phase."""
    import jax.numpy as jnp

    from actor_critic_tpu.utils.checkpoint import checkpointed_train

    def step(state):
        return state + 1, {"loss": jnp.asarray(0.0)}

    with telemetry.TelemetrySession(tmp_path, sample_resources=False):
        state, _ = checkpointed_train(
            step, jnp.asarray(0), num_iterations=3,
            log_fn=lambda it, m: None,
        )
    assert int(state) == 3
    complete = [
        e for e in _read_jsonl(tmp_path / "spans.jsonl") if e["ph"] == "X"
    ]
    names = [e["name"] for e in complete]
    assert names.count("update") == 3 and names.count("log") == 3, names
    ck = [e for e in complete if e["name"] == "checkpoint"]
    assert len(ck) == 1 and ck[0]["args"]["saved"] is False, ck
