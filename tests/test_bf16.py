"""bf16 compute path: every fused trainer's bf16_compute flag produces a
runnable, finite train step with f32 params (mixed precision — MXU-sized
matmuls in bf16, accumulation/optimizer in f32)."""

import jax
import jax.numpy as jnp
import pytest

from actor_critic_tpu.algos import a2c, impala
from actor_critic_tpu.envs import make_cartpole, make_pong


@pytest.mark.parametrize(
    "mod,cfg,make_env",
    [
        (a2c, a2c.A2CConfig(num_envs=8, rollout_steps=4, hidden=(16,),
                            bf16_compute=True), make_cartpole),
        (impala, impala.ImpalaConfig(num_envs=4, rollout_steps=4, hidden=(16,),
                                     bf16_compute=True), make_cartpole),
    ],
)
def test_bf16_train_step_finite(mod, cfg, make_env):
    env = make_env()
    state = mod.init_state(env, cfg, jax.random.key(0))
    # params stay f32 (mixed precision: casts happen in the modules)
    assert all(
        x.dtype == jnp.float32
        for x in jax.tree.leaves(state.params)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
    )
    step = jax.jit(mod.make_train_step(env, cfg), donate_argnums=0)
    for _ in range(3):
        state, metrics = step(state)
    assert bool(jnp.isfinite(metrics["loss"]))


