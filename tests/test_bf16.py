"""bf16 compute path: every fused trainer's bf16_compute flag produces a
runnable, finite train step with f32 params (mixed precision — MXU-sized
matmuls in bf16, accumulation/optimizer in f32), and — ISSUE 19 — the
`--update-dtype bf16` path lands same-seed eval parity with fp32 on every
on-policy algo, mirroring the PR 8 replay-dtype parity suite."""

import os
import tempfile

import jax
import jax.numpy as jnp
import pytest

from actor_critic_tpu.algos import a2c, impala, ppo
from actor_critic_tpu.envs import make_cartpole, make_point_mass, make_pong


@pytest.mark.parametrize(
    "mod,cfg,make_env",
    [
        (a2c, a2c.A2CConfig(num_envs=8, rollout_steps=4, hidden=(16,),
                            bf16_compute=True), make_cartpole),
        (impala, impala.ImpalaConfig(num_envs=4, rollout_steps=4, hidden=(16,),
                                     bf16_compute=True), make_cartpole),
    ],
)
def test_bf16_train_step_finite(mod, cfg, make_env):
    env = make_env()
    state = mod.init_state(env, cfg, jax.random.key(0))
    # params stay f32 (mixed precision: casts happen in the modules)
    assert all(
        x.dtype == jnp.float32
        for x in jax.tree.leaves(state.params)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
    )
    step = jax.jit(mod.make_train_step(env, cfg), donate_argnums=0)
    for _ in range(3):
        state, metrics = step(state)
    assert bool(jnp.isfinite(metrics["loss"]))


# -- ISSUE 19: --update-dtype bf16 vs fp32 eval parity ----------------------
#
# Same-seed short runs in both precisions must BOTH learn point_mass
# (optimal 0, random ≈ −6) and land within a tolerance of each other —
# bf16 matmul compute with fp32 accumulators must not change what the
# policy converges to. Configs were tuned so the fp32 leg demonstrably
# learns in a few seconds on CPU; thresholds mirror PR 8's
# test_eval_parity_fp32_vs_mixed.
#
# The six training legs run behind ONE session-scoped fixture (ISSUE 20
# satellite) under the ISSUE 4 persistent compilation cache: these legs
# are compile-bound (~4 s XLA compile vs ~0.3 s of actual training per
# leg on this 1-core host), so the steady-state tier-1 run deserializes
# every leg's programs instead of recompiling them — measured 24 s cold
# vs 10 s warm (~17 s clawed back from the second run onward). The
# assertions are unchanged; only where the compiled programs come from
# moved.

_PARITY_CACHE_DIR = os.environ.get(
    "BF16_PARITY_CACHE_DIR",
    os.path.join(
        tempfile.gettempdir(), "actor_critic_tpu_bf16_parity_cache"
    ),
)

_PARITY_CFGS = {
    "ppo": (ppo, lambda bf16: ppo.PPOConfig(
        num_envs=32, rollout_steps=16, epochs=4, num_minibatches=2,
        lr=3e-3, hidden=(32, 32), bf16_compute=bf16,
    ), 120),
    "a2c": (a2c, lambda bf16: a2c.A2CConfig(
        num_envs=32, rollout_steps=16, lr=3e-3, hidden=(32, 32),
        bf16_compute=bf16,
    ), 200),
    "impala": (impala, lambda bf16: impala.ImpalaConfig(
        num_envs=32, rollout_steps=16, lr=3e-3, hidden=(32, 32),
        bf16_compute=bf16,
    ), 200),
}


def _train_and_eval(mod, env, cfg, iters, seed):
    state = mod.init_state(env, cfg, jax.random.key(seed))
    step = jax.jit(mod.make_train_step(env, cfg), donate_argnums=0)
    for _ in range(iters):
        state, _ = step(state)
    eval_fn = jax.jit(mod.make_eval_fn(env, cfg), static_argnums=(2, 3))
    return float(eval_fn(state, jax.random.key(99), 32, 16))


@pytest.fixture(scope="session")
def bf16_parity_legs():
    """Lazy per-algo trainer: `legs('ppo') -> {False: ret, True: ret}`,
    each algo's two precision legs trained at most once per session,
    all compiles routed through the persistent cache so repeat tier-1
    runs skip straight to the ~0.3 s of actual training per leg."""
    from actor_critic_tpu.utils import compile_cache

    trained: dict = {}

    def legs(algo: str) -> dict:
        if algo not in trained:
            mod, make_cfg, iters = _PARITY_CFGS[algo]
            env = make_point_mass()
            with compile_cache.temporary_cache(_PARITY_CACHE_DIR):
                trained[algo] = {
                    bf16: _train_and_eval(
                        mod, env, make_cfg(bf16), iters, seed=0
                    )
                    for bf16 in (False, True)
                }
        return trained[algo]

    return legs


@pytest.mark.parametrize("algo", ["ppo", "a2c", "impala"])
def test_eval_parity_fp32_vs_bf16(algo, bf16_parity_legs):
    results = bf16_parity_legs(algo)
    assert results[False] > -1.0, results
    assert results[True] > -1.0, results
    assert abs(results[False] - results[True]) < 1.0, results


