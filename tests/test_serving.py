"""Policy-serving gateway (ISSUE 10): end-to-end loopback-HTTP tests —
served actions match direct act(), micro-batch equivalence at mixed
request sizes, hot-swap under in-flight load, 503 on dispatcher stall —
plus store/batcher/engine units."""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from actor_critic_tpu import serving
from actor_critic_tpu.algos import ppo
from actor_critic_tpu.envs import make_cartpole


# ---------------------------------------------------------------- helpers


class StubEngine:
    """jax-free engine: action = obs[:, 0] * params['scale'][0]."""

    max_rows = 8

    def __init__(self, pad_s: float = 0.0):
        self.pad_s = pad_s
        self.flush_rows: list[int] = []

    def prepare_params(self, params):
        return {k: np.array(v) for k, v in params.items()}

    def act(self, params, obs):
        if self.pad_s:
            time.sleep(self.pad_s)
        obs = np.asarray(obs)
        self.flush_rows.append(obs.shape[0])
        return obs[:, 0] * params["scale"][0]


def _post(url: str, body: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url: str, timeout: float = 10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture
def ppo_serving():
    """A real PPO CartPole engine + params + a warmed gateway on an
    ephemeral port; yields (gateway, engine, raw params, spec, cfg)."""
    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(16, 16))
    engine = serving.PolicyEngine(
        spec, cfg, algo="ppo", buckets=(1, 2, 4, 8)
    )
    params = serving.init_params(spec, cfg, "ppo", seed=0)
    store = serving.PolicyStore()
    store.register("default", engine, params)
    engine.warm(store.get().params)
    gw = serving.ServeGateway(store, port=0, max_wait_us=500.0)
    yield gw, engine, params, spec, cfg
    gw.close()


# ---------------------------------------------------------------- units


def test_policy_store_register_swap_and_routes():
    store = serving.PolicyStore()
    eng = StubEngine()
    store.register("a", eng, {"scale": np.ones(1, np.float32)})
    store.register("b", eng, {"scale": np.full(1, 2.0, np.float32)})
    assert store.default_id == "a"  # first registration wins
    assert store.ids() == {"a": 0, "b": 0}
    assert store.get().policy_id == "a"
    assert store.get("b").version == 0
    with pytest.raises(serving.UnknownPolicy):
        store.get("nope")
    with pytest.raises(ValueError):
        store.register("a", eng, {"scale": np.ones(1)})
    old = store.get("a")
    new = store.swap("a", {"scale": np.full(1, 5.0, np.float32)})
    assert new.version == 1 and store.get("a").version == 1
    # Handles are immutable snapshots: the pre-swap handle still serves
    # its original params (in-flight requests never see a torn swap).
    assert float(old.params["scale"][0]) == 1.0
    assert float(new.params["scale"][0]) == 5.0


def test_batcher_groups_mixed_sizes_and_preserves_order():
    store = serving.PolicyStore()
    eng = StubEngine()
    store.register("default", eng, {"scale": np.ones(1, np.float32)})
    batcher = serving.MicroBatcher(store, start=False, max_wait_us=0.0)
    reqs = [
        batcher.submit(np.full((n, 3), float(i + 1), np.float32))
        for i, n in enumerate((1, 3, 2, 8, 1))
    ]
    while batcher.queue_depth():
        batcher._flush_once(block=False)
    for i, (req, n) in enumerate(zip(reqs, (1, 3, 2, 8, 1))):
        actions, version = req.result
        assert version == 0
        np.testing.assert_array_equal(
            actions, np.full(n, float(i + 1), np.float32)
        )
    # 1+3+2 fit the 8-row budget, the 8-row request does not (requests
    # are never split), and the trailing 1 backfills the remaining
    # slack of the FIRST flush — standby-style packing; the 8 flushes
    # alone after.
    assert eng.flush_rows == [7, 8]


def test_batcher_owns_the_payload():
    """submit() copies: a client reusing its buffer after submit must
    not tear an already-enqueued request (PR 6 zero-copy class)."""
    store = serving.PolicyStore()
    store.register("default", StubEngine(), {"scale": np.ones(1, np.float32)})
    batcher = serving.MicroBatcher(store, start=False)
    buf = np.full((2, 3), 7.0, np.float32)
    req = batcher.submit(buf)
    buf.fill(-1.0)  # client-side reuse before the flush
    batcher._flush_once(block=False)
    np.testing.assert_array_equal(req.result[0], [7.0, 7.0])


def test_batcher_rejects_oversized_and_overflow():
    store = serving.PolicyStore()
    store.register("default", StubEngine(), {"scale": np.ones(1, np.float32)})
    batcher = serving.MicroBatcher(store, start=False, queue_limit=2)
    with pytest.raises(ValueError):
        batcher.submit(np.zeros((9, 3), np.float32))  # > max_rows=8
    batcher.submit(np.zeros((1, 3), np.float32))
    batcher.submit(np.zeros((1, 3), np.float32))
    with pytest.raises(serving.QueueFull):
        batcher.submit(np.zeros((1, 3), np.float32))
    assert batcher.metrics.snapshot()["rejected_total"] == 1


def test_engine_rejects_bad_config():
    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(8,))
    with pytest.raises(ValueError):
        serving.PolicyEngine(spec, cfg, buckets=())
    with pytest.raises(ValueError):
        serving.PolicyEngine(spec, cfg, buckets=(0, 4))
    with pytest.raises(ValueError):
        serving.make_act_program(spec, cfg, algo="ddpg", sample=True)
    with pytest.raises(ValueError):
        serving.make_act_program(spec, cfg, algo="impala")


# ---------------------------------------------------------------- e2e HTTP


def test_served_actions_match_direct_act(ppo_serving):
    """POST /v1/act == the greedy program applied directly: the gateway
    adds batching/padding, never different actions."""
    gw, engine, params, spec, cfg = ppo_serving
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(5, *spec.obs_shape)).astype(np.float32)
    direct = np.asarray(
        jax.jit(ppo.make_greedy_act(spec, cfg))(params, obs)
    )
    status, body = _post(gw.url + "/v1/act", {"obs": obs.tolist()})
    assert status == 200
    assert body["policy"] == "default" and body["version"] == 0
    np.testing.assert_array_equal(np.asarray(body["actions"]), direct)
    # Single-obs auto-batching: same action, unwrapped payload.
    status, body = _post(gw.url + "/v1/act", {"obs": obs[0].tolist()})
    assert status == 200
    assert np.asarray(body["actions"]).shape == direct[0].shape
    assert np.asarray(body["actions"]) == direct[0]


def test_micro_batch_equivalence_at_mixed_request_sizes(ppo_serving):
    """Concurrent requests of mixed sizes, flushed together through the
    bucketed program, answer exactly what each would get alone."""
    gw, engine, params, spec, cfg = ppo_serving
    rng = np.random.default_rng(1)
    sizes = (1, 3, 2, 1, 4)
    payloads = [
        rng.normal(size=(n, *spec.obs_shape)).astype(np.float32)
        for n in sizes
    ]
    direct = jax.jit(ppo.make_greedy_act(spec, cfg))
    results: list = [None] * len(sizes)

    def worker(i: int) -> None:
        results[i] = _post(
            gw.url + "/v1/act", {"obs": payloads[i].tolist()}
        )

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(sizes))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    for i, n in enumerate(sizes):
        status, body = results[i]
        assert status == 200, body
        np.testing.assert_array_equal(
            np.asarray(body["actions"]),
            np.asarray(direct(params, payloads[i])),
        )


def test_standby_backfill_rows_poisoned_do_not_leak(
    ppo_serving, monkeypatch
):
    """Bucket backfill isolation (ISSUE 20 satellite): the standby rows
    `pad_to_bucket` appends are dead weight, so poisoning them with
    padsan's menu (NaN / ±3e38) must not move a single byte of the
    first-n actions — the row-independent MLP plus act()'s [:n] slice
    are the guard, and this pins them outside the sanitizer too."""
    from actor_critic_tpu.utils import compile_cache

    gw, engine, params, spec, cfg = ppo_serving
    rng = np.random.default_rng(7)
    orig = compile_cache.pad_to_bucket
    for n, fill in ((3, np.nan), (5, 3.0e38), (6, -3.0e38)):
        obs = rng.normal(size=(n, *spec.obs_shape)).astype(np.float32)
        clean = engine.act(params, obs)

        def poisoned(x, buckets, axis=0, _fill=fill):
            out, mask = orig(x, buckets, axis)
            out = np.array(out)
            out[x.shape[0]:] = _fill
            return out, mask

        monkeypatch.setattr(compile_cache, "pad_to_bucket", poisoned)
        dirty = engine.act(params, obs)
        monkeypatch.setattr(compile_cache, "pad_to_bucket", orig)
        assert dirty.shape[0] == n
        assert clean.tobytes() == dirty.tobytes()


def test_concurrent_mixed_sizes_match_batch1_bitwise(ppo_serving):
    """Strictest no-cross-row-contamination contract (ISSUE 20
    satellite): concurrent mixed-size requests, merged and padded
    through the bucket ladder, must answer BITWISE what each row gets
    from a batch-1 dispatch — not just the same size-n direct act."""
    gw, engine, params, spec, cfg = ppo_serving
    rng = np.random.default_rng(3)
    sizes = (1, 3, 2, 1, 4)
    payloads = [
        rng.normal(size=(n, *spec.obs_shape)).astype(np.float32)
        for n in sizes
    ]
    results: list = [None] * len(sizes)

    def worker(i: int) -> None:
        results[i] = _post(
            gw.url + "/v1/act", {"obs": payloads[i].tolist()}
        )

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(sizes))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    for i, n in enumerate(sizes):
        status, body = results[i]
        assert status == 200, body
        for j in range(n):
            solo = engine.act(params, payloads[i][j:j + 1])
            assert solo.shape[0] == 1
            got = np.asarray(body["actions"], dtype=solo.dtype)[j]
            assert got.tobytes() == solo[0].tobytes()


def test_unknown_policy_and_bad_payloads(ppo_serving):
    gw, *_ = ppo_serving
    status, body = _post(gw.url + "/v1/act", {"obs": [0.0] * 4,
                                              "policy": "ghost"})
    assert status == 404 and "ghost" in body["error"]
    status, body = _post(gw.url + "/v1/act", {})
    assert status == 400
    status, body = _post(gw.url + "/v1/act", {"obs": [[0.0, 1.0]]})
    assert status == 400 and "obs must be shaped" in body["error"]
    status, body = _post(gw.url + "/v1/act", {"obs": "garbage"})
    assert status == 400


def test_hot_swap_under_in_flight_load():
    """Swaps land mid-traffic without dropping requests: every response
    is exact for the version it claims, versions only move forward."""
    store = serving.PolicyStore()
    eng = StubEngine(pad_s=0.002)  # keep flushes slow enough to overlap
    store.register("default", eng, {"scale": np.ones(1, np.float32)})
    gw = serving.ServeGateway(store, port=0, max_wait_us=500.0)
    try:
        stop = threading.Event()
        failures: list = []

        def client(c: int) -> None:
            last_version = -1
            i = 0
            while not stop.is_set():
                fill = float(100 * c + i + 1)
                status, body = _post(
                    gw.url + "/v1/act",
                    {"obs": [[fill, 0.0], [fill, 0.0]]},
                )
                if status != 200:
                    failures.append((c, i, status, body))
                    return
                v = body["version"]
                expect = fill * (v + 1.0)
                if body["actions"] != [expect, expect] or v < last_version:
                    failures.append((c, i, body))
                    return
                last_version = v
                i += 1

        threads = [
            threading.Thread(target=client, args=(c,)) for c in range(3)
        ]
        for t in threads:
            t.start()
        for v in range(1, 5):
            time.sleep(0.05)
            # scale == version + 1, the invariant clients verify
            store.swap(
                "default",
                {"scale": np.full(1, v + 1.0, np.float32)},
                version=v,
            )
        time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(10)
        assert not failures, failures[:3]
        assert store.get("default").version == 4
    finally:
        gw.close()


def test_swap_endpoint_roundtrip(tmp_path):
    """POST /v1/swap restores a params-only checkpoint and bumps the
    served version without dropping the route."""
    spec = make_cartpole().spec
    cfg = ppo.PPOConfig(hidden=(8, 8))
    engine = serving.PolicyEngine(spec, cfg, algo="ppo", buckets=(1, 4))
    p0 = serving.init_params(spec, cfg, "ppo", seed=0)
    p1 = serving.init_params(spec, cfg, "ppo", seed=1)
    serving.export_policy_params(str(tmp_path / "ck"), p1)
    store = serving.PolicyStore()
    store.register("default", engine, p0)
    gw = serving.ServeGateway(store, port=0)
    try:
        status, body = _post(
            gw.url + "/v1/swap",
            {"policy": "default", "checkpoint": str(tmp_path / "ck")},
        )
        assert status == 200 and body["version"] == 1
        swapped = store.get("default").params
        np.testing.assert_allclose(
            np.asarray(swapped["params"]["torso"]["dense_0"]["kernel"]),
            np.asarray(p1["params"]["torso"]["dense_0"]["kernel"]),
            rtol=1e-6,
        )
        status, body = _post(
            gw.url + "/v1/swap", {"policy": "default"}
        )
        assert status == 400
        status, body = _post(
            gw.url + "/v1/swap",
            {"policy": "ghost", "checkpoint": str(tmp_path / "ck")},
        )
        assert status == 404
    finally:
        gw.close()


def test_503_on_dispatcher_stall():
    """A dead dispatcher or a full queue must answer 503 (load shed),
    and /healthz must flip to 503 'stalled'."""
    store = serving.PolicyStore()
    store.register("default", StubEngine(), {"scale": np.ones(1, np.float32)})
    batcher = serving.MicroBatcher(store, queue_limit=4, start=True)
    gw = serving.ServeGateway(
        store, port=0, batcher=batcher, request_timeout_s=2.0,
        stall_after_s=0.2,
    )
    try:
        # Stall the dispatcher: close() joins the thread but we keep
        # the server up — submissions now see DispatcherDown.
        batcher.close()
        status, body = _post(gw.url + "/v1/act", {"obs": [[1.0, 2.0]]})
        assert status == 503, body
        status, raw = _get(gw.url + "/healthz")
        assert status == 503
        assert json.loads(raw)["status"] == "stalled"
    finally:
        gw.close()


def test_queue_overflow_returns_503():
    store = serving.PolicyStore()
    store.register("default", StubEngine(), {"scale": np.ones(1, np.float32)})
    # Unstarted dispatcher with a tiny queue: requests pile up.
    batcher = serving.MicroBatcher(store, queue_limit=2, start=False)
    # submit() refuses only when a started thread died; fill directly.
    batcher.submit(np.zeros((1, 2), np.float32))
    batcher.submit(np.zeros((1, 2), np.float32))
    gw = serving.ServeGateway(store, port=0, batcher=batcher)
    try:
        status, body = _post(gw.url + "/v1/act", {"obs": [[1.0, 2.0]]})
        assert status == 503 and "capacity" in body["error"]
    finally:
        gw.close()


def test_metrics_and_healthz_surface_serving_gauges(ppo_serving):
    gw, *_ = ppo_serving
    _post(gw.url + "/v1/act", {"obs": [[0.0, 0.0, 0.0, 0.0]]})
    status, text = _get(gw.url + "/metrics")
    assert status == 200
    assert "actor_critic_serving_requests_total" in text
    assert "actor_critic_serving_latency_p99_ms" in text
    assert "actor_critic_serving_requests_default" in text
    status, raw = _get(gw.url + "/healthz")
    assert status == 200
    health = json.loads(raw)
    assert health["dispatcher"]["alive"] is True
    assert health["policies"] == {"default": 0}
    status, raw = _get(gw.url + "/v1/policies")
    assert status == 200
    assert json.loads(raw)["default"] == "default"


def test_ephemeral_port_is_reported():
    """port=0 binds an OS-assigned port, reported on the gateway object
    (the ISSUE 10 satellite contract the loadgen/CI rely on)."""
    store = serving.PolicyStore()
    store.register("default", StubEngine(), {"scale": np.ones(1, np.float32)})
    a = serving.ServeGateway(store, port=0)
    b = serving.ServeGateway(store, port=0)
    try:
        assert a.port != 0 and b.port != 0 and a.port != b.port
        assert str(a.port) in a.url
        status, _ = _get(a.url + "/healthz")
        assert status == 200
    finally:
        a.close()
        b.close()


def test_multi_policy_routing_over_http():
    """Two resident policies answer under their own ids; default routes
    unnamed requests; per-policy counters split on /metrics."""
    store = serving.PolicyStore()
    eng = StubEngine()
    store.register("champ", eng, {"scale": np.ones(1, np.float32)})
    store.register("canary", eng, {"scale": np.full(1, 3.0, np.float32)})
    gw = serving.ServeGateway(store, port=0, max_wait_us=0.0)
    try:
        status, body = _post(
            gw.url + "/v1/act", {"obs": [[2.0, 0.0]], "policy": "canary"}
        )
        assert status == 200 and body["actions"] == [6.0]
        status, body = _post(gw.url + "/v1/act", {"obs": [[2.0, 0.0]]})
        assert status == 200 and body["actions"] == [2.0]
        assert body["policy"] == "champ"
        _, text = _get(gw.url + "/metrics")
        assert "actor_critic_serving_requests_champ 1" in text
        assert "actor_critic_serving_requests_canary 1" in text
    finally:
        gw.close()


def test_run_report_resources_serving_row():
    """run_report's Resources section renders the serving gauge row
    when serving metrics are present (ISSUE 10 docs satellite)."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "run_report",
        Path(__file__).parent.parent / "scripts" / "run_report.py",
    )
    run_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(run_report)

    rows = [
        {"ts": 1.0, "recompiles": 0, "serving": {
            "requests_total": 10, "actions_total": 40, "flushes_total": 4,
            "batch_occupancy": 0.62, "latency_p50_ms": 3.1,
            "latency_p99_ms": 9.9, "queue_depth": 2,
            "rejected_total": 1, "errors_total": 0}},
        {"ts": 2.0, "recompiles": 0, "serving": {
            "requests_total": 30, "actions_total": 120, "flushes_total": 11,
            "batch_occupancy": 0.7, "latency_p50_ms": 3.0,
            "latency_p99_ms": 8.5, "queue_depth": 4,
            "rejected_total": 1, "errors_total": 0}},
    ]
    text = "\n".join(run_report.resource_summary(rows))
    assert "**serving**" in text
    assert "30 requests / 120 actions" in text
    assert "p50 3.0 ms / p99 8.5 ms" in text
    assert "queue depth mean 3.0 / max 4" in text
    assert "rejected 1" in text
    # No serving samples -> no serving row.
    assert "serving" not in "\n".join(
        run_report.resource_summary([{"ts": 1.0, "recompiles": 0}])
    )


def test_sampled_session_writes_serving_gauge(tmp_path):
    """A gateway under a sampling TelemetrySession lands `serving` rows
    in resources.jsonl — the run_report Resources row's source."""
    from actor_critic_tpu import telemetry

    store = serving.PolicyStore()
    store.register("default", StubEngine(), {"scale": np.ones(1, np.float32)})
    session = telemetry.TelemetrySession(
        tmp_path, resource_interval_s=0.05, serve_port=None
    )
    gw = serving.ServeGateway(store, port=0, session=session)
    try:
        _post(gw.url + "/v1/act", {"obs": [[1.0, 2.0]]})
        time.sleep(0.3)
        # The session-rendered /metrics rides the sampler registry.
        status, text = _get(gw.url + "/metrics")
        assert status == 200
        assert "actor_critic_serving_requests_total" in text
        assert "actor_critic_up 1" in text  # full exporter exposition
    finally:
        gw.close()
        session.close()
    rows = [
        json.loads(line)
        for line in (tmp_path / "resources.jsonl").read_text().splitlines()
    ]
    assert any(isinstance(r.get("serving"), dict) for r in rows)


def test_mirror_backend_matches_xla_backend():
    """backend='mirror' (numpy host mirror, no XLA dispatch) serves the
    same greedy actions as the jitted program — continuous-control PPO,
    where greedy == the policy mean (discrete argmax could flip on
    float32-vs-numpy near-ties)."""
    from actor_critic_tpu.envs import make_pendulum

    spec = make_pendulum().spec
    cfg = ppo.PPOConfig(hidden=(16, 16))
    params = serving.init_params(spec, cfg, "ppo", seed=0)
    xla = serving.PolicyEngine(spec, cfg, algo="ppo", buckets=(1, 4, 8))
    mirror = serving.PolicyEngine(
        spec, cfg, algo="ppo", buckets=(1, 4, 8), backend="mirror"
    )
    assert mirror.warm(mirror.prepare_params(params)) == 0
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(5, *spec.obs_shape)).astype(np.float32)
    np.testing.assert_allclose(
        mirror.act(mirror.prepare_params(params), obs),
        xla.act(xla.prepare_params(params), obs),
        rtol=1e-5, atol=1e-6,
    )
    # Mirror params install as frozen numpy snapshots (publisher
    # contract) and reject conv torsos / sampling.
    frozen = mirror.prepare_params(params)
    leaf = frozen["params"]["pi_torso"]["dense_0"]["kernel"]
    with pytest.raises(ValueError):
        leaf[0, 0] = 1.0
    with pytest.raises(ValueError):
        serving.PolicyEngine(
            spec, cfg, algo="ppo", backend="mirror", sample=True
        )
    with pytest.raises(ValueError):
        serving.PolicyEngine(spec, cfg, algo="ppo", backend="tpu")


def test_mirror_backend_serves_over_http():
    """A mirror-backend gateway answers /v1/act with no compiled
    programs at all (CPU-only serving host shape)."""
    from actor_critic_tpu.envs import make_pendulum

    spec = make_pendulum().spec
    cfg = ppo.PPOConfig(hidden=(8, 8))
    engine = serving.PolicyEngine(
        spec, cfg, algo="ppo", buckets=(1, 4), backend="mirror"
    )
    params = serving.init_params(spec, cfg, "ppo", seed=0)
    store = serving.PolicyStore()
    store.register("default", engine, params)
    gw = serving.ServeGateway(store, port=0, max_wait_us=200.0)
    try:
        status, body = _post(
            gw.url + "/v1/act", {"obs": [[0.1, 0.2, 0.3]]}
        )
        assert status == 200
        assert np.asarray(body["actions"]).shape == (1, spec.action_dim)
    finally:
        gw.close()


# ----------------------------------------------------- tracing (ISSUE 16)


def _post_traced(url: str, body: dict, trace_id: str | None = None):
    """POST returning (status, body, response x-trace-id header)."""
    headers = {"Content-Type": "application/json"}
    if trace_id is not None:
        headers["x-trace-id"] = trace_id
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), headers=headers
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, json.loads(r.read()), r.headers.get("x-trace-id")


def test_trace_id_minted_echoed_and_propagated(ppo_serving):
    gw, *_ = ppo_serving
    obs = {"obs": [[0.0, 0.0, 0.0, 0.0]]}
    # No header: the gateway mints a 16-hex id, echoes it in the
    # response header AND the body.
    status, body, tid = _post_traced(gw.url + "/v1/act", obs)
    assert status == 200
    assert re.fullmatch(r"[0-9a-f]{16}", tid), tid
    assert body["trace"] == tid
    # Caller-minted id: propagated end-to-end unchanged.
    status, body, tid = _post_traced(
        gw.url + "/v1/act", obs, trace_id="deadbeefcafef00d"
    )
    assert status == 200
    assert tid == body["trace"] == "deadbeefcafef00d"
    # Hostile oversize header: capped, not copied into every span row.
    status, body, tid = _post_traced(
        gw.url + "/v1/act", obs, trace_id="x" * 500
    )
    assert status == 200 and len(body["trace"]) <= 64


def test_request_spans_linked_by_flow_events(tmp_path):
    """The tentpole contract: one traced /v1/act request leaves the
    full hop chain in spans.jsonl — serve_request/parse/queue_wait/
    respond carrying its trace id, the serve_dispatch flush that served
    it, and s/t/f flow events sharing the trace's flow id so Perfetto
    draws one connected track across the thread handoff."""
    from actor_critic_tpu import telemetry
    from actor_critic_tpu.telemetry.spans import flow_id_of

    store = serving.PolicyStore()
    store.register(
        "default", StubEngine(), {"scale": np.ones(1, np.float32)}
    )
    session = telemetry.TelemetrySession(
        tmp_path, sample_resources=False, serve_port=None
    )
    gw = serving.ServeGateway(store, port=0, session=session)
    try:
        status, body, _ = _post_traced(
            gw.url + "/v1/act", {"obs": [[2.0, 0.0]]},
            trace_id="cafe0000cafe0000",
        )
        assert status == 200 and body["trace"] == "cafe0000cafe0000"
    finally:
        gw.close()
        session.close()
    events = [
        json.loads(line)
        for line in (tmp_path / "spans.jsonl").read_text().splitlines()
    ]
    tid = "cafe0000cafe0000"
    spans = {
        e["name"]: e for e in events
        if e.get("ph") == "X" and (e.get("args") or {}).get("trace") == tid
    }
    for name in ("serve_request", "serve_parse", "serve_queue_wait",
                 "serve_respond"):
        assert name in spans, (name, sorted(spans))
    assert spans["serve_request"]["args"]["status"] == 200
    # the queue-wait span names the flush that served the request, and
    # that flush's serve_dispatch span exists with batch stats
    flush = spans["serve_queue_wait"]["args"]["flush"]
    dispatches = [
        e for e in events if e.get("ph") == "X"
        and e.get("name") == "serve_dispatch"
        and (e.get("args") or {}).get("flush") == flush
    ]
    assert len(dispatches) == 1
    assert dispatches[0]["args"]["requests"] >= 1
    assert 0.0 < dispatches[0]["args"]["occupancy"] <= 1.0
    # flow triplet: start (gateway thread), step (dispatcher), end
    # (gateway, inside the serve_request slice), one shared id
    flows = [e for e in events if e.get("ph") in ("s", "t", "f")
             and e.get("id") == flow_id_of(tid)]
    phases = sorted(e["ph"] for e in flows)
    assert phases == ["f", "s", "t"], flows
    fin = next(e for e in flows if e["ph"] == "f")
    req = spans["serve_request"]
    assert req["ts"] <= fin["ts"] <= req["ts"] + req["dur"]


def test_slo_histograms_and_burn_on_metrics():
    """Per-policy cumulative histogram + SLO burn gauges ride /metrics
    in the Prometheus convention; an impossible SLO class burns > 1."""
    store = serving.PolicyStore()
    eng = StubEngine(pad_s=0.002)
    store.register(
        "default", eng, {"scale": np.ones(1, np.float32)},
        slo_ms=0.001,  # unmeetable: every request violates
    )
    gw = serving.ServeGateway(store, port=0, max_wait_us=0.0)
    try:
        for _ in range(4):
            status, _ = _post(gw.url + "/v1/act", {"obs": [[1.0, 0.0]]})
            assert status == 200
        _, text = _get(gw.url + "/metrics")
    finally:
        gw.close()
    samples = {}
    for line in text.splitlines():
        if line and not line.startswith("#"):
            head, val = line.rsplit(" ", 1)
            samples[head] = float(val)
    # true cumulative histogram: +Inf bucket == count == 4 requests
    fam = "actor_critic_serving_latency_ms"
    assert samples[fam + '_bucket{policy="default",le="+Inf"}'] == 4
    assert samples[fam + '_count{policy="default"}'] == 4
    assert samples[fam + '_sum{policy="default"}'] > 0
    bucket_vals = [
        v for k, v in samples.items() if k.startswith(fam + "_bucket")
    ]
    assert sorted(bucket_vals)[-1] == 4  # cumulative, monotone to count
    # SLO layer: class, violations, burn (every request over 1 us SLO)
    assert samples["actor_critic_serving_slo_ms_default"] == 0.001
    assert samples["actor_critic_serving_slo_violations_default"] == 4
    assert samples["actor_critic_serving_slo_burn_default"] > 1.0
    assert samples["actor_critic_serving_slo_burn"] == samples[
        "actor_critic_serving_slo_burn_default"
    ]
    # percentile window size rides along (small-n honesty)
    assert samples["actor_critic_serving_latency_window_n"] == 4


def test_slo_class_rides_swap():
    """A hot-swap must not drop the policy's SLO class (the class is
    an operator declaration about the POLICY id, not one params tree)."""
    store = serving.PolicyStore()
    eng = StubEngine()
    store.register(
        "default", eng, {"scale": np.ones(1, np.float32)}, slo_ms=25.0
    )
    assert store.get("default").slo_ms == 25.0
    store.swap("default", {"scale": np.full(1, 2.0, np.float32)})
    assert store.get("default").slo_ms == 25.0


def test_percentile_linear_interpolation():
    from actor_critic_tpu.serving.batcher import _percentile

    assert _percentile([], 99) == 0.0
    assert _percentile([7.0], 99) == 7.0
    assert _percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5  # numpy 'linear'
    assert _percentile([1.0, 2.0, 3.0, 4.0], 99) == pytest.approx(3.97)
    assert _percentile([1.0, 2.0], 100) == 2.0


def test_shed_counter_distinct_from_reject():
    """Dispatcher-down/timeout sheds count separately from the
    queue-capacity reject counter (two different saturation stories)."""
    store = serving.PolicyStore()
    store.register(
        "default", StubEngine(), {"scale": np.ones(1, np.float32)}
    )
    batcher = serving.MicroBatcher(store, queue_limit=4, start=True)
    gw = serving.ServeGateway(store, port=0, batcher=batcher)
    try:
        batcher.close()  # dispatcher gone: the next act is shed
        status, _ = _post(gw.url + "/v1/act", {"obs": [[1.0, 2.0]]})
        assert status == 503
        snap = batcher.metrics.snapshot()
        assert snap["shed_total"] == 1
        assert snap["rejected_total"] == 0
    finally:
        gw.close()


def test_run_report_request_trace_table_and_flight_section(tmp_path):
    """run_report renders the per-request critical-path table from
    serve_* spans, and the flight-recorder 'last seconds before death'
    section from a flight dump (ISSUE 16 report satellites)."""
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "run_report",
        Path(__file__).parent.parent / "scripts" / "run_report.py",
    )
    run_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(run_report)

    spans = [
        {"name": "serve_request", "ph": "X", "ts": 0.0, "dur": 9000.0,
         "args": {"trace": "aaaa", "status": 200}},
        {"name": "serve_parse", "ph": "X", "ts": 0.0, "dur": 500.0,
         "args": {"trace": "aaaa"}},
        {"name": "serve_queue_wait", "ph": "X", "ts": 500.0,
         "dur": 3000.0, "args": {"trace": "aaaa", "flush": 7}},
        {"name": "serve_dispatch", "ph": "X", "ts": 3500.0, "dur": 5000.0,
         "args": {"flush": 7, "occupancy": 0.5, "requests": 2}},
        {"name": "serve_respond", "ph": "X", "ts": 9100.0, "dur": 400.0,
         "args": {"trace": "aaaa"}},
        {"name": "serve_request", "ph": "X", "ts": 0.0, "dur": 2000.0,
         "args": {"trace": "bbbb", "status": 200}},
    ]
    lines = run_report.request_traces(spans)
    text = "\n".join(lines)
    assert "2 traced request(s)" in text
    rows = [ln for ln in lines if ln.startswith("| `")]
    assert rows[0].startswith("| `aaaa`")  # slowest first
    assert "| 9.00 | 0.50 | 3.00 | 5.00 | 7 | 0.5 | 0.40 |" in rows[0]
    assert "| `bbbb` | 200 | 2.00 | — | — | — | — | — | — |" in text
    # no serving spans -> no section
    assert run_report.request_traces([{"name": "update", "ph": "X"}]) == []

    # flight section: dump -> rendered table with relative offsets
    from actor_critic_tpu.telemetry import flight

    rec = flight.FlightRecorder(
        tmp_path / flight.RING_FILENAME, slots=8, slot_size=256,
        meta={"rank": 1},
    )
    rec.record("event_stall", open_span="update")
    rec.dump("stall")
    rec.close()
    flines = run_report.flight_summary(str(tmp_path))
    ftext = "\n".join(flines)
    assert "flight_dump_stall_1.json" in ftext
    assert "reason: **stall**" in ftext
    assert "**event_stall**" in ftext and "open_span" in ftext
    assert run_report.flight_summary(str(tmp_path / "empty")) == []
    # the full render wires both sections in
    (tmp_path / "spans.jsonl").write_text(
        "\n".join(json.dumps(s) for s in spans) + "\n"
    )
    report = run_report.render(str(tmp_path))
    assert "Slowest traced requests" in report or "| `aaaa`" in report
    assert "Flight recorder" in report
