"""scripts/tb_export.py: JSONL run logs (the JsonlLogger 'iter' key
format) convert into TensorBoard event files with the right step axis."""

import importlib.util
import json
import os

import pytest

pytest.importorskip("tensorflow")

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "tb_export.py",
)


def _load():
    spec = importlib.util.spec_from_file_location("tb_export", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_export_real_logger_format(tmp_path):
    """Rows as utils.logging.JsonlLogger writes them ('iter' key)."""
    from actor_critic_tpu.utils.logging import JsonlLogger

    p = tmp_path / "m.jsonl"
    logger = JsonlLogger(path=str(p), echo=False)
    for i in (10, 20, 30):
        logger.log(i, {"loss": 1.0 / i})
    logger.close()

    tb_export = _load()
    n = tb_export.export(str(p), str(tmp_path / "tb"))
    assert n == 3
    files = [f for f in (tmp_path / "tb").rglob("*") if f.is_file()]
    assert files

    # step axis must be the logged iterations, not line numbers
    from tensorflow.python.summary.summary_iterator import summary_iterator

    steps = set()
    for f in files:
        for ev in summary_iterator(str(f)):
            if ev.summary.value:
                steps.add(int(ev.step))
    assert steps == {10, 20, 30}, steps
