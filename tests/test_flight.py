"""Crash flight recorder (telemetry/flight.py, ISSUE 16): the bounded
mmap ring keeps exactly the last N records across wrap, survives the
owner dying WITHOUT close() (SIGKILL has no exit handlers — the page
cache is the durability story), skips torn slots instead of
misparsing them, and dumps/harvests into the flight_dump_*.json files
run_report.py renders."""

import json
import os
import signal
import struct
import subprocess
import sys

from actor_critic_tpu.telemetry import flight


def _ring(tmp_path, **kw):
    kw.setdefault("slots", 16)
    kw.setdefault("slot_size", 256)
    return flight.FlightRecorder(tmp_path / flight.RING_FILENAME, **kw)


def test_ring_keeps_last_n_records_across_wrap(tmp_path):
    rec = _ring(tmp_path)
    for i in range(40):
        rec.record("tick", i=i)
    got = flight.harvest(rec.path)
    assert len(got) == 16  # ring capacity, not 40
    assert [r["i"] for r in got] == list(range(24, 40))  # oldest first
    assert all(r["kind"] == "tick" and "t" in r for r in got)
    rec.close()


def test_harvest_without_close_survives_owner_death(tmp_path):
    """The SIGKILL contract, end to end: a child process writes records
    and is SIGKILLed mid-life (no close, no flush, no exit handler);
    the parent harvests the ring file afterwards."""
    ring = tmp_path / flight.RING_FILENAME
    code = (
        "import os, signal, sys\n"
        "from actor_critic_tpu.telemetry import flight\n"
        f"r = flight.FlightRecorder({str(ring)!r}, slots=16, slot_size=256,"
        " meta={'who': 'victim'})\n"
        "for i in range(10):\n"
        "    r.record('work', i=i)\n"
        "print('READY', flush=True)\n"
        "signal.pause()\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", code], stdout=subprocess.PIPE,
        text=True, cwd=os.path.dirname(os.path.dirname(__file__)),
        env=env,
    )
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.kill()  # SIGKILL: no python code runs after this
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    got = flight.harvest(ring)
    assert [r["kind"] for r in got] == ["meta"] + ["work"] * 10
    assert got[0]["who"] == "victim"
    assert [r["i"] for r in got[1:]] == list(range(10))


def test_torn_slot_is_skipped_not_misparsed(tmp_path):
    rec = _ring(tmp_path)
    for i in range(5):
        rec.record("tick", i=i)
    rec.close()
    # Corrupt record 2's payload in place: valid length, garbage JSON —
    # what a writer dying mid-slot (or a racing read) leaves behind.
    with open(rec.path, "r+b") as f:
        buf = bytearray(f.read())
        off = 24 + 2 * 256  # header+seq, slot 2
        (length,) = struct.unpack_from("<I", buf, off)
        buf[off + 4:off + 4 + length] = b"\xff" * length
        f.seek(0)
        f.write(buf)
    got = flight.harvest(rec.path)
    assert [r["i"] for r in got] == [0, 1, 3, 4]  # slot 2 dropped, rest kept


def test_harvest_rejects_missing_and_foreign_files(tmp_path):
    assert flight.harvest(tmp_path / "nope.ring") == []
    junk = tmp_path / "junk.ring"
    junk.write_bytes(b"not a ring at all" * 10)
    assert flight.harvest(junk) == []


def test_oversize_record_truncates_to_marker(tmp_path):
    rec = _ring(tmp_path)
    rec.record("fat", blob="x" * 4096)
    (got,) = flight.harvest(rec.path)
    assert got["kind"] == "fat" and got["truncated"] is True
    assert "blob" not in got
    rec.close()


def test_record_never_raises_after_close(tmp_path):
    rec = _ring(tmp_path)
    rec.close()
    rec.record("tick", i=1)  # must be a silent no-op
    rec.close()  # idempotent


def test_init_zeroes_a_stale_ring(tmp_path):
    a = _ring(tmp_path)
    a.record("old_run", i=1)
    a.close()
    b = _ring(tmp_path)  # same path: previous run's records must vanish
    b.record("new_run", i=2)
    kinds = [r["kind"] for r in flight.harvest(b.path)]
    assert kinds == ["new_run"]
    b.close()


def test_mirror_and_gauge_hooks_shape_records(tmp_path):
    rec = _ring(tmp_path)
    rec.mirror({"name": "serve_request", "ph": "X", "ts": 1.0,
                "dur": 250.0, "args": {"trace": "abc"}, "pid": 7})
    rec.mirror({"name": "req", "ph": "s", "ts": 2.0, "id": 9})
    rec.record_gauges({
        "ts": 123.0, "rss_bytes": 100, "alive": True,
        "serving": {"queue_depth": 3, "policy": "default"},
    })
    span, flow, gauges = flight.harvest(rec.path)
    assert span["kind"] == "span" and span["name"] == "serve_request"
    assert span["args"]["trace"] == "abc" and "pid" not in span
    assert flow["kind"] == "trace_evt" and flow["ph"] == "s"
    assert gauges["kind"] == "gauges"
    assert gauges["rss_bytes"] == 100
    assert gauges["serving_queue_depth"] == 3
    assert "ts" not in gauges and "alive" not in gauges
    assert "serving_policy" not in gauges  # non-numeric leaf dropped
    rec.close()


def test_dump_writes_durable_json_and_find_dumps_sees_it(tmp_path):
    rec = _ring(tmp_path, meta={"rank": 3})
    for i in range(4):
        rec.record("tick", i=i)
    path = rec.dump("stall")
    assert os.path.basename(path) == "flight_dump_stall_1.json"
    body = json.load(open(path))
    assert body["flight_dump"] is True and body["reason"] == "stall"
    assert body["meta"] == {"rank": 3}
    assert [r["kind"] for r in body["records"]] == ["meta"] + ["tick"] * 4
    # second dump numbers itself, both discoverable
    rec.dump("stall")
    assert [os.path.basename(p) for p in flight.find_dumps(tmp_path)] == [
        "flight_dump_stall_1.json", "flight_dump_stall_2.json",
    ]
    rec.close()


def test_signal_dump_chains_to_previous_handler(tmp_path):
    rec = _ring(tmp_path)
    rec.record("about_to_die")
    seen = []
    prev = signal.signal(signal.SIGUSR1, lambda s, f: seen.append(s))
    try:
        rec.install_signal_dump(signals=(signal.SIGUSR1,))
        os.kill(os.getpid(), signal.SIGUSR1)
        dumps = flight.find_dumps(tmp_path)
        assert len(dumps) == 1 and "signal_" in dumps[0]
        assert seen == [signal.SIGUSR1]  # previous handler still ran
    finally:
        signal.signal(signal.SIGUSR1, prev)
        rec.close()


def test_session_mirrors_spans_and_dumps_on_divergence(tmp_path):
    """TelemetrySession wiring: completed spans and health events
    mirror into the flight ring, and a durable event (divergence/stall)
    dumps the ring to a flight_dump_*.json next to the other sinks —
    the self-service half of the post-mortem path (harvest() is the
    SIGKILL half)."""
    from actor_critic_tpu import telemetry

    with telemetry.TelemetrySession(
        tmp_path, run_info={"seed": 5}, sample_resources=False,
        serve_port=None,
    ) as s:
        assert s.flight is not None
        with telemetry.span("update", it=3):
            pass
        s.event("divergence", metric="loss", value="nan")
    records = flight.harvest(tmp_path / flight.RING_FILENAME)
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "meta" and records[0]["seed"] == 5
    assert "span" in kinds and "event_divergence" in kinds
    span = next(r for r in records if r["kind"] == "span")
    assert span["name"] == "update"
    dumps = flight.find_dumps(tmp_path)
    assert len(dumps) == 1 and "divergence" in dumps[0]
    body = json.load(open(dumps[0]))
    # the dump happened BEFORE the close-path records, at event time
    assert body["reason"] == "divergence"
    assert any(r.get("kind") == "span" for r in body["records"])


def test_session_flight_off_switch(tmp_path):
    from actor_critic_tpu import telemetry

    with telemetry.TelemetrySession(
        tmp_path, sample_resources=False, serve_port=None, flight=False,
    ) as s:
        assert s.flight is None
    assert not (tmp_path / flight.RING_FILENAME).exists()
