"""Checkpoint/resume under --async-actors (ISSUE 9 satellite; mirrors
tests/test_host_resume.py for the async actor–learner driver).

Async resume contract: the device state (params/opt/PRNG) restores
EXACTLY, and the save tree carries ALL A per-actor pools' normalizer
states (`host_loop.async_host_ckpt_state`) — each actor pool runs
independent running stats, so every one must round-trip; actor
collection restarts fresh episodes, same as the lockstep contract.
"""

import jax
import numpy as np
import pytest

from actor_critic_tpu.algos import ppo
from actor_critic_tpu.envs.host_pool import HostEnvPool
from actor_critic_tpu.utils.checkpoint import Checkpointer


def _tiny_cfg():
    return ppo.PPOConfig(
        num_envs=4, rollout_steps=8, epochs=1, num_minibatches=1,
        hidden=(16,),
    )


def _pools():
    # Two actors, disjoint seed strides (the build_actor_pools layout).
    return [
        HostEnvPool("CartPole-v1", 2, seed=0),
        HostEnvPool("CartPole-v1", 2, seed=100003),
    ]


def _trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_async_resume_restores_exact_state(tmp_path):
    cfg = _tiny_cfg()
    pools = _pools()
    with Checkpointer(tmp_path / "ck") as ck:
        p1, o1, _ = ppo.train_host_async(
            pools, cfg, 3, seed=0, log_every=0, ckpt=ck, save_every=2,
        )
        ck.wait()
        assert ck.latest_step() == 3
    for p in pools:
        p.close()

    # "New process": fresh pools, resume finds the run complete — no
    # actors start (restored normalizer stats stay untouched), history
    # is empty, device state is bit-equal.
    pools2 = _pools()
    with Checkpointer(tmp_path / "ck") as ck:
        p2, o2, history = ppo.train_host_async(
            pools2, cfg, 3, seed=0, log_every=0, ckpt=ck, resume=True,
        )
    _trees_equal(p1, p2)
    _trees_equal(o1, o2)
    assert history == []
    # EVERY actor pool's normalizer state came back through set_state
    # (count > the single reset batch a fresh pool would carry).
    for pool in pools2:
        assert float(pool.obs_rms.count) > 100.0, float(pool.obs_rms.count)
    for p in pools2:
        p.close()


def test_async_resume_continues_training(tmp_path):
    cfg = _tiny_cfg()
    pools = _pools()
    with Checkpointer(tmp_path / "ck") as ck:
        ppo.train_host_async(
            pools, cfg, 2, seed=0, log_every=0, ckpt=ck, save_every=1,
        )
        ck.wait()
    for p in pools:
        p.close()

    pools2 = _pools()
    with Checkpointer(tmp_path / "ck") as ck:
        _, _, history = ppo.train_host_async(
            pools2, cfg, 4, seed=0, log_every=1, ckpt=ck, save_every=1,
            resume=True,
        )
        assert ck.latest_step() == 4
    # Only blocks 3..4 were consumed (1-based iteration ids).
    assert [it for it, _ in history] == [3, 4]
    for p in pools2:
        p.close()


def test_async_resume_rejects_changed_actor_count(tmp_path):
    """The save tree carries one normalizer state per actor pool;
    resuming with a different --async-actors silently misassigns env
    shards' statistics — refuse loudly instead."""
    cfg = _tiny_cfg()
    pools = _pools()
    with Checkpointer(tmp_path / "ck") as ck:
        ppo.train_host_async(
            pools, cfg, 2, seed=0, log_every=0, ckpt=ck, save_every=1,
        )
        ck.wait()
    for p in pools:
        p.close()

    one_pool = [HostEnvPool("CartPole-v1", 4, seed=0)]
    with Checkpointer(tmp_path / "ck") as ck:
        with pytest.raises(ValueError, match="original --async-actors"):
            ppo.train_host_async(
                one_pool, cfg, 4, seed=0, log_every=0, ckpt=ck,
                resume=True,
            )
    for p in one_pool:
        p.close()
