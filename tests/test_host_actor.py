"""Numpy host-actor mirror parity (models/host_actor.py).

The mirrors must produce the SAME deterministic quantities (logits,
means, log-stds, values, deterministic actions) as the flax modules they
shadow — sampling then differs only by the RNG source. Plus: the overlap
path of the host trainers runs end-to-end and still learns.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu.algos import ddpg, ppo, sac
from actor_critic_tpu.envs.host_pool import HostEnvPool
from actor_critic_tpu.envs.jax_env import EnvSpec
from actor_critic_tpu.models import host_actor
from actor_critic_tpu.models.networks import (
    ActorCriticDiscrete,
    ActorCriticGaussian,
    DeterministicActor,
    SquashedGaussianActor,
)

ATOL = 1e-5


def _np_params(params):
    return jax.device_get(params)


def test_mirror_discrete_parity():
    net = ActorCriticDiscrete(num_actions=3, hidden=(16, 16))
    obs = jnp.asarray(np.random.default_rng(0).standard_normal((5, 4)), jnp.float32)
    params = net.init(jax.random.key(0), obs)
    dist, value = net.apply(params, obs)

    spec = EnvSpec(obs_shape=(4,), action_dim=3, discrete=True)
    policy = host_actor.make_ppo_host_policy(spec, None)
    p = _np_params(params)["params"]
    z = host_actor._mlp(p["torso"], np.asarray(obs), host_actor._tanh)
    logits = host_actor._dense(p["policy"], z)
    v = host_actor._dense(p["value"], z)[..., 0]
    np.testing.assert_allclose(logits, np.asarray(dist.logits), atol=ATOL)
    np.testing.assert_allclose(v, np.asarray(value), atol=ATOL)

    # Sampling: actions in range, log_prob matches the device dist's.
    a, logp, vv = policy(_np_params(params), np.asarray(obs), np.random.default_rng(1))
    assert a.shape == (5,) and ((0 <= a) & (a < 3)).all()
    np.testing.assert_allclose(
        logp, np.asarray(dist.log_prob(jnp.asarray(a))), atol=1e-4
    )
    np.testing.assert_allclose(vv, np.asarray(value), atol=ATOL)


def test_mirror_gaussian_parity():
    net = ActorCriticGaussian(action_dim=2, hidden=(16, 16))
    obs = jnp.asarray(np.random.default_rng(0).standard_normal((5, 3)), jnp.float32)
    params = net.init(jax.random.key(0), obs)
    dist, value = net.apply(params, obs)

    spec = EnvSpec(obs_shape=(3,), action_dim=2, discrete=False)
    policy = host_actor.make_ppo_host_policy(spec, None)
    a, logp, v = policy(_np_params(params), np.asarray(obs), np.random.default_rng(1))
    np.testing.assert_allclose(v, np.asarray(value), atol=ATOL)
    # log_prob of the numpy-sampled action must match the device dist.
    np.testing.assert_allclose(
        logp, np.asarray(dist.log_prob(jnp.asarray(a))), atol=1e-4
    )
    # Value-only mirror (overlap GAE baselines) matches the critic head.
    vf = host_actor.make_ppo_host_value(spec, None)
    np.testing.assert_allclose(
        vf(_np_params(params), np.asarray(obs)), np.asarray(value), atol=ATOL
    )


def test_mirror_value_discrete_parity():
    net = ActorCriticDiscrete(num_actions=3, hidden=(16, 16))
    obs = jnp.asarray(np.random.default_rng(2).standard_normal((7, 4)), jnp.float32)
    params = net.init(jax.random.key(0), obs)
    _, value = net.apply(params, obs)
    spec = EnvSpec(obs_shape=(4,), action_dim=3, discrete=True)
    vf = host_actor.make_ppo_host_value(spec, None)
    np.testing.assert_allclose(
        vf(_np_params(params), np.asarray(obs)), np.asarray(value), atol=ATOL
    )


def test_mirror_ddpg_parity():
    cfg = ddpg.DDPGConfig(hidden=(16, 16), warmup_steps=0, exploration_noise=0.0)
    net = DeterministicActor(action_dim=2, hidden=(16, 16))
    obs = jnp.asarray(np.random.default_rng(0).standard_normal((5, 3)), jnp.float32)
    params = net.init(jax.random.key(0), obs)
    want = np.asarray(net.apply(params, obs))

    spec = EnvSpec(obs_shape=(3,), action_dim=2, discrete=False)
    act = host_actor.make_ddpg_host_explore(spec, cfg)
    got = act(_np_params(params), np.asarray(obs), np.random.default_rng(1), 10)
    np.testing.assert_allclose(got, want, atol=ATOL)

    # Warmup: uniform random in [-1, 1].
    cfg2 = ddpg.DDPGConfig(hidden=(16, 16), warmup_steps=100)
    act2 = host_actor.make_ddpg_host_explore(spec, cfg2)
    r = act2(_np_params(params), np.asarray(obs), np.random.default_rng(1), 10)
    assert (np.abs(r) <= 1.0).all() and not np.allclose(r, want, atol=1e-3)


def test_mirror_sac_deterministic_parts():
    cfg = sac.SACConfig(hidden=(16, 16), warmup_steps=0)
    net = SquashedGaussianActor(action_dim=2, hidden=(16, 16))
    obs = jnp.asarray(np.random.default_rng(0).standard_normal((5, 3)), jnp.float32)
    params = net.init(jax.random.key(0), obs)
    dist = net.apply(params, obs)

    p = _np_params(params)["params"]
    z = host_actor._mlp(p["torso"], np.asarray(obs), host_actor._relu)
    mean = host_actor._dense(p["mean"], z)
    log_std = np.clip(
        host_actor._dense(p["log_std"], z),
        host_actor._LOG_STD_MIN, host_actor._LOG_STD_MAX,
    )
    np.testing.assert_allclose(mean, np.asarray(dist.mean), atol=ATOL)
    np.testing.assert_allclose(log_std, np.asarray(dist.log_std), atol=ATOL)

    spec = EnvSpec(obs_shape=(3,), action_dim=2, discrete=False)
    act = host_actor.make_sac_host_explore(spec, cfg)
    a = act(_np_params(params), np.asarray(obs), np.random.default_rng(1), 10)
    assert a.shape == (5, 2) and (np.abs(a) < 1.0).all()


def test_supports_mirror():
    net = ActorCriticDiscrete(num_actions=2, hidden=(8,))
    params = net.init(jax.random.key(0), jnp.zeros((1, 4)))
    assert host_actor.supports_mirror(jax.device_get(params))
    # CNN torso → not mirrorable.
    pix = ActorCriticDiscrete(num_actions=2, pixel_obs=True)
    pparams = pix.init(jax.random.key(0), jnp.zeros((1, 36, 36, 4), jnp.uint8))
    assert not host_actor.supports_mirror(jax.device_get(pparams))


def test_ppo_host_overlap_trains():
    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=8, epochs=1, num_minibatches=1, hidden=(16,)
    )
    pool = HostEnvPool("CartPole-v1", num_envs=2, seed=0)
    _, _, history = ppo.train_host(
        pool, cfg, num_iterations=3, seed=0, log_every=1, overlap=True
    )
    assert len(history) == 3
    assert all(np.isfinite(m["loss"]) for _, m in history)
    pool.close()


def test_ddpg_host_overlap_trains():
    cfg = ddpg.DDPGConfig(
        num_envs=2, steps_per_iter=4, updates_per_iter=1, buffer_capacity=256,
        batch_size=8, warmup_steps=8, hidden=(16,),
    )
    pool = HostEnvPool("Pendulum-v1", num_envs=2, seed=0, normalize_reward=False)
    learner, history = ddpg.train_host(
        pool, cfg, num_iterations=4, seed=0, log_every=1, overlap=True
    )
    assert len(history) == 4
    assert all(np.isfinite(m["critic_loss"]) for _, m in history)
    pool.close()


@pytest.mark.slow
def test_overlap_learning_parity_cartpole():
    """Overlap on vs off, same seed and budget: the 1-update-stale mirror
    must not change the learning OUTCOME (round-2 verdict weak #4). Both
    arms train PPO on a host CartPole pool for 40 iterations; both must
    clear the same return floor and land within a factor of each other.
    (Calibrated: both arms reach ~170-235 at this budget; trajectories
    differ only by RNG source + 1-step staleness.)"""
    cfg = ppo.PPOConfig(
        num_envs=8, rollout_steps=128, epochs=4, num_minibatches=4,
        lr=2.5e-4, entropy_coef=0.01, hidden=(32, 32),
    )
    finals = {}
    for overlap in (True, False):
        pool = HostEnvPool("CartPole-v1", num_envs=8, seed=0)
        hist: list = []
        ppo.train_host(
            pool, cfg, num_iterations=40, seed=0, log_every=5,
            log_fn=lambda it, m: hist.append((it, m)), overlap=overlap,
        )
        pool.close()
        finals[overlap] = np.mean([m["recent_return"] for _, m in hist[-4:]])
    assert finals[True] >= 120, finals
    assert finals[False] >= 120, finals
    ratio = min(finals.values()) / max(finals.values())
    assert ratio > 0.4, finals


def test_greedy_mirror_parity():
    """The host greedy-eval mirrors must equal the device mode policies
    exactly (they replace the per-step device round-trip in
    host_evaluate)."""
    # PPO discrete: argmax logits == dist.mode().
    net = ActorCriticDiscrete(num_actions=3, hidden=(16, 16))
    obs = jnp.asarray(np.random.default_rng(5).standard_normal((6, 4)), jnp.float32)
    params = net.init(jax.random.key(3), obs)
    dist, _ = net.apply(params, obs)
    spec = EnvSpec(obs_shape=(4,), action_dim=3, discrete=True)
    act = host_actor.make_ppo_host_greedy(spec, None)
    np.testing.assert_array_equal(
        act(_np_params(params), np.asarray(obs)), np.asarray(dist.mode())
    )

    # PPO Gaussian: mean head == dist.mode().
    gnet = ActorCriticGaussian(action_dim=2, hidden=(16, 16))
    gobs = jnp.asarray(np.random.default_rng(6).standard_normal((6, 3)), jnp.float32)
    gparams = gnet.init(jax.random.key(4), gobs)
    gdist, _ = gnet.apply(gparams, gobs)
    gspec = EnvSpec(obs_shape=(3,), action_dim=2, discrete=False)
    gact = host_actor.make_ppo_host_greedy(gspec, None)
    np.testing.assert_allclose(
        gact(_np_params(gparams), np.asarray(gobs)),
        np.asarray(gdist.mode()), atol=ATOL,
    )

    # DDPG: noiseless tanh actor.
    dnet = DeterministicActor(action_dim=2, hidden=(16, 16))
    dparams = dnet.init(jax.random.key(5), gobs)
    dact = host_actor.make_ddpg_host_greedy(gspec, None)
    np.testing.assert_allclose(
        dact(_np_params(dparams), np.asarray(gobs)),
        np.asarray(dnet.apply(dparams, gobs)), atol=ATOL,
    )

    # SAC: tanh(mean) == the algo's greedy act.
    scfg = sac.SACConfig(hidden=(16, 16))
    snet = SquashedGaussianActor(action_dim=2, hidden=(16, 16))
    sparams = snet.init(jax.random.key(6), gobs)
    sact = host_actor.make_sac_host_greedy(gspec, scfg)
    want = sac.make_greedy_act(2, scfg)(sparams, gobs)
    np.testing.assert_allclose(
        sact(_np_params(sparams), np.asarray(gobs)), np.asarray(want), atol=ATOL
    )
