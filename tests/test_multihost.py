"""multihost_init (parallel/mesh.py, SURVEY.md §5.8) fallback exercise.

The environment has no cluster, so the DCN path itself can't connect —
what CAN and must be tested is the documented fallback contract:

1. with no recognizable cluster environment, `multihost_init()` swallows
   JAX's auto-detection failure and the process proceeds single-host
   (a fresh interpreter, because the call must precede backend init);
2. a *detected-but-misconfigured* cluster env still lands in the same
   swallow-and-warn path rather than silently proceeding un-warned;
3. calling it after the backend is already initialized surfaces JAX's
   RuntimeError instead of swallowing it (real misuse must be loud).
"""

import os
import subprocess
import sys

import pytest


def _run(code: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    # CPU-only child; disarm the axon site hook (the JAX_PLATFORMS=cpu
    # without empty PALLAS_AXON_POOL_IPS combination deadlocks — see
    # tests/conftest.py).
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # Make sure no cluster-ish variables leak in from the driver.
    for var in ("JAX_COORDINATOR_ADDRESS", "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE"):
        env.pop(var, None)
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, env=env,
    )


@pytest.mark.slow
def test_no_cluster_falls_back_single_host():
    proc = _run(
        "from actor_critic_tpu.parallel import multihost_init\n"
        "import jax\n"
        "multihost_init()\n"  # before any backend init
        "assert jax.process_count() == 1\n"
        "assert jax.device_count() >= 1\n"
        "print('single-host ok')\n"
    )
    assert proc.returncode == 0, proc.stderr
    assert "single-host ok" in proc.stdout


@pytest.mark.slow
def test_misconfigured_cluster_env_warns_not_crashes():
    proc = _run(
        "import os\n"
        # A malformed coordinator triggers detection, then init failure.
        "os.environ['JAX_COORDINATOR_ADDRESS'] = 'not-a-host:bad-port'\n"
        "import logging; logging.basicConfig(level=logging.WARNING)\n"
        "from actor_critic_tpu.parallel import multihost_init\n"
        "import jax\n"
        "multihost_init()\n"
        "assert jax.process_count() == 1\n"
        "print('fallback ok')\n"
    )
    assert proc.returncode == 0, proc.stderr
    assert "fallback ok" in proc.stdout
    # The documented warn-on-fallback behavior (mesh.py docstring): a
    # misconfigured cluster must not be silent.
    assert "continuing" in proc.stderr or "single-host" in proc.stderr


# --- the REAL two-process DCN exercise (VERDICT round 4, missing #3) ------
#
# Everything above tests the FALLBACK contract; this spawns two actual
# processes against a localhost coordinator (4 fake CPU devices each) and
# runs a psum whose operands live on different processes — the DCN path
# initializing and moving bytes at least once in CI.

_WORKER = r"""
import os, sys
proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
from actor_critic_tpu.parallel import multihost_init
multihost_init(
    coordinator=f"127.0.0.1:{port}", num_processes=nprocs, process_id=proc_id
)
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

assert jax.process_count() == nprocs, jax.process_count()
assert len(jax.devices()) == 4 * nprocs, len(jax.devices())

mesh = Mesh(np.asarray(jax.devices()), ("dp",))
n = len(jax.devices())
arr = jax.make_array_from_callback(
    (n,), NamedSharding(mesh, P("dp")),
    lambda idx: np.arange(n, dtype=np.float32)[idx],
)
f = jax.jit(
    shard_map(
        lambda x: jax.lax.psum(x, "dp"), mesh=mesh,
        in_specs=P("dp"), out_specs=P(),
    )
)
total = np.asarray(f(arr).addressable_data(0))
assert float(total[0]) == n * (n - 1) / 2, total  # 0+1+...+7 = 28
print(f"proc {proc_id}: psum across {nprocs} processes ok -> {float(total[0])}")
"""


@pytest.mark.slow
def test_two_process_distributed_psum():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the worker sets its own device count
    env.pop("JAX_PLATFORMS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
        assert "psum across 2 processes ok -> 28.0" in out, out
