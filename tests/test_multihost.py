"""multihost_init (parallel/mesh.py, SURVEY.md §5.8) fallback exercise.

The environment has no cluster, so the DCN path itself can't connect —
what CAN and must be tested is the documented fallback contract:

1. with no recognizable cluster environment, `multihost_init()` swallows
   JAX's auto-detection failure and the process proceeds single-host
   (a fresh interpreter, because the call must precede backend init);
2. a *detected-but-misconfigured* cluster env still lands in the same
   swallow-and-warn path rather than silently proceeding un-warned;
3. calling it after the backend is already initialized surfaces JAX's
   RuntimeError instead of swallowing it (real misuse must be loud).
"""

import os
import subprocess
import sys

import pytest


def _run(code: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    # CPU-only child; disarm the axon site hook (the JAX_PLATFORMS=cpu
    # without empty PALLAS_AXON_POOL_IPS combination deadlocks — see
    # tests/conftest.py).
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # Make sure no cluster-ish variables leak in from the driver.
    for var in ("JAX_COORDINATOR_ADDRESS", "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE"):
        env.pop(var, None)
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, env=env,
    )


@pytest.mark.slow
def test_no_cluster_falls_back_single_host():
    proc = _run(
        "from actor_critic_tpu.parallel import multihost_init\n"
        "import jax\n"
        "multihost_init()\n"  # before any backend init
        "assert jax.process_count() == 1\n"
        "assert jax.device_count() >= 1\n"
        "print('single-host ok')\n"
    )
    assert proc.returncode == 0, proc.stderr
    assert "single-host ok" in proc.stdout


@pytest.mark.slow
def test_misconfigured_cluster_env_warns_not_crashes():
    proc = _run(
        "import os\n"
        # A malformed coordinator triggers detection, then init failure.
        "os.environ['JAX_COORDINATOR_ADDRESS'] = 'not-a-host:bad-port'\n"
        "import logging; logging.basicConfig(level=logging.WARNING)\n"
        "from actor_critic_tpu.parallel import multihost_init\n"
        "import jax\n"
        "multihost_init()\n"
        "assert jax.process_count() == 1\n"
        "print('fallback ok')\n"
    )
    assert proc.returncode == 0, proc.stderr
    assert "fallback ok" in proc.stdout
    # The documented warn-on-fallback behavior (mesh.py docstring): a
    # misconfigured cluster must not be silent.
    assert "continuing" in proc.stderr or "single-host" in proc.stderr
