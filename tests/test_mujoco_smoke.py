"""MuJoCo host-path train smoke (round-2 verdict weak #2): the exact
machinery the long BASELINE.md runs depend on — `ppo.train_host` on a real
MuJoCo HostEnvPool with eval + checkpoint/resume — exercised cheaply in
CI. Everything else host-path is tested on CartPole pools only; this
guards the MuJoCo-specific surface (obs normalization over 17-dim states,
raw-reward episode tracking, truncation-at-1000 plumbing).
"""

import json

import numpy as np
import pytest

pytest.importorskip("mujoco")
gym = pytest.importorskip("gymnasium")

import jax  # noqa: E402

from actor_critic_tpu.algos import ppo  # noqa: E402
from actor_critic_tpu.envs.host_pool import HostEnvPool  # noqa: E402
from actor_critic_tpu.utils.checkpoint import Checkpointer  # noqa: E402


@pytest.mark.slow
def test_ppo_halfcheetah_train_eval_resume(tmp_path):
    cfg = ppo.PPOConfig(
        num_envs=2, rollout_steps=32, epochs=2, num_minibatches=4,
        hidden=(32, 32), anneal_iters=6, lr_final=0.0,
    )

    def make_pool():
        return HostEnvPool(
            "HalfCheetah-v5", num_envs=2, seed=0,
            normalize_obs=True, normalize_reward=True,
        )

    history: list = []
    ckpt = Checkpointer(str(tmp_path / "ck"))
    pool = make_pool()
    try:
        ppo.train_host(
            pool, cfg, num_iterations=3, seed=0, log_every=1,
            log_fn=lambda it, m: history.append((it, m)),
            eval_every=3, eval_envs=2, eval_steps=60,
            ckpt=ckpt, save_every=3,
        )
    finally:
        ckpt.close()
        pool.close()

    assert [it for it, _ in history] == [1, 2, 3]
    for _, m in history:
        assert np.isfinite(m["loss"]) and np.isfinite(m["v_loss"])
    # The eval row rode the iteration-3 log entry and is finite.
    assert "eval_return" in history[-1][1]
    assert np.isfinite(history[-1][1]["eval_return"])
    # Metrics round-trip strict JSON (the JSONL logger contract).
    json.dumps(history[-1][1])

    # Resume picks up at the saved iteration and runs the remainder.
    resumed: list = []
    ckpt2 = Checkpointer(str(tmp_path / "ck"))
    pool2 = make_pool()
    try:
        ppo.train_host(
            pool2, cfg, num_iterations=5, seed=0, log_every=1,
            log_fn=lambda it, m: resumed.append((it, m)),
            ckpt=ckpt2, save_every=100, resume=True,
        )
    finally:
        ckpt2.close()
        pool2.close()
    assert [it for it, _ in resumed] == [4, 5]
    for _, m in resumed:
        assert np.isfinite(m["loss"])
