"""SAC tests: soft-update mechanics, alpha auto-tuning, learning on the
analytic point-mass env (SURVEY.md §4; BASELINE.json:10)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from actor_critic_tpu import replay
from actor_critic_tpu.algos import sac
from actor_critic_tpu.algos.common import OffPolicyTransition, evaluate
from actor_critic_tpu.envs import make_point_mass


def _small_cfg(**kw):
    base = dict(
        num_envs=16,
        steps_per_iter=4,
        updates_per_iter=2,
        buffer_capacity=32768,
        batch_size=64,
        hidden=(32, 32),
        actor_lr=1e-3,
        critic_lr=1e-3,
        alpha_lr=1e-3,
        warmup_steps=128,
    )
    base.update(kw)
    return sac.SACConfig(**base)


def _filled_learner(cfg, key=0, n_items=512, obs_dim=1, act_dim=1):
    k = jax.random.key(key)
    k, lk, dk = jax.random.split(k, 3)
    learner = sac.init_learner((obs_dim,), act_dim, cfg, lk)
    ks = jax.random.split(dk, 4)
    batch = OffPolicyTransition(
        obs=jax.random.normal(ks[0], (n_items, obs_dim)),
        action=jax.random.uniform(ks[1], (n_items, act_dim), minval=-1, maxval=1),
        reward=jax.random.normal(ks[2], (n_items,)),
        next_obs=jax.random.normal(ks[3], (n_items, obs_dim)),
        terminated=jnp.zeros((n_items,)),
        done=jnp.zeros((n_items,)),
    )
    return learner._replace(replay=replay.add_batch(learner.replay, batch))


def _params_equal(a, b):
    return all(
        bool(jnp.all(x == y)) for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


class TestUpdateMechanics:
    def test_warmup_blocks_learning(self):
        cfg = _small_cfg(updates_per_iter=1)
        learner = _filled_learner(cfg)
        new, _ = sac.make_update_loop(1, cfg)(learner, jnp.asarray(False))
        assert _params_equal(new.actor_params, learner.actor_params)
        assert float(new.log_alpha) == float(learner.log_alpha)
        assert int(new.update_count) == 0

    def test_update_moves_everything(self):
        cfg = _small_cfg(updates_per_iter=1)
        learner = _filled_learner(cfg)
        new, metrics = sac.make_update_loop(1, cfg)(learner, jnp.asarray(True))
        assert not _params_equal(new.critic_params, learner.critic_params)
        assert not _params_equal(new.actor_params, learner.actor_params)
        assert float(new.log_alpha) != float(learner.log_alpha)
        # target critic moved slightly, not copied
        assert not _params_equal(new.target_critic, learner.target_critic)
        assert not _params_equal(new.target_critic, new.critic_params)
        for v in metrics.values():
            assert np.isfinite(float(v))

    def test_fixed_alpha_stays_fixed(self):
        cfg = _small_cfg(updates_per_iter=4, fixed_alpha=0.2)
        learner = _filled_learner(cfg)
        new, metrics = sac.make_update_loop(1, cfg)(learner, jnp.asarray(True))
        np.testing.assert_allclose(float(jnp.exp(new.log_alpha)), 0.2, rtol=1e-6)
        np.testing.assert_allclose(float(metrics["alpha"]), 0.2, rtol=1e-6)

    def test_alpha_tunes_toward_target_entropy(self):
        """α must move opposite the entropy gap: entropy above target ⇒
        α decays, entropy below target ⇒ α grows. Either branch asserts."""
        cfg = _small_cfg(updates_per_iter=32, init_alpha=1.0, alpha_lr=1e-2)
        learner = _filled_learner(cfg)
        new, metrics = sac.make_update_loop(1, cfg)(learner, jnp.asarray(True))
        entropy = float(metrics["entropy_est"])
        target = sac._target_entropy(1, cfg)
        assert abs(entropy - target) > 1e-3, "gap too small to test direction"
        if entropy > target:
            assert float(new.log_alpha) < float(learner.log_alpha)
        else:
            assert float(new.log_alpha) > float(learner.log_alpha)

    def test_config_rejects_nonpositive_alpha(self):
        with pytest.raises(ValueError):
            sac.SACConfig(init_alpha=0.0)
        with pytest.raises(ValueError):
            sac.SACConfig(fixed_alpha=-0.1)


class TestFusedTrainer:
    def test_smoke_and_accounting(self):
        env = make_point_mass()
        cfg = _small_cfg()
        state, metrics = sac.train(env, cfg, num_iterations=3, seed=0)
        assert int(state.update_step) == 3
        assert int(state.env_steps) == 3 * cfg.steps_per_iter * cfg.num_envs
        for v in metrics.values():
            assert np.isfinite(float(v))

    def test_sac_learns_point_mass(self):
        env = make_point_mass()
        cfg = _small_cfg(updates_per_iter=4, warmup_steps=256)
        state, _ = sac.train(env, cfg, num_iterations=250, seed=0)
        actor, _ = sac._modules(env.spec.action_dim, cfg)
        ret = evaluate(
            env,
            lambda p, o: actor.apply(p, o).mode(),
            state.learner.actor_params,
            jax.random.key(9),
            num_envs=32,
            num_steps=16,
        )
        # Optimal 0; random ≈ −6. Entropy bonus keeps it off exact optimum.
        assert float(ret) > -1.0, float(ret)


class TestHostPath:
    def test_host_ingest_update(self):
        cfg = _small_cfg(updates_per_iter=1, warmup_steps=0, batch_size=32)
        learner = sac.init_learner((3,), 2, cfg, jax.random.key(0))
        ingest = sac.make_host_ingest_update(2, cfg)
        K, E = 4, 8
        k = jax.random.key(1)
        traj = OffPolicyTransition(
            obs=jax.random.normal(k, (K, E, 3)),
            action=jnp.zeros((K, E, 2)),
            reward=jnp.ones((K, E)),
            next_obs=jax.random.normal(k, (K, E, 3)),
            terminated=jnp.zeros((K, E)),
            done=jnp.zeros((K, E)),
        )
        learner, metrics = ingest(learner, traj, jnp.asarray(K * E, jnp.int32))
        assert int(learner.replay.size) == K * E
        assert int(learner.update_count) == 1
        assert np.isfinite(float(metrics["critic_loss"]))


@pytest.mark.slow
def test_sac_learns_jax_pendulum_fused():
    """Fused-path learning test on the pure-JAX Pendulum: rollout + HBM
    replay + updates in one XLA program reach greedy eval >= -250
    within 2000 iterations / 128k env steps (the recorded run —
    results/sac_jax_pendulum_cpu.jsonl — is at -137 by 192k steps; a
    random policy scores ~-1200, an always-max-torque one ~-880)."""
    from actor_critic_tpu.envs import make_pendulum

    env = make_pendulum()
    cfg = sac.SACConfig(
        num_envs=8, steps_per_iter=8, updates_per_iter=8,
        hidden=(128, 128), batch_size=128, warmup_steps=1000,
    )
    state = sac.init_state(env, cfg, jax.random.key(0))
    step = jax.jit(sac.make_train_step(env, cfg), donate_argnums=0)
    eval_fn = jax.jit(sac.make_eval_fn(env, cfg), static_argnums=(2, 3))
    best = -float("inf")
    for it in range(2000):
        state, m = step(state)
        if (it + 1) % 500 == 0:
            best = max(best, float(eval_fn(state, jax.random.key(1), 8, 200)))
    assert best >= -250.0, f"jax pendulum not learned: best eval {best}"
