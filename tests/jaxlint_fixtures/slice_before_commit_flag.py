"""slice-before-commit flag fixture: padded buffers reaching commit
points (data-plane slot, socket response) with junk lanes intact.

Parsed (never imported) by tests/test_jaxlint.py.
"""

from actor_critic_tpu.utils.compile_cache import pad_to_bucket


def enqueue_padded(ring, obs, buckets):
    padded, mask = pad_to_bucket(obs, buckets)
    # the data-plane slot now holds junk rows a consumer will decode
    ring.put(padded, version=1)


def respond_padded(sock, obs, buckets):
    padded, _ = pad_to_bucket(obs, buckets)
    # the client receives bucket-width rows it never asked for
    sock.send(padded)
